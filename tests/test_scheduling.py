"""Token-budget continuous-batching scheduler (scheduling.py + the
ServingEngine tick loop): budget-interleaved prefill chunks stay
token-exact, priority admission orders the queue, SLO shedding raises
structured rejections, decode preemption + recompute-resume is token-
and logprob-exact, and the uid index keeps streaming accessors O(1)."""

import numpy as np
import pytest

from accelerate_tpu.generation import generate
from accelerate_tpu.models import LlamaConfig, create_llama_model
from accelerate_tpu.scheduling import Scheduler, SchedulerConfig, ShedError
from accelerate_tpu.serving import ServingEngine


@pytest.fixture(scope="module")
def tiny_llama():
    return create_llama_model(LlamaConfig.tiny(), seq_len=32)


def _reference(model, prompt, n):
    out = generate(model, np.asarray(prompt, np.int32)[None], max_new_tokens=n)
    return np.asarray(out)[0]


# --------------------------------------------------------------------- #
# policy unit tests (no jax, no engine)
# --------------------------------------------------------------------- #


def test_scheduler_config_validation():
    with pytest.raises(ValueError, match="mode"):
        SchedulerConfig(mode="lifo")
    with pytest.raises(ValueError, match="token_budget"):
        SchedulerConfig(token_budget=0)
    with pytest.raises(ValueError, match="max_queue_depth"):
        SchedulerConfig(max_queue_depth=0)
    with pytest.raises(ValueError, match="shed_action"):
        SchedulerConfig(shed_action="drop")


def test_scheduler_policy_decisions():
    s = Scheduler(SchedulerConfig(token_budget=64, max_queue_depth=2,
                                  max_queue_wait_s=1.0, enable_preemption=True))
    # ordering: class first, then submission order; fifo ignores class
    assert s.order_key(0, 7) < s.order_key(1, 3)
    assert s.order_key(1, 3) < s.order_key(1, 4)
    fifo = Scheduler(SchedulerConfig(mode="fifo", token_budget=64))
    assert fifo.order_key(5, 3) < fifo.order_key(0, 4)
    # budget: decodes claim theirs first; fifo is unbudgeted
    assert s.tick_budget(4, 8) == 32
    assert s.tick_budget(100, 8) == 0
    assert fifo.tick_budget(100, 8) == float("inf")
    # shedding: floor protects priority 0; thresholds gate
    assert s.shed_on_submit(0, 99) is None
    assert s.shed_on_submit(1, 2) is not None
    assert s.shed_on_submit(1, 1) is None
    assert s.shed_on_wait(1, 2.0) is not None
    assert s.shed_on_wait(0, 2.0) is None
    # victim: youngest of the least-important class, strictly below incoming
    decoding = [(0, 1, 5), (1, 2, 6), (2, 2, 9), (3, 0, 2)]
    assert s.pick_victim(0, decoding) == 2  # priority 2, uid 9
    assert s.pick_victim(2, decoding) is None  # nothing strictly below
    off = Scheduler(SchedulerConfig())
    assert off.pick_victim(0, decoding) is None  # preemption disabled
    # speculative gating
    gated = Scheduler(SchedulerConfig(speculative_priorities=(0,)))
    assert gated.use_speculative([0, 0]) and not gated.use_speculative([0, 1])
    assert Scheduler(SchedulerConfig()).use_speculative([3, 7])


def test_serving_scheduler_kwargs_handler():
    from accelerate_tpu.utils import ServingSchedulerKwargs

    kw = ServingSchedulerKwargs(token_budget=128, enable_preemption=True)
    cfg = kw.to_scheduler_config()
    assert isinstance(cfg, SchedulerConfig)
    assert cfg.token_budget == 128 and cfg.enable_preemption
    assert kw.to_kwargs() == {"token_budget": 128, "enable_preemption": True}


# --------------------------------------------------------------------- #
# budget-interleaved prefill
# --------------------------------------------------------------------- #


def test_budget_interleaves_long_prefill_token_exact(tiny_llama):
    """A 20-token prompt under a 12-token budget streams one chunk window
    per tick while the short request keeps decoding — and both outputs
    still equal static generate()."""
    short = (np.arange(4) % 250 + 1).astype(np.int32)
    long = (np.arange(20) % 250 + 1).astype(np.int32)
    eng = ServingEngine(
        tiny_llama, num_slots=2, prompt_buckets=(4, 8), tick_block=1,
        scheduler=SchedulerConfig(token_budget=12),
    )
    a = eng.submit(short, max_new_tokens=8)
    b = eng.submit(long, max_new_tokens=4)
    eng.step()
    # the short request produced tokens; the long prefill is mid-stream
    assert eng.partial(a).size >= 1
    assert eng.partial(b).size == 0 and eng.poll(b) is None
    state_b, _ = eng._locate(b)
    assert state_b == "active"  # holds a slot in the prefill phase
    eng.run()
    np.testing.assert_array_equal(eng.poll(a), _reference(tiny_llama, short, 8))
    np.testing.assert_array_equal(eng.poll(b), _reference(tiny_llama, long, 4))


def test_tiny_budget_cannot_livelock(tiny_llama):
    """token_budget=1 is below every window width: forced progress still
    drains the queue and outputs stay exact."""
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 250, size=n).astype(np.int32) for n in (3, 11, 6)]
    eng = ServingEngine(
        tiny_llama, num_slots=2, prompt_buckets=(4, 8), tick_block=2,
        scheduler=SchedulerConfig(token_budget=1),
    )
    outs = eng.generate_many(prompts, max_new_tokens=4)
    for p, got in zip(prompts, outs):
        np.testing.assert_array_equal(got, _reference(tiny_llama, p, 4))


def test_priority_orders_admission(tiny_llama):
    """With one slot, a later high-priority submission admits before
    earlier low-priority ones (and fifo mode ignores priority)."""
    p_lo = np.asarray([5, 6, 7], np.int32)
    p_hi = np.asarray([9, 9], np.int32)
    eng = ServingEngine(tiny_llama, num_slots=1, prompt_buckets=(4,))
    eng.submit(np.ones(3, np.int32), max_new_tokens=2)  # occupies the slot
    lo = eng.submit(p_lo, max_new_tokens=2, priority=1)
    hi = eng.submit(p_hi, max_new_tokens=2, priority=0)
    order = []
    while eng.queue or eng.active_count:
        eng.step()
        for uid in (lo, hi):
            if eng.poll(uid) is not None and uid not in order:
                order.append(uid)
    assert order == [hi, lo]


# --------------------------------------------------------------------- #
# SLO load shedding
# --------------------------------------------------------------------- #


def test_submit_depth_shed_is_structured(tiny_llama):
    eng = ServingEngine(
        tiny_llama, num_slots=1, prompt_buckets=(4,),
        scheduler=SchedulerConfig(max_queue_depth=1),
    )
    eng.submit(np.ones(3, np.int32), max_new_tokens=2, priority=1)
    with pytest.raises(ShedError) as ei:
        eng.submit(np.ones(3, np.int32), max_new_tokens=2, priority=1)
    assert ei.value.queue_depth == 1 and ei.value.priority == 1
    assert "max_queue_depth" in ei.value.reason
    # priority 0 is below the shed floor: never rejected
    ok = eng.submit(np.ones(3, np.int32), max_new_tokens=2, priority=0)
    assert isinstance(ok, int)
    assert eng.metrics.requests_shed == 1


def test_queue_wait_shed_surfaces_via_poll(tiny_llama):
    eng = ServingEngine(
        tiny_llama, num_slots=1, prompt_buckets=(4,),
        scheduler=SchedulerConfig(max_queue_wait_s=0.0),
    )
    keep = eng.submit(np.ones(3, np.int32), max_new_tokens=3, priority=0)
    stale = eng.submit(np.ones(4, np.int32), max_new_tokens=3, priority=1)
    eng.run()
    np.testing.assert_array_equal(eng.poll(keep), _reference(tiny_llama, np.ones(3), 3))
    with pytest.raises(ShedError) as ei:
        eng.poll(stale)
    assert ei.value.uid == stale and ei.value.queue_wait_ms >= 0.0
    with pytest.raises(ShedError):
        eng.partial(stale)
    assert eng.metrics.requests_shed == 1


def test_deprioritize_action_demotes_instead_of_rejecting(tiny_llama):
    eng = ServingEngine(
        tiny_llama, num_slots=1, prompt_buckets=(4,),
        scheduler=SchedulerConfig(max_queue_depth=1, shed_action="deprioritize"),
    )
    eng.submit(np.ones(3, np.int32), max_new_tokens=2, priority=1)
    demoted = eng.submit(np.ones(3, np.int32), max_new_tokens=2, priority=1)
    _, req = eng._locate(demoted)
    assert req.priority == 99  # deprioritize_to default
    eng.run()
    assert eng.poll(demoted) is not None  # still served, just later
    assert eng.metrics.requests_deprioritized == 1


# --------------------------------------------------------------------- #
# decode preemption + recompute resume
# --------------------------------------------------------------------- #


def test_preempt_resume_token_and_logprob_exact(tiny_llama):
    """A high-priority arrival evicts the decoding low-priority request
    (dense slot pressure); the victim resumes by recompute and its FULL
    output + logprobs equal an unpreempted control run."""
    p_victim = (np.arange(6) % 250 + 1).astype(np.int32)
    p_urgent = np.asarray([3, 1, 4, 1, 5], np.int32)
    eng = ServingEngine(
        tiny_llama, num_slots=1, prompt_buckets=(8,), tick_block=2,
        scheduler=SchedulerConfig(enable_preemption=True),
    )
    victim = eng.submit(p_victim, max_new_tokens=10, priority=1)
    eng.step()
    streamed = eng.partial(victim).copy()
    assert streamed.size >= 1
    urgent = eng.submit(p_urgent, max_new_tokens=4, priority=0)
    eng.step()
    # the victim was evicted and requeued with its generated-so-far tokens
    state, req = eng._locate(victim)
    assert state == "queued" and req.preempted
    np.testing.assert_array_equal(eng.partial(victim), streamed)  # nothing lost
    assert eng.metrics.decode_preemptions == 1
    eng.run()
    assert eng.metrics.resumes == 1
    np.testing.assert_array_equal(eng.poll(urgent), _reference(tiny_llama, p_urgent, 4))
    np.testing.assert_array_equal(eng.poll(victim), _reference(tiny_llama, p_victim, 10))
    # logprob-exact vs an unpreempted control engine (same uid -> same chain)
    control = ServingEngine(tiny_llama, num_slots=1, prompt_buckets=(8,), tick_block=2)
    c = control.submit(p_victim, max_new_tokens=10, priority=1)
    control.run()
    np.testing.assert_array_equal(eng.logprobs(victim), control.logprobs(c))


def test_preempt_resume_exact_under_sampling(tiny_llama):
    """Temperature sampling across a preemption: the carried key chain
    makes the resumed stream identical to the unpreempted control."""
    p_victim = (np.arange(5) % 250 + 2).astype(np.int32)
    kwargs = dict(num_slots=1, prompt_buckets=(8,), tick_block=2,
                  temperature=1.0, top_k=8, seed=7)
    eng = ServingEngine(
        tiny_llama, scheduler=SchedulerConfig(enable_preemption=True), **kwargs
    )
    victim = eng.submit(p_victim, max_new_tokens=9, priority=1)
    eng.step()
    eng.submit(np.ones(4, np.int32), max_new_tokens=3, priority=0)
    eng.run()
    assert eng.metrics.decode_preemptions == 1  # the scenario actually fired
    control = ServingEngine(tiny_llama, **kwargs)
    c = control.submit(p_victim, max_new_tokens=9, priority=1)
    control.run()
    np.testing.assert_array_equal(eng.poll(victim), control.poll(c))
    np.testing.assert_array_equal(eng.logprobs(victim), control.logprobs(c))


def test_paged_pool_pressure_preempts_youngest_low_priority(tiny_llama):
    """Pool exhaustion with a more important request waiting evicts the
    low-priority decode, frees its blocks NOW, and both finish exact."""
    p1 = (np.arange(4) % 250 + 1).astype(np.int32)
    p2 = np.asarray([8, 7, 6, 5], np.int32)
    eng = ServingEngine(
        tiny_llama, num_slots=2, prompt_buckets=(4, 8), tick_block=2,
        max_len=16, paged_block_size=4, pool_blocks=5,
        scheduler=SchedulerConfig(enable_preemption=True),
    )
    victim = eng.submit(p1, max_new_tokens=10, priority=1)
    eng.step()  # victim decodes, holding all 4 usable blocks
    assert eng.pool_free_blocks == 0
    urgent = eng.submit(p2, max_new_tokens=4, priority=0)
    eng.step()
    state, req = eng._locate(victim)
    assert state == "queued" and req.preempted  # evicted for the pool, not a slot
    eng.run()
    np.testing.assert_array_equal(eng.poll(urgent), _reference(tiny_llama, p2, 4))
    np.testing.assert_array_equal(eng.poll(victim), _reference(tiny_llama, p1, 10))
    assert eng.pool_free_blocks == 4  # every block returned


def test_cancel_preempted_and_requeued_request(tiny_llama):
    """Cancelling a preempted request returns its carried tokens and
    fully forgets the id (poll never resolves, accessors raise)."""
    eng = ServingEngine(
        tiny_llama, num_slots=1, prompt_buckets=(8,), tick_block=2,
        scheduler=SchedulerConfig(enable_preemption=True),
    )
    victim = eng.submit(np.arange(1, 7, dtype=np.int32), max_new_tokens=10, priority=1)
    eng.step()
    eng.submit(np.ones(4, np.int32), max_new_tokens=3, priority=0)
    eng.step()
    state, _ = eng._locate(victim)
    assert state == "queued"  # preempted-and-requeued
    carried = eng.cancel(victim)
    assert carried.size >= 1  # generated-so-far tokens come back
    eng.run()
    assert eng.poll(victim) is None
    with pytest.raises(KeyError):
        eng.partial(victim)
    with pytest.raises(KeyError):
        eng.cancel(victim)


def test_preemption_rejected_with_draft_model(tiny_llama):
    draft = create_llama_model(LlamaConfig.tiny(num_hidden_layers=1), seq_len=32, seed=1)
    with pytest.raises(NotImplementedError, match="preemption"):
        ServingEngine(
            tiny_llama, num_slots=1, prompt_buckets=(8,), draft_model=draft,
            scheduler=SchedulerConfig(enable_preemption=True),
        )


# --------------------------------------------------------------------- #
# stop sequences across a tick-block boundary
# --------------------------------------------------------------------- #


def test_stop_sequence_spans_tick_block_boundary(tiny_llama):
    """tick_block=2 delivers generated positions as [0] (prefill), [1,2],
    [3,4], ... — a stop pair at positions (2,3) straddles two device
    ticks, so the match logic must see across the block boundary."""
    prompt = np.ones((4,), np.int32)
    full = _reference(tiny_llama, prompt, 8)
    gen = full[len(prompt):]
    stop = [int(gen[2]), int(gen[3])]
    first = next(i for i in range(len(gen) - 1) if [int(gen[i]), int(gen[i + 1])] == stop)
    eng = ServingEngine(tiny_llama, num_slots=1, prompt_buckets=(4,), tick_block=2)
    uid = eng.submit(prompt, max_new_tokens=8, stop_sequences=[stop])
    eng.run()
    got = eng.poll(uid)
    assert len(got) == len(prompt) + first + 2
    np.testing.assert_array_equal(got, full[: len(got)])
    assert list(got[-2:]) == stop


def test_stop_sequence_on_resumed_request(tiny_llama):
    """preempt -> resume preserves the generated tail, so a stop sequence
    completed after the resume still fires at the exact position."""
    prompt = (np.arange(6) % 250 + 1).astype(np.int32)
    full = _reference(tiny_llama, prompt, 10)
    gen = full[len(prompt):]
    stop = [int(gen[6]), int(gen[7])]
    first = next(i for i in range(len(gen) - 1) if [int(gen[i]), int(gen[i + 1])] == stop)
    eng = ServingEngine(
        tiny_llama, num_slots=1, prompt_buckets=(8,), tick_block=2,
        scheduler=SchedulerConfig(enable_preemption=True),
    )
    victim = eng.submit(prompt, max_new_tokens=10, priority=1, stop_sequences=[stop])
    eng.step()  # 3 tokens streamed, stop not yet reachable
    eng.submit(np.ones(4, np.int32), max_new_tokens=3, priority=0)
    eng.run()
    assert eng.metrics.decode_preemptions == 1
    got = eng.poll(victim)
    assert len(got) == len(prompt) + first + 2
    np.testing.assert_array_equal(got, full[: len(got)])


# --------------------------------------------------------------------- #
# speculative gating (per-priority opt-in)
# --------------------------------------------------------------------- #


def test_speculative_gating_plain_tick_stays_exact(tiny_llama):
    """speculative_priorities=() routes every tick through the PLAIN
    target tick of a draft-equipped engine — outputs must still equal
    target greedy (the {t,d} pair tick advances only the target half)."""
    draft = create_llama_model(LlamaConfig.tiny(num_hidden_layers=1), seq_len=32, seed=1)
    rng = np.random.default_rng(2)
    prompts = [rng.integers(1, 250, size=n).astype(np.int32) for n in (5, 8)]
    eng = ServingEngine(
        tiny_llama, num_slots=2, prompt_buckets=(8,), tick_block=2,
        draft_model=draft, gamma=3,
        scheduler=SchedulerConfig(speculative_priorities=()),
    )
    for p, got in zip(prompts, eng.generate_many(prompts, max_new_tokens=6)):
        np.testing.assert_array_equal(got, _reference(tiny_llama, p, 6))
    assert eng.spec_stats["steps"] == 0  # never speculated


def test_speculative_gating_opted_in_class_speculates(tiny_llama):
    draft = create_llama_model(LlamaConfig.tiny(num_hidden_layers=1), seq_len=32, seed=1)
    p = (np.arange(5) % 250 + 1).astype(np.int32)
    eng = ServingEngine(
        tiny_llama, num_slots=2, prompt_buckets=(8,), tick_block=2,
        draft_model=draft, gamma=3,
        scheduler=SchedulerConfig(speculative_priorities=(0,)),
    )
    uid = eng.submit(p, max_new_tokens=6, priority=0)
    eng.run()
    np.testing.assert_array_equal(eng.poll(uid), _reference(tiny_llama, p, 6))
    assert eng.spec_stats["steps"] > 0


# --------------------------------------------------------------------- #
# O(1) uid index + scheduler telemetry
# --------------------------------------------------------------------- #


def test_uid_index_tracks_lifecycle(tiny_llama):
    eng = ServingEngine(tiny_llama, num_slots=1, prompt_buckets=(4,))
    u1 = eng.submit(np.ones(3, np.int32), max_new_tokens=2)
    u2 = eng.submit(np.ones(3, np.int32), max_new_tokens=2)
    assert eng._locate(u1)[0] == "queued" and eng._locate(u2)[0] == "queued"
    eng.step()
    assert eng._locate(u1)[0] in ("active", "done")
    eng.run()
    assert eng._locate(u1) == ("done", None) and eng._locate(u2) == ("done", None)
    with pytest.raises(KeyError):
        eng._locate(999)
    # cancelled ids leave the index entirely
    u3 = eng.submit(np.ones(3, np.int32), max_new_tokens=2)
    eng.cancel(u3)
    with pytest.raises(KeyError):
        eng._locate(u3)


def test_itl_and_queue_wait_metrics_exposed(tiny_llama):
    eng = ServingEngine(tiny_llama, num_slots=2, prompt_buckets=(8,), tick_block=2)
    eng.generate_many([np.ones(4, np.int32), np.ones(6, np.int32)], max_new_tokens=6)
    snap = eng.metrics.snapshot()
    assert snap["itl_ms_p50"] is not None and snap["itl_ms_p95"] >= snap["itl_ms_p50"]
    assert snap["queue_wait_ms_p50"] is not None
    assert snap["requests_shed"] == 0 and snap["decode_preemptions"] == 0
    text = eng.metrics.prometheus_text()
    assert 'accelerate_tpu_serving_itl_ms{quantile="0.95"}' in text
    assert 'accelerate_tpu_serving_queue_wait_ms{quantile="0.5"}' in text
    assert "accelerate_tpu_serving_decode_preemptions_total 0" in text


def test_scheduler_events_land_in_telemetry_and_summarize(tiny_llama, tmp_path):
    from accelerate_tpu.telemetry import EventLog, read_events, render_text, summarize

    log = EventLog(str(tmp_path / "sched.jsonl"), rank=0)
    eng = ServingEngine(
        tiny_llama, num_slots=1, prompt_buckets=(8,), tick_block=2,
        telemetry_log=log,
        scheduler=SchedulerConfig(enable_preemption=True, max_queue_wait_s=30.0),
    )
    victim = eng.submit(np.arange(1, 7, dtype=np.int32), max_new_tokens=10, priority=1)
    eng.step()
    eng.submit(np.ones(4, np.int32), max_new_tokens=3, priority=0)
    eng.run()
    assert eng.poll(victim) is not None
    log.close()
    events = read_events(str(tmp_path / "sched.jsonl"))
    names = [e["name"] for e in events if e.get("kind") == "event"]
    assert "admit" in names and "preempt_decode" in names and "resume" in names
    admit = next(e for e in events if e.get("name") == "admit")
    assert "priority" in admit and "queue_wait_ms" in admit
    report = summarize(events)
    sched = report["scheduler"]
    assert sched["admitted"] >= 2 and sched["preempted"] == 1 and sched["resumed"] == 1
    assert "scheduler:" in render_text(report)


def test_fifo_mode_matches_legacy_behavior(tiny_llama):
    """mode='fifo' ignores priorities and budgets: strict submission
    order, outputs exact — the A/B baseline bench_serving measures."""
    rng = np.random.default_rng(5)
    prompts = [rng.integers(1, 250, size=n).astype(np.int32) for n in (3, 9, 5)]
    eng = ServingEngine(
        tiny_llama, num_slots=1, prompt_buckets=(4, 8),
        scheduler=SchedulerConfig(mode="fifo", token_budget=4, enable_preemption=True),
    )
    uids = [eng.submit(p, max_new_tokens=4, priority=pr) for p, pr in zip(prompts, (1, 1, 0))]
    done_order = []
    while eng.queue or eng.active_count:
        eng.step()
        for u in uids:
            if eng.poll(u) is not None and u not in done_order:
                done_order.append(u)
    assert done_order == uids  # submission order, priority ignored
    for p, u in zip(prompts, uids):
        np.testing.assert_array_equal(eng.poll(u), _reference(tiny_llama, p, 4))
