"""Beam search (generation.py beam_search): greedy equivalence at beam 1,
score dominance over greedy, EOS freezing, batching."""

import numpy as np
import pytest

from accelerate_tpu.generation import beam_search, generate
from accelerate_tpu.models import LlamaConfig, create_llama_model


@pytest.fixture(scope="module")
def tiny_llama():
    return create_llama_model(LlamaConfig.tiny(), seq_len=16)


def _seq_logprob(model, ids, prompt_len):
    """fp32 log-prob of the generated suffix under the model (teacher-forced)."""
    import jax

    logits = np.asarray(model.apply_fn(model.params, ids))
    logp = np.asarray(jax.nn.log_softmax(logits.astype(np.float32), axis=-1))
    total = 0.0
    for t in range(prompt_len - 1, ids.shape[1] - 1):
        total += logp[0, t, ids[0, t + 1]]
    return total


def test_beam1_equals_greedy(tiny_llama):
    ids = (np.arange(2 * 6).reshape(2, 6) % 250).astype(np.int32)
    want = np.asarray(generate(tiny_llama, ids, max_new_tokens=5))
    got = np.asarray(beam_search(tiny_llama, ids, max_new_tokens=5, num_beams=1))
    np.testing.assert_array_equal(got, want)


def test_beams_never_score_below_greedy(tiny_llama):
    """The selected beam's sequence log-prob must be >= greedy's (with
    length_penalty 1 and no EOS both have the same length)."""
    ids = (np.arange(7) % 250).astype(np.int32)[None]
    greedy = np.asarray(generate(tiny_llama, ids, max_new_tokens=6))
    beam = np.asarray(beam_search(tiny_llama, ids, max_new_tokens=6, num_beams=4))
    lp_greedy = _seq_logprob(tiny_llama, greedy, 7)
    lp_beam = _seq_logprob(tiny_llama, beam, 7)
    assert lp_beam >= lp_greedy - 1e-4, (lp_beam, lp_greedy)


def test_reported_score_matches_recomputed(tiny_llama):
    ids = np.ones((1, 5), np.int32)
    out, score = beam_search(tiny_llama, ids, max_new_tokens=4, num_beams=3, return_scores=True)
    lp = _seq_logprob(tiny_llama, np.asarray(out), 5)
    np.testing.assert_allclose(float(score[0]), lp / 4.0, atol=2e-3)  # /len**1.0


def test_eos_freezes_beam(tiny_llama):
    """Non-vacuous: pick the eos from the BEAM's own output so the freeze
    path is always exercised."""
    ids = np.ones((1, 4), np.int32)
    free = np.asarray(beam_search(tiny_llama, ids, max_new_tokens=8, num_beams=3))[0]
    eos = int(free[6])  # a token the winning beam actually emits mid-sequence
    out = np.asarray(
        beam_search(tiny_llama, ids, max_new_tokens=8, num_beams=3, eos_token_id=eos)
    )[0]
    gen = out[4:].tolist()
    assert eos in gen, (eos, gen)
    after = gen[gen.index(eos):]
    assert all(t == eos for t in after), gen


def test_batched_rows_independent(tiny_llama):
    """Each batch row's beam result equals its solo run."""
    a = (np.arange(6) % 250).astype(np.int32)
    c = (np.arange(50, 56) % 250).astype(np.int32)
    both = np.asarray(beam_search(tiny_llama, np.stack([a, c]), max_new_tokens=4, num_beams=3))
    solo_a = np.asarray(beam_search(tiny_llama, a[None], max_new_tokens=4, num_beams=3))
    solo_c = np.asarray(beam_search(tiny_llama, c[None], max_new_tokens=4, num_beams=3))
    np.testing.assert_array_equal(both[0], solo_a[0])
    np.testing.assert_array_equal(both[1], solo_c[0])


def test_validation(tiny_llama):
    ids = np.ones((1, 4), np.int32)
    with pytest.raises(ValueError, match="num_beams"):
        beam_search(tiny_llama, ids, num_beams=0)
    with pytest.raises(ValueError, match="max_new_tokens"):
        beam_search(tiny_llama, ids, max_new_tokens=0)
    with pytest.raises(ValueError, match="max_position_embeddings"):
        beam_search(tiny_llama, ids, max_new_tokens=999)
