"""Numerics & precision analyzer (``analysis.numerics`` +
``analysis.numerics_rules``): the interval lattice against hand-computed
bounds (widening termination through scan/while, cond joins, cast
provenance round-trips, relational softmax refinements), the TPU601-606
rules with their clean twins, the compression numerics-model coverage
gate, the dogfood surfaces (build_train_step / ServingEngine /
examples), and the CLI (text/json/sarif/selfcheck/AST tier/strict
TPU602 gate)."""

import json
import math
import os
import subprocess
import sys
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.analysis.numerics import (
    DEFAULT_ASSUME,
    AbsVal,
    Interval,
    NumericsInterpreter,
    NumericsReport,
    _input_absvals,
    dtype_eps,
    dtype_max,
    numerics_check,
)
from accelerate_tpu.analysis.numerics_rules import (
    COMPRESSION_NUMERICS,
    check_key_reuse_source,
)
from accelerate_tpu.parallel.mesh import MeshConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

f32, f16, bf16 = jnp.float32, jnp.float16, jnp.bfloat16


def _rules(report: NumericsReport):
    return sorted({f.rule for f in report.findings})


def _out_iv(report: NumericsReport, i=0):
    o = report.outputs[i]
    return (o.lo, o.hi)


@pytest.fixture
def mesh1():
    return MeshConfig(data=1).build(jax.devices()[:1])


# --------------------------------------------------------------------- #
# the interval lattice (hand-computed references)
# --------------------------------------------------------------------- #


def test_interval_arithmetic_exact(mesh1):
    """log(x^2 + 1) / 2 on x in [-2, 3]: the pipeline's bounds are
    hand-computable and must match EXACTLY."""

    def step(x):
        return jnp.log(x**2 + 1.0) / 2.0

    r = numerics_check(step, jax.ShapeDtypeStruct((8,), f32), mesh=mesh1, assume=(-2.0, 3.0))
    lo, hi = _out_iv(r)
    assert lo == 0.0
    assert hi == pytest.approx(math.log(10.0) / 2.0, abs=1e-15)
    assert r.findings == []


def test_monotone_and_corner_transfers(mesh1):
    cases = [
        (lambda x: jnp.exp(x), (-1.0, 2.0), (math.exp(-1), math.exp(2))),
        (lambda x: jnp.tanh(x), (-50.0, 50.0), (-1.0, 1.0)),
        (lambda x: jnp.abs(x), (-3.0, 2.0), (0.0, 3.0)),
        (lambda x: -x, (-3.0, 2.0), (-2.0, 3.0)),
        (lambda x: x * 2.0 + 1.0, (-1.0, 1.0), (-1.0, 3.0)),
        (lambda x: jnp.maximum(x, 0.5), (-1.0, 1.0), (0.5, 1.0)),
        (lambda x: jnp.sqrt(jnp.maximum(x, 0.0)), (-4.0, 9.0), (0.0, 3.0)),
    ]
    for fn, assume, want in cases:
        r = numerics_check(fn, jax.ShapeDtypeStruct((4,), f32), mesh=mesh1, assume=assume)
        lo, hi = _out_iv(r)
        assert lo == pytest.approx(want[0], abs=1e-12), fn
        assert hi == pytest.approx(want[1], abs=1e-12), fn


def test_reduce_sum_scales_by_axis_length(mesh1):
    def step(x):
        return jnp.sum(x, axis=-1)

    r = numerics_check(step, jax.ShapeDtypeStruct((4, 100), f32), mesh=mesh1, assume=(-1.0, 2.0))
    assert _out_iv(r) == (-100.0, 200.0)


def test_psum_of_literal_is_group_size(mesh8):
    def step(x):
        return x * 0.0 + jax.lax.psum(1, "data")

    r = numerics_check(step, jax.ShapeDtypeStruct((4,), f32), mesh=mesh8)
    assert _out_iv(r) == (8.0, 8.0)


def test_scan_widening_terminates_and_is_sound(mesh1):
    """A growing carry widens to +inf (termination); a damped carry and a
    loop-invariant bound stay tight."""

    def growing(x):
        def body(c, _):
            return c + 1.0, c

        out, _ = jax.lax.scan(body, x, None, length=1000)
        return out

    r = numerics_check(growing, jax.ShapeDtypeStruct((), f32), mesh=mesh1)
    lo, hi = _out_iv(r)
    assert hi == math.inf and lo == DEFAULT_ASSUME[0] + 1.0  # lo moves once, then stable

    def damped(x):
        def body(c, _):
            return c * 0.5, c

        out, _ = jax.lax.scan(body, x, None, length=1000)
        return out

    r = numerics_check(damped, jax.ShapeDtypeStruct((), f32), mesh=mesh1)
    # the fixpoint carry is the init join [-16, 16]; the scan output is
    # the post-body carry 0.5*[-16, 16] — sound and tight, no widening
    assert _out_iv(r) == (-8.0, 8.0)


def test_while_widening_terminates(mesh1):
    def wloop(x):
        def cond(c):
            return c[1] < 10

        def body(c):
            return (c[0] + 1.0, c[1] + 1)

        return jax.lax.while_loop(cond, body, (x, 0))[0]

    r = numerics_check(wloop, jax.ShapeDtypeStruct((), f32), mesh=mesh1)
    lo, hi = _out_iv(r)
    assert hi == math.inf  # grows without a provable bound
    assert lo == DEFAULT_ASSUME[0]  # the zero-trip join keeps the init's lo


def test_cond_branches_join(mesh1):
    def step(x):
        return jax.lax.cond(x.sum() > 0, lambda v: v * 2.0, lambda v: v - 1.0, x)

    r = numerics_check(step, jax.ShapeDtypeStruct((4,), f32), mesh=mesh1, assume=(-1.0, 1.0))
    # branch 1: [-2, 2]; branch 2: [-2, 0]; join: [-2, 2]
    assert _out_iv(r) == (-2.0, 2.0)


def test_cast_provenance_round_trip(mesh1):
    """bf16 -> f32 -> bf16 keeps the 7-bit effective mantissa through the
    upcast (information does not come back)."""

    def step(x):
        return (x.astype(jnp.float32) * 2.0).astype(jnp.bfloat16)

    r = numerics_check(step, jax.ShapeDtypeStruct((4,), bf16), mesh=mesh1)
    assert r.outputs[0].mant == 7

    def stays_wide(x):
        return x * 2.0

    r = numerics_check(stays_wide, jax.ShapeDtypeStruct((4,), f32), mesh=mesh1)
    assert r.outputs[0].mant == 23


def test_interval_primitives():
    a = Interval(-2.0, 3.0)
    b = Interval(1.0, 4.0)
    assert a.join(b) == Interval(-2.0, 4.0)
    assert a.widen(Interval(-2.0, 5.0)) == Interval(-2.0, math.inf)
    assert a.widen(Interval(-3.0, 3.0)) == Interval(-math.inf, 3.0)
    assert a.contains_zero and not b.contains_zero
    assert Interval(-1.0, 2.0).magnitude() == 2.0
    assert dtype_max("float16") == 65504.0
    assert dtype_eps("bfloat16") == 2.0**-7


# --------------------------------------------------------------------- #
# TPU601-606: defect fires (priced), clean twin silent
# --------------------------------------------------------------------- #


def test_tpu601_low_precision_dot_and_clean_twin(mesh1):
    def low(x, w):
        return x @ w

    bad = numerics_check(
        low, jax.ShapeDtypeStruct((8, 512), bf16), jax.ShapeDtypeStruct((512, 16), bf16), mesh=mesh1
    )
    assert "TPU601" in _rules(bad)
    [f] = [f for f in bad.findings if f.rule == "TPU601"]
    assert "512" in f.message and "2" in f.message  # K and the priced K*eps/2 bound

    def fixed(x, w):
        return jax.lax.dot(x, w, preferred_element_type=jnp.float32)

    clean = numerics_check(
        fixed, jax.ShapeDtypeStruct((8, 512), bf16), jax.ShapeDtypeStruct((512, 16), bf16), mesh=mesh1
    )
    assert clean.findings == []

    # a short contraction is below the pricing floor
    short = numerics_check(
        low, jax.ShapeDtypeStruct((8, 64), bf16), jax.ShapeDtypeStruct((64, 16), bf16), mesh=mesh1
    )
    assert "TPU601" not in _rules(short)


def test_tpu601_forced_low_precision_sum(mesh1):
    def forced(x):  # a genuinely bf16 accumulator (lax.reduce, bf16 add)
        return jax.lax.reduce(x, jnp.bfloat16(0), jax.lax.add, (1,))

    r = numerics_check(forced, jax.ShapeDtypeStruct((4, 1024), bf16), mesh=mesh1)
    assert "TPU601" in _rules(r)

    def default_sum(x):  # jnp upcasts the accumulator to f32 on its own
        return jnp.sum(x, axis=-1)

    assert "TPU601" not in _rules(
        numerics_check(default_sum, jax.ShapeDtypeStruct((4, 1024), bf16), mesh=mesh1)
    )
    # jnp.sum(dtype=bf16) ALSO accumulates f32 and narrows once — clean
    assert "TPU601" not in _rules(
        numerics_check(
            lambda x: jnp.sum(x, axis=-1, dtype=jnp.bfloat16),
            jax.ShapeDtypeStruct((4, 1024), bf16),
            mesh=mesh1,
        )
    )


def test_tpu602_softmax_overflow_and_guarded_twin(mesh1):
    def bad(x):
        e = jnp.exp(x)
        return e / jnp.sum(e, axis=-1, keepdims=True)

    r = numerics_check(bad, jax.ShapeDtypeStruct((8, 64), f16), mesh=mesh1)
    assert "TPU602" in _rules(r)
    # two genuine overflow sites: the exp itself AND the f16 cast of the
    # (huge) sum — each a distinct fix point
    overflows = [f for f in r.findings if f.rule == "TPU602"]
    assert all(f.is_error for f in overflows)  # the strict-gate rule
    exp_f = next(f for f in overflows if f.message.startswith("exp"))
    assert "6.55e+04" in exp_f.message  # the dtype max is priced
    assert "running max" in exp_f.message  # the fix is named

    def good(x):
        m = jnp.max(x, axis=-1, keepdims=True)
        e = jnp.exp(x - m)  # relational: x - max(x) in [lo-hi, 0]
        return e / jnp.sum(e, axis=-1, keepdims=True)

    clean = numerics_check(good, jax.ShapeDtypeStruct((8, 64), f16), mesh=mesh1)
    assert clean.findings == []
    assert _out_iv(clean) == (0.0, 1.0)  # the x/sum(x) refinement

    # the same unguarded softmax in f32 cannot overflow at +-16
    assert "TPU602" not in _rules(numerics_check(bad, jax.ShapeDtypeStruct((8, 64), f32), mesh=mesh1))


def test_tpu602_fp16_variance_cancellation_with_assume(mesh1):
    """E[x^2] overflows fp16 once |x| can reach 1e3 — the squared term
    tops 65504 (the E[x^2]-E[x]^2 cancellation recipe); computing the
    moments in f32 is the fix."""

    def var_f16(x):
        return jnp.mean(x * x, axis=-1, dtype=jnp.float16) - jnp.mean(x, axis=-1, dtype=jnp.float16) ** 2

    r = numerics_check(var_f16, jax.ShapeDtypeStruct((4, 64), f16), mesh=mesh1, assume=(-1e3, 1e3))
    assert "TPU602" in _rules(r)

    def var_f32(x):
        x32 = x.astype(jnp.float32)
        return jnp.mean(x32 * x32, axis=-1) - jnp.mean(x32, axis=-1) ** 2

    assert "TPU602" not in _rules(
        numerics_check(var_f32, jax.ShapeDtypeStruct((4, 64), f16), mesh=mesh1, assume=(-1e3, 1e3))
    )


def test_tpu602_no_cascade_from_unguarded_div(mesh1):
    """One unguarded div must report TPU603 once — not a TPU602 wall from
    its infinite downstream intervals."""

    def step(x, n):
        y = x / n  # unbounded
        return (y * 2.0).astype(jnp.float16)

    r = numerics_check(
        step, jax.ShapeDtypeStruct((4,), f32), jax.ShapeDtypeStruct((4,), f32), mesh=mesh1
    )
    assert _rules(r) == ["TPU603"]


def test_tpu603_singularities_and_guards(mesh1):
    x = jax.ShapeDtypeStruct((8,), f32)

    def d(a, b):
        return a / b

    def lg(a):
        return jnp.log(a)

    def rs(a):
        return jax.lax.rsqrt(a)

    assert "TPU603" in _rules(numerics_check(d, x, x, mesh=mesh1))
    assert "TPU603" in _rules(numerics_check(lg, x, mesh=mesh1))
    assert "TPU603" in _rules(numerics_check(rs, x, mesh=mesh1))

    def d_ok(a, b):
        return a / jnp.maximum(b, 1e-6)

    def lg_ok(a):
        return jnp.log(jnp.exp(a))  # exp > 0

    def rs_ok(a):
        return jax.lax.rsqrt(a * a + 1e-6)

    assert "TPU603" not in _rules(numerics_check(d_ok, x, x, mesh=mesh1))
    assert "TPU603" not in _rules(numerics_check(lg_ok, x, mesh=mesh1))
    assert "TPU603" not in _rules(numerics_check(rs_ok, x, mesh=mesh1))


def test_tpu604_update_below_ulp_and_master_weights(mesh1):
    p16 = jax.ShapeDtypeStruct((64, 64), bf16)
    p32 = jax.ShapeDtypeStruct((64, 64), f32)

    def upd(p, g):
        return p - 1e-4 * g

    bad = numerics_check(upd, p16, p16, mesh=mesh1)
    assert "TPU604" in _rules(bad)
    [f] = [f for f in bad.findings if f.rule == "TPU604"]
    assert "master weights" in f.message and "eps" in f.message  # priced + the fix named

    # f32 master weights: clean
    assert "TPU604" not in _rules(numerics_check(upd, p32, p32, mesh=mesh1))

    # a big enough lr is representable: clean
    def big_upd(p, g):
        return p - 0.1 * g

    assert "TPU604" not in _rules(numerics_check(big_upd, p16, p16, mesh=mesh1))

    # epsilon-guard on an INTERMEDIATE (not a param leaf) must not fire
    def guard(x):
        t = jnp.exp(x.astype(jnp.float16))
        return t + jnp.float16(1e-5)

    assert "TPU604" not in _rules(
        numerics_check(guard, jax.ShapeDtypeStruct((8,), f16), mesh=mesh1, assume=(-4.0, 2.0))
    )


def test_tpu605_key_reuse_jaxpr_tier(mesh1):
    def reuse(seed):
        k = jax.random.key(seed)
        return jax.random.normal(k, (4,)) + jax.random.uniform(k, (4,))

    r = numerics_check(reuse, jax.ShapeDtypeStruct((), jnp.uint32), mesh=mesh1)
    assert "TPU605" in _rules(r)

    def split(seed):
        k = jax.random.key(seed)
        k1, k2 = jax.random.split(k)
        return jax.random.normal(k1, (4,)) + jax.random.uniform(k2, (4,))

    assert "TPU605" not in _rules(numerics_check(split, jax.ShapeDtypeStruct((), jnp.uint32), mesh=mesh1))


def test_tpu605_loop_invariant_key_in_scan(mesh1):
    """A key captured by a multi-iteration scan body and drawn from every
    iteration is reuse (same bits each trip); a per-iteration fold_in is
    the clean discipline."""

    def loop_reuse(seed, x):
        k = jax.random.key(seed)

        def body(c, _):
            return c + jax.random.normal(k, (4,)), None

        out, _ = jax.lax.scan(body, x, None, length=5)
        return out

    r = numerics_check(
        loop_reuse, jax.ShapeDtypeStruct((), jnp.uint32), jax.ShapeDtypeStruct((4,), f32), mesh=mesh1
    )
    assert "TPU605" in _rules(r)
    [f] = [f for f in r.findings if f.rule == "TPU605"]
    assert "loop iteration" in f.message

    def loop_folded(seed, x):
        k = jax.random.key(seed)

        def body(c, i):
            return c + jax.random.normal(jax.random.fold_in(k, i), (4,)), None

        out, _ = jax.lax.scan(body, x, jnp.arange(5), length=5)
        return out

    assert "TPU605" not in _rules(
        numerics_check(
            loop_folded, jax.ShapeDtypeStruct((), jnp.uint32), jax.ShapeDtypeStruct((4,), f32), mesh=mesh1
        )
    )


def test_tpu606_compressed_wire_and_twins(mesh8):
    from accelerate_tpu.parallel.compression import compressed_psum_mean

    def bf16_wire(g):
        return compressed_psum_mean({"w": g}, "data", "bf16")

    r = numerics_check(bf16_wire, jax.ShapeDtypeStruct((8, 16), f32), mesh=mesh8)
    assert "TPU606" in _rules(r)
    [f] = [f for f in r.findings if f.rule == "TPU606"]
    assert "amax" in f.message and "error feedback" in f.message  # the EQuARX-style bound

    def int8_wire(g):
        return compressed_psum_mean({"w": g}, "data", "int8")

    r = numerics_check(int8_wire, jax.ShapeDtypeStruct((8, 16), f32), mesh=mesh8)
    assert "TPU606" in _rules(r)
    assert any("254" in f.message for f in r.findings if f.rule == "TPU606")

    # exact f32 reduction: clean
    def exact(g):
        n = jax.lax.psum(1, "data")
        return jax.lax.psum(g, "data") / n

    assert "TPU606" not in _rules(numerics_check(exact, jax.ShapeDtypeStruct((8, 16), f32), mesh=mesh8))

    # an error-feedback scheme carries the residual: clean
    def with_feedback(g, e):
        n = jax.lax.psum(1, "data")
        c = (g + e).astype(jnp.bfloat16)
        red = jax.lax.psum(c, "data").astype(jnp.float32) / n
        new_e = (g + e) - c.astype(jnp.float32)
        return red, new_e

    assert "TPU606" not in _rules(
        numerics_check(
            with_feedback, jax.ShapeDtypeStruct((8, 16), f32), jax.ShapeDtypeStruct((8, 16), f32), mesh=mesh8
        )
    )


def test_powersgd_is_numerics_clean(mesh8):
    """PowerSGD reduces f32 factors (never a narrowed wire payload) and
    carries error feedback — the whole TPU6xx tier must stay silent."""
    from accelerate_tpu.parallel.compression import powersgd_psum_mean

    def psgd(g, e, q):
        return powersgd_psum_mean({"w": g}, "data", {"error": {"w": e}, "q": {"w": q}}, 2)

    r = numerics_check(
        psgd,
        jax.ShapeDtypeStruct((32, 16), f32),
        jax.ShapeDtypeStruct((32, 16), f32),
        jax.ShapeDtypeStruct((16, 2), f32),
        mesh=mesh8,
    )
    assert r.findings == []


# --------------------------------------------------------------------- #
# AST tier (TPU605 over source text)
# --------------------------------------------------------------------- #


def test_key_reuse_ast_tier_fires_and_split_is_clean():
    bad = textwrap.dedent(
        '''
        """Fixture."""
        import jax


        def sample(key, n):
            a = jax.random.normal(key, (n,))
            b = jax.random.uniform(key, (n,))
            return a + b
        '''
    )
    found = check_key_reuse_source(bad, path="<t>")
    assert [f.rule for f in found] == ["TPU605"]
    assert "bit-identical" in found[0].message

    good = bad.replace(
        "def sample(key, n):",
        "def sample(key, n):\n    key, sub = jax.random.split(key)",
    ).replace("jax.random.uniform(key", "jax.random.uniform(sub")
    assert check_key_reuse_source(good, path="<t>") == []

    # a rebind between draws (fold_in discipline) is clean too
    rebind = textwrap.dedent(
        '''
        """Fixture."""
        import jax


        def sample(key, n):
            a = jax.random.normal(key, (n,))
            key = jax.random.fold_in(key, 1)
            b = jax.random.uniform(key, (n,))
            return a + b
        '''
    )
    assert check_key_reuse_source(rebind, path="<t>") == []


# --------------------------------------------------------------------- #
# suppression / filtering / report surfaces
# --------------------------------------------------------------------- #


def test_findings_anchor_to_source_and_inline_suppression(tmp_path, mesh1):
    import importlib.util

    mod = tmp_path / "lowdot.py"
    mod.write_text(
        textwrap.dedent(
            '''
            """Fixture: low-precision accumulation, suppressed inline."""
            import jax.numpy as jnp


            def step(x, w):
                return x @ w  # tpu-lint: disable=TPU601
            '''
        )
    )
    spec = importlib.util.spec_from_file_location("lowdot", mod)
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    r = numerics_check(
        m.step,
        jax.ShapeDtypeStruct((8, 512), bf16),
        jax.ShapeDtypeStruct((512, 16), bf16),
        mesh=mesh1,
    )
    assert "TPU601" not in _rules(r)


def test_select_ignore_filtering(mesh1):
    def step(x, w):
        return x @ w

    a = jax.ShapeDtypeStruct((8, 512), bf16)
    b = jax.ShapeDtypeStruct((512, 16), bf16)
    assert _rules(numerics_check(step, a, b, mesh=mesh1, ignore=("TPU601",))) == []
    assert _rules(numerics_check(step, a, b, mesh=mesh1, select=("TPU601",))) == ["TPU601"]


def test_report_dict_and_text_surfaces(mesh8):
    def step(x):
        m = jnp.max(x, axis=-1, keepdims=True)
        e = jnp.exp(x - m)
        return e / jnp.sum(e, axis=-1, keepdims=True)

    r = numerics_check(step, jax.ShapeDtypeStruct((8, 64), f16), mesh=mesh8, assume=(-8.0, 8.0))
    d = r.as_dict()
    assert d["assume"] == [-8.0, 8.0]
    assert d["eqns_interpreted"] == r.n_eqns > 0
    assert d["outputs"][0]["lo"] == 0.0 and d["outputs"][0]["hi"] == 1.0
    assert d["outputs"][0]["effective_mantissa_bits"] == 10
    assert d["findings"] == []
    text = r.render_text()
    assert "inputs assumed in [-8, 8]" in text
    assert "findings: none" in text
    assert "[0, 1]" in text


# --------------------------------------------------------------------- #
# selfcheck + registry drift (the executable spec)
# --------------------------------------------------------------------- #


def test_run_numerics_selfcheck_passes(mesh8):
    from accelerate_tpu.analysis.selfcheck import run_numerics_selfcheck

    ok, lines = run_numerics_selfcheck(mesh8)
    assert ok, "\n".join(lines)
    joined = "\n".join(lines)
    for rule in ("TPU601", "TPU602", "TPU603", "TPU604", "TPU605", "TPU606"):
        assert f"{rule} fixture: detected" in joined
        assert f"{rule} clean twin: zero findings" in joined
    assert any("interval reference" in line and "exact" in line for line in lines)


def test_selfcheck_fixture_count_matches_registry(mesh8):
    """Registry drift gate: every registered TPU6xx rule has a seeded
    defect AND a clean twin; TPU602 is the error-severity strict gate."""
    from accelerate_tpu.analysis.rules import ERROR, RULES
    from accelerate_tpu.analysis.selfcheck import _numerics_clean_fixtures, _numerics_fixtures

    registered = {rid for rid in RULES if rid.startswith("TPU6")}
    assert registered == {"TPU601", "TPU602", "TPU603", "TPU604", "TPU605", "TPU606"}
    assert set(_numerics_fixtures(mesh8)) == registered
    assert set(_numerics_clean_fixtures(mesh8)) == registered
    assert RULES["TPU602"].severity == ERROR
    assert all(RULES[r].severity == "warning" for r in registered - {"TPU602"})
    assert all(RULES[r].tier == "numerics" for r in registered)


# --------------------------------------------------------------------- #
# compression numerics-model coverage (the COLLECTIVE_EFFECTS pattern)
# --------------------------------------------------------------------- #


def test_every_compression_entry_point_has_numerics_model():
    """Every public compression method must carry a numerics model
    (wire dtype, error-feedback flag, per-leaf error bound) — a new
    compression mode cannot land outside the analysis stack."""
    from accelerate_tpu.parallel import compression

    for method in compression.METHODS:
        assert method in COMPRESSION_NUMERICS, f"no numerics model for {method!r}"
        model = COMPRESSION_NUMERICS[method]
        assert model.wire_dtype
        assert isinstance(model.error_feedback, bool)
        # the bound is a usable function of (amax, n)
        assert model.bound(1.0, 8) >= 0.0
        assert model.describe
    # schemes without error feedback must price a nonzero bound;
    # powersgd's residual carry is what licenses its zero steady-state bound
    assert COMPRESSION_NUMERICS["bf16"].bound(1.0, 8) > 0
    assert COMPRESSION_NUMERICS["int8"].bound(1.0, 8) > 0
    assert COMPRESSION_NUMERICS["powersgd"].error_feedback


# --------------------------------------------------------------------- #
# dogfood: build_train_step / ServingEngine / examples
# --------------------------------------------------------------------- #


def test_build_train_step_numerics_clean():
    """The fast-path train step program (the REAL jitted function, with
    the fp16 scale threaded) carries no TPU6xx findings — the loss-scale
    division is provably guarded by the scaler's >= 1 invariant."""
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.test_utils import RegressionDataset, RegressionModel, linear_loss_fn
    from accelerate_tpu.utils.random import key_for_step

    acc = Accelerator()
    model = acc.prepare_model(RegressionModel())
    optimizer = acc.prepare_optimizer(optax.sgd(0.1))
    acc.prepare_data_loader(RegressionDataset(length=64))
    step = acc.build_train_step(linear_loss_fn)
    inner = step._jitted.__wrapped__

    grad_buf = jax.tree_util.tree_map(lambda x: jnp.zeros_like(x, dtype=jnp.float32), model.params)
    scale_state = {"scale": jnp.float32(1.0), "growth": jnp.int32(0)}
    batch = {"x": jnp.zeros((16, 1), jnp.float32), "y": jnp.zeros((16, 1), jnp.float32)}
    report = numerics_check(
        inner,
        model.params, optimizer.opt_state, grad_buf, None, batch, scale_state,
        jnp.bool_(True), key_for_step(0), jnp.float32(-1.0), {},
        mesh=acc.mesh,
    )
    assert report.n_eqns > 10
    assert report.findings == [], [f.message for f in report.findings]

    # build_eval_step's jitted program too
    eval_step = acc.build_eval_step(lambda p, b: linear_loss_fn(p, b))
    eval_report = numerics_check(
        lambda p, b: linear_loss_fn(acc._compute_cast(p), b),
        model.params, batch, mesh=acc.mesh,
    )
    assert eval_report.findings == [], [f.message for f in eval_report.findings]


def test_serving_engine_numerics_dogfood():
    from accelerate_tpu.models import LlamaConfig, create_llama_model
    from accelerate_tpu.serving import ServingEngine

    model = create_llama_model(LlamaConfig.tiny(), seq_len=16)
    eng = ServingEngine(model, num_slots=2, prompt_buckets=(8, 16))
    reports = eng.numerics_check()
    assert set(reports) == {"prefill", "decode_tick", "resume_recompute"}
    for name, rep in reports.items():
        assert rep.n_eqns > 50, name
        # the strict-gate rule and the whole tier must be clean on the
        # repo's own serving programs
        assert rep.findings == [], (name, [f.message for f in rep.findings])


def test_example_numerics_check_runs():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "numerics_example", os.path.join(REPO, "examples", "by_feature", "numerics_check.py")
    )
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    mesh = MeshConfig(data=1).build(jax.devices()[:1])
    seeded = numerics_check(m.train_step, *m.train_step_sample_args(), mesh=mesh)
    assert any(f.rule == "TPU601" for f in seeded.findings)
    fixed = numerics_check(m.fixed_step, *m.fixed_step_sample_args(), mesh=mesh)
    assert fixed.findings == []


def test_accelerator_numerics_check_surface():
    from accelerate_tpu import Accelerator

    acc = Accelerator()

    def step(x):
        return jnp.log(x)  # TPU603: operand can be <= 0

    report = acc.numerics_check(step, jax.ShapeDtypeStruct((8,), f32))
    assert "TPU603" in {f.rule for f in report.findings}
    assert report.ok  # warnings only

    clean = acc.numerics_check(step, jax.ShapeDtypeStruct((8,), f32), assume=(1.0, 10.0))
    assert clean.findings == []


# --------------------------------------------------------------------- #
# input assumption plumbing
# --------------------------------------------------------------------- #


def test_assume_per_leaf_overrides(mesh1):
    def step(x, n):
        return x / n

    # a per-leaf assume that keeps the denominator off zero: clean
    r = numerics_check(
        step,
        jax.ShapeDtypeStruct((8,), f32),
        jax.ShapeDtypeStruct((8,), f32),
        mesh=mesh1,
        assume=[(-16.0, 16.0), (1.0, 128.0)],
    )
    assert r.findings == []
    assert _out_iv(r) == (-16.0, 16.0)


def test_input_absvals_defaults(mesh1):
    from accelerate_tpu.analysis.jaxpr_lint import _trace

    def step(x, i):
        return x, i

    closed, _ = _trace(
        step, (jax.ShapeDtypeStruct((4,), f32), jax.ShapeDtypeStruct((4,), jnp.int32)), mesh1
    )
    vals = _input_absvals(closed, None, None)
    assert vals[0].iv == Interval(*DEFAULT_ASSUME) and vals[0].param_like
    assert not vals[1].iv.known  # ints carry no assumption


# --------------------------------------------------------------------- #
# CLI: selfcheck / text / json / sarif / AST tier / strict TPU602 gate
# --------------------------------------------------------------------- #

CPU_ENV = {**os.environ, "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}


def _run_cli(*args, timeout=240):
    return subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.cli", *args],
        capture_output=True, text=True, env=CPU_ENV, timeout=timeout, cwd=REPO,
    )


@pytest.mark.slow
def test_cli_numerics_check_selfcheck():
    result = _run_cli("numerics-check", "--selfcheck")
    assert result.returncode == 0, result.stderr
    for rule in ("TPU601", "TPU602", "TPU603", "TPU604", "TPU605", "TPU606"):
        assert f"{rule} fixture: detected" in result.stdout
        assert f"{rule} clean twin: zero findings" in result.stdout
    assert "interval reference" in result.stdout and "exact" in result.stdout


@pytest.mark.slow
def test_cli_numerics_check_example_text_json_sarif(tmp_path):
    target = (
        "numerics-check", "examples/by_feature/numerics_check.py::train_step", "--mesh", "data=8",
    )
    result = _run_cli(*target)
    assert result.returncode == 0, result.stderr  # TPU601 is a warning
    assert "TPU601" in result.stdout
    assert "output value intervals" in result.stdout

    js = _run_cli(*target, "--format", "json")
    assert js.returncode == 0, js.stderr
    payload = json.loads(js.stdout)
    assert payload["eqns_interpreted"] > 0
    assert any(f["rule"] == "TPU601" for f in payload["findings"])

    sarif = _run_cli(*target, "--format", "sarif")
    assert sarif.returncode == 0, sarif.stderr
    doc = json.loads(sarif.stdout)
    assert doc["version"] == "2.1.0"
    assert any(res["ruleId"] == "TPU601" for res in doc["runs"][0]["results"])


@pytest.mark.slow
def test_cli_numerics_check_strict_gate_on_tpu602(tmp_path):
    """The error-severity rule fails the CLI without --strict — the
    mechanism that promotes TPU602 into the make lint gate."""
    mod = tmp_path / "hot_softmax.py"
    mod.write_text(
        textwrap.dedent(
            '''
            """Fixture: fp16 softmax without max subtraction."""
            import jax
            import jax.numpy as jnp


            def step(x):
                e = jnp.exp(x)
                return e / jnp.sum(e, axis=-1, keepdims=True)


            def step_sample_args():
                return (jax.ShapeDtypeStruct((8, 64), jnp.float16),)
            '''
        )
    )
    result = _run_cli("numerics-check", f"{mod}::step", "--mesh", "data=1")
    assert result.returncode == 1
    assert "TPU602" in result.stdout

    # --assume narrow enough that exp cannot overflow: passes
    # (= form: argparse would read a leading -4 as an option otherwise)
    result = _run_cli("numerics-check", f"{mod}::step", "--mesh", "data=1", "--assume=-4,4")
    assert result.returncode == 0, result.stdout


@pytest.mark.slow
def test_cli_numerics_check_ast_tier(tmp_path):
    mod = tmp_path / "reuse.py"
    mod.write_text(
        textwrap.dedent(
            '''
            """Fixture: AST-tier key reuse."""
            import jax


            def draw(key):
                a = jax.random.normal(key, (4,))
                b = jax.random.uniform(key, (4,))
                return a + b
            '''
        )
    )
    result = _run_cli("numerics-check", str(mod))
    assert result.returncode == 0  # warning severity
    assert "TPU605" in result.stdout
