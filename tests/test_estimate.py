"""``accelerate-tpu estimate-memory`` internals: parameter-count parsing,
the dtype table, safetensors header counting, repo-id routing, and the
``--jaxpr`` flight-check path."""

import json
import struct

import pytest

from accelerate_tpu.commands.estimate import (
    DTYPE_BYTES,
    _repo_id_like,
    count_params_from_safetensors,
    estimate_command,
    estimate_parser,
    estimate_table,
    parse_param_count,
)


def test_parse_param_count_suffixes():
    assert parse_param_count("7B") == 7_000_000_000
    assert parse_param_count("124M") == 124_000_000
    assert parse_param_count("350K") == 350_000
    assert parse_param_count("350000") == 350_000
    assert parse_param_count(" 1.5b ") == 1_500_000_000
    assert parse_param_count("0.5M") == 500_000


def test_parse_param_count_rejects_garbage():
    with pytest.raises(ValueError):
        parse_param_count("seven billion")


def test_estimate_table_training_math():
    rows = estimate_table(1000, mesh_devices=4, training=True)
    assert len(rows) == len(DTYPE_BYTES)
    by_dtype = {r["dtype"]: r for r in rows}
    f32 = by_dtype["float32"]
    assert f32["inference_bytes"] == 4000
    # Adam: weights + fp32 grads + 2 fp32 moments
    assert f32["training_bytes"] == 4000 + 1000 * 4 * 3
    assert f32["inference_per_device"] == 1000.0
    bf16 = by_dtype["bfloat16"]
    assert bf16["inference_bytes"] == 2000
    assert bf16["training_bytes"] == 2000 + 1000 * 4 * 3


def test_estimate_table_inference_only():
    rows = estimate_table(1000, mesh_devices=2, training=False)
    assert all(r["training_bytes"] is None for r in rows)
    assert all(r["training_per_device"] is None for r in rows)


def test_repo_id_like_routing():
    assert _repo_id_like("meta-llama/Llama-3.2-1B")
    assert not _repo_id_like("7B")
    assert not _repo_id_like("weights/model.safetensors")  # path typo, not a repo
    assert not _repo_id_like("a/b/c")


def _write_safetensors(path, tensors):
    """Minimal safetensors writer: header + zero data."""
    header = {}
    offset = 0
    for name, shape in tensors.items():
        n = 1
        for d in shape:
            n *= d
        header[name] = {"dtype": "F32", "shape": list(shape), "data_offsets": [offset, offset + n * 4]}
        offset += n * 4
    blob = json.dumps(header).encode()
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(blob)))
        f.write(blob)
        f.write(b"\0" * offset)


def test_count_params_from_safetensors_file_and_dir(tmp_path):
    _write_safetensors(tmp_path / "a.safetensors", {"w": (10, 20), "b": (20,)})
    _write_safetensors(tmp_path / "b.safetensors", {"v": (5, 5)})
    assert count_params_from_safetensors(str(tmp_path / "a.safetensors")) == 220
    assert count_params_from_safetensors(str(tmp_path)) == 245
    assert count_params_from_safetensors(str(tmp_path / "nope.txt")) == 0


def test_estimate_command_param_table(capsys):
    args = estimate_parser().parse_args(["124M", "--num_devices", "4"])
    assert estimate_command(args) == 0
    out = capsys.readouterr().out
    assert "124,000,000" in out
    assert "bfloat16" in out and "fits/device" in out


def test_estimate_command_jaxpr_path(tmp_path, capsys):
    """--jaxpr upgrades the table into a per-device flight report."""
    import textwrap

    mod = tmp_path / "step_mod.py"
    mod.write_text(
        textwrap.dedent(
            '''
            """Fixture step for estimate --jaxpr."""
            import jax
            import jax.numpy as jnp


            def step(w, x):
                return (x @ w).sum()


            def step_sample_args():
                return (
                    jax.ShapeDtypeStruct((128, 64), jnp.float32),
                    jax.ShapeDtypeStruct((32, 128), jnp.float32),
                )
            '''
        )
    )
    args = estimate_parser().parse_args([f"{mod}::step", "--jaxpr", "--mesh", "data=2"])
    assert estimate_command(args) == 0
    out = capsys.readouterr().out
    assert "peak HBM / device" in out
    assert "verdict: fits" in out


def test_estimate_command_jaxpr_arg_specs(tmp_path, capsys):
    import textwrap

    mod = tmp_path / "step_mod2.py"
    mod.write_text(
        textwrap.dedent(
            '''
            """Fixture step without a sample-args convention."""


            def step(w, x):
                return (x @ w).sum()
            '''
        )
    )
    args = estimate_parser().parse_args(
        [f"{mod}::step", "--jaxpr", "--arg", "f32[128,64]", "--arg", "f32[32,128]"]
    )
    assert estimate_command(args) == 0
    assert "peak HBM / device" in capsys.readouterr().out
