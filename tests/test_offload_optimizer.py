"""Host-memory offload of optimizer state (ZeRO-offload / FSDP cpu-offload
analogue; reference: utils/dataclasses.py:1100-1180 offload_optimizer_device,
accelerator.py:1694-1750 cpu_offload wiring).

``ParallelismPlugin(offload_optimizer=True)``: optimizer moments live on
``pinned_host`` memory-kind shardings; the jitted step pulls them through
HBM (in-jit, overlap-schedulable) and the updated state streams back after
the step. These tests pin three properties on the 8-device CPU fake mesh:

* residence — array leaves persistently live in ``pinned_host`` memory,
  scalar leaves (adam's count) stay in device memory (XLA rejects host
  placement on scalars);
* exactness — identical losses and parameters vs the non-offloaded step,
  in every composition (ZeRO, fp16, grad accumulation, fsdp mesh,
  imperative path);
* round-trips — checkpoint save/load preserves values and host residence.
"""

import numpy as np
import optax
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from accelerate_tpu import Accelerator  # noqa: E402
from accelerate_tpu.modeling import Model  # noqa: E402
from accelerate_tpu.utils.compat import supports_memory_kind  # noqa: E402
from accelerate_tpu.utils.dataclasses import MeshConfig, ParallelismPlugin  # noqa: E402

# offload is a memory-kind feature: without pinned_host (old CPU backends)
# the Accelerator degrades to in-device state and residence can't be tested
pytestmark = pytest.mark.skipif(
    not supports_memory_kind("pinned_host"),
    reason="backend has no pinned_host memory kind",
)


def mlp_apply(params, x):
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    return h @ params["w2"] + params["b2"]


def make_model(seed=0):
    # w1 is 16*256 = 4096 elements: exactly fsdp_rules_for's min_size, so
    # the fsdp composition actually shards at least one moment leaf
    r = np.random.default_rng(seed)
    params = {
        "w1": r.normal(0, 0.1, (16, 256)).astype(np.float32),
        "b1": np.zeros(256, np.float32),
        "w2": r.normal(0, 0.1, (256, 4)).astype(np.float32),
        "b2": np.zeros(4, np.float32),
    }
    return Model(mlp_apply, params, name="mlp")


def loss_fn(p, b):
    return jnp.mean((mlp_apply(p, b["x"]) - b["y"]) ** 2)


def batches(n=6, bs=16, seed=1):
    r = np.random.default_rng(seed)
    return [
        {"x": r.normal(0, 1, (bs, 16)).astype(np.float32), "y": r.normal(0, 1, (bs, 4)).astype(np.float32)}
        for _ in range(n)
    ]


def make_acc(offload, zero=False, mp="no", accum=1, fsdp=False):
    mc = MeshConfig(data=4, fsdp=2) if fsdp else MeshConfig(data=8)
    return Accelerator(
        parallelism_plugin=ParallelismPlugin(
            mesh_config=mc, offload_optimizer=offload, shard_optimizer_state=zero
        ),
        mixed_precision=mp,
        gradient_accumulation_steps=accum,
    )


def train(acc, n=6):
    model = acc.prepare_model(make_model())
    opt = acc.prepare_optimizer(optax.adam(0.01))
    step = acc.build_train_step(loss_fn)
    losses = [float(step(b)) for b in batches(n)]
    return model, opt, losses


def state_kinds(opt):
    return sorted({(l.ndim, l.sharding.memory_kind) for l in jax.tree_util.tree_leaves(opt.opt_state)})


def test_state_lives_on_pinned_host():
    acc = make_acc(offload=True)
    model, opt, losses = train(acc)
    kinds = state_kinds(opt)
    assert (2, "pinned_host") in kinds and (1, "pinned_host") in kinds, kinds
    assert (0, "device") in kinds  # adam count stays in device memory
    # residence persists across steps (the push restores the host home)
    assert all(np.isfinite(losses))


def test_loss_and_param_parity_with_dense_state():
    accel_states = []
    for offload in (False, True):
        acc = make_acc(offload)
        accel_states.append(train(acc))
        from accelerate_tpu.state import AcceleratorState, PartialState

        AcceleratorState._reset_state()
        PartialState._reset_state()
    (m0, _, l0), (m1, _, l1) = accel_states
    np.testing.assert_allclose(l0, l1, rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(m0.params), jax.tree_util.tree_leaves(m1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


@pytest.mark.parametrize(
    "kwargs",
    [
        {"zero": True},  # ZeRO-1/2 data-axis layout kept on the host copy
        {"mp": "fp16"},  # fp16 finite-gate cond path
        {"accum": 2},  # apply under the outer sync cond
        {"fsdp": True},  # sharded params -> moments inherit fsdp layout
    ],
    ids=["zero", "fp16", "accum2", "fsdp"],
)
def test_offload_compositions_run_and_reside(kwargs):
    acc = make_acc(True, **kwargs)
    model, opt, losses = train(acc)
    assert all(np.isfinite(losses))
    assert (2, "pinned_host") in state_kinds(opt)
    if kwargs.get("zero") or kwargs.get("fsdp"):
        # at least one moment leaf actually sharded over the mesh
        sharded = [
            l
            for l in jax.tree_util.tree_leaves(opt.opt_state)
            if l.ndim >= 1 and l.sharding.memory_kind == "pinned_host" and not l.sharding.is_fully_replicated
        ]
        assert sharded


def test_imperative_path_parity():
    """backward/step (reference idiom) matches the fast path with offload."""
    acc = make_acc(True)
    model = acc.prepare_model(make_model())
    opt = acc.prepare_optimizer(optax.adam(0.01))
    for b in batches(4):
        loss = acc.backward_loss(loss_fn, b) if hasattr(acc, "backward_loss") else None
        if loss is None:
            acc.backward(loss_fn, b)
        opt.step()
        opt.zero_grad()
    assert (2, "pinned_host") in state_kinds(opt)

    from accelerate_tpu.state import AcceleratorState, PartialState

    AcceleratorState._reset_state()
    PartialState._reset_state()
    acc2 = make_acc(False)
    model2 = acc2.prepare_model(make_model())
    opt2 = acc2.prepare_optimizer(optax.adam(0.01))
    for b in batches(4):
        if hasattr(acc2, "backward_loss"):
            acc2.backward_loss(loss_fn, b)
        else:
            acc2.backward(loss_fn, b)
        opt2.step()
        opt2.zero_grad()
    for a, b_ in zip(jax.tree_util.tree_leaves(model.params), jax.tree_util.tree_leaves(model2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-6)


def test_checkpoint_roundtrip_preserves_host_residence(tmp_path):
    acc = make_acc(True)
    model, opt, _ = train(acc, n=3)
    ref_leaves = [np.asarray(jax.device_get(l)) for l in jax.tree_util.tree_leaves(opt.opt_state)]
    acc.save_state(str(tmp_path / "ckpt"))
    # perturb, then restore
    opt.opt_state = jax.tree_util.tree_map(lambda l: l * 0, opt.opt_state)
    acc.load_state(str(tmp_path / "ckpt"))
    for ref, got in zip(ref_leaves, jax.tree_util.tree_leaves(opt.opt_state)):
        np.testing.assert_allclose(ref, np.asarray(jax.device_get(got)), rtol=1e-7)
    assert (2, "pinned_host") in state_kinds(opt)
