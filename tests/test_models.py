"""Model zoo tests: forward shapes, sharded training step on hybrid meshes,
attention-kernel equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, MeshConfig, ParallelismPlugin
from accelerate_tpu.models import (
    BertConfig,
    LlamaConfig,
    bert_classification_loss,
    causal_lm_loss,
    create_bert_model,
    create_llama_model,
)


def test_bert_forward_shape():
    model = create_bert_model(BertConfig.tiny(), seq_len=16)
    ids = jnp.zeros((2, 16), jnp.int32)
    mask = jnp.ones((2, 16), jnp.bool_)
    logits = model(ids, mask)
    assert logits.shape == (2, 2)


def test_bert_train_step_tp_mesh():
    acc = Accelerator(
        mixed_precision="bf16",
        parallelism_plugin=ParallelismPlugin(mesh_config=MeshConfig(data=2, tensor=4)),
    )
    model = acc.prepare_model(create_bert_model(BertConfig.tiny(), seq_len=16))
    # TP rules actually applied: query kernel sharded over tensor axis
    from jax.sharding import PartitionSpec as P

    q_sharding = model.params["encoder"]["layer_0"]["attention"]["query"]["kernel"].sharding
    assert q_sharding.spec == P(None, "tensor")
    optimizer = acc.prepare_optimizer(optax.adamw(1e-3))
    step = acc.build_train_step(lambda p, b: bert_classification_loss(p, b, model.apply_fn))
    batch = {
        "input_ids": jnp.zeros((8, 16), jnp.int32),
        "attention_mask": jnp.ones((8, 16), jnp.bool_),
        "labels": jnp.zeros((8,), jnp.int32),
    }
    from accelerate_tpu.parallel.mesh import batch_sharding

    batch = jax.device_put(batch, batch_sharding(acc.mesh))
    loss1 = step(batch)
    loss2 = step(batch)
    assert float(loss2) < float(loss1)  # it learns


def test_llama_forward_and_loss():
    model = create_llama_model(LlamaConfig.tiny(), seq_len=32)
    ids = jnp.ones((2, 32), jnp.int32)
    logits = model(ids)
    assert logits.shape == (2, 32, 256)
    loss = causal_lm_loss(model.params, {"input_ids": ids}, model.apply_fn)
    assert jnp.isfinite(loss)


def test_llama_train_step_4d_mesh():
    """dp x fsdp x seq x tensor hybrid — the full Megatron-style layout."""
    acc = Accelerator(
        mixed_precision="bf16",
        parallelism_plugin=ParallelismPlugin(mesh_config=MeshConfig(data=1, fsdp=2, seq=2, tensor=2)),
    )
    model = acc.prepare_model(create_llama_model(LlamaConfig.tiny(), seq_len=32))
    optimizer = acc.prepare_optimizer(optax.adamw(1e-3))
    step = acc.build_train_step(lambda p, b: causal_lm_loss(p, b, model.apply_fn))
    from accelerate_tpu.parallel.mesh import batch_sharding

    batch = jax.device_put({"input_ids": jnp.ones((4, 32), jnp.int32)}, batch_sharding(acc.mesh))
    loss = step(batch)
    assert jnp.isfinite(loss)


def test_llama_scan_vs_loop_equivalence():
    cfg_scan = LlamaConfig.tiny(scan_layers=True, remat=False)
    cfg_loop = LlamaConfig.tiny(scan_layers=False, remat=False)
    m_scan = create_llama_model(cfg_scan, seed=0, seq_len=16)
    m_loop = create_llama_model(cfg_loop, seed=0, seq_len=16)
    # same per-layer param count
    total = lambda m: sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(m.params))
    assert total(m_scan) == total(m_loop)


def test_flash_attention_matches_reference():
    from accelerate_tpu.ops.attention import dot_product_attention
    from accelerate_tpu.ops.flash_attention import flash_attention

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(2, 64, 4, 16)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(2, 64, 4, 16)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(2, 64, 4, 16)), jnp.float32)
    ref = dot_product_attention(q, k, v, causal=True, use_flash=False)
    out = flash_attention(q, k, v, causal=True, block_size=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_flash_attention_gqa_and_grad():
    from accelerate_tpu.ops.attention import dot_product_attention
    from accelerate_tpu.ops.flash_attention import flash_attention

    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.normal(size=(1, 48, 8, 16)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 48, 2, 16)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(1, 48, 2, 16)), jnp.float32)

    ref_fn = lambda q: dot_product_attention(q, k, v, causal=True, use_flash=False).sum()
    fl_fn = lambda q: flash_attention(q, k, v, causal=True, block_size=16).sum()
    np.testing.assert_allclose(
        np.asarray(jax.grad(fl_fn)(q)), np.asarray(jax.grad(ref_fn)(q)), atol=2e-4, rtol=2e-4
    )


def test_flash_attention_uneven_blocks():
    from accelerate_tpu.ops.attention import dot_product_attention
    from accelerate_tpu.ops.flash_attention import flash_attention

    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.normal(size=(1, 50, 2, 8)), jnp.float32)  # 50 % 16 != 0
    k = jnp.asarray(rng.normal(size=(1, 50, 2, 8)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(1, 50, 2, 8)), jnp.float32)
    ref = dot_product_attention(q, k, v, use_flash=False)
    out = flash_attention(q, k, v, block_size=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_llama_scan_tp_rules_apply():
    """Regression: stacked (scan) params must get the Megatron column/row
    splits on the right dims — not the layer-scan dim."""
    from jax.sharding import PartitionSpec as P

    acc = Accelerator(parallelism_plugin=ParallelismPlugin(mesh_config=MeshConfig(data=4, tensor=2)))
    model = acc.prepare_model(create_llama_model(LlamaConfig.tiny(), seq_len=16))
    blk = model.params["layers"]["block"]
    assert blk["attn"]["q_proj"]["kernel"].sharding.spec == P(None, None, "tensor")
    assert blk["attn"]["o_proj"]["kernel"].sharding.spec == P(None, "tensor")
    assert blk["mlp"]["down_proj"]["kernel"].sharding.spec == P(None, "tensor")


def test_causal_lm_loss_masks_final_position():
    """Auto-derived labels must not train the last position against id 0."""
    model = create_llama_model(LlamaConfig.tiny(), seq_len=8)
    ids = jnp.ones((2, 8), jnp.int32)

    def logits_probe(params, batch, apply_fn):
        return causal_lm_loss(params, batch, apply_fn)

    base = float(causal_lm_loss(model.params, {"input_ids": ids}, model.apply_fn))
    # explicit labels + mask replicating the auto behavior must match
    labels = jnp.pad(ids[:, 1:], ((0, 0), (0, 1)))
    mask = jnp.ones((2, 8)).at[:, -1].set(0.0)
    explicit = float(
        causal_lm_loss(model.params, {"input_ids": ids, "labels": labels, "loss_mask": mask}, model.apply_fn)
    )
    np.testing.assert_allclose(base, explicit, rtol=1e-6)


def test_hf_bert_weight_import(tmp_path):
    """Synthetic HF-named checkpoint -> our pytree (transposes + renames)."""
    from accelerate_tpu.models.hub import convert_hf_bert_state, load_hf_bert
    from safetensors.numpy import save_file

    cfg = BertConfig.tiny()
    h, ffn, vocab = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    rng = np.random.default_rng(0)
    state = {
        "bert.embeddings.word_embeddings.weight": rng.normal(size=(vocab, h)).astype(np.float32),
        "bert.embeddings.position_embeddings.weight": rng.normal(size=(cfg.max_position_embeddings, h)).astype(np.float32),
        "bert.embeddings.token_type_embeddings.weight": rng.normal(size=(2, h)).astype(np.float32),
        "bert.embeddings.LayerNorm.weight": np.ones(h, np.float32),
        "bert.embeddings.LayerNorm.bias": np.zeros(h, np.float32),
        "bert.pooler.dense.weight": rng.normal(size=(h, h)).astype(np.float32),
        "bert.pooler.dense.bias": np.zeros(h, np.float32),
        "classifier.weight": rng.normal(size=(2, h)).astype(np.float32),
        "classifier.bias": np.zeros(2, np.float32),
    }
    for i in range(cfg.num_hidden_layers):
        p = f"bert.encoder.layer.{i}."
        state.update({
            p + "attention.self.query.weight": rng.normal(size=(h, h)).astype(np.float32),
            p + "attention.self.query.bias": np.zeros(h, np.float32),
            p + "attention.self.key.weight": rng.normal(size=(h, h)).astype(np.float32),
            p + "attention.self.key.bias": np.zeros(h, np.float32),
            p + "attention.self.value.weight": rng.normal(size=(h, h)).astype(np.float32),
            p + "attention.self.value.bias": np.zeros(h, np.float32),
            p + "attention.output.dense.weight": rng.normal(size=(h, h)).astype(np.float32),
            p + "attention.output.dense.bias": np.zeros(h, np.float32),
            p + "attention.output.LayerNorm.weight": np.ones(h, np.float32),
            p + "attention.output.LayerNorm.bias": np.zeros(h, np.float32),
            p + "intermediate.dense.weight": rng.normal(size=(ffn, h)).astype(np.float32),
            p + "intermediate.dense.bias": np.zeros(ffn, np.float32),
            p + "output.dense.weight": rng.normal(size=(h, ffn)).astype(np.float32),
            p + "output.dense.bias": np.zeros(h, np.float32),
            p + "output.LayerNorm.weight": np.ones(h, np.float32),
            p + "output.LayerNorm.bias": np.zeros(h, np.float32),
        })
    save_file(state, str(tmp_path / "model.safetensors"))
    model = load_hf_bert(str(tmp_path / "model.safetensors"), config=cfg)
    # transposition check: our kernel == HF weight.T
    got = np.asarray(model.params["encoder"]["layer_0"]["attention"]["query"]["kernel"])
    np.testing.assert_allclose(got, state["bert.encoder.layer.0.attention.self.query.weight"].T)
    assert model.imported_weight_count == len(state)
    # model runs with imported weights
    logits = model(jnp.zeros((2, 16), jnp.int32), jnp.ones((2, 16), jnp.bool_))
    assert logits.shape == (2, 2)


def test_hf_llama_weight_import_scan_stacking(tmp_path):
    from accelerate_tpu.models.hub import convert_hf_llama_state

    cfg = LlamaConfig.tiny()
    h, kv = cfg.hidden_size, cfg.num_key_value_heads * (cfg.hidden_size // cfg.num_attention_heads)
    rng = np.random.default_rng(1)
    state = {"model.embed_tokens.weight": rng.normal(size=(cfg.vocab_size, h)).astype(np.float32),
             "model.norm.weight": np.ones(h, np.float32)}
    for i in range(cfg.num_hidden_layers):
        p = f"model.layers.{i}."
        state.update({
            p + "self_attn.q_proj.weight": rng.normal(size=(h, h)).astype(np.float32),
            p + "self_attn.k_proj.weight": rng.normal(size=(kv, h)).astype(np.float32),
            p + "self_attn.v_proj.weight": rng.normal(size=(kv, h)).astype(np.float32),
            p + "self_attn.o_proj.weight": rng.normal(size=(h, h)).astype(np.float32),
            p + "mlp.gate_proj.weight": rng.normal(size=(cfg.intermediate_size, h)).astype(np.float32),
            p + "mlp.up_proj.weight": rng.normal(size=(cfg.intermediate_size, h)).astype(np.float32),
            p + "mlp.down_proj.weight": rng.normal(size=(h, cfg.intermediate_size)).astype(np.float32),
            p + "input_layernorm.weight": np.ones(h, np.float32),
            p + "post_attention_layernorm.weight": np.ones(h, np.float32),
        })
    tree = convert_hf_llama_state(
        state,
        scan_layers=True,
        num_heads=cfg.num_attention_heads,
        num_kv_heads=cfg.num_key_value_heads,
    )
    # stacked with leading layer dim, transposed
    assert tree["layers"]["block"]["attn"]["q_proj"]["kernel"].shape == (cfg.num_hidden_layers, h, h)
    # v is untouched; q/k are re-paired for the interleaved rope convention
    from accelerate_tpu.models.hub import _rope_interleave_permute

    np.testing.assert_allclose(
        tree["layers"]["block"]["attn"]["v_proj"]["kernel"][1],
        state["model.layers.1.self_attn.v_proj.weight"].T,
    )
    np.testing.assert_allclose(
        tree["layers"]["block"]["attn"]["q_proj"]["kernel"][1],
        _rope_interleave_permute(
            state["model.layers.1.self_attn.q_proj.weight"].T, h // cfg.num_attention_heads
        ),
    )
    # tied lm_head fallback
    np.testing.assert_allclose(tree["lm_head"]["kernel"], state["model.embed_tokens.weight"].T)


def test_bert_dropout_trains_differently():
    """Dropout actually fires when an rng is supplied."""
    model = create_bert_model(BertConfig.tiny(), seq_len=16)
    batch = {
        "input_ids": jnp.zeros((4, 16), jnp.int32),
        "attention_mask": jnp.ones((4, 16), jnp.bool_),
        "labels": jnp.zeros((4,), jnp.int32),
    }
    det = bert_classification_loss(model.params, batch, model.apply_fn)
    drop1 = bert_classification_loss(model.params, batch, model.apply_fn, rng=jax.random.key(1))
    drop2 = bert_classification_loss(model.params, batch, model.apply_fn, rng=jax.random.key(2))
    assert float(det) != float(drop1) or float(det) != float(drop2)


def test_attention_mask_with_explicit_flash_raises():
    from accelerate_tpu.ops.attention import dot_product_attention

    q = jnp.ones((1, 8, 2, 4))
    with pytest.raises(ValueError):
        dot_product_attention(q, q, q, mask=jnp.ones((1, 1, 8, 8), bool), use_flash=True)


def test_causal_alignment_decode_shape():
    """Sq < Sk causal attention is bottom-right aligned in both paths."""
    from accelerate_tpu.ops.attention import dot_product_attention
    from accelerate_tpu.ops.flash_attention import flash_attention

    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.normal(size=(1, 4, 2, 8)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 32, 2, 8)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(1, 32, 2, 8)), jnp.float32)
    ref = dot_product_attention(q, k, v, causal=True, use_flash=False)
    out = flash_attention(q, k, v, causal=True, block_size=8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)
    # bottom-right alignment: the LAST query sees every key, so its causal
    # output equals unmasked attention of that single query
    unmasked_last = dot_product_attention(q[:, -1:], k, v, causal=False, use_flash=False)
    np.testing.assert_allclose(np.asarray(ref[:, -1:]), np.asarray(unmasked_last), atol=2e-5, rtol=2e-5)


# ---------------------------------------------------------------------- #
# ResNet (CV model; reference: examples/cv_example.py trains ResNet-50)
# ---------------------------------------------------------------------- #


def test_resnet_forward_shape():
    from accelerate_tpu.models import ResNetConfig, create_resnet_model

    model = create_resnet_model(ResNetConfig.tiny(), image_size=32)
    logits = model.eval()(jnp.zeros((2, 32, 32, 3), jnp.float32))
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32
    assert "batch_stats" in model.state


def test_resnet_has_state_train_step_updates_bn():
    """build_train_step(has_state=True) threads BatchNorm running stats:
    they must change across steps, gradient-free, and the loss must drop."""
    from accelerate_tpu.models import ResNetConfig, create_resnet_model, resnet_classification_loss
    from accelerate_tpu.parallel.mesh import batch_sharding

    acc = Accelerator(mixed_precision="bf16")
    model = acc.prepare_model(create_resnet_model(ResNetConfig.tiny(), image_size=16))
    acc.prepare_optimizer(optax.sgd(0.1, momentum=0.9))
    step = acc.build_train_step(
        lambda p, s, b: resnet_classification_loss(p, s, b, model.apply_fn), has_state=True
    )
    rng = np.random.default_rng(0)
    batch = {
        "images": rng.normal(size=(16, 16, 16, 3)).astype(np.float32),
        "labels": rng.integers(0, 10, size=(16,)).astype(np.int32),
    }
    batch = jax.device_put(batch, batch_sharding(acc.mesh))
    stats_before = np.array(jax.tree_util.tree_leaves(model.state)[0])
    losses = [float(step(batch)) for _ in range(5)]
    stats_after = np.array(jax.tree_util.tree_leaves(model.state)[0])
    assert losses[-1] < losses[0], losses
    assert not np.allclose(stats_before, stats_after)
    # eval path consumes the running stats without mutating them
    logits = model.eval()(batch["images"])
    assert logits.shape == (16, 10)


def test_resnet_tp_sharding_rules_apply():
    from jax.sharding import PartitionSpec as P

    from accelerate_tpu.models import ResNetConfig, create_resnet_model

    acc = Accelerator(
        parallelism_plugin=ParallelismPlugin(mesh_config=MeshConfig(data=2, tensor=4)),
    )
    # num_classes divisible by the tensor axis so the head split survives
    # _prune_spec's divisibility check
    model = acc.prepare_model(create_resnet_model(ResNetConfig.tiny(num_classes=12), image_size=16))
    head = model.params["head"]["kernel"]
    assert head.sharding.spec == P(None, "tensor")
    conv = model.params["conv_init"]["kernel"]
    assert conv.sharding.spec == P(None, None, None, "tensor")


# ---------------------------------------------------------------------- #
# ViT (transformer CV model)
# ---------------------------------------------------------------------- #


def test_vit_forward_and_train_step():
    from accelerate_tpu.models import ViTConfig, create_vit_model, vit_classification_loss
    from accelerate_tpu.parallel.mesh import batch_sharding

    acc = Accelerator(mixed_precision="bf16")
    model = acc.prepare_model(create_vit_model(ViTConfig.tiny()))
    acc.prepare_optimizer(optax.adamw(1e-3))
    step = acc.build_train_step(lambda p, b: vit_classification_loss(p, b, model.apply_fn))
    rng = np.random.default_rng(0)
    batch = {
        "images": rng.normal(size=(16, 32, 32, 3)).astype(np.float32),
        "labels": rng.integers(0, 10, size=(16,)).astype(np.int32),
    }
    batch = jax.device_put(batch, batch_sharding(acc.mesh))
    losses = [float(step(batch)) for _ in range(5)]
    assert losses[-1] < losses[0], losses
    eval_step = acc.build_eval_step(lambda p, x: model.apply_fn(p, x))
    logits = eval_step(batch["images"])
    assert logits.shape == (16, 10) and str(logits.dtype) == "float32"


def test_vit_tp_rules_apply():
    from jax.sharding import PartitionSpec as P

    from accelerate_tpu.models import ViTConfig, create_vit_model

    acc = Accelerator(
        parallelism_plugin=ParallelismPlugin(mesh_config=MeshConfig(data=2, tensor=4)),
    )
    model = acc.prepare_model(create_vit_model(ViTConfig.tiny()))
    q = model.params["block_0"]["attention/query"]["kernel"]
    assert q.sharding.spec == P(None, "tensor")
    up = model.params["block_0"]["mlp/up"]["kernel"]
    assert up.sharding.spec == P(None, "tensor")


def test_gptneox_tp_sharding_applies():
    """NeoX TP rules put attention/MLP kernels on the tensor axis."""
    from accelerate_tpu import Accelerator
    from accelerate_tpu.models import GPTNeoXConfig, create_gptneox_model
    from accelerate_tpu.utils.dataclasses import MeshConfig, ParallelismPlugin

    acc = Accelerator(parallelism_plugin=ParallelismPlugin(mesh_config=MeshConfig(data=4, tensor=2)))
    model = acc.prepare_model(create_gptneox_model(GPTNeoXConfig.tiny(), seq_len=8))
    spec = model.param_shardings["layer_0"]["attn"]["q_proj"]["kernel"].spec
    assert "tensor" in str(spec), spec
    out = model(np.zeros((2, 8), np.int32))
    assert out.shape == (2, 8, 256)


def test_whisper_forward_and_train_step():
    """Whisper family: conv frontend + enc-dec transformer trains through
    the standard prepare/build_train_step path with the seq2seq loss."""
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.models import WhisperConfig, create_whisper_model
    from accelerate_tpu.models.t5 import seq2seq_lm_loss

    acc = Accelerator()
    model = acc.prepare_model(create_whisper_model(seed=0))
    acc.prepare_optimizer(optax.adamw(3e-3))
    step = acc.build_train_step(lambda p, b: seq2seq_lm_loss(p, b, model.apply_fn))

    rng = np.random.default_rng(0)
    batch = {
        "input_ids": rng.standard_normal((8, 16, 8)).astype(np.float32),  # log-mels
        "labels": rng.integers(0, 250, size=(8, 6)).astype(np.int32),
    }
    losses = [float(step(batch)) for _ in range(10)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses
