"""PartialState / AcceleratorState / GradientState unit tests
(reference analogue: tests/test_state_checkpointing.py + test_utils/scripts/
test_script.py process-control sections)."""

import pytest

from accelerate_tpu import DistributedType, MeshConfig, ParallelismPlugin
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState


def test_partial_state_singleton():
    a = PartialState()
    b = PartialState()
    assert a.__dict__ is b.__dict__
    assert a.num_devices == 8
    assert a.is_main_process
    assert a.is_last_process  # single process
    assert a.process_index == 0


def test_wait_for_everyone_single_process():
    PartialState().wait_for_everyone()  # no-op, must not raise


def test_split_between_processes_single():
    with PartialState().split_between_processes([1, 2, 3]) as chunk:
        assert chunk == [1, 2, 3]


def test_split_between_processes_padding_matrix():
    """Reference state.py:417-506 semantics across faked ranks: uneven list
    split, tensor inputs padded AS TENSORS with the last row, dict values
    padded per-key."""
    import numpy as np

    state = PartialState()
    state.num_processes_host = 4
    arr = np.arange(10 * 3, dtype=np.float32).reshape(10, 3)
    for rank, expect_rows in ((0, 3), (1, 3), (2, 2), (3, 2)):
        state.process_index_host = rank
        # list without padding: uneven split, first `remainder` ranks get +1
        with state.split_between_processes(list(range(10))) as chunk:
            assert len(chunk) == expect_rows, (rank, chunk)
        # tensor with padding: equal rows everywhere, pad = repeated last row
        with state.split_between_processes(arr, apply_padding=True) as chunk:
            assert isinstance(chunk, np.ndarray), type(chunk)
            assert chunk.shape == (3, 3), (rank, chunk.shape)
            if expect_rows == 2:
                np.testing.assert_array_equal(chunk[-1], arr[-1])
        # dict of tensors with padding
        with state.split_between_processes({"x": arr.copy()}, apply_padding=True) as chunk:
            assert chunk["x"].shape == (3, 3)
    # degenerate: fewer items than processes
    state.process_index_host = 3
    with state.split_between_processes([7, 8], apply_padding=True) as chunk:
        assert chunk == [8], chunk  # empty slice padded with the last item


def test_on_main_process_decorator():
    state = PartialState()
    calls = []
    state.on_main_process(lambda: calls.append(1))()
    assert calls == [1]


def test_accelerator_state_mesh_default_dp():
    state = AcceleratorState()
    assert dict(state.mesh.shape)["data"] == 8
    assert state.distributed_type == DistributedType.DATA_PARALLEL


def test_accelerator_state_hybrid_mesh():
    plugin = ParallelismPlugin(mesh_config=MeshConfig(data=2, fsdp=2, tensor=2))
    state = AcceleratorState(parallelism_plugin=plugin)
    shape = dict(state.mesh.shape)
    assert (shape["data"], shape["fsdp"], shape["tensor"]) == (2, 2, 2)
    assert state.distributed_type == DistributedType.HYBRID


def test_accelerator_state_mixed_precision():
    state = AcceleratorState(mixed_precision="bf16")
    assert state.mixed_precision == "bf16"
    assert state.dtype_policy.compute_dtype == "bfloat16"
    assert state.dtype_policy.param_dtype == "float32"


def test_gradient_state_defaults():
    gs = GradientState()
    assert gs.sync_gradients
    assert gs.num_steps == 1
    assert not gs.end_of_dataloader
    assert gs.remainder == -1


def test_mesh_config_fill_and_errors():
    assert MeshConfig(data=-1, tensor=2).sizes(8) == {
        "pipe": 1, "data": 4, "fsdp": 1, "expert": 1, "seq": 1, "tensor": 2,
    }
    with pytest.raises(ValueError):
        MeshConfig(data=3).sizes(8)
    with pytest.raises(ValueError):
        MeshConfig(data=-1, fsdp=-1).sizes(8)


def test_mesh_config_from_env(monkeypatch):
    monkeypatch.setenv("ACCELERATE_MESH_TENSOR", "4")
    monkeypatch.setenv("ACCELERATE_MESH_DATA", "2")
    cfg = MeshConfig.from_env()
    assert cfg.tensor == 4 and cfg.data == 2
