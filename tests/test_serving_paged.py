"""Paged KV-cache serving (ops/paged_kv.py + ServingEngine paged mode):
token-exact parity with the dense engine and with static generate(),
block-pool accounting, admission control under a tight pool, and shared
prefix blocks. The tiny llama fixture is GQA (4 heads / 2 KV heads), so
the grouped paged-attention branch runs in every test here."""

import numpy as np
import pytest

from accelerate_tpu.generation import generate
from accelerate_tpu.models import LlamaConfig, create_llama_model
from accelerate_tpu.ops.paged_kv import BlockAllocator
from accelerate_tpu.serving import ServingEngine


@pytest.fixture(scope="module")
def tiny_llama():
    return create_llama_model(LlamaConfig.tiny(), seq_len=16)


def _reference(model, prompt, n):
    return np.asarray(generate(model, np.asarray(prompt, np.int32)[None], max_new_tokens=n))[0]


def test_paged_matches_generate_mixed_lengths(tiny_llama):
    """8 mixed-length prompts through 2 slots with a 4-row block pool:
    every output equals static generate(), and every block returns to the
    free list after the queue drains."""
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 250, size=n).astype(np.int32) for n in (3, 8, 5, 12, 2, 7, 9, 4)]
    eng = ServingEngine(tiny_llama, num_slots=2, prompt_buckets=(4, 8, 16), paged_block_size=4)
    free0 = eng.pool_free_blocks
    outs = eng.generate_many(prompts, max_new_tokens=5)
    for prompt, got in zip(prompts, outs):
        np.testing.assert_array_equal(got, _reference(tiny_llama, prompt, 5))
    assert eng.pool_free_blocks == free0


def test_paged_matches_dense_engine(tiny_llama):
    """The paged tick (one batched program) and the dense tick (vmapped
    per-row programs) emit identical tokens — the layouts are
    numerically interchangeable, not just both-plausible."""
    rng = np.random.default_rng(3)
    prompts = [rng.integers(1, 250, size=n).astype(np.int32) for n in (6, 11, 2, 9)]
    dense = ServingEngine(tiny_llama, num_slots=2, prompt_buckets=(4, 8, 16))
    paged = ServingEngine(tiny_llama, num_slots=2, prompt_buckets=(4, 8, 16), paged_block_size=8)
    for d, p in zip(dense.generate_many(prompts, 6), paged.generate_many(prompts, 6)):
        np.testing.assert_array_equal(d, p)


def test_tight_pool_admission_control(tiny_llama):
    """A pool too small for all slots at once serializes admission
    instead of corrupting: 4 slots but only ~1 request's worth of
    blocks — outputs stay exact and the pool drains back."""
    rng = np.random.default_rng(1)
    prompts = [rng.integers(1, 250, size=n).astype(np.int32) for n in (3, 8, 5, 12)]
    eng = ServingEngine(
        tiny_llama, num_slots=4, prompt_buckets=(4, 8, 16), paged_block_size=4, pool_blocks=8
    )
    outs = eng.generate_many(prompts, max_new_tokens=5)
    for prompt, got in zip(prompts, outs):
        np.testing.assert_array_equal(got, _reference(tiny_llama, prompt, 5))
    assert eng.pool_free_blocks == 7  # block 0 is the trash sink


def test_pool_capacity_win_vs_dense(tiny_llama):
    """The point of paging: pool bytes are set by tokens in flight, not
    slots x max_len. 8 slots x max_len=128 dense rows would need 8*32
    4-row blocks; a 24-block pool (~1/10th) still serves 8 slots."""
    rng = np.random.default_rng(2)
    prompts = [rng.integers(1, 250, size=5).astype(np.int32) for _ in range(8)]
    eng = ServingEngine(
        tiny_llama, num_slots=8, prompt_buckets=(8,), paged_block_size=4, pool_blocks=24
    )
    dense_equivalent_blocks = 8 * (128 // 4)
    assert eng._pcfg.num_blocks < dense_equivalent_blocks // 10
    outs = eng.generate_many(prompts, max_new_tokens=4)
    for prompt, got in zip(prompts, outs):
        np.testing.assert_array_equal(got, _reference(tiny_llama, prompt, 4))


def test_midstream_submit(tiny_llama):
    eng = ServingEngine(tiny_llama, num_slots=2, prompt_buckets=(8,), paged_block_size=4)
    a = eng.submit(np.arange(1, 7, dtype=np.int32), max_new_tokens=8)
    eng.step()
    b = eng.submit(np.arange(20, 25, dtype=np.int32), max_new_tokens=4)
    eng.run()
    np.testing.assert_array_equal(eng.poll(a), _reference(tiny_llama, np.arange(1, 7), 8))
    np.testing.assert_array_equal(eng.poll(b), _reference(tiny_llama, np.arange(20, 25), 4))


def test_shared_prefix_blocks(tiny_llama):
    """Requests sharing a registered prefix alias its FULL blocks
    (refcounted) instead of re-allocating; outputs equal full-prompt
    generate(); unregister returns the shared blocks."""
    prefix = (np.arange(9) % 250 + 3).astype(np.int32)  # 2 full 4-blocks + 1 tail row
    eng = ServingEngine(tiny_llama, num_slots=2, prompt_buckets=(4, 8), paged_block_size=4)
    pid = eng.register_prefix(prefix)
    held = eng.pool_free_blocks
    a = eng.submit(np.asarray([5, 6], np.int32), max_new_tokens=4, prefix_id=pid)
    b = eng.submit(np.asarray([9], np.int32), max_new_tokens=4, prefix_id=pid)
    eng.run()
    for uid, sfx in ((a, [5, 6]), (b, [9])):
        full = np.concatenate([prefix, np.asarray(sfx, np.int32)])
        np.testing.assert_array_equal(eng.poll(uid), _reference(tiny_llama, full, 4))
    assert eng.pool_free_blocks == held  # per-request blocks freed, prefix still held
    eng.unregister_prefix(pid)
    assert eng.pool_free_blocks == held + 2  # the 2 shared full blocks came back


def test_paged_validation(tiny_llama):
    with pytest.raises(ValueError, match="paged_block_size"):
        ServingEngine(tiny_llama, pool_blocks=8)
    with pytest.raises(ValueError, match="paged_block_size"):
        ServingEngine(tiny_llama, paged_block_size=0)
    eng = ServingEngine(
        tiny_llama, num_slots=1, prompt_buckets=(8,), paged_block_size=4, pool_blocks=4
    )
    with pytest.raises(ValueError, match="pool blocks"):
        eng.submit(np.ones((8,), np.int32), max_new_tokens=8)  # needs more than 3 usable


def test_unsatisfiable_request_raises_not_busyloops(tiny_llama):
    """A request that passes the static submit check but can never be
    admitted (registered prefixes hold too much of the pool) raises from
    run() instead of spinning forever."""
    eng = ServingEngine(
        tiny_llama, num_slots=1, prompt_buckets=(4, 8), paged_block_size=4, pool_blocks=8
    )
    eng.register_prefix((np.arange(16) % 250 + 1).astype(np.int32))  # holds 4 blocks
    eng.submit(np.ones((8,), np.int32), max_new_tokens=8)  # needs 4, only 3 ever free
    with pytest.raises(RuntimeError, match="pool blocks"):
        eng.run()


def test_paged_with_smaller_max_len(tiny_llama):
    """An engine max_len below the model's horizon still pages correctly:
    the block table follows the MODEL's cache width while reservations
    follow max_len (regression: the first cut sized the table by max_len
    and crashed in paste)."""
    prompt = (np.arange(7) % 250 + 1).astype(np.int32)
    eng = ServingEngine(
        tiny_llama, num_slots=2, prompt_buckets=(8,), max_len=64, paged_block_size=4
    )
    [got] = eng.generate_many([prompt], max_new_tokens=4)
    np.testing.assert_array_equal(got, _reference(tiny_llama, prompt, 4))
    pid = eng.register_prefix((np.arange(9) % 250 + 2).astype(np.int32))
    uid = eng.submit(np.asarray([5], np.int32), max_new_tokens=3, prefix_id=pid)
    eng.run()
    full = np.concatenate([(np.arange(9) % 250 + 2).astype(np.int32), [5]])
    np.testing.assert_array_equal(eng.poll(uid), _reference(tiny_llama, full, 3))


def test_busy_slots_then_drain_is_not_deadlock(tiny_llama):
    """All slots busy at admit time + every active request finishing
    within the same tick must NOT trip the unsatisfiable-head guard
    (regression: the first cut keyed on 'nothing admitted' instead of
    'pool-blocked' and raised here — and crashed dense engines)."""
    for kwargs in ({}, {"paged_block_size": 4}):
        eng = ServingEngine(tiny_llama, num_slots=1, prompt_buckets=(8,), tick_block=8, **kwargs)
        a = eng.submit(np.arange(1, 5, dtype=np.int32), max_new_tokens=10)  # > tick_block
        b = eng.submit(np.arange(5, 9, dtype=np.int32), max_new_tokens=3)
        eng.run()  # must complete without RuntimeError/AttributeError
        np.testing.assert_array_equal(eng.poll(a), _reference(tiny_llama, np.arange(1, 5), 10))
        np.testing.assert_array_equal(eng.poll(b), _reference(tiny_llama, np.arange(5, 9), 3))


def test_kernel_in_engine_matches_dense(tiny_llama):
    """The exact composition TPU serving runs — ServingEngine paged tick
    through the Pallas paged-attention kernel — in interpret mode on
    CPU, token-exact vs the dense engine (tiny shapes: interpret mode
    executes the grid in Python)."""
    import accelerate_tpu.ops.paged_kv as pkv

    prompts = [np.arange(1, 1 + n, dtype=np.int32) for n in (3, 6)]
    dense = ServingEngine(tiny_llama, num_slots=2, prompt_buckets=(8,), tick_block=2)
    want = dense.generate_many(prompts, max_new_tokens=3)
    pkv.FORCE_KERNEL_INTERPRET = True
    try:
        eng = ServingEngine(
            tiny_llama, num_slots=2, prompt_buckets=(8,), tick_block=2, paged_block_size=4
        )
        got = eng.generate_many(prompts, max_new_tokens=3)
    finally:
        pkv.FORCE_KERNEL_INTERPRET = False
    for w, g in zip(want, got):
        np.testing.assert_array_equal(w, g)


def test_kernel_in_engine_tp_sharded(tiny_llama):
    """TP-sharded paged serving through the kernel: the pool is sharded
    over `tensor` (heads), the kernel runs per-shard under shard_map
    (a pallas_call can't be auto-partitioned), and tokens equal the
    unsharded dense engine's."""
    import jax

    import accelerate_tpu.ops.paged_kv as pkv
    from accelerate_tpu.big_modeling import shard_model
    from accelerate_tpu.parallel.mesh import MeshConfig

    prompt = (np.arange(8) % 250).astype(np.int32)
    dense = ServingEngine(tiny_llama, num_slots=2, prompt_buckets=(8,), tick_block=2)
    [want] = dense.generate_many([prompt], max_new_tokens=3)

    model = create_llama_model(LlamaConfig.tiny(), seq_len=16)
    shard_model(model, MeshConfig(data=1, tensor=2).build(jax.devices()[:2]))
    pkv.FORCE_KERNEL_INTERPRET = True
    try:
        eng = ServingEngine(model, num_slots=2, prompt_buckets=(8,), tick_block=2, paged_block_size=4)
        [got] = eng.generate_many([prompt], max_new_tokens=3)
    finally:
        pkv.FORCE_KERNEL_INTERPRET = False
    np.testing.assert_array_equal(got, want)


def test_paged_engine_on_data_sharded_mesh(tiny_llama):
    """A mesh with data > 1: GSPMD propagates shardings onto the pool
    between pastes, so the tick must adapt instead of pinning the
    shardings it saw at construction (regression: the eagerly-compiled
    tick rejected the runtime arrays with a sharding mismatch)."""
    import jax

    from accelerate_tpu.big_modeling import shard_model
    from accelerate_tpu.parallel.mesh import MeshConfig

    prompts = [np.arange(1, 1 + n, dtype=np.int32) for n in (3, 6, 9)]
    dense = ServingEngine(tiny_llama, num_slots=2, prompt_buckets=(8, 16), tick_block=2)
    want = dense.generate_many(prompts, max_new_tokens=3)

    model = create_llama_model(LlamaConfig.tiny(), seq_len=16)
    shard_model(model, MeshConfig(data=2, tensor=2).build(jax.devices()[:4]))
    eng = ServingEngine(model, num_slots=2, prompt_buckets=(8, 16), tick_block=2, paged_block_size=4)
    got = eng.generate_many(prompts, max_new_tokens=3)
    for w, g in zip(want, got):
        np.testing.assert_array_equal(w, g)


@pytest.fixture(scope="module")
def tiny_mistral():
    from accelerate_tpu.models import MistralConfig, create_mistral_model

    return create_mistral_model(MistralConfig.tiny(sliding_window=4), seq_len=32)


def test_windowed_request_pool_cost_is_window_bound(tiny_mistral):
    """A windowed model's request reserves only O(window + max_new)
    blocks: a 24-token prompt fits a 3-usable-block pool (the
    unwindowed plan would need 8 blocks) and stays token-exact."""
    from accelerate_tpu.generation import generate

    prompt = (np.arange(24) % 250 + 1).astype(np.int32)
    eng = ServingEngine(
        tiny_mistral, num_slots=1, prompt_buckets=(8,), paged_block_size=4, pool_blocks=4
    )
    [got] = eng.generate_many([prompt], max_new_tokens=6)
    want = np.asarray(generate(tiny_mistral, prompt[None], max_new_tokens=6))[0]
    np.testing.assert_array_equal(got, want)
    assert eng.pool_free_blocks == 3


def test_window_recycles_blocks_mid_decode(tiny_mistral):
    """Blocks behind the moving frontier return to the pool WHILE the
    request is still decoding (the long-generation capacity win)."""
    eng = ServingEngine(
        tiny_mistral, num_slots=1, prompt_buckets=(8,), paged_block_size=4, tick_block=2
    )
    eng.submit(np.arange(1, 5, dtype=np.int32), max_new_tokens=16)
    eng.step()  # admit + first tick
    after_admit = eng.pool_free_blocks
    recovered = False
    while eng.active_count:
        eng.step()
        if eng.active_count and eng.pool_free_blocks > after_admit:
            recovered = True
    assert recovered  # freed behind the frontier before retirement
    assert eng.pool_free_blocks == eng._pcfg.num_blocks - 1  # all drained


def test_windowed_prefix_token_exact(tiny_mistral):
    """Prefix sharing under a window: below-band prefix blocks are never
    aliased (they start as trash) and outputs still equal full-prompt
    generate()."""
    from accelerate_tpu.generation import generate

    prefix = (np.arange(9) % 250 + 3).astype(np.int32)
    eng = ServingEngine(tiny_mistral, num_slots=2, prompt_buckets=(4, 8), paged_block_size=4)
    pid = eng.register_prefix(prefix)
    uids = [eng.submit(np.asarray(s, np.int32), max_new_tokens=4, prefix_id=pid) for s in ([5, 6], [9])]
    eng.run()
    for uid, sfx in zip(uids, ([5, 6], [9])):
        full = np.concatenate([prefix, np.asarray(sfx, np.int32)])
        want = np.asarray(generate(tiny_mistral, full[None], max_new_tokens=4))[0]
        np.testing.assert_array_equal(eng.poll(uid), want)
    eng.unregister_prefix(pid)
    assert eng.pool_free_blocks == eng._pcfg.num_blocks - 1


def test_windowed_shared_prefix_alias_and_expiry():
    """Prefix blocks INSIDE the band are aliased and then expire
    mid-decode (refcount drop, not free) while another slot still
    shares them — the refcount path the plain prefix test never enters
    (its aliases fall below the band)."""
    from accelerate_tpu.generation import generate
    from accelerate_tpu.models import MistralConfig, create_mistral_model

    m = create_mistral_model(MistralConfig.tiny(sliding_window=8), seq_len=32)
    prefix = (np.arange(8) % 250 + 3).astype(np.int32)  # 2 full in-band blocks
    eng = ServingEngine(m, num_slots=2, prompt_buckets=(4, 16), paged_block_size=4, tick_block=2)
    pid = eng.register_prefix(prefix)
    assert len(eng._prefixes[pid]["block_ids"]) == 2  # both registered (in band)
    uids = [eng.submit(np.asarray([s], np.int32), max_new_tokens=10, prefix_id=pid) for s in (5, 9)]
    eng.step()
    assert any(eng._slot_shared[s] for s in range(2))  # in-band aliases installed
    eng.run()
    for uid, sfx in zip(uids, (5, 9)):
        full = np.concatenate([prefix, [sfx]]).astype(np.int32)
        want = np.asarray(generate(m, full[None], max_new_tokens=10))[0]
        np.testing.assert_array_equal(eng.poll(uid), want)
    # all request blocks drained; the prefix still holds its own refs
    assert all(v == 1 for v in eng._shared_refs.values())
    eng.unregister_prefix(pid)
    assert eng.pool_free_blocks == eng._pcfg.num_blocks - 1


def test_windowed_prefix_registration_is_band_capped():
    """A long prefix on a windowed model registers only in-band blocks:
    O(window), not O(prefix)."""
    from accelerate_tpu.models import MistralConfig, create_mistral_model

    m = create_mistral_model(MistralConfig.tiny(sliding_window=4), seq_len=32)
    prefix = (np.arange(24) % 250 + 1).astype(np.int32)  # 6 full 4-blocks
    eng = ServingEngine(m, num_slots=1, prompt_buckets=(8,), paged_block_size=4, pool_blocks=4)
    pid = eng.register_prefix(prefix)  # unwindowed would need 6 > 3 usable
    assert len(eng._prefixes[pid]["block_ids"]) <= 2
    eng.unregister_prefix(pid)
    assert eng.pool_free_blocks == 3


def test_paged_sampling_matches_dense_chain(tiny_llama):
    """Temperature sampling: the paged batched tick splits per-row keys
    in the same order as the dense vmapped tick, so sampled outputs are
    identical for the same seed."""
    prompts = [np.arange(1, 6, dtype=np.int32), np.arange(7, 10, dtype=np.int32)]
    kw = dict(num_slots=2, prompt_buckets=(8,), temperature=0.9, top_k=5, seed=11)
    dense = ServingEngine(tiny_llama, **kw)
    paged = ServingEngine(tiny_llama, paged_block_size=4, **kw)
    for d, p in zip(dense.generate_many(prompts, 6), paged.generate_many(prompts, 6)):
        np.testing.assert_array_equal(d, p)


def test_block_allocator():
    alloc = BlockAllocator(5)
    assert alloc.free_count == 4
    got = alloc.alloc(3)
    assert len(got) == 3 and 0 not in got
    assert alloc.alloc(2) is None  # only 1 left
    alloc.free(got)
    assert alloc.free_count == 4
    with pytest.raises(ValueError):
        alloc.free([0])  # the trash sink is never allocatable/freeable
    with pytest.raises(ValueError):
        BlockAllocator(1)
