"""Paged-attention decode kernel (ops/pallas_paged_attention.py) vs an
XLA gather reference, in Pallas interpret mode on CPU: MHA/GQA, ragged
per-row frontiers, trash-sink pad entries, sliding-window bands, and
bf16 inputs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.ops.pallas_paged_attention import paged_decode_attention


def _reference(q, kp, vp, tbl, cur, window=None):
    """The XLA paged math from ops/paged_kv.py, inlined: gather pages,
    mask to (cur - W, cur], softmax, weighted sum."""
    b, h, d = q.shape
    nb, bs, hkv, _ = kp.shape
    mb = tbl.shape[1]
    k_all = kp[tbl].reshape(b, mb * bs, hkv, d).astype(jnp.float32)
    v_all = vp[tbl].reshape(b, mb * bs, hkv, d).astype(jnp.float32)
    pos = jnp.arange(mb * bs)
    live = pos[None, :] <= cur[:, None]
    if window is not None:
        live &= pos[None, :] > cur[:, None] - window
    g = h // hkv
    qg = q.astype(jnp.float32).reshape(b, hkv, g, d)
    s = jnp.einsum("bhgd,bkhd->bhgk", qg, k_all) / np.sqrt(d)
    s = jnp.where(live[:, None, None, :], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhgk,bkhd->bhgd", p, v_all)
    return out.reshape(b, h, d).astype(q.dtype)


def _setup(rng, b, h, hkv, d, bs, mb, nb, max_cur, dtype=jnp.float32):
    keys = jax.random.split(rng, 4)
    q = jax.random.normal(keys[0], (b, h, d), dtype)
    kp = jax.random.normal(keys[1], (nb, bs, hkv, d), dtype)
    vp = jax.random.normal(keys[2], (nb, bs, hkv, d), dtype)
    # each row gets a distinct random set of non-trash blocks for its
    # live region; entries beyond are the trash sink (0), as the engine
    # builds them
    rng_np = np.random.default_rng(0)
    cur = rng_np.integers(0, max_cur + 1, size=b).astype(np.int32)
    tbl = np.zeros((b, mb), np.int32)
    avail = list(range(1, nb))
    for i in range(b):
        used = cur[i] // bs + 1
        picks = rng_np.choice(avail, size=used, replace=False)
        for blk in picks:
            avail.remove(blk)
        tbl[i, :used] = picks
    return q, kp, vp, jnp.asarray(tbl), jnp.asarray(cur)


CASES = [
    # b, h, hkv, d, bs, mb, window
    pytest.param(3, 4, 4, 32, 8, 4, None, id="mha"),
    pytest.param(3, 4, 2, 32, 8, 4, None, id="gqa"),
    pytest.param(2, 4, 2, 32, 8, 4, 5, id="gqa-window"),
    pytest.param(4, 2, 1, 16, 4, 8, None, id="many-pages"),
    pytest.param(2, 4, 2, 32, 8, 4, 100, id="window-wider-than-history"),
]


@pytest.mark.parametrize("b,h,hkv,d,bs,mb,window", CASES)
def test_kernel_matches_gather_reference(b, h, hkv, d, bs, mb, window):
    nb = b * mb + 1
    q, kp, vp, tbl, cur = _setup(jax.random.PRNGKey(1), b, h, hkv, d, bs, mb, nb, max_cur=mb * bs - 1)
    out = paged_decode_attention(q, kp, vp, tbl, cur, sliding_window=window, interpret=True)
    want = _reference(q, kp, vp, tbl, cur, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_zero_frontier_rows():
    """cur=0 (a fresh or inactive slot): only position 0 attends —
    never a NaN from an empty softmax."""
    b, h, hkv, d, bs, mb = 2, 2, 2, 16, 4, 2
    q, kp, vp, tbl, _ = _setup(jax.random.PRNGKey(2), b, h, hkv, d, bs, mb, b * mb + 1, max_cur=0)
    cur = jnp.zeros((b,), jnp.int32)
    out = paged_decode_attention(q, kp, vp, tbl, cur, interpret=True)
    want = _reference(q, kp, vp, tbl, cur)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_bf16_inputs():
    b, h, hkv, d, bs, mb = 2, 4, 2, 32, 8, 3
    q, kp, vp, tbl, cur = _setup(
        jax.random.PRNGKey(3), b, h, hkv, d, bs, mb, b * mb + 1, max_cur=mb * bs - 1, dtype=jnp.bfloat16
    )
    out = paged_decode_attention(q, kp, vp, tbl, cur, interpret=True)
    want = _reference(q, kp, vp, tbl, cur)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(want, np.float32), atol=2e-2, rtol=2e-2
    )


def test_window_excludes_old_pages_exactly():
    """A hand-checkable case: window 4 at cur=10 keeps positions 7..10
    only — the kernel must match a dense softmax over exactly those."""
    b, h, hkv, d, bs, mb = 1, 2, 2, 16, 4, 3
    q, kp, vp, tbl, _ = _setup(jax.random.PRNGKey(4), b, h, hkv, d, bs, mb, b * mb + 1, max_cur=11)
    cur = jnp.asarray([10], jnp.int32)
    out = paged_decode_attention(q, kp, vp, tbl, cur, sliding_window=4, interpret=True)
    k_all = kp[tbl].reshape(1, mb * bs, hkv, d)
    v_all = vp[tbl].reshape(1, mb * bs, hkv, d)
    sl = slice(7, 11)
    s = jnp.einsum("bhd,bkhd->bhk", q.astype(jnp.float32), k_all[:, sl].astype(jnp.float32)) / np.sqrt(d)
    p = jax.nn.softmax(s, axis=-1)
    want = jnp.einsum("bhk,bkhd->bhd", p, v_all[:, sl].astype(jnp.float32))
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5, rtol=2e-5)
