"""Tracker tests (reference analogue: tests/test_tracking.py, 870 LoC —
trackers with temp dirs and mocked APIs)."""

import json

from accelerate_tpu import Accelerator
from accelerate_tpu.tracking import GeneralTracker, JSONLTracker, filter_trackers


def test_jsonl_tracker_logs(tmp_path):
    t = JSONLTracker("run", logging_dir=str(tmp_path))
    t.start()  # backend init is deferred to start() (reference: tracking.py:318)
    t.store_init_configuration({"lr": 0.1})
    t.log({"loss": 1.5}, step=0)
    t.log({"loss": 0.5}, step=1)
    lines = [json.loads(l) for l in (tmp_path / "run" / "metrics.jsonl").read_text().splitlines()]
    assert lines[0]["loss"] == 1.5 and lines[1]["_step"] == 1
    assert json.loads((tmp_path / "run" / "config.json").read_text()) == {"lr": 0.1}


def test_accelerator_tracking_end_to_end(tmp_path):
    acc = Accelerator(log_with="jsonl", project_dir=str(tmp_path))
    acc.init_trackers("proj", config={"bs": 8})
    acc.log({"metric": 2.0}, step=3)
    acc.end_training()
    lines = (tmp_path / "proj" / "metrics.jsonl").read_text().splitlines()
    assert json.loads(lines[0])["metric"] == 2.0


def test_filter_trackers_skips_unavailable(tmp_path):
    trackers = filter_trackers(["jsonl", "wandb"], str(tmp_path), "p")
    names = [t.name for t in trackers]
    assert "jsonl" in names  # wandb may or may not be installed; jsonl always


def test_custom_tracker_instance_passthrough(tmp_path):
    class MyTracker(GeneralTracker):
        name = "mine"
        requires_logging_directory = False

        def __init__(self):
            super().__init__()
            self.logged = []
            self.started = False

        def start(self):
            self.started = True

        def store_init_configuration(self, values):
            pass

        def log(self, values, step=None, **kw):
            self.logged.append(values)

    mine = MyTracker()
    trackers = filter_trackers([mine], None, "p")
    assert trackers == [mine]
    assert mine.started  # start() is called on passthrough instances too


def test_start_deferred_until_filter(tmp_path):
    """Constructing a tracker must not touch the filesystem/backend; only
    start() (called by filter_trackers / init_trackers) does."""
    t = JSONLTracker("run", logging_dir=str(tmp_path))
    assert not (tmp_path / "run").exists()
    t.start()
    assert (tmp_path / "run").exists()


def test_get_tracker_no_trackers_returns_noop_blank():
    """Reference parity: with NO active trackers get_tracker returns a
    blank no-op GeneralTracker so user code can call it unconditionally;
    the ValueError is kept for a named tracker missing among ACTIVE ones."""
    acc = Accelerator()
    t = acc.get_tracker("wandb")
    assert isinstance(t, GeneralTracker)
    # every tracker surface no-ops instead of raising
    assert t.log({"loss": 1.0}, step=0) is None
    assert t.store_init_configuration({"lr": 0.1}) is None
    assert t.tracker is None
    t.start()
    t.finish()
    # unwrap path also safe
    assert acc.get_tracker("tensorboard", unwrap=True) is not None or True


def test_get_tracker_missing_among_active_still_raises(tmp_path):
    import pytest

    acc = Accelerator(log_with="jsonl", project_dir=str(tmp_path))
    acc.init_trackers("proj")
    assert acc.get_tracker("jsonl").name == "jsonl"
    with pytest.raises(ValueError, match="not an active tracker"):
        acc.get_tracker("wandb")
