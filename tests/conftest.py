"""Test harness bootstrap: force an 8-device CPU fake mesh.

This is the multi-chip CI story from SURVEY.md §4 — the reference cannot
simulate multi-node without hardware; JAX can
(``--xla_force_host_platform_device_count``), so every sharding/collective
test runs against a real 8-way mesh on CPU. Must run before any backend
initialisation (the axon TPU plugin registers at interpreter start, so the
platform override happens via jax.config, not env)."""

import os

from accelerate_tpu.utils.environment import force_host_platform

force_host_platform(8)

# Persistent XLA compilation cache: the suite's wall-clock is dominated by
# 8-device fake-mesh compiles, which are identical run to run. Exported via
# os.environ too so subprocess-launched scripts (CLI/examples tests) share
# the same cache.
_CACHE_DIR = os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/accelerate_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

import jax

jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import pytest


@pytest.fixture(autouse=True, scope="module")
def bound_live_executables():
    """Drop jit caches after every test module. With the whole suite's
    executables held live, XLA:CPU's compiler segfaults on a fresh
    compile late in the run (reproduced at ~570 live programs; either
    half of the suite — ~290 — is fine, and no single file triggers
    it). Clearing per module bounds the live set to one file's worth;
    cross-module recompiles hit the persistent disk cache, so the
    wall-clock cost is small."""
    yield
    import jax

    jax.clear_caches()


@pytest.fixture(autouse=True)
def reset_singletons():
    """Reset borg singletons between tests (reference analogue:
    AccelerateTestCase.tearDown, test_utils/testing.py:639-651)."""
    yield
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


@pytest.fixture
def mesh8():
    from accelerate_tpu.parallel.mesh import MeshConfig

    return MeshConfig(data=8).build()
