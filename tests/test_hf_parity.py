"""Numerical parity of HF checkpoint importers against transformers.

The round-1 advisor caught T5 position-bias divergence ("imported
checkpoints silently produce wrong outputs"); these tests make that class
of bug impossible to ship for any family: build a tiny random HF model,
save safetensors, import with models/hub.py, and compare fp32 logits
element-wise. Tracing under ``jax.default_matmul_precision("highest")``
removes JAX's bf16-decomposed fp32 matmuls from the comparison (the
framework applies the same policy when ``mixed_precision="no"``).
"""

from __future__ import annotations

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

TOL = 2e-4  # fp32 elementwise tolerance across frameworks


def _save(model, tmp_path):
    model.save_pretrained(str(tmp_path), safe_serialization=True)
    return str(tmp_path)


def test_llama_import_matches_transformers(tmp_path):
    import jax

    from accelerate_tpu.models import LlamaConfig
    from accelerate_tpu.models.hub import load_hf_llama

    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0, rms_norm_eps=1e-6,
    )
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()
    ids = torch.randint(0, 128, (2, 16))
    with torch.no_grad():
        want = hf(ids).logits.numpy()

    cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-6, scan_layers=False, remat=False,
    )
    model = load_hf_llama(_save(hf, tmp_path), cfg)
    with jax.default_matmul_precision("highest"):
        got = np.asarray(model.apply_fn(model.params, ids.numpy().astype(np.int32)))
    np.testing.assert_allclose(got, want, atol=TOL)


def test_llama3_rope_scaling_matches_transformers(tmp_path):
    """Llama-3.1/3.2-style rope_scaling (piecewise llama3 frequency
    rescale): without it every rotary angle is wrong at every position,
    so parity here gates real Llama-3.x checkpoint support."""
    import jax

    from accelerate_tpu.models import LlamaConfig
    from accelerate_tpu.models.hub import load_hf_llama

    scaling = {
        "rope_type": "llama3",
        "factor": 8.0,
        "low_freq_factor": 1.0,
        "high_freq_factor": 4.0,
        "original_max_position_embeddings": 32,
    }
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0, rms_norm_eps=1e-6,
        rope_scaling=dict(scaling),
    )
    torch.manual_seed(2)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()
    ids = torch.randint(0, 128, (2, 48))  # long enough to cross the band
    with torch.no_grad():
        want = hf(ids).logits.numpy()

    cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-6, scan_layers=False, remat=False,
        rope_scaling=dict(scaling),
    )
    model = load_hf_llama(_save(hf, tmp_path), cfg)
    with jax.default_matmul_precision("highest"):
        got = np.asarray(model.apply_fn(model.params, ids.numpy().astype(np.int32)))
    np.testing.assert_allclose(got, want, atol=TOL)

    # and the guard: unsupported types refuse rather than mis-rotate
    from accelerate_tpu.models.llama import rope_frequencies

    import pytest as _pytest

    with _pytest.raises(NotImplementedError, match="made_up"):
        rope_frequencies(16, 1e4, {"rope_type": "made_up", "factor": 2.0})


def test_dynamic_ntk_rope_scaling_matches_transformers(tmp_path):
    """Dynamic NTK: base grows with the deployed length past the original
    context. HF recomputes per forward seq_len; here the static input
    length plays that role."""
    import jax

    from accelerate_tpu.models import LlamaConfig
    from accelerate_tpu.models.hub import load_hf_llama

    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=32,  # HF's dynamic orig = config max
        rope_theta=10000.0, rms_norm_eps=1e-6,
        rope_scaling={"rope_type": "dynamic", "factor": 2.0},
    )
    torch.manual_seed(5)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()
    ids = torch.randint(0, 128, (2, 48))  # past the 32-token original context
    with torch.no_grad():
        want = hf(ids).logits.numpy()

    cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-6, scan_layers=False, remat=False,
        rope_scaling={"rope_type": "dynamic", "factor": 2.0, "original_max_position_embeddings": 32},
    )
    model = load_hf_llama(_save(hf, tmp_path), cfg)
    with jax.default_matmul_precision("highest"):
        got = np.asarray(model.apply_fn(model.params, ids.numpy().astype(np.int32)))
    np.testing.assert_allclose(got, want, atol=TOL)


def test_yarn_rope_scaling_matches_transformers(tmp_path):
    """YaRN (NTK-by-parts) scaling — DeepSeek/Qwen long-context configs."""
    import jax

    from accelerate_tpu.models import LlamaConfig
    from accelerate_tpu.models.hub import load_hf_llama

    scaling = {"rope_type": "yarn", "factor": 4.0, "original_max_position_embeddings": 32}
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rope_theta=10000.0, rms_norm_eps=1e-6,
        rope_scaling=dict(scaling),
    )
    torch.manual_seed(3)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()
    ids = torch.randint(0, 128, (2, 48))
    with torch.no_grad():
        want = hf(ids).logits.numpy()

    cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-6, scan_layers=False, remat=False,
        rope_scaling=dict(scaling),
    )
    model = load_hf_llama(_save(hf, tmp_path), cfg)
    with jax.default_matmul_precision("highest"):
        got = np.asarray(model.apply_fn(model.params, ids.numpy().astype(np.int32)))
    np.testing.assert_allclose(got, want, atol=TOL)


def test_longrope_scaling_matches_transformers(tmp_path):
    """Phi-3-128k-style longrope: per-dim short/long factor tables selected
    by sequence length, with the sqrt-log attention factor."""
    import jax

    from accelerate_tpu.models import Phi3Config
    from accelerate_tpu.models.hub import load_hf_phi3

    d_half = 8  # head_dim 16 -> 8 rope dims
    short = [1.0 + 0.05 * i for i in range(d_half)]
    long = [1.5 + 0.2 * i for i in range(d_half)]
    hf_cfg = transformers.Phi3Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=128, original_max_position_embeddings=32,
        rope_theta=10000.0, rms_norm_eps=1e-6, sliding_window=None,
        pad_token_id=0,  # the 32k-vocab default index overflows this tiny vocab
        rope_scaling={"type": "longrope", "short_factor": short, "long_factor": long},
    )
    torch.manual_seed(4)
    hf = transformers.Phi3ForCausalLM(hf_cfg).eval()

    cfg = Phi3Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=128, rms_norm_eps=1e-6, sliding_window=None,
        scan_layers=False, remat=False,
        # original_max_position_embeddings at the TOP level, exactly like
        # Phi-3's config.json (not inside the rope_scaling dict)
        original_max_position_embeddings=32,
        rope_scaling={"type": "longrope", "short_factor": short, "long_factor": long},
    )
    model = load_hf_phi3(_save(hf, tmp_path), cfg)
    for S in (16, 48):  # below and above the 32-token switch point
        ids = torch.randint(0, 128, (2, S))
        with torch.no_grad():
            want = hf(ids).logits.numpy()
        with jax.default_matmul_precision("highest"):
            got = np.asarray(model.apply_fn(model.params, ids.numpy().astype(np.int32)))
        np.testing.assert_allclose(got, want, atol=TOL, err_msg=f"S={S}")


def test_llama_import_scan_layers_matches_transformers(tmp_path):
    import jax

    from accelerate_tpu.models import LlamaConfig
    from accelerate_tpu.models.hub import load_hf_llama

    hf_cfg = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=32, rms_norm_eps=1e-6,
    )
    torch.manual_seed(1)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()
    ids = torch.randint(0, 64, (1, 8))
    with torch.no_grad():
        want = hf(ids).logits.numpy()

    cfg = LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=32, rms_norm_eps=1e-6, scan_layers=True, remat=False,
    )
    model = load_hf_llama(_save(hf, tmp_path), cfg)
    with jax.default_matmul_precision("highest"):
        got = np.asarray(model.apply_fn(model.params, ids.numpy().astype(np.int32)))
    np.testing.assert_allclose(got, want, atol=TOL)


def test_gpt2_import_matches_transformers(tmp_path):
    import jax

    from accelerate_tpu.models import GPT2Config
    from accelerate_tpu.models.hub import load_hf_gpt2

    hf_cfg = transformers.GPT2Config(
        vocab_size=128, n_embd=64, n_layer=2, n_head=4, n_positions=64,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    torch.manual_seed(0)
    hf = transformers.GPT2LMHeadModel(hf_cfg).eval()
    ids = torch.randint(0, 128, (2, 12))
    with torch.no_grad():
        want = hf(ids).logits.numpy()

    cfg = GPT2Config(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, max_position_embeddings=64,
    )
    model = load_hf_gpt2(_save(hf, tmp_path), cfg)
    with jax.default_matmul_precision("highest"):
        got = np.asarray(model.apply_fn(model.params, ids.numpy().astype(np.int32)))
    np.testing.assert_allclose(got, want, atol=TOL)


def test_bert_import_matches_transformers(tmp_path):
    import jax

    from accelerate_tpu.models import BertConfig
    from accelerate_tpu.models.hub import load_hf_bert

    hf_cfg = transformers.BertConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=64, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0,
    )
    torch.manual_seed(0)
    hf = transformers.BertForSequenceClassification(hf_cfg).eval()
    ids = torch.randint(0, 128, (2, 12))
    mask = torch.ones_like(ids)
    with torch.no_grad():
        want = hf(ids, attention_mask=mask).logits.numpy()

    cfg = BertConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=64, num_labels=2,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
    )
    model = load_hf_bert(_save(hf, tmp_path), cfg)
    with jax.default_matmul_precision("highest"):
        got = np.asarray(
            model.apply_fn(model.params, ids.numpy().astype(np.int32), mask.numpy().astype(bool))
        )
    np.testing.assert_allclose(got, want, atol=TOL)


def test_t5_import_matches_transformers(tmp_path):
    import jax

    from accelerate_tpu.models import T5Config
    from accelerate_tpu.models.hub import load_hf_t5

    hf_cfg = transformers.T5Config(
        vocab_size=96, d_model=64, d_kv=16, d_ff=128, num_layers=2,
        num_heads=4, relative_attention_num_buckets=8, dropout_rate=0.0,
    )
    torch.manual_seed(0)
    hf = transformers.T5ForConditionalGeneration(hf_cfg).eval()
    enc = torch.randint(0, 96, (1, 10))
    dec = torch.randint(0, 96, (1, 6))
    with torch.no_grad():
        want = hf(input_ids=enc, decoder_input_ids=dec).logits.numpy()

    cfg = T5Config(
        vocab_size=96, hidden_size=64, head_dim=16, intermediate_size=128,
        num_layers=2, num_attention_heads=4, relative_attention_num_buckets=8,
    )
    model = load_hf_t5(_save(hf, tmp_path), cfg)
    with jax.default_matmul_precision("highest"):
        got = np.asarray(
            model.apply_fn(model.params, enc.numpy().astype(np.int32), dec.numpy().astype(np.int32))
        )
    np.testing.assert_allclose(got, want, atol=TOL)


def test_gptneox_import_matches_transformers(tmp_path):
    import jax

    from accelerate_tpu.models import GPTNeoXConfig
    from accelerate_tpu.models.hub import load_hf_gptneox

    hf_cfg = transformers.GPTNeoXConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=256,
        max_position_embeddings=64, rotary_pct=0.25,
        hidden_dropout=0.0, attention_dropout=0.0,
        use_parallel_residual=True, layer_norm_eps=1e-5, tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = transformers.GPTNeoXForCausalLM(hf_cfg).eval()
    ids = torch.randint(0, 128, (2, 12))
    with torch.no_grad():
        want = hf(ids).logits.numpy()

    cfg = GPTNeoXConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=256,
        max_position_embeddings=64, rotary_pct=0.25,
    )
    model = load_hf_gptneox(_save(hf, tmp_path), cfg)
    with jax.default_matmul_precision("highest"):
        got = np.asarray(model.apply_fn(model.params, ids.numpy().astype(np.int32)))
    np.testing.assert_allclose(got, want, atol=TOL)


def test_gptneox_import_non_parallel_residual(tmp_path):
    import jax

    from accelerate_tpu.models import GPTNeoXConfig
    from accelerate_tpu.models.hub import load_hf_gptneox

    hf_cfg = transformers.GPTNeoXConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=32, rotary_pct=1.0,
        hidden_dropout=0.0, attention_dropout=0.0,
        use_parallel_residual=False, tie_word_embeddings=False,
    )
    torch.manual_seed(2)
    hf = transformers.GPTNeoXForCausalLM(hf_cfg).eval()
    ids = torch.randint(0, 64, (1, 8))
    with torch.no_grad():
        want = hf(ids).logits.numpy()

    cfg = GPTNeoXConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=32, rotary_pct=1.0, use_parallel_residual=False,
    )
    model = load_hf_gptneox(_save(hf, tmp_path), cfg)
    with jax.default_matmul_precision("highest"):
        got = np.asarray(model.apply_fn(model.params, ids.numpy().astype(np.int32)))
    np.testing.assert_allclose(got, want, atol=TOL)


def test_mistral_import_matches_transformers(tmp_path):
    """Mistral = llama weights + sliding-window band. window 4 < seq 16,
    so any off-by-one in the band mask (ours vs HF's eager sliding-window
    path) breaks element-wise logits parity."""
    import jax

    from accelerate_tpu.models import MistralConfig
    from accelerate_tpu.models.hub import load_hf_mistral

    hf_cfg = transformers.MistralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0, rms_norm_eps=1e-6,
        sliding_window=4, attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf = transformers.MistralForCausalLM(hf_cfg).eval()
    ids = torch.randint(0, 128, (2, 16))
    with torch.no_grad():
        want = hf(ids).logits.numpy()

    cfg = MistralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0, rms_norm_eps=1e-6,
        sliding_window=4, scan_layers=False, remat=False,
    )
    model = load_hf_mistral(_save(hf, tmp_path), cfg)
    with jax.default_matmul_precision("highest"):
        got = np.asarray(model.apply_fn(model.params, ids.numpy().astype(np.int32)))
    np.testing.assert_allclose(got, want, atol=TOL)


def test_qwen2_import_matches_transformers(tmp_path):
    """Qwen2 = llama + q/k/v bias vectors; the biases rotate with their
    output channels, so a missed rope re-pairing on the BIAS (not just
    the kernel) breaks element-wise parity."""
    import jax

    from accelerate_tpu.models import Qwen2Config
    from accelerate_tpu.models.hub import load_hf_qwen2

    hf_cfg = transformers.Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0, rms_norm_eps=1e-6,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = transformers.Qwen2ForCausalLM(hf_cfg).eval()
    ids = torch.randint(0, 128, (2, 16))
    with torch.no_grad():
        want = hf(ids).logits.numpy()

    cfg = Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0, rms_norm_eps=1e-6,
        scan_layers=False, remat=False,
    )
    model = load_hf_qwen2(_save(hf, tmp_path), cfg)
    with jax.default_matmul_precision("highest"):
        got = np.asarray(model.apply_fn(model.params, ids.numpy().astype(np.int32)))
    np.testing.assert_allclose(got, want, atol=TOL)


def test_qwen2_import_scan_layers_and_tied_head(tmp_path):
    """Scan-stacked import (biases stack along the layer dim) with a tied
    LM head (the small-variant config)."""
    import jax

    from accelerate_tpu.models import Qwen2Config
    from accelerate_tpu.models.hub import load_hf_qwen2

    hf_cfg = transformers.Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0, rms_norm_eps=1e-6,
        tie_word_embeddings=True,
    )
    torch.manual_seed(1)
    hf = transformers.Qwen2ForCausalLM(hf_cfg).eval()
    ids = torch.randint(0, 128, (1, 12))
    with torch.no_grad():
        want = hf(ids).logits.numpy()

    cfg = Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0, rms_norm_eps=1e-6,
        scan_layers=True, remat=False, tie_word_embeddings=True,
    )
    model = load_hf_qwen2(_save(hf, tmp_path), cfg)
    with jax.default_matmul_precision("highest"):
        got = np.asarray(model.apply_fn(model.params, ids.numpy().astype(np.int32)))
    np.testing.assert_allclose(got, want, atol=TOL)


def test_phi3_import_matches_transformers(tmp_path):
    """Phi-3 = llama weights shipped FUSED (qkv_proj, gate_up_proj) + a
    sliding window: the importer's split points and chunk order are
    exactly what element-wise parity pins down (window 8 < seq 16 so the
    band bites too)."""
    import jax

    from accelerate_tpu.models import Phi3Config
    from accelerate_tpu.models.hub import load_hf_phi3

    hf_cfg = transformers.Phi3Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0, rms_norm_eps=1e-5,
        sliding_window=8, attn_implementation="eager",
        pad_token_id=0, bos_token_id=1, eos_token_id=2,  # defaults exceed the tiny vocab
    )
    torch.manual_seed(0)
    hf = transformers.Phi3ForCausalLM(hf_cfg).eval()
    ids = torch.randint(0, 128, (2, 16))
    with torch.no_grad():
        want = hf(ids).logits.numpy()

    cfg = Phi3Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0, rms_norm_eps=1e-5,
        sliding_window=8, scan_layers=False, remat=False,
    )
    model = load_hf_phi3(_save(hf, tmp_path), cfg)
    with jax.default_matmul_precision("highest"):
        got = np.asarray(model.apply_fn(model.params, ids.numpy().astype(np.int32)))
    np.testing.assert_allclose(got, want, atol=TOL)


def test_gemma_import_matches_transformers(tmp_path):
    """Gemma = llama skeleton + explicit head_dim (!= hidden/heads here,
    on purpose) + MQA + GeGLU + (1+scale) norms + sqrt(hidden) embedding
    scaling + always-tied LM head — each deviation breaks element-wise
    parity on its own if mis-imported."""
    import jax

    from accelerate_tpu.models import GemmaConfig
    from accelerate_tpu.models.hub import load_hf_gemma

    hf_cfg = transformers.GemmaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=1,
        head_dim=32, max_position_embeddings=64, rope_theta=10000.0,
        rms_norm_eps=1e-6, hidden_act="gelu_pytorch_tanh",
    )
    torch.manual_seed(0)
    hf = transformers.GemmaForCausalLM(hf_cfg).eval()
    ids = torch.randint(0, 128, (2, 16))
    with torch.no_grad():
        want = hf(ids).logits.numpy()

    cfg = GemmaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=1,
        head_dim=32, max_position_embeddings=64, rope_theta=10000.0,
        rms_norm_eps=1e-6, scan_layers=False, remat=False,
    )
    model = load_hf_gemma(_save(hf, tmp_path), cfg)
    with jax.default_matmul_precision("highest"):
        got = np.asarray(model.apply_fn(model.params, ids.numpy().astype(np.int32)))
    np.testing.assert_allclose(got, want, atol=TOL)


def test_mixtral_import_matches_transformers(tmp_path):
    """MoE family parity: with generous expert capacity (no token drops)
    our GShard-style dispatch computes exactly HF's top-2 renormalized
    routing, so logits match element-wise."""
    import jax

    from accelerate_tpu.models import MixtralConfig
    from accelerate_tpu.models.hub import load_hf_mixtral

    hf_cfg = transformers.MixtralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=64, rms_norm_eps=1e-6, rope_theta=10000.0,
        attention_dropout=0.0, tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = transformers.MixtralForCausalLM(hf_cfg).eval()
    ids = torch.randint(0, 128, (2, 12))
    with torch.no_grad():
        want = hf(ids).logits.numpy()

    cfg = MixtralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=64, rms_norm_eps=1e-6, rope_theta=10000.0,
        capacity_factor=8.0,  # no drops: every token keeps both experts
    )
    model = load_hf_mixtral(_save(hf, tmp_path), cfg)
    with jax.default_matmul_precision("highest"):
        got = np.asarray(model.apply_fn(model.params, ids.numpy().astype(np.int32)))
    np.testing.assert_allclose(got, want, atol=TOL)


def test_vit_import_matches_transformers(tmp_path):
    import jax

    from accelerate_tpu.models import ViTConfig
    from accelerate_tpu.models.hub import load_hf_vit

    hf_cfg = transformers.ViTConfig(
        image_size=32, patch_size=8, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128, num_labels=10,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        layer_norm_eps=1e-6,
    )
    torch.manual_seed(0)
    hf = transformers.ViTForImageClassification(hf_cfg).eval()
    images = torch.randn(2, 3, 32, 32)
    with torch.no_grad():
        want = hf(images).logits.numpy()

    cfg = ViTConfig(
        image_size=32, patch_size=8, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128, num_classes=10,
    )
    model = load_hf_vit(_save(hf, tmp_path), cfg)
    # our forward takes NHWC
    x = images.numpy().transpose(0, 2, 3, 1)
    with jax.default_matmul_precision("highest"):
        got = np.asarray(model.apply_fn(model.params, x))
    np.testing.assert_allclose(got, want, atol=TOL)


def test_whisper_import_matches_transformers(tmp_path):
    import jax

    from accelerate_tpu.models.whisper import WhisperConfig
    from accelerate_tpu.models.hub import load_hf_whisper

    hf_cfg = transformers.WhisperConfig(
        vocab_size=128, num_mel_bins=8, d_model=32,
        encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=64, decoder_ffn_dim=64,
        max_source_positions=16, max_target_positions=16,
        pad_token_id=0, bos_token_id=1, eos_token_id=2, decoder_start_token_id=1,
        suppress_tokens=[], begin_suppress_tokens=[],
    )
    torch.manual_seed(0)
    hf = transformers.WhisperForConditionalGeneration(hf_cfg).eval()
    feats = torch.randn(2, 8, 32)  # [B, mel, frames]; frames = 2*max_source_positions
    dec_ids = torch.randint(0, 128, (2, 6))
    with torch.no_grad():
        want = hf(input_features=feats, decoder_input_ids=dec_ids).logits.numpy()

    cfg = WhisperConfig(
        vocab_size=128, num_mel_bins=8, d_model=32,
        encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=64, decoder_ffn_dim=64,
        max_source_positions=16, max_target_positions=16, max_decode_len=16,
    )
    model = load_hf_whisper(_save(hf, tmp_path), cfg)
    with jax.default_matmul_precision("highest"):
        got = np.asarray(
            model.apply_fn(
                model.params,
                feats.numpy().transpose(0, 2, 1),  # feature-last
                dec_ids.numpy().astype(np.int32),
            )
        )
    np.testing.assert_allclose(got, want, atol=TOL)


def test_whisper_cached_generation_matches_full_rerun():
    from accelerate_tpu.generation import generate_seq2seq
    from accelerate_tpu.models.whisper import create_whisper_model

    m = create_whisper_model(seed=3)
    feats = np.random.default_rng(5).standard_normal((2, 16, 8)).astype(np.float32)
    dec = np.zeros((2, 1), np.int32)
    for _ in range(5):
        logits = m.apply_fn(m.params, feats, dec)
        nxt = np.asarray(logits)[:, -1].argmax(-1).astype(np.int32)
        dec = np.concatenate([dec, nxt[:, None]], axis=1)
    out = np.asarray(generate_seq2seq(m, feats, max_new_tokens=5))
    np.testing.assert_array_equal(out, dec)


def test_clip_import_matches_transformers(tmp_path):
    import jax

    from accelerate_tpu.models.clip import CLIPConfig
    from accelerate_tpu.models.hub import load_hf_clip

    hf_cfg = transformers.CLIPConfig(
        text_config={
            "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
            "num_hidden_layers": 2, "num_attention_heads": 4,
            "max_position_embeddings": 16, "eos_token_id": 2,
            "bos_token_id": 1, "pad_token_id": 0,
        },
        vision_config={
            "hidden_size": 32, "intermediate_size": 64, "num_hidden_layers": 2,
            "num_attention_heads": 4, "image_size": 16, "patch_size": 8,
        },
        projection_dim=32,
    )
    torch.manual_seed(0)
    hf = transformers.CLIPModel(hf_cfg).eval()
    pix = torch.randn(2, 3, 16, 16)
    ids = torch.randint(3, 120, (2, 16))
    ids[:, 10] = 2  # eos
    with torch.no_grad():
        out = hf(input_ids=ids, pixel_values=pix)
        want_img = out.image_embeds.numpy()
        want_txt = out.text_embeds.numpy()

    cfg = CLIPConfig(
        image_size=16, patch_size=8, vision_hidden_size=32, vision_layers=2,
        vision_heads=4, vision_ffn_dim=64, vocab_size=128, max_text_positions=16,
        text_hidden_size=32, text_layers=2, text_heads=4, text_ffn_dim=64,
        eos_token_id=2, projection_dim=32,
    )
    model = load_hf_clip(_save(hf, tmp_path), cfg)
    with jax.default_matmul_precision("highest"):
        img, txt, scale = model.apply_fn(
            model.params,
            pix.numpy().transpose(0, 2, 3, 1),  # NHWC
            ids.numpy().astype(np.int32),
        )
    np.testing.assert_allclose(np.asarray(img), want_img, atol=TOL)
    np.testing.assert_allclose(np.asarray(txt), want_txt, atol=TOL)
    assert float(scale) == pytest.approx(float(hf.logit_scale.item()), rel=1e-6)


def test_qwen3_import_matches_transformers(tmp_path):
    """Qwen3: llama layout + per-head q/k RMSNorm (scales re-paired for the
    interleaved rope convention) + explicit head_dim != hidden/heads."""
    import jax

    from accelerate_tpu.models import Qwen3Config
    from accelerate_tpu.models.hub import load_hf_qwen3

    hf_cfg = transformers.Qwen3Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=24,  # deliberately != 64/4: the decoupled-width knob
        max_position_embeddings=64, rope_theta=1e6, rms_norm_eps=1e-6,
    )
    torch.manual_seed(6)
    hf = transformers.Qwen3ForCausalLM(hf_cfg).eval()
    ids = torch.randint(0, 128, (2, 16))
    with torch.no_grad():
        want = hf(ids).logits.numpy()

    cfg = Qwen3Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=24, max_position_embeddings=64, rope_theta=1e6, rms_norm_eps=1e-6,
        scan_layers=False, remat=False,
    )
    model = load_hf_qwen3(_save(hf, tmp_path), cfg)
    with jax.default_matmul_precision("highest"):
        got = np.asarray(model.apply_fn(model.params, ids.numpy().astype(np.int32)))
    np.testing.assert_allclose(got, want, atol=TOL)


def test_olmo2_import_matches_transformers(tmp_path):
    """OLMo2: post-norm layout (outputs normalized pre-residual, no input
    norms) + FLAT q/k RMSNorm (scales re-paired per head_dim group)."""
    import jax

    from accelerate_tpu.models import Olmo2Config
    from accelerate_tpu.models.hub import load_hf_olmo2

    hf_cfg = transformers.Olmo2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=500000.0, rms_norm_eps=1e-6,
    )
    torch.manual_seed(7)
    hf = transformers.Olmo2ForCausalLM(hf_cfg).eval()
    # random norm scales so the flat-vs-per-head re-pairing is actually load-bearing
    with torch.no_grad():
        for layer in hf.model.layers:
            layer.self_attn.q_norm.weight.copy_(torch.rand_like(layer.self_attn.q_norm.weight) + 0.5)
            layer.self_attn.k_norm.weight.copy_(torch.rand_like(layer.self_attn.k_norm.weight) + 0.5)
    ids = torch.randint(0, 128, (2, 16))
    with torch.no_grad():
        want = hf(ids).logits.numpy()

    cfg = Olmo2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=500000.0, rms_norm_eps=1e-6,
        scan_layers=False, remat=False,
    )
    model = load_hf_olmo2(_save(hf, tmp_path), cfg)
    with jax.default_matmul_precision("highest"):
        got = np.asarray(model.apply_fn(model.params, ids.numpy().astype(np.int32)))
    np.testing.assert_allclose(got, want, atol=TOL)


def test_gemma2_import_matches_transformers(tmp_path):
    """Gemma2: sandwich norms, attention+final logit softcapping,
    query_pre_attn_scalar scale, and the alternating sliding/full layer
    pattern (the tiny window makes the band load-bearing at S=16)."""
    import jax

    from accelerate_tpu.models import Gemma2Config
    from accelerate_tpu.models.hub import load_hf_gemma2

    hf_cfg = transformers.Gemma2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=64, rms_norm_eps=1e-6,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        query_pre_attn_scalar=32, sliding_window=8,  # scalar != head_dim: load-bearing
    )
    torch.manual_seed(8)
    hf = transformers.Gemma2ForCausalLM(hf_cfg).eval()
    ids = torch.randint(0, 128, (2, 16))
    with torch.no_grad():
        want = hf(ids).logits.numpy()

    cfg = Gemma2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=64, rms_norm_eps=1e-6,
        query_pre_attn_scalar=32.0, sliding_window=8, remat=False,
        layer_types=tuple(hf_cfg.layer_types),  # HF's own alternation
    )
    model = load_hf_gemma2(_save(hf, tmp_path), cfg)
    with jax.default_matmul_precision("highest"):
        got = np.asarray(model.apply_fn(model.params, ids.numpy().astype(np.int32)))
    np.testing.assert_allclose(got, want, atol=TOL)


def test_gemma3_import_matches_transformers(tmp_path):
    """Gemma3 text: sandwich norms + per-head qk-norm + DUAL rope bases
    (sliding layers theta 10k unscaled, full layers theta 1M with linear
    rope_scaling) + the sliding band — all load-bearing at this size."""
    import jax

    from accelerate_tpu.models import Gemma3Config
    from accelerate_tpu.models.hub import load_hf_gemma3

    hf_cfg = transformers.Gemma3TextConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=64, rms_norm_eps=1e-6,
        query_pre_attn_scalar=32, sliding_window=8,
        rope_theta=1_000_000.0, rope_local_base_freq=10_000.0,
        rope_scaling={"rope_type": "linear", "factor": 8.0},
        layer_types=["sliding_attention", "full_attention"],
    )
    torch.manual_seed(9)
    hf = transformers.Gemma3ForCausalLM(hf_cfg).eval()
    with torch.no_grad():  # randomize the tiny norm scales: re-pairing load-bearing
        for layer in hf.model.layers:
            layer.self_attn.q_norm.weight.copy_(torch.rand_like(layer.self_attn.q_norm.weight))
            layer.self_attn.k_norm.weight.copy_(torch.rand_like(layer.self_attn.k_norm.weight))
    ids = torch.randint(0, 128, (2, 16))
    with torch.no_grad():
        want = hf(ids).logits.numpy()

    cfg = Gemma3Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=64, rms_norm_eps=1e-6,
        query_pre_attn_scalar=32.0, sliding_window=8, remat=False,
        rope_scaling={"rope_type": "linear", "factor": 8.0},
        layer_types=("sliding_attention", "full_attention"),
    )
    model = load_hf_gemma3(_save(hf, tmp_path), cfg)
    with jax.default_matmul_precision("highest"):
        got = np.asarray(model.apply_fn(model.params, ids.numpy().astype(np.int32)))
    np.testing.assert_allclose(got, want, atol=TOL)


def test_qwen3_moe_import_matches_transformers(tmp_path):
    """Qwen3-MoE: Qwen3 attention (per-head qk-norm) + routed experts with
    norm_topk_prob combine weights — HF's routing comment ("only diff with
    the mixtral sparse moe block") is the contract under test. Capacity is
    set high enough that the GShard dispatch drops nothing, making the
    dense comparison exact."""
    import jax

    from accelerate_tpu.models import Qwen3MoeConfig
    from accelerate_tpu.models.hub import load_hf_qwen3_moe

    hf_cfg = transformers.Qwen3MoeConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=64, rms_norm_eps=1e-6,
        rope_theta=1e6,  # match the family default (HF's own default is 10k)
        num_experts=4, num_experts_per_tok=2, moe_intermediate_size=48,
        norm_topk_prob=True, decoder_sparse_step=1, mlp_only_layers=[],
    )
    torch.manual_seed(10)
    hf = transformers.Qwen3MoeForCausalLM(hf_cfg).eval()
    with torch.no_grad():
        for layer in hf.model.layers:
            layer.self_attn.q_norm.weight.copy_(torch.rand_like(layer.self_attn.q_norm.weight) + 0.5)
            layer.self_attn.k_norm.weight.copy_(torch.rand_like(layer.self_attn.k_norm.weight) + 0.5)
    ids = torch.randint(0, 128, (2, 16))
    with torch.no_grad():
        want = hf(ids).logits.numpy()

    cfg = Qwen3MoeConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=64, rms_norm_eps=1e-6,
        num_local_experts=4, num_experts_per_tok=2, moe_intermediate_size=48,
        capacity_factor=8.0,  # no token ever dropped at this size
    )
    model = load_hf_qwen3_moe(_save(hf, tmp_path), cfg)
    with jax.default_matmul_precision("highest"):
        got = np.asarray(model.apply_fn(model.params, ids.numpy().astype(np.int32)))
    np.testing.assert_allclose(got, want, atol=TOL)
