"""Sharding-rule engine tests: the strategy-as-layout core."""

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from accelerate_tpu.parallel import MeshConfig, fsdp_rules_for, infer_shardings, spec_for_path
from accelerate_tpu.parallel.mesh import batch_sharding, data_parallel_size


def tiny_params():
    return {
        "layer_0": {"kernel": np.zeros((64, 128)), "bias": np.zeros((128,))},
        "layer_1": {"kernel": np.zeros((128, 64)), "bias": np.zeros((64,))},
        "norm": {"scale": np.zeros((64,))},
    }


def test_infer_shardings_default_replicated(mesh8):
    sh = infer_shardings(tiny_params(), [], mesh8)
    assert sh["layer_0"]["kernel"].spec == P()


def test_infer_shardings_rules():
    mesh = MeshConfig(data=2, tensor=4).build()
    rules = [(r"layer_\d+/kernel", P(None, "tensor"))]
    sh = infer_shardings(tiny_params(), rules, mesh)
    assert sh["layer_0"]["kernel"].spec == P(None, "tensor")
    assert sh["layer_0"]["bias"].spec == P()


def test_rule_pruned_when_not_divisible():
    mesh = MeshConfig(data=1, tensor=8).build()
    # 64 % 8 == 0 but a 3-dim would not be; use a dim that does not divide
    params = {"w": np.zeros((6, 10))}
    sh = infer_shardings(params, [("w", P("tensor", None))], mesh)
    assert sh["w"].spec == P(None, None) or sh["w"].spec == P()


def test_fsdp_auto_rules():
    mesh = MeshConfig(data=2, fsdp=4).build()
    params = {"big": np.zeros((128, 256)), "small": np.zeros((4,))}
    rules = fsdp_rules_for(params, mesh, min_size=1024)
    sh = infer_shardings(params, rules, mesh)
    # big gets its largest dim sharded over fsdp
    assert sh["big"].spec == P(None, "fsdp")
    # small stays replicated
    assert sh["small"].spec == P()


def test_sharded_param_placement_and_math():
    mesh = MeshConfig(data=2, fsdp=4).build()
    params = {"w": np.arange(32.0).reshape(8, 4)}
    rules = fsdp_rules_for(params, mesh, min_size=1)
    sh = infer_shardings(params, rules, mesh)
    sharded = jax.device_put(params, sh)

    def loss(p, x):
        return ((x @ p["w"]) ** 2).sum()

    g = jax.jit(jax.grad(loss))(sharded, np.ones((2, 8), np.float32))
    # grads inherit sharding layout; math matches unsharded reference
    expected = jax.grad(loss)(params, np.ones((2, 8), np.float32))
    np.testing.assert_allclose(np.asarray(g["w"]), np.asarray(expected["w"]), rtol=1e-6)


def test_batch_sharding_and_dp_size():
    mesh = MeshConfig(data=4, fsdp=2).build()
    assert data_parallel_size(mesh) == 8
    bs = batch_sharding(mesh)
    assert bs.spec == P(("data", "fsdp"))


def test_spec_for_path_first_match_wins():
    rules = [("kernel", P("tensor")), (".*", P())]
    assert spec_for_path("a/kernel", rules) == P("tensor")
    assert spec_for_path("a/bias", rules) == P()


class TestZeroOptimizerSharding:
    """ZeRO-1/2: optimizer moments shard over the data axis while params
    stay replicated (reference: DeepSpeed stages 1/2,
    src/accelerate/utils/deepspeed.py:253-294)."""

    def _setup(self, shard: bool):
        import optax

        from accelerate_tpu import Accelerator, ParallelismPlugin
        from accelerate_tpu.models import BertConfig, bert_classification_loss, create_bert_model

        plugin = ParallelismPlugin(mesh_config=MeshConfig(data=8), shard_optimizer_state=shard)
        acc = Accelerator(parallelism_plugin=plugin)
        model = acc.prepare_model(create_bert_model(BertConfig.tiny(), seq_len=8))
        opt = acc.prepare_optimizer(optax.adamw(1e-3))
        return acc, model, opt

    def test_moments_sharded_params_replicated(self):
        acc, model, opt = self._setup(shard=True)
        # params replicated
        p_leaf = [l for l in jax.tree_util.tree_leaves(model.params) if getattr(l, "ndim", 0) >= 2][0]
        assert p_leaf.sharding.spec == P()
        # adam moments sharded over data
        mu_specs = [
            l.sharding.spec
            for l in jax.tree_util.tree_leaves(opt.opt_state)
            if getattr(l, "ndim", 0) >= 2
        ]
        assert mu_specs, "expected matrix-shaped moment leaves"
        assert any("data" in str(s) for s in mu_specs), mu_specs
        # memory: addressable shard of a moment is 1/8 of the full leaf
        big = [
            l for l in jax.tree_util.tree_leaves(opt.opt_state) if getattr(l, "ndim", 0) >= 2
        ][0]
        shard_elems = big.sharding.shard_shape(big.shape)
        assert int(np.prod(shard_elems)) * 8 == int(np.prod(big.shape))

        # layout survives a train step and training still converges
        from accelerate_tpu.models import bert_classification_loss

        step = acc.build_train_step(lambda p, b: bert_classification_loss(p, b, model.apply_fn))
        rng = np.random.default_rng(0)
        batch = {
            "input_ids": rng.integers(0, 64, size=(16, 8)).astype(np.int32),
            "attention_mask": np.ones((16, 8), np.bool_),
            "labels": rng.integers(0, 2, size=(16,)).astype(np.int32),
        }
        l0 = float(step(batch))
        for _ in range(3):
            l1 = float(step(batch))
        assert np.isfinite(l0) and l1 < l0
        big_after = [
            l for l in jax.tree_util.tree_leaves(opt.opt_state) if getattr(l, "ndim", 0) >= 2
        ][0]
        assert "data" in str(big_after.sharding.spec)

    def test_flag_off_moments_replicated(self):
        acc, model, opt = self._setup(shard=False)
        for l in jax.tree_util.tree_leaves(opt.opt_state):
            if getattr(l, "ndim", 0) >= 2:
                spec = getattr(l.sharding, "spec", None)
                assert spec is None or "data" not in str(spec)


def test_unknown_axis_in_user_rule_raises():
    """A typo'd axis name in user sharding rules must raise, not silently
    replicate (framework-internal specs stay lenient: _prune_spec lenient=True)."""
    mesh = MeshConfig(data=2, tensor=4).build()
    tree = {"w": jax.ShapeDtypeStruct((8, 8), jax.numpy.float32)}
    with pytest.raises(ValueError, match="tesnor"):
        infer_shardings(tree, [(r"w", P(None, "tesnor"))], mesh)
