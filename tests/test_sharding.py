"""Sharding-rule engine tests: the strategy-as-layout core."""

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from accelerate_tpu.parallel import MeshConfig, fsdp_rules_for, infer_shardings, spec_for_path
from accelerate_tpu.parallel.mesh import batch_sharding, data_parallel_size


def tiny_params():
    return {
        "layer_0": {"kernel": np.zeros((64, 128)), "bias": np.zeros((128,))},
        "layer_1": {"kernel": np.zeros((128, 64)), "bias": np.zeros((64,))},
        "norm": {"scale": np.zeros((64,))},
    }


def test_infer_shardings_default_replicated(mesh8):
    sh = infer_shardings(tiny_params(), [], mesh8)
    assert sh["layer_0"]["kernel"].spec == P()


def test_infer_shardings_rules():
    mesh = MeshConfig(data=2, tensor=4).build()
    rules = [(r"layer_\d+/kernel", P(None, "tensor"))]
    sh = infer_shardings(tiny_params(), rules, mesh)
    assert sh["layer_0"]["kernel"].spec == P(None, "tensor")
    assert sh["layer_0"]["bias"].spec == P()


def test_rule_pruned_when_not_divisible():
    mesh = MeshConfig(data=1, tensor=8).build()
    # 64 % 8 == 0 but a 3-dim would not be; use a dim that does not divide
    params = {"w": np.zeros((6, 10))}
    sh = infer_shardings(params, [("w", P("tensor", None))], mesh)
    assert sh["w"].spec == P(None, None) or sh["w"].spec == P()


def test_fsdp_auto_rules():
    mesh = MeshConfig(data=2, fsdp=4).build()
    params = {"big": np.zeros((128, 256)), "small": np.zeros((4,))}
    rules = fsdp_rules_for(params, mesh, min_size=1024)
    sh = infer_shardings(params, rules, mesh)
    # big gets its largest dim sharded over fsdp
    assert sh["big"].spec == P(None, "fsdp")
    # small stays replicated
    assert sh["small"].spec == P()


def test_sharded_param_placement_and_math():
    mesh = MeshConfig(data=2, fsdp=4).build()
    params = {"w": np.arange(32.0).reshape(8, 4)}
    rules = fsdp_rules_for(params, mesh, min_size=1)
    sh = infer_shardings(params, rules, mesh)
    sharded = jax.device_put(params, sh)

    def loss(p, x):
        return ((x @ p["w"]) ** 2).sum()

    g = jax.jit(jax.grad(loss))(sharded, np.ones((2, 8), np.float32))
    # grads inherit sharding layout; math matches unsharded reference
    expected = jax.grad(loss)(params, np.ones((2, 8), np.float32))
    np.testing.assert_allclose(np.asarray(g["w"]), np.asarray(expected["w"]), rtol=1e-6)


def test_batch_sharding_and_dp_size():
    mesh = MeshConfig(data=4, fsdp=2).build()
    assert data_parallel_size(mesh) == 8
    bs = batch_sharding(mesh)
    assert bs.spec == P(("data", "fsdp"))


def test_spec_for_path_first_match_wins():
    rules = [("kernel", P("tensor")), (".*", P())]
    assert spec_for_path("a/kernel", rules) == P("tensor")
    assert spec_for_path("a/bias", rules) == P()
