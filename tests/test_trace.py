"""Fleet-wide request tracing (telemetry/trace.py), the crash flight
recorder (telemetry/flightrec.py), priced critical-path decomposition
(telemetry/critpath.py), and the HTTP telemetry endpoint
(telemetry/httpd.py) — plus their router/engine/CLI wiring."""

import json
import os
import subprocess
import sys
import threading
import urllib.request

import numpy as np
import pytest

from accelerate_tpu.scheduling import ShedError
from accelerate_tpu.telemetry.critpath import CritPathMonitor, decompose, render_critpath
from accelerate_tpu.telemetry.eventlog import EventLog, merge_events, read_events
from accelerate_tpu.telemetry.flightrec import FlightRecorder, read_dump, render_dump
from accelerate_tpu.telemetry.httpd import TelemetryHTTPD
from accelerate_tpu.telemetry.trace import (
    TraceConfig,
    Tracer,
    chrome_trace,
    traces_from_events,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CPU_ENV = {**os.environ, "PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu"}


def _ticking_clock(step_s=0.010):
    t = [0.0]

    def clock():
        t[0] += step_s
        return t[0]

    return clock


# --------------------------------------------------------------------- #
# tracer
# --------------------------------------------------------------------- #


def test_tracer_segments_are_frontier_contiguous():
    tr = Tracer(clock=_ticking_clock())
    tid = tr.start(fuid=7)
    tr.seg(tid, "queue_wait", accounted_ms=10.0)
    tr.seg(tid, "admit")
    tr.seg(tid, "prefill", tokens=8)
    tr.window(tid, "decode", tokens=2)
    tr.window(tid, "decode", tokens=2)
    tr.finish(tid, status="ok")
    (done,) = tr.completed()
    assert done["status"] == "ok"
    assert done["meta"]["fuid"] == 7
    # frontier-contiguous spans: each span starts where the previous one
    # ended, so the only time outside any span is the finish() call
    # itself (exactly one 10ms tick of the fake clock)
    frontier = 0.0
    for sp in done["spans"]:
        assert sp["t0_ms"] == pytest.approx(frontier)
        frontier = sp["t0_ms"] + sp["dur_ms"]
    seg_sum = sum(sp["dur_ms"] for sp in done["spans"])
    assert done["dur_ms"] - seg_sum == pytest.approx(10.0)
    names = [sp["name"] for sp in done["spans"]]
    assert names == ["queue_wait", "admit", "prefill", "decode"]
    decode = done["spans"][-1]
    assert decode["tokens"] == 4  # consecutive windows merged + summed


def test_tracer_seg_breaks_a_window_merge():
    tr = Tracer(clock=_ticking_clock())
    tid = tr.start()
    tr.window(tid, "decode", tokens=1)
    tr.seg(tid, "preempt")
    tr.window(tid, "decode", tokens=1)
    tr.finish(tid)
    (done,) = tr.completed()
    assert [sp["name"] for sp in done["spans"]] == ["decode", "preempt", "decode"]


def test_tracer_noops_on_none_unknown_and_finished_ids():
    tr = Tracer(clock=_ticking_clock())
    tr.seg(None, "prefill")
    tr.window(None, "decode")
    tr.finish(None)
    tr.seg(12345, "prefill")  # never started
    tid = tr.start()
    tr.finish(tid, status="ok")
    tr.seg(tid, "decode")  # already sealed: must not raise or mutate
    tr.finish(tid, status="failed")
    (done,) = tr.completed()
    assert done["status"] == "ok"
    assert done["spans"] == []


def test_tracer_ring_trims_completed():
    tr = Tracer(max_traces=4, clock=_ticking_clock())
    for i in range(10):
        tid = tr.start(i=i)
        tr.finish(tid)
    done = tr.completed()
    assert len(done) == 4
    assert [t["meta"]["i"] for t in done] == [6, 7, 8, 9]


def test_tracer_discard_and_shed_status():
    tr = Tracer(clock=_ticking_clock())
    a = tr.start()
    tr.discard(a)
    b = tr.start()
    tr.finish(b, status="shed", reason="queue full")
    done = tr.completed()
    assert [t["id"] for t in done] == [b]
    assert done[0]["status"] == "shed"
    assert done[0]["meta"]["reason"] == "queue full"


def test_trace_jsonl_emission_and_reconstruction(tmp_path):
    path = str(tmp_path / "run.jsonl")
    log = EventLog(path, rank=0)
    tr = Tracer(clock=_ticking_clock(), log=log)
    tid = tr.start(fuid=3)
    tr.seg(tid, "queue_wait")
    tr.seg(tid, "prefill", tokens=4)
    tr.window(tid, "decode", tokens=2)
    tr.finish(tid, status="ok")
    log.close()
    events = read_events(path)
    spans = [e for e in events if e.get("kind") == "span" and e["name"].startswith("trace.")]
    completes = [e for e in events if e.get("name") == "trace_complete"]
    assert len(spans) == 3 and len(completes) == 1
    assert all(e.get("trace") == tid for e in spans + completes)
    # eventlog-compatible: reconstruction recovers the same decomposition
    (rec,) = traces_from_events(events)
    assert rec["id"] == tid and rec["status"] == "ok"
    assert [sp["name"] for sp in rec["spans"]] == ["queue_wait", "prefill", "decode"]
    # one fake-clock tick (finish) is the only time outside the spans
    assert rec["dur_ms"] - sum(sp["dur_ms"] for sp in rec["spans"]) == pytest.approx(10.0)


def test_chrome_trace_export_loads_in_perfetto_shape():
    tr = Tracer(clock=_ticking_clock())
    for i in range(2):
        tid = tr.start(fuid=i)
        tr.seg(tid, "prefill")
        tr.window(tid, "decode", tokens=1)
        tr.finish(tid)
    doc = chrome_trace(tr.completed())
    assert isinstance(doc["traceEvents"], list)
    xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert len(xs) == 4  # 2 traces x 2 spans
    assert all({"name", "ts", "dur", "pid", "tid"} <= set(e) for e in xs)
    json.dumps(doc)  # must be plain-JSON serializable for the viewer


# --------------------------------------------------------------------- #
# critical path
# --------------------------------------------------------------------- #


def _mk_trace(segs, status="ok", tid=1, meta=None):
    spans, t0 = [], 0.0
    for name, dur, extra in segs:
        spans.append({"name": name, "t0_ms": t0, "dur_ms": dur, **extra})
        t0 += dur
    return {"id": tid, "status": status, "dur_ms": t0, "spans": spans, "meta": meta or {}}


def test_decompose_percentiles_and_share():
    traces = [
        _mk_trace([("prefill", 10.0, {}), ("decode", 30.0, {})], tid=1),
        _mk_trace([("prefill", 20.0, {}), ("decode", 40.0, {})], tid=2),
    ]
    rep = decompose(traces)
    assert rep["count"] == 2 and rep["completed"] == 2
    assert rep["by_class"]["prefill"]["p50_ms"] == 10.0
    assert rep["by_class"]["prefill"]["p95_ms"] == 20.0
    assert rep["by_class"]["decode"]["total_ms"] == 70.0
    assert rep["by_class"]["decode"]["share"] == pytest.approx(0.7)
    text = render_critpath(rep)
    assert "prefill" in text and "decode" in text


def test_critpath_latches_once_per_class_and_resets():
    mon = CritPathMonitor()
    bad = _mk_trace(
        [("kv_handoff", 1.0, {"moved_bytes": 100, "predicted_bytes": 200})], tid=1
    )
    mon.observe(bad)
    mon.observe(_mk_trace(
        [("kv_handoff", 1.0, {"moved_bytes": 1, "predicted_bytes": 999})], tid=2
    ))
    assert list(mon.drift_events) == ["kv_handoff"]
    assert mon.drift_events["kv_handoff"]["trace"] == 1  # first excursion wins
    mon.reset()
    assert mon.drift_events == {}


def test_critpath_skips_paste_and_recompute_spans():
    mon = CritPathMonitor()
    # decode-side paste span has no byte pair; recompute failovers move
    # no KV by design — neither may latch
    mon.observe(_mk_trace([("kv_handoff", 1.0, {"phase": "paste", "rows": 3})]))
    mon.observe(_mk_trace(
        [("failover", 1.0, {"path": "recompute", "moved_bytes": 0, "predicted_bytes": 999})]
    ))
    assert mon.drift_events == {}


def test_critpath_queue_wait_vs_scheduler_accounting():
    mon = CritPathMonitor()
    mon.observe(_mk_trace([("queue_wait", 50.0, {"accounted_ms": 10.0})], tid=9))
    assert list(mon.drift_events) == ["queue_wait"]
    assert mon.drift_events["queue_wait"]["check"] == "scheduler_accounting"
    # tiny absolute gaps never latch (coarse-clock noise floor)
    mon2 = CritPathMonitor()
    mon2.observe(_mk_trace([("queue_wait", 1.8, {"accounted_ms": 0.2})]))
    assert mon2.drift_events == {}


def test_critpath_prefill_vs_injected_price():
    mon = CritPathMonitor(price_prefill_us=lambda tokens: tokens * 1000.0)
    mon.observe(_mk_trace(
        [("prefill", 500.0, {"tokens": 8, "compute_ms": 100.0})], tid=4
    ))  # predicted 8 ms vs computed 100 ms: > 2x threshold
    assert list(mon.drift_events) == ["prefill"]
    assert mon.drift_events["prefill"]["check"] == "prefill_compute_us"


# --------------------------------------------------------------------- #
# flight recorder
# --------------------------------------------------------------------- #


def test_flightrec_ring_keeps_last_n_in_order():
    fr = FlightRecorder(8, name="r0")
    for i in range(20):
        fr.record({"kind": "event", "name": f"e{i}", "seq": i})
    tail = fr.tail()
    assert [e["name"] for e in tail] == [f"e{i}" for i in range(12, 20)]
    assert fr.tail(2)[-1]["name"] == "e19"


def test_flightrec_dump_write_read_render(tmp_path):
    fr = FlightRecorder(8, name="r1")
    fr.record({"kind": "event", "name": "replica_state", "state": "dead"})
    path = str(tmp_path / "flight.json")
    doc = fr.dump(
        reason="dead: boom", inflight=[{"uid": 1, "state": "active"}],
        open_spans=[{"trace": 5, "name": "decode"}], path=path,
    )
    assert doc["path"] == path
    back = read_dump(path)
    assert back["reason"] == "dead: boom"
    assert back["events"][-1]["name"] == "replica_state"
    assert back["inflight"][0]["uid"] == 1
    text = render_dump(back)
    assert "dead: boom" in text and "replica_state" in text


def test_flightrec_dump_never_raises_on_hostile_payloads(tmp_path):
    fr = FlightRecorder(8, name="r2")
    fr.record({"kind": "event", "name": "weird", "payload": object()})
    # deep path: parents are created on demand
    ok = fr.dump(reason="x", path=str(tmp_path / "deep" / "dir" / "f.json"))
    assert ok["path"] and read_dump(ok["path"])["reason"] == "x"  # object() coerced
    # unwritable path (a file where a directory is needed): reported, not raised
    (tmp_path / "blocker").write_text("")
    doc = fr.dump(reason="x", path=str(tmp_path / "blocker" / "f.json"))
    assert doc["reason"] == "x" and "write_error" in doc and "path" not in doc


# --------------------------------------------------------------------- #
# eventlog: per-process sequence numbers + deterministic merge
# --------------------------------------------------------------------- #


def test_eventlog_seq_monotonic_and_taps(tmp_path):
    log = EventLog(str(tmp_path / "a.jsonl"), rank=0)
    seen = []
    log.add_tap(seen.append)
    log.event("one")
    log.event("two")
    log.close()
    recs = read_events(str(tmp_path / "a.jsonl"))
    seqs = [r["seq"] for r in recs]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    assert [r["name"] for r in seen] == ["one", "two"]  # tap saw every record
    log2 = EventLog(None, rank=0)  # taps fire even with no sink
    log2.add_tap(seen.append)
    log2.event("three")
    assert seen[-1]["name"] == "three"
    log2.remove_tap(seen.append)
    log2.event("four")
    assert seen[-1]["name"] == "three"


def test_merge_events_deterministic_and_tolerates_old_logs(tmp_path):
    log = EventLog(str(tmp_path / "new.jsonl"), rank=0, clock=lambda: 100.0)
    log.event("n1")
    log.event("n2")
    log.close()
    new = read_events(str(tmp_path / "new.jsonl"))
    old = [{"v": 1, "ts": 100.0, "rank": 0, "kind": "event", "name": "legacy"}]  # no seq
    merged = merge_events(old, new)
    # same ts: the legacy record (no seq -> -1) sorts first, then by seq
    assert [r["name"] for r in merged] == ["legacy", "n1", "n2"]
    assert merge_events(new, old) == merged  # input order can't change the result


# --------------------------------------------------------------------- #
# HTTP endpoint
# --------------------------------------------------------------------- #


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=5) as resp:
            return resp.status, resp.read(), resp.headers
    except urllib.error.HTTPError as e:  # non-2xx still carries a body
        return e.code, e.read(), e.headers


def test_httpd_metrics_healthz_traces_and_404():
    metrics = 'fleet_up{replica="r0"} 1\n'
    health = {"r0": {"health": "healthy"}, "r1": {"health": "dead"}}
    with TelemetryHTTPD(
        metrics_fn=lambda: metrics,
        health_fn=lambda: health,
        traces_fn=lambda n: [{"id": i} for i in range(min(n, 5))],
    ) as srv:
        status, body, headers = _get(srv.url("/metrics"))
        assert status == 200
        assert body == metrics.encode("utf-8")  # byte-identical exposition
        assert headers["Content-Type"].startswith("text/plain; version=0.0.4")
        status, body, _ = _get(srv.url("/healthz"))
        assert status == 200 and json.loads(body)["serving"] is True
        status, body, _ = _get(srv.url("/traces?n=2"))
        assert status == 200 and len(json.loads(body)["traces"]) == 2
        status, _, _ = _get(srv.url("/nope"))
        assert status == 404
    # all replicas down -> 503 (load balancers must stop routing here)
    with TelemetryHTTPD(
        metrics_fn=lambda: "", health_fn=lambda: {"r0": {"health": "dead"}}
    ) as srv:
        status, body, _ = _get(srv.url("/healthz"))
        assert status == 503 and json.loads(body)["serving"] is False


# --------------------------------------------------------------------- #
# knobs + error surfaces
# --------------------------------------------------------------------- #


def test_telemetry_kwargs_trace_config():
    from accelerate_tpu.utils.dataclasses import TelemetryKwargs

    assert TelemetryKwargs().trace_config() is None
    cfg = TelemetryKwargs(
        trace_requests=True, flight_capacity=64, flight_dump_dir="/tmp/fd"
    ).trace_config()
    assert isinstance(cfg, TraceConfig)
    assert cfg.flight_capacity == 64 and cfg.flight_dump_dir == "/tmp/fd"
    with pytest.raises(ValueError):
        TelemetryKwargs(flight_capacity=2)


def test_shed_error_carries_trace_id():
    e = ShedError("queue full", priority=1, queue_depth=9, trace_id=42)
    assert e.trace_id == 42 and "trace=42" in str(e)
    assert ShedError("queue full").trace_id is None


def test_fleet_request_error_names_trace():
    from accelerate_tpu.serving_fleet import FleetRequestError

    e = FleetRequestError(3, "lost", "no snapshot", trace_id=17)
    assert e.trace_id == 17 and "(trace 17)" in str(e)
    assert FleetRequestError(3, "unknown").trace_id is None


# --------------------------------------------------------------------- #
# summarize integration
# --------------------------------------------------------------------- #


def _traced_run_jsonl(tmp_path, *, drift=False):
    path = str(tmp_path / "traced.jsonl")
    log = EventLog(path, rank=0)
    mon = CritPathMonitor(log)
    tr = Tracer(clock=_ticking_clock(), log=log, on_finish=mon.observe)
    for i in range(3):
        tid = tr.start(fuid=i)
        tr.seg(tid, "queue_wait", accounted_ms=10.0)
        tr.seg(tid, "prefill", tokens=8)
        moved = 100 if (drift and i == 0) else 4096
        tr.seg(tid, "kv_handoff", tokens=8, moved_bytes=moved, predicted_bytes=4096)
        tr.window(tid, "decode", tokens=4)
        tr.finish(tid, status="ok")
    log.event("flight_dump", replica="r0", reason="dead: boom", events=5)
    log.close()
    return path


def test_summarize_traces_section_and_render(tmp_path):
    from accelerate_tpu.telemetry import render_text, summarize_file

    report = summarize_file(_traced_run_jsonl(tmp_path, drift=True))
    traces = report["traces"]
    assert traces["count"] == 3 and traces["completed"] == 3
    assert set(traces["by_class"]) == {"queue_wait", "prefill", "kv_handoff", "decode"}
    assert len(traces["drift_events"]) == 1
    assert traces["drift_events"][0]["segment"] == "kv_handoff"
    assert traces["flight_dumps"] == 1
    assert report["warnings"] >= 1  # the latched trace_drift counts
    text = render_text(report)
    assert "traces:" in text and "kv_handoff" in text and "DRIFT" in text
    assert "flight dumps" in text
    clean = summarize_file(_traced_run_jsonl(tmp_path, drift=False))
    assert clean["traces"]["drift_events"] == []


def test_cli_trace_summarize_export_flightdump_selfcheck(tmp_path):
    path = _traced_run_jsonl(tmp_path, drift=True)

    def cli(*argv):
        return subprocess.run(
            [sys.executable, "-m", "accelerate_tpu.commands.cli", "trace", *argv],
            capture_output=True, text=True, env=CPU_ENV, timeout=240, cwd=REPO,
        )

    out = cli("summarize", path)
    assert out.returncode == 0, out.stderr
    assert "kv_handoff" in out.stdout and "DRIFT" in out.stdout
    out = cli("summarize", path, "--format", "json")
    assert json.loads(out.stdout)["completed"] == 3
    assert cli("summarize", path, "--strict").returncode == 1  # drift latched
    chrome = str(tmp_path / "chrome.json")
    out = cli("export", path, "-o", chrome)
    assert out.returncode == 0, out.stderr
    doc = json.load(open(chrome))
    assert any(e.get("ph") == "X" for e in doc["traceEvents"])
    fr = FlightRecorder(8, name="r0")
    fr.record({"kind": "event", "name": "replica_state", "state": "dead"})
    dpath = str(tmp_path / "flight.json")
    fr.dump(reason="dead: boom", path=dpath)
    out = cli("flight-dump", dpath)
    assert out.returncode == 0 and "dead: boom" in out.stdout
    out = cli("selfcheck")
    assert out.returncode == 0, out.stdout + out.stderr
    assert "OK" in out.stdout


def test_fleet_check_clean_over_threaded_telemetry_modules():
    out = subprocess.run(
        [
            sys.executable, "-m", "accelerate_tpu.commands.cli", "fleet-check",
            "accelerate_tpu/telemetry/httpd.py",
            "accelerate_tpu/telemetry/flightrec.py",
            "accelerate_tpu/telemetry/trace.py",
        ],
        capture_output=True, text=True, env=CPU_ENV, timeout=240, cwd=REPO,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "0 finding(s)" in out.stdout


# --------------------------------------------------------------------- #
# handoff codec v2: the trace id rides the wire blob
# --------------------------------------------------------------------- #


def test_handoff_codec_trace_roundtrip_and_v1_compat():
    from accelerate_tpu.serving_fleet import HandoffCodec

    class _Eng:
        _row_template = {
            "k": np.zeros((2, 3), np.float32), "v": np.zeros((2, 3), np.float32)
        }

    handoff = {
        "prompt": np.arange(4, dtype=np.int32), "total": 4, "max_new_tokens": 2,
        "next_tok": 7, "lp": -1.25, "key_data": np.zeros(2, np.uint32),
        "cache": {"k": np.ones((2, 3), np.float32), "v": np.full((2, 3), 2.0, np.float32)},
        "wire_bytes": 48, "reused_prefix_tokens": 0, "trace": 42,
    }
    dec = HandoffCodec.decode(HandoffCodec.encode(handoff), _Eng())
    assert dec["trace"] == 42
    np.testing.assert_array_equal(dec["cache"]["v"], handoff["cache"]["v"])
    # v1 blob (no trace key at all) must still decode — trace comes back None
    v1 = {k: v for k, v in handoff.items() if k != "trace"}
    assert HandoffCodec.decode(HandoffCodec.encode(v1), _Eng())["trace"] is None


# --------------------------------------------------------------------- #
# fleet integration (jax, CPU)
# --------------------------------------------------------------------- #


@pytest.fixture(scope="module")
def tiny_llama():
    from accelerate_tpu.models import LlamaConfig, create_llama_model

    return create_llama_model(LlamaConfig.tiny(), seq_len=16)


@pytest.fixture(autouse=True)
def bound_live_executables_per_test():
    yield
    import sys as _sys

    jax = _sys.modules.get("jax")
    if jax is not None:
        jax.clear_caches()


def _traced_fleet(model, *, roles=None, handoff="auto", **cfg_kw):
    from accelerate_tpu.serving_fleet import FleetConfig, FleetRouter

    cfg_kw.setdefault("prefix_reuse", False)
    return FleetRouter.from_model(
        model, num_replicas=2,
        config=FleetConfig(roles=roles, handoff=handoff, **cfg_kw),
        trace=True, num_slots=2, prompt_buckets=(4, 8), tick_block=2,
    )


def _warm(router, rng, lens=(4, 8, 10)):
    for rep in router.replicas:
        for n in lens:
            rep.engine.submit(rng.integers(1, 250, size=n).astype(np.int32), max_new_tokens=2)
        rep.engine.run()


def test_traced_disaggregated_fleet_end_to_end(tiny_llama):
    """One trace per request across the prefill->handoff->decode hop:
    frontier-contiguous segments reconcile with e2e latency, the handoff
    span's bytes match the pre-priced prediction, and no drift latches."""
    fr = _traced_fleet(tiny_llama, roles=("prefill", "decode"), handoff="always")
    emitted = []
    for rep in fr.replicas:
        rep.engine._log.add_tap(emitted.append)
    rng = np.random.default_rng(3)
    prompts = [(np.arange(1, 7) % 250 + i).astype(np.int32) for i in range(3)]
    uids = [fr.submit(p, max_new_tokens=4) for p in prompts]
    out = fr.run()
    assert sorted(out) == sorted(uids)
    traces = [t for t in fr.tracer.completed() if "fuid" in t["meta"]]
    assert len(traces) == len(uids)
    for tr in traces:
        assert tr["status"] == "ok"
        names = {sp["name"] for sp in tr["spans"]}
        assert {"prefill", "kv_handoff", "queue_wait", "admit", "decode"} <= names
        seg_sum = sum(sp["dur_ms"] for sp in tr["spans"])
        assert abs(tr["dur_ms"] - seg_sum) / tr["dur_ms"] <= 0.05
        (ho,) = [
            sp for sp in tr["spans"]
            if sp["name"] == "kv_handoff" and sp.get("moved_bytes") is not None
        ]
        assert ho["moved_bytes"] == ho["predicted_bytes"] > 0
        decode = [sp for sp in tr["spans"] if sp["name"] == "decode"]
        # the FIRST generated token is minted during prefill and rides
        # the handoff blob; decode windows cover the remaining three
        assert sum(sp["tokens"] for sp in decode) == 4 - 1
    assert fr.critpath.drift_events == {}
    # the kv_handoff fleet event carries the trace id (satellite: events
    # are joinable against traces)
    ho_events = [e for e in emitted if e.get("name") == "kv_handoff"]
    assert ho_events and all(e.get("trace") is not None for e in ho_events)


@pytest.mark.parametrize("action", ["crash", "poison", "hang"])
def test_every_chaos_fault_class_dumps_the_flight_recorder(tiny_llama, action):
    """ISSUE 18 acceptance: crash, poison, AND hang must each leave a
    flight-recorder dump on the faulted replica whose tail contains the
    injected fault's event."""
    from accelerate_tpu.test_utils.fault_injection import ReplicaChaos

    fr = _traced_fleet(tiny_llama, quarantine_after_timeouts=1)
    emitted = []
    for rep in fr.replicas:
        rep.engine._log.add_tap(emitted.append)
    rng = np.random.default_rng(5)
    _warm(fr, rng)
    uids = [
        fr.submit((np.arange(1, 6) % 250 + i).astype(np.int32), max_new_tokens=6)
        for i in range(4)
    ]
    fr.step()
    if action == "hang":
        fr.config.tick_timeout_s = 0.05
        chaos_kw = {"action": "hang", "hang_s": 0.2, "repeat": True}
    else:
        chaos_kw = {"action": action}
    with ReplicaChaos("pre_tick", replica="r0", **chaos_kw) as chaos:
        out = fr.run()
    assert chaos.fired
    assert sorted(out) == sorted(uids)  # failover saved every request
    rep = next(r for r in fr.replicas if r.name == "r0")
    expected = {"crash": "dead", "poison": "quarantined", "hang": "quarantined"}[action]
    assert fr.health()["r0"]["health"] == expected
    dump = rep.flightrec.last_dump
    assert dump is not None and dump["reason"].startswith(expected)
    tail = dump["events"]
    if action == "hang":
        assert any(e.get("name") == "replica_timeout" for e in tail)
        assert any(
            e.get("name") == "replica_state" and "timeout" in str(e.get("reason", ""))
            for e in tail
        )
    else:
        marker = {"crash": "SimulatedCrash", "poison": "NonFinitePoison"}[action]
        assert any(
            e.get("name") == "replica_state" and marker in str(e.get("reason", ""))
            for e in tail
        )
    # the dump is a flight_dump event too, so offline summarize counts it
    assert any(e.get("name") == "flight_dump" for e in emitted)


def test_httpd_serves_router_bytes_and_survives_chaos_scrape(tiny_llama):
    """/metrics on a real port is byte-identical to fleet_prometheus_text,
    and a replica crash WHILE the endpoint is being scraped never breaks
    a request (the ISSUE 18 regression: formatting happens outside any
    lock the failover path needs)."""
    from accelerate_tpu.test_utils.fault_injection import ReplicaChaos

    fr = _traced_fleet(tiny_llama)
    rng = np.random.default_rng(7)
    _warm(fr, rng)
    with TelemetryHTTPD.for_router(fr) as srv:
        status, body, _ = _get(srv.url("/metrics"))
        assert status == 200
        assert body == fr.prometheus_text().encode("utf-8")
        uids = [
            fr.submit((np.arange(1, 6) % 250 + i).astype(np.int32), max_new_tokens=6)
            for i in range(4)
        ]
        fr.step()
        scrape_errors, stop = [], threading.Event()

        def scraper():
            while not stop.is_set():
                try:
                    s1, b1, _ = _get(srv.url("/metrics"))
                    s2, b2, _ = _get(srv.url("/healthz"))
                    assert s1 == 200 and b1
                    assert s2 in (200, 503) and json.loads(b2)["replicas"]
                except Exception as e:  # noqa: BLE001 — the regression under test
                    scrape_errors.append(e)
                    return

        t = threading.Thread(target=scraper, daemon=True)
        t.start()
        with ReplicaChaos("pre_tick", replica="r0", action="crash") as chaos:
            out = fr.run()
        stop.set()
        t.join(timeout=10)
        assert chaos.fired and sorted(out) == sorted(uids)
        assert not scrape_errors, scrape_errors
        # post-crash scrape reflects the transition and completed traces
        status, body, _ = _get(srv.url("/healthz"))
        health = json.loads(body)
        assert health["replicas"]["r0"]["health"] == "dead"
        assert health["serving"] is True  # r1 still serves -> keep routing
        status, body, _ = _get(srv.url("/traces?n=100"))
        got = json.loads(body)["traces"]
        assert status == 200 and len([t for t in got if "fuid" in t["meta"]]) == len(uids)
