"""Runtime telemetry subsystem (telemetry/): event-log schema, step-time
split, recompile watchdog, MFU math, HBM drift, summarize, CLI, and the
Accelerator wiring."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from accelerate_tpu.telemetry import (
    EventLog,
    HBMSampler,
    StepTelemetry,
    Telemetry,
    diff_signatures,
    flops_from_compiled,
    goodput,
    mfu,
    peak_flops,
    read_events,
    render_text,
    signature_of,
    summarize,
    summarize_file,
)

CPU_ENV = {**os.environ, "PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu"}


# --------------------------------------------------------------------- #
# event log
# --------------------------------------------------------------------- #


def test_eventlog_schema_and_kinds(tmp_path):
    path = str(tmp_path / "run.jsonl")
    log = EventLog(path, rank=3, main_process_only=False, buffer_lines=2, clock=lambda: 123.5)
    log.counter("hbm_bytes_in_use", 1024)
    log.event("recompile", severity="warning", step=7)
    with log.span("prefill", bucket=32):
        pass
    log.close()
    events = read_events(path)
    assert len(events) == 3
    for e in events:
        assert e["v"] == 1 and e["rank"] == 3 and e["ts"] == 123.5
        assert e["kind"] in ("span", "counter", "event")
    # `seq` is the per-process monotonic counter (additive in-place to
    # v1 — readers tolerate records without it); its absolute value
    # depends on everything emitted earlier in the process
    assert {k: v for k, v in events[0].items() if k != "seq"} == {
        "v": 1, "ts": 123.5, "rank": 3, "kind": "counter",
        "name": "hbm_bytes_in_use", "value": 1024}
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs) and len(set(seqs)) == 3
    assert events[1]["severity"] == "warning" and events[1]["step"] == 7
    assert events[2]["name"] == "prefill" and events[2]["dur_ms"] >= 0


def test_eventlog_buffers_and_flushes(tmp_path):
    path = str(tmp_path / "run.jsonl")
    log = EventLog(path, rank=0, buffer_lines=10)
    log.counter("a", 1)
    assert read_events(path) == []  # still buffered
    log.flush()
    assert len(read_events(path)) == 1
    log.close()


def test_eventlog_disabled_modes(tmp_path):
    # no path -> no-op, still returns the record for in-memory use
    rec = EventLog(None).counter("x", 1)
    assert rec["value"] == 1
    # non-main rank under main_process_only -> writes nothing
    path = str(tmp_path / "rank1.jsonl")
    log = EventLog(path, rank=1, main_process_only=True)
    assert not log.enabled
    log.counter("x", 1)
    log.close()
    assert not os.path.exists(path) or read_events(path) == []


def test_eventlog_rejects_bad_kind():
    with pytest.raises(ValueError):
        EventLog(None).emit("bogus", "x")


def test_eventlog_coerces_array_fields(tmp_path):
    path = str(tmp_path / "run.jsonl")
    log = EventLog(path, rank=0)
    log.event("weird", arr=np.zeros((2, 3), np.float32), scalar=np.int32(7))
    log.close()
    [e] = read_events(path)
    assert e["scalar"] == 7
    assert e["arr"] == "float32[2,3]"


def test_read_events_skips_corrupt_lines(tmp_path):
    path = tmp_path / "run.jsonl"
    path.write_text('{"v": 1, "kind": "counter", "name": "a", "value": 1}\n{truncated\n')
    assert len(read_events(str(path))) == 1


# --------------------------------------------------------------------- #
# step telemetry: split + watchdog
# --------------------------------------------------------------------- #


def _jit_step():
    import jax

    return jax.jit(lambda x: (x @ x).sum())


def test_step_split_on_cpu(tmp_path):
    import jax.numpy as jnp

    path = str(tmp_path / "run.jsonl")
    st = StepTelemetry(EventLog(path, rank=0))
    step = st.wrap(_jit_step())
    x = jnp.ones((32, 32))
    for _ in range(5):
        step(x)
    st.log.close()
    events = [e for e in read_events(path) if e["kind"] == "span"]
    assert len(events) == 5
    first, rest = events[0], events[1:]
    assert first["compile"] is True and first["dispatch_ms"] > 0
    for e in rest:
        assert e["step"] > 0
        assert e["dur_ms"] >= 0 and e["data_wait_ms"] >= 0
        assert e["execute_ms"] >= 0 and e["dispatch_ms"] >= 0
        assert abs(e["dur_ms"] - (e["data_wait_ms"] + e["dispatch_ms"] + e["execute_ms"])) < 0.01
    summary = st.summary()
    assert summary["steps"] == 5
    assert summary["p50_step_ms"] is not None and summary["p95_step_ms"] is not None
    assert summary["compile_ms"] > 0
    assert 0 < summary["goodput"] <= 1.0


def test_recompile_watchdog_fires_once_per_miss_and_stays_silent():
    import jax.numpy as jnp

    st = StepTelemetry(warmup_steps=1)
    step = st.wrap(_jit_step())
    big, small = jnp.ones((32, 32)), jnp.ones((16, 16))
    for _ in range(5):
        step(big)
    assert st.recompiles == 0  # warmup + steady: silent
    step(small)  # post-warmup shape change -> exactly one event
    assert st.recompiles == 1
    [ev] = st.recompile_events
    assert ev["severity"] == "warning"
    assert any("32,32" in c and "16,16" in c for c in ev["changed"])
    # 100 steady-state steps on the new shape: silent
    for _ in range(100):
        step(small)
    assert st.recompiles == 1
    # returning to a previously-seen shape is a jit cache HIT: still silent
    step(big)
    assert st.recompiles == 1


def test_watchdog_overhead_under_2_percent_of_bench_step():
    """Fixed per-call instrumentation cost (timeline + watchdog + event
    record), measured with a no-op step so nothing else contributes: must
    be far below 2% of the CPU benchmark loop's step time (>= 10 ms, so
    the budget is 200 us/call; steady-state measures ~15 us). A
    wall-clock A/B against a real matmul loop is too noisy on shared CPU
    runners — the bare loop itself varies by >10% run to run."""
    import time

    st = StepTelemetry(warmup_steps=1)
    batch = {
        "input_ids": np.zeros((8, 128), np.int32),
        "attention_mask": np.zeros((8, 128), np.bool_),
        "labels": np.zeros((8,), np.int32),
    }
    step = st.wrap(lambda b: None)
    for _ in range(20):  # warm caches (treedef path cache, seen signatures)
        step(batch)
    n = 1000
    t0 = time.perf_counter()
    for _ in range(n):
        step(batch)
    per_call_us = (time.perf_counter() - t0) / n * 1e6
    assert per_call_us < 200, f"telemetry fixed overhead {per_call_us:.1f} us/call exceeds budget"
    assert st.recompiles == 0  # and the loop stayed watchdog-silent


def test_signature_diff_names_changed_leaf():
    a = signature_of({"input_ids": np.zeros((8, 128), np.int32)})
    b = signature_of({"input_ids": np.zeros((8, 256), np.int32)})
    [change] = diff_signatures(a, b)
    assert "input_ids" in change and "int32[8,128]" in change and "int32[8,256]" in change


def test_step_context_manager_counts_steps():
    st = StepTelemetry(watchdog=False)
    for _ in range(3):
        with st.step() as handle:
            handle.done(None)
    assert st.step_index == 3 and len(st.records) == 3


# --------------------------------------------------------------------- #
# MFU / goodput
# --------------------------------------------------------------------- #


def test_mfu_math_known_flops_matmul():
    # a [512,512]x[512,512] matmul is 2*512^3 FLOPs; at 1 TFLOP/s peak and
    # 1 ms/step the utilisation is exactly 2*512^3 / 1e9
    flops = 2 * 512**3
    got = mfu(flops, step_time_s=1e-3, n_devices=1, peak=1e12)
    assert got == pytest.approx(flops / 1e9)
    # two devices halve per-device utilisation
    assert mfu(flops, 1e-3, 2, peak=1e12) == pytest.approx(flops / 2e9)
    # generation table path
    assert mfu(flops, 1e-3, 1, generation="v5e") == pytest.approx(flops / 1e-3 / peak_flops("v5e"))
    with pytest.raises(ValueError):
        mfu(flops, 0.0)


def test_flops_from_compiled_cost_analysis():
    import jax
    import jax.numpy as jnp

    lowered = jax.jit(lambda a, b: a @ b).lower(
        jax.ShapeDtypeStruct((128, 128), jnp.float32), jax.ShapeDtypeStruct((128, 128), jnp.float32)
    )
    flops = flops_from_compiled(lowered.compile())
    if flops is not None:  # backend-dependent; when reported it must be the matmul
        assert flops == pytest.approx(2 * 128**3, rel=0.25)
    assert flops_from_compiled(object()) is None


def test_step_records_carry_mfu():
    import jax.numpy as jnp

    st = StepTelemetry(warmup_steps=1, flops_per_step=2 * 32**3, peak_flops_per_device=1e12)
    step = st.wrap(_jit_step())
    x = jnp.ones((32, 32))
    for _ in range(4):
        step(x)
    steady = st.steady_records()
    assert steady and all(0 < r["mfu"] <= 1 for r in steady if "mfu" in r)
    assert "mfu" in st.summary()


def test_goodput_fraction():
    recs = [
        {"dur_ms": 10.0, "data_wait_ms": 5.0, "dispatch_ms": 1.0, "execute_ms": 4.0},
        {"dur_ms": 10.0, "data_wait_ms": 0.0, "dispatch_ms": 2.0, "execute_ms": 8.0},
    ]
    assert goodput(recs) == pytest.approx(0.75)
    assert goodput([]) is None


# --------------------------------------------------------------------- #
# HBM sampling + drift
# --------------------------------------------------------------------- #


def test_hbm_drift_event_fires_over_threshold(tmp_path):
    path = str(tmp_path / "run.jsonl")
    log = EventLog(path, rank=0)
    stats = {"bytes_in_use": 100, "peak_bytes_in_use": 130 * 2**20, "bytes_limit": 16 * 2**30}
    sampler = HBMSampler(log, static_peak_bytes=100 * 2**20, stats_fn=lambda: stats)
    sampler.sample()
    assert sampler.drift_event is not None  # 30% > 20%
    assert sampler.drift_event["rel_error"] == pytest.approx(0.3)
    sampler.sample()  # drift reported ONCE, not per sample
    log.close()
    drift = [e for e in read_events(path) if e["name"] == "hbm_drift"]
    static = [e for e in read_events(path) if e["name"] == "hbm_static_estimate"]
    assert len(drift) == 1 and len(static) == 1
    assert static[0]["bytes"] == 100 * 2**20


def test_hbm_no_drift_under_threshold():
    stats = {"bytes_in_use": 0, "peak_bytes_in_use": 110 * 2**20, "bytes_limit": 0}
    sampler = HBMSampler(static_peak_bytes=100 * 2**20, stats_fn=lambda: stats)
    sampler.sample()
    assert sampler.drift_event is None  # 10% < 20%
    assert sampler.observed_peak_bytes == 110 * 2**20


def test_hbm_sampler_degrades_when_backend_reports_nothing():
    sampler = HBMSampler(stats_fn=lambda: None)
    assert sampler.sample() is None and sampler.samples == 0


# --------------------------------------------------------------------- #
# summarize + CLI
# --------------------------------------------------------------------- #


def _make_run_jsonl(tmp_path):
    import jax.numpy as jnp

    path = str(tmp_path / "run.jsonl")
    stats = {"bytes_in_use": 1 << 20, "peak_bytes_in_use": 130 << 20, "bytes_limit": 16 << 30}
    tel = Telemetry(
        path, rank=0, warmup_steps=1, hbm_sample_every=1,
        static_hbm_bytes=100 << 20,
        flops_per_step=2 * 32**3, peak_flops_per_device=1e12,
    )
    tel.hbm._stats_fn = lambda: stats
    step = tel.wrap(_jit_step())
    x = jnp.ones((32, 32))
    for _ in range(5):
        step(x)
    step(jnp.ones((16, 16)))  # one recompile
    tel.close()
    return path


def test_summarize_reports_every_headline(tmp_path):
    path = _make_run_jsonl(tmp_path)
    report = summarize_file(path)
    steps = report["steps"]
    assert steps["count"] == 6 and steps["recompiles"] == 1
    assert steps["p50_step_ms"] is not None and steps["p95_step_ms"] is not None
    assert steps["compile_ms"] > 0 and steps["mfu"] is not None
    assert steps["recompile_details"][0]["changed"]
    hbm = report["hbm"]
    assert hbm["observed_peak_bytes"] == 130 << 20
    assert hbm["static_peak_bytes"] == 100 << 20
    assert hbm["drift_events"] and hbm["drift_events"][0]["rel_error"] == pytest.approx(0.3)
    assert hbm["headroom_bytes"] == (16 << 30) - (130 << 20)
    text = render_text(report)
    for needle in ("step time", "recompiles", "MFU", "observed peak", "static estimate", "DRIFT"):
        assert needle in text, text


def test_summarize_empty_and_serving_sections():
    assert summarize([])["events"] == 0
    report = summarize([
        {"kind": "counter", "name": "serving.tokens_generated", "value": 10},
        {"kind": "counter", "name": "serving.tokens_generated", "value": 42},
    ])
    assert report["serving"]["tokens_generated"] == 42  # last write wins
    assert "tokens_generated" in render_text(report)


@pytest.mark.slow
def test_cli_summarize_text_and_json(tmp_path):
    path = _make_run_jsonl(tmp_path)
    out = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.cli", "telemetry", "summarize", path],
        capture_output=True, text=True, env=CPU_ENV, timeout=240,
    )
    assert out.returncode == 0, out.stderr
    assert "step time" in out.stdout and "recompiles" in out.stdout
    out = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.cli", "telemetry", "summarize", path, "--format", "json"],
        capture_output=True, text=True, env=CPU_ENV, timeout=240,
    )
    assert out.returncode == 0, out.stderr
    parsed = json.loads(out.stdout)
    assert parsed["steps"]["recompiles"] == 1
    # --strict exits nonzero on the recorded recompile warning
    out = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.cli", "telemetry", "summarize", path, "--strict"],
        capture_output=True, text=True, env=CPU_ENV, timeout=240,
    )
    assert out.returncode == 1


@pytest.mark.slow
def test_cli_telemetry_selfcheck():
    out = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.cli", "telemetry", "selfcheck"],
        capture_output=True, text=True, env=CPU_ENV, timeout=240,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "OK" in out.stdout


# --------------------------------------------------------------------- #
# Accelerator wiring
# --------------------------------------------------------------------- #


def _regression_setup(acc):
    import optax

    from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel

    model = acc.prepare_model(RegressionModel())
    opt = acc.prepare_optimizer(optax.sgd(0.1))
    dl = acc.prepare_data_loader(RegressionDataset(length=64, seed=0), batch_size=16)

    def loss_fn(p, b):
        pred = model.apply_fn(p, b["x"])
        return ((pred - b["y"]) ** 2).mean()

    return model, opt, dl, loss_fn


def test_accelerator_telemetry_end_to_end(tmp_path):
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import TelemetryKwargs

    acc = Accelerator(
        project_dir=str(tmp_path),
        kwargs_handlers=[TelemetryKwargs(hbm_sample_every=1, forward_to_trackers_every=0)],
    )
    model, opt, dl, loss_fn = _regression_setup(acc)
    step = acc.telemetry.wrap(acc.build_train_step(loss_fn))
    for _ in range(4):
        for batch in dl:
            step(batch)
    acc.telemetry.close()
    path = str(tmp_path / "telemetry.jsonl")
    assert acc.telemetry.path == path and os.path.exists(path)
    events = read_events(path)
    assert [e for e in events if e["kind"] == "span" and e["name"] == "step"]
    # prepare() marker was emitted only if telemetry existed then; this run
    # created it after prepare — summary still complete
    summary = acc.telemetry.summary()
    assert summary["steps"] == 4 and summary["recompiles"] == 0


def test_accelerator_accumulate_times_imperative_steps(tmp_path):
    from accelerate_tpu import Accelerator

    acc = Accelerator(project_dir=str(tmp_path))
    model, opt, dl, loss_fn = _regression_setup(acc)
    acc.telemetry  # arm telemetry BEFORE the loop so accumulate records
    batch = next(iter(dl))
    for _ in range(3):
        with acc.accumulate():
            acc.backward(loss_fn, batch)
            opt.step()
    assert acc.telemetry.steps.step_index == 3
    recs = list(acc.telemetry.steps.records)
    assert all(r["dur_ms"] >= 0 for r in recs)


def test_accelerator_prepare_marker_when_telemetry_armed(tmp_path):
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.test_utils.training import RegressionModel

    acc = Accelerator(project_dir=str(tmp_path))
    acc.telemetry  # arm first
    acc.prepare(RegressionModel(), optax.sgd(0.1))
    acc.telemetry.close()
    events = read_events(str(tmp_path / "telemetry.jsonl"))
    markers = [e for e in events if e["name"] == "prepare"]
    assert markers and markers[-1]["models"] == 1 and markers[-1]["optimizers"] == 1
    assert "mesh" in markers[-1] and markers[-1]["mixed_precision"] == "no"


def test_telemetry_forwards_to_trackers(tmp_path):
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import TelemetryKwargs

    acc = Accelerator(
        log_with="jsonl",
        project_dir=str(tmp_path),
        kwargs_handlers=[TelemetryKwargs(forward_to_trackers_every=2, hbm_sample_every=0)],
    )
    acc.init_trackers("proj")
    model, opt, dl, loss_fn = _regression_setup(acc)
    step = acc.telemetry.wrap(acc.build_train_step(loss_fn))
    batch = next(iter(dl))
    for _ in range(6):
        step(batch)
    acc.end_training()
    lines = [json.loads(l) for l in (tmp_path / "proj" / "metrics.jsonl").read_text().splitlines()]
    forwarded = [l for l in lines if any(k.startswith("telemetry/") for k in l)]
    assert forwarded, lines
    assert any("telemetry/step_ms" in l for l in forwarded)
    assert all(l["telemetry/recompiles"] == 0 for l in forwarded)


def test_telemetry_disabled_keeps_in_memory_summary(tmp_path):
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import TelemetryKwargs

    acc = Accelerator(project_dir=str(tmp_path), kwargs_handlers=[TelemetryKwargs(enabled=False)])
    model, opt, dl, loss_fn = _regression_setup(acc)
    step = acc.telemetry.wrap(acc.build_train_step(loss_fn))
    batch = next(iter(dl))
    for _ in range(3):
        step(batch)
    assert acc.telemetry.path is None
    assert not os.path.exists(str(tmp_path / "telemetry.jsonl"))
    assert acc.telemetry.summary()["steps"] == 3


def test_flight_check_seeds_static_hbm_estimate(tmp_path):
    import jax.numpy as jnp

    from accelerate_tpu import Accelerator

    acc = Accelerator(project_dir=str(tmp_path))

    def step_fn(x):
        return (x * 2.0).sum()

    acc.telemetry  # arm
    report = acc.flight_check(step_fn, jnp.ones((128, 128), jnp.float32))
    if report.peak_hbm_bytes:
        assert acc.telemetry.hbm.static_peak_bytes == report.peak_hbm_bytes
        acc.telemetry.close()
        events = read_events(str(tmp_path / "telemetry.jsonl"))
        assert any(e["name"] == "hbm_static_estimate" for e in events)


def test_profile_kwargs_passthrough_warns_once_for_dropped(tmp_path, caplog):
    """jax 0.4.37 has no profiler options: non-default tracer levels must
    warn exactly once per process and the trace must still run; on newer
    jax they pass through silently."""
    import inspect
    import logging

    import jax

    from accelerate_tpu import Accelerator, accelerator as accel_mod
    from accelerate_tpu.utils import ProfileKwargs

    acc = Accelerator(project_dir=str(tmp_path))
    handler = ProfileKwargs(output_trace_dir=str(tmp_path / "prof"), host_tracer_level=3)
    accel_mod._dropped_profile_options_warned = False
    with caplog.at_level(logging.WARNING):
        with acc.profile(handler):
            pass
        with acc.profile(handler):  # second use: no second warning
            pass
    supported = (
        getattr(jax.profiler, "ProfileOptions", None) is not None
        and "profiler_options" in inspect.signature(jax.profiler.start_trace).parameters
    )
    drop_warnings = [r for r in caplog.records if "ProfileKwargs option" in r.getMessage()]
    if supported:
        assert not drop_warnings
    else:
        assert len(drop_warnings) == 1
        assert "host_tracer_level" in drop_warnings[0].getMessage()
    assert any(os.scandir(str(tmp_path / "prof")))


def test_profile_create_perfetto_link_reaches_start_trace(tmp_path, monkeypatch):
    import jax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import ProfileKwargs

    seen = {}

    def fake_start(log_dir, create_perfetto_link=False, create_perfetto_trace=False):
        seen.update(
            create_perfetto_link=create_perfetto_link,
            create_perfetto_trace=create_perfetto_trace,
            log_dir=log_dir,
        )

    monkeypatch.setattr(jax.profiler, "start_trace", fake_start)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    acc = Accelerator(project_dir=str(tmp_path))
    with acc.profile(ProfileKwargs(output_trace_dir=str(tmp_path), create_perfetto_link=True)):
        pass
    assert seen.get("create_perfetto_link") is True


def test_watchdog_state_is_per_wrapper():
    """A function wrapped AFTER other steps already ran gets its own
    warmup: its first compiles are attributed, not misreported as
    recompiles (regression: global warmup counted imperative steps)."""
    import jax
    import jax.numpy as jnp

    st = StepTelemetry(warmup_steps=2)
    # imperative steps consume global step_index first
    for _ in range(5):
        with st.step() as h:
            h.done(None)
    step_a = st.wrap(jax.jit(lambda x: (x @ x).sum()))
    x = jnp.ones((24, 24))
    for _ in range(4):
        step_a(x)
    assert st.recompiles == 0  # step_a's first compile was warmup, not a miss
    # a second independently wrapped function likewise gets fresh warmup
    step_b = st.wrap(jax.jit(lambda x: (x + 1).sum()))
    for _ in range(3):
        step_b(x)
    assert st.recompiles == 0
    # but a genuine post-warmup shape change on either wrapper still fires
    step_a(jnp.ones((12, 12)))
    assert st.recompiles == 1


# --------------------------------------------------------------------- #
# NonFiniteWatchdog (the runtime counterpart of numerics TPU602)
# --------------------------------------------------------------------- #


def test_nonfinite_watchdog_cadence_latch_and_trajectory(tmp_path):
    import math

    from accelerate_tpu.telemetry import NonFiniteWatchdog
    from accelerate_tpu.telemetry.eventlog import EventLog, read_events

    path = str(tmp_path / "run.jsonl")
    log = EventLog(path, rank=0)
    wd = NonFiniteWatchdog(log, every=2)
    assert wd.enabled
    # off-cadence steps probe nothing
    assert wd.observe(1, loss=float("nan")) is None
    for step in range(0, 6, 2):
        rec = wd.observe(step, loss=1.0, grad_norm=0.5, loss_scale=2.0**15)
        assert rec["bad_leaf"] is None
    assert wd.probes == 3 and wd.nonfinite_event is None
    # a backoff followed by the overflow: one latched event, scale staircase kept
    wd.observe(6, loss=1.0, loss_scale=2.0**14)
    wd.observe(8, loss=float("inf"), loss_scale=2.0**13)
    wd.observe(10, loss=float("nan"), loss_scale=2.0**13)  # latched: no 2nd event
    assert wd.nonfinite_event is not None
    assert wd.nonfinite_event["leaf"] == "loss"
    assert wd.scale_backoffs == 2
    log.close()
    events = read_events(path)
    assert sum(1 for e in events if e.get("name") == "nonfinite") == 1
    scales = [e for e in events if e.get("name") == "loss_scale"]
    assert [e["scale"] for e in scales] == [2.0**15, 2.0**14, 2.0**13]
    s = wd.summary()
    assert s["nonfinite"] and s["first_bad_leaf"] == "loss"
    assert s["loss_scale"]["backoffs"] == 2 and s["loss_scale"]["max"] == 2.0**15
    assert not math.isnan(s["loss_scale"]["current"])


def test_nonfinite_watchdog_names_first_bad_grad_leaf():
    import numpy as np

    from accelerate_tpu.telemetry import NonFiniteWatchdog

    wd = NonFiniteWatchdog(every=1)
    rec = wd.observe(
        0, grads={"w": np.ones(4), "inner": {"b": np.array([0.0, float("nan")])}}
    )
    assert rec["bad_leaf"] == "grads['inner']['b']"
    assert wd.nonfinite_event["leaf"] == "grads['inner']['b']"


def test_nonfinite_summarize_section(tmp_path):
    from accelerate_tpu.telemetry import NonFiniteWatchdog
    from accelerate_tpu.telemetry.eventlog import EventLog
    from accelerate_tpu.telemetry.summarize import render_text, summarize_file

    path = str(tmp_path / "run.jsonl")
    log = EventLog(path, rank=0)
    wd = NonFiniteWatchdog(log, every=1)
    wd.observe(0, loss=1.0, loss_scale=1024.0)
    wd.observe(1, loss=float("nan"), loss_scale=512.0)
    log.close()
    report = summarize_file(path)
    assert report["nonfinite"]["events"][0]["leaf"] == "loss"
    assert report["nonfinite"]["loss_scale"]["backoffs"] == 1
    text = render_text(report)
    assert "NONFINITE at step 1" in text and "loss scale" in text


def test_fast_path_probes_nonfinite_watchdog(tmp_path):
    """TelemetryKwargs(nonfinite_every=N) wires the probe into the fast
    path: a clean run stays silent; the fp16 loss-scale value lands in
    the trajectory."""
    import numpy as np
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.test_utils import RegressionDataset, RegressionModel, linear_loss_fn
    from accelerate_tpu.utils import TelemetryKwargs

    path = str(tmp_path / "run.jsonl")
    acc = Accelerator(
        mixed_precision="fp16",
        kwargs_handlers=[TelemetryKwargs(output_path=path, nonfinite_every=2)],
    )
    acc.telemetry  # arm before building the step
    model = acc.prepare_model(RegressionModel())
    acc.prepare_optimizer(optax.sgd(0.05))
    loader = acc.prepare_data_loader(RegressionDataset(length=64))
    loader.batch_size = 16 // max(1, acc.num_data_shards)
    step = acc.build_train_step(linear_loss_fn)
    done = 0
    while done < 6:
        for batch in loader:
            step(batch)
            done += 1
            if done >= 6:
                break
    wd = acc.telemetry.nonfinite
    assert wd.probes >= 2
    # grad overflow during fp16 scale calibration is the SCALER's job
    # (skip + backoff), counted but never latched; the loss stays finite
    assert wd.nonfinite_event is None
    assert wd.scale_trajectory and wd.scale_trajectory[-1][1] >= 1.0
    summary = acc.telemetry.summary()
    assert summary["nonfinite"]["nonfinite"] is False
    assert summary["nonfinite"]["scaler_skips"] >= 0


def test_wire_counter_records_and_flags_drift(tmp_path):
    """Telemetry.record_wire_bytes: the predicted/measured byte pair lands
    as a wire_bytes event, accumulates in summary(), and disagreement past
    the threshold fires the warning twin (the perf_model_drift discipline
    applied to bytes)."""
    from accelerate_tpu.telemetry import Telemetry, read_events

    path = str(tmp_path / "wire.jsonl")
    tel = Telemetry(path)
    ok = tel.record_wire_bytes(1000, 1005, label="step")
    assert ok["drift"] <= 0.01
    bad = tel.record_wire_bytes(1000, 2000, label="step")
    assert bad["drift"] == 1.0
    tel.close()
    events = [e for e in read_events(path) if e.get("name") == "wire_bytes"]
    assert len(events) == 2
    assert events[0]["severity"] == "info" and events[1]["severity"] == "warning"
    assert tel.summary()["wire_bytes"][0]["predicted_bytes"] == 1000


def test_hlo_wire_bytes_parses_collectives():
    """The HLO wire counter prices list- and iota-form replica groups and
    tuple-shaped collectives through the shared costmodel ring formulas."""
    from accelerate_tpu.analysis.costmodel import ring_wire_bytes
    from accelerate_tpu.telemetry.wire import hlo_wire_bytes

    hlo = "\n".join([
        "  %all-reduce = f32[128]{0} all-reduce(f32[128]{0} %x), replica_groups=[1,8]<=[8]",
        "  %all-gather.1 = s8[64]{0} all-gather(s8[8]{0} %y), replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}",
        "  %reduce-scatter.2 = f32[16]{0} reduce-scatter(f32[128]{0} %z), replica_groups=[1,8]<=[8]",
        "  %all-to-all.3 = (s8[1,8]{1,0}, s8[1,8]{1,0}) all-to-all(s8[1,8]{1,0} %a, /*index=1*/s8[1,8]{1,0} %b), replica_groups={{0,1}}",
        "  %tuple = (f32[4]{0}) tuple(f32[4]{0} %w)",  # not a collective
    ])
    out = hlo_wire_bytes(hlo)
    assert out["by_primitive"]["psum"] == ring_wire_bytes("psum", 128 * 4, 8)
    assert out["by_primitive"]["all_gather"] == ring_wire_bytes("all_gather", 64, 8)
    assert out["by_primitive"]["reduce_scatter"] == ring_wire_bytes("reduce_scatter", 16 * 4 * 8, 8)
    assert out["by_primitive"]["all_to_all"] == ring_wire_bytes("all_to_all", 16, 2)
    assert out["total"] == sum(out["by_primitive"].values())
    assert len(out["sites"]) == 4


def test_wire_dtype_upcast_detection_and_one_time_warning():
    """A compressed wire whose dominant collective moves a wider dtype
    than requested (the XLA:CPU bf16->f32 upcast) fires ONE
    ``wire_dtype_upcast`` warning naming the platform; a narrow wire and
    small wide control collectives stay silent."""
    from accelerate_tpu.telemetry import Telemetry
    from accelerate_tpu.telemetry.wire import hlo_collective_sites, wire_dtype_upcast

    upcast_hlo = "\n".join([
        # the big gradient leg got upcast to f32...
        "  %ar = f32[4096]{0} all-reduce(f32[4096]{0} %g), replica_groups=[1,8]<=[8]",
        # ...while a tiny f32 loss pmean is legitimate next to any scheme
        "  %loss = f32[] all-reduce(f32[] %l), replica_groups=[1,8]<=[8]",
    ])
    sites = hlo_collective_sites(upcast_hlo)
    assert sites[0]["dtypes"] == {"f32": 4096 * 4}
    up = wire_dtype_upcast(sites, "bf16")
    assert up["measured_dtype"] == "f32" and up["requested_bytes"] == 2
    narrow = hlo_collective_sites(
        "  %ar = bf16[4096]{0} all-reduce(bf16[4096]{0} %g), replica_groups=[1,8]<=[8]\n"
        "  %loss = f32[] all-reduce(f32[] %l), replica_groups=[1,8]<=[8]\n"
    )
    assert wire_dtype_upcast(narrow, "bf16") is None  # dominant site is narrow
    assert wire_dtype_upcast(sites, None) is None  # no compression requested

    tel = Telemetry(None)
    r1 = tel.record_wire_bytes(
        100, 100, requested_wire_dtype="bf16", sites=sites, platform="cpu"
    )
    assert r1["dtype_upcast"]["measured_dtype"] == "f32"
    r2 = tel.record_wire_bytes(
        100, 100, requested_wire_dtype="bf16", sites=sites, platform="cpu"
    )
    assert "dtype_upcast" not in r2, "warning must latch after the first firing"
