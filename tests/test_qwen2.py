"""Qwen2 family (models/qwen2.py): biased q/k/v through decode, TP
sharding of the bias vectors, and serving. HF importer parity lives in
test_hf_parity.py."""

import numpy as np
import pytest

from accelerate_tpu.generation import generate
from accelerate_tpu.models import Qwen2Config, create_qwen2_model


@pytest.fixture(scope="module")
def tiny_qwen2():
    return create_qwen2_model(Qwen2Config.tiny(), seq_len=16)


def test_bias_params_exist(tiny_qwen2):
    block = tiny_qwen2.params["layers"]["block"]["attn"]
    for proj in ("q_proj", "k_proj", "v_proj"):
        assert "bias" in block[proj], proj
    assert "bias" not in block["o_proj"]


def test_greedy_decode_matches_full_prefix(tiny_qwen2):
    ids = (np.arange(2 * 8).reshape(2, 8) % 250 + 1).astype(np.int32)
    out = np.asarray(generate(tiny_qwen2, ids, max_new_tokens=6))
    full = ids
    for _ in range(6):
        logits = np.asarray(tiny_qwen2(full))
        full = np.concatenate([full, logits[:, -1].argmax(-1).astype(np.int32)[:, None]], 1)
    np.testing.assert_array_equal(out, full)


def test_tp_sharded_bias_decode(tiny_qwen2):
    """The bias sharding rules split q/k/v biases over `tensor` with
    their kernels: TP-sharded greedy tokens == single-device tokens."""
    import jax

    from accelerate_tpu.big_modeling import shard_model
    from accelerate_tpu.parallel.mesh import MeshConfig

    prompt = (np.arange(8) % 250).astype(np.int32)[None]
    want = np.asarray(generate(tiny_qwen2, prompt, max_new_tokens=5))

    model = create_qwen2_model(Qwen2Config.tiny(), seq_len=16)
    mesh = MeshConfig(data=1, tensor=2).build(jax.devices()[:2])
    shard_model(model, mesh)
    bias_sh = model.param_shardings["layers"]["block"]["attn"]["q_proj"]["bias"]
    assert "tensor" in str(bias_sh.spec), bias_sh.spec  # actually split, not replicated
    got = np.asarray(generate(model, prompt, max_new_tokens=5))
    np.testing.assert_array_equal(got, want)


def test_paged_serving(tiny_qwen2):
    from accelerate_tpu.serving import ServingEngine

    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 250, size=n).astype(np.int32) for n in (3, 9, 6)]
    eng = ServingEngine(tiny_qwen2, num_slots=2, prompt_buckets=(4, 8, 16), paged_block_size=4)
    outs = eng.generate_many(prompts, max_new_tokens=5)
    for p, got in zip(prompts, outs):
        ref = np.asarray(generate(tiny_qwen2, p[None], max_new_tokens=5))[0]
        np.testing.assert_array_equal(got, ref)
