"""Unit tests for the small support modules (reference analogues:
tests/test_scheduler.py, test_optimizer.py, test_memory_utils.py,
test_logging.py, test_kwargs_handlers.py)."""

import logging

import jax
import numpy as np
import optax
import pytest

from accelerate_tpu.scheduler import AcceleratedScheduler
from accelerate_tpu.utils.memory import (
    find_executable_batch_size,
    release_memory,
    should_reduce_batch_size,
)
from accelerate_tpu.utils.random import key_for_step, set_seed, synchronize_rng_states


# -------------------------- memory --------------------------------------


def test_find_executable_batch_size_halves_on_oom():
    attempts = []

    @find_executable_batch_size(starting_batch_size=64)
    def train(batch_size):
        attempts.append(batch_size)
        if batch_size > 16:
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory allocating")
        return batch_size

    assert train() == 16
    assert attempts == [64, 32, 16]


def test_find_executable_batch_size_reraises_non_oom():
    @find_executable_batch_size(starting_batch_size=8)
    def train(batch_size):
        raise ValueError("not an oom")

    with pytest.raises(ValueError):
        train()


def test_find_executable_batch_size_exhausted():
    @find_executable_batch_size(starting_batch_size=2)
    def train(batch_size):
        raise RuntimeError("RESOURCE_EXHAUSTED")

    with pytest.raises(RuntimeError, match="No executable batch size|RESOURCE_EXHAUSTED"):
        train()


def test_should_reduce_batch_size_patterns():
    assert should_reduce_batch_size(RuntimeError("RESOURCE_EXHAUSTED: HBM"))
    assert should_reduce_batch_size(MemoryError("Ran out of memory"))
    assert not should_reduce_batch_size(ValueError("shape mismatch"))


def test_release_memory_rebinds_to_none():
    a, b = np.ones(4), np.ones(4)
    a, b = release_memory(a, b)
    assert a is None and b is None


# -------------------------- scheduler -----------------------------------


def test_scheduler_scales_by_data_shards_and_roundtrips():
    sched = AcceleratedScheduler(optax.linear_schedule(1.0, 0.0, 100), optimizers=None)
    n = sched._data_shards()
    sched.step()
    assert sched.step_count == n
    lr = sched.get_last_lr()[0]
    assert lr == pytest.approx(1.0 - n / 100)
    state = sched.state_dict()
    sched2 = AcceleratedScheduler(optax.linear_schedule(1.0, 0.0, 100), optimizers=None)
    sched2.load_state_dict(state)
    assert sched2.step_count == sched.step_count


def test_scheduler_split_batches_no_scaling():
    sched = AcceleratedScheduler(
        optax.linear_schedule(1.0, 0.0, 100), optimizers=None, split_batches=True
    )
    sched.step()
    assert sched.step_count == 1


# -------------------------- rng -----------------------------------------


def test_set_seed_reproducible_key_chain():
    set_seed(123)
    k1 = key_for_step(5)
    set_seed(123)
    k2 = key_for_step(5)
    assert jax.random.uniform(k1) == jax.random.uniform(k2)
    k3 = key_for_step(6)
    assert jax.random.uniform(k2) != jax.random.uniform(k3)


def test_key_for_step_extra_folds_differ():
    set_seed(0)
    base = key_for_step(1)
    folded = key_for_step(1, 7)
    assert jax.random.uniform(base) != jax.random.uniform(folded)


def test_set_seed_seeds_python_and_numpy():
    import random as pyrandom

    set_seed(99)
    a = (pyrandom.random(), np.random.rand())
    set_seed(99)
    b = (pyrandom.random(), np.random.rand())
    assert a == b


def test_synchronize_rng_states_runs():
    synchronize_rng_states(["numpy", "python"])  # single process: no-op path


# -------------------------- logging -------------------------------------


def test_get_logger_main_process_only(caplog):
    from accelerate_tpu.logging import get_logger

    logger = get_logger("accelerate_tpu.test_unit")
    with caplog.at_level(logging.INFO, logger="accelerate_tpu.test_unit"):
        logger.info("visible", main_process_only=True)
    assert any("visible" in r.message for r in caplog.records)


def test_warning_once_dedups(caplog):
    from accelerate_tpu.logging import get_logger

    logger = get_logger("accelerate_tpu.test_unit2")
    with caplog.at_level(logging.WARNING, logger="accelerate_tpu.test_unit2"):
        logger.warning_once("only once please")
        logger.warning_once("only once please")
    assert sum("only once please" in r.message for r in caplog.records) == 1


# -------------------------- kwargs / dataclasses ------------------------


def test_mesh_config_from_env(monkeypatch):
    from accelerate_tpu.parallel.mesh import MeshConfig

    monkeypatch.setenv("ACCELERATE_MESH_DATA", "2")
    monkeypatch.setenv("ACCELERATE_MESH_TENSOR", "4")
    cfg = MeshConfig.from_env()
    assert cfg.data == 2 and cfg.tensor == 4


def test_precision_type_rejects_unknown():
    from accelerate_tpu.utils.dataclasses import PrecisionType

    with pytest.raises(ValueError):
        PrecisionType("fp64x")


def test_gradient_accumulation_plugin_validation():
    from accelerate_tpu.utils.dataclasses import GradientAccumulationPlugin

    plugin = GradientAccumulationPlugin(num_steps=4)
    assert plugin.num_steps == 4
    with pytest.raises((ValueError, TypeError)):
        GradientAccumulationPlugin(num_steps=0)


def test_get_free_port_is_bindable():
    """get_free_port returns a port another socket can immediately bind
    (reference: utils/other.py get_free_port)."""
    import socket

    from accelerate_tpu.utils.environment import get_free_port

    port = get_free_port()
    assert 1024 <= port <= 65535
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", port))  # must not raise


def test_launch_resolves_port_once_for_multiprocess(tmp_path):
    """A 2-process launch without --main_process_port picks one free port
    for the whole group (per-rank resolution would deadlock rendezvous)."""
    import os
    import subprocess
    import sys

    script = tmp_path / "s.py"
    script.write_text(
        "from accelerate_tpu import Accelerator\n"
        "acc = Accelerator()\n"
        "assert acc.num_processes == 2\n"
        "print('PORT_OK', acc.process_index)\n"
    )
    env = {**os.environ, "PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu"}
    result = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.cli", "launch",
         "--num_processes", "2", "--cpu", "--fake_devices", "4", str(script)],
        capture_output=True, text=True, env=env, timeout=240,
    )
    assert result.returncode == 0, result.stderr + result.stdout
    assert result.stdout.count("PORT_OK") >= 1


def test_api_docs_generator_is_deterministic():
    """scripts/gen_api_docs.py must be reproducible (no memory-address
    reprs) and cover the core public surface."""
    import importlib.util
    import pathlib

    spec = importlib.util.spec_from_file_location(
        "gen_api_docs", pathlib.Path(__file__).parent.parent / "scripts" / "gen_api_docs.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    page = mod.render_module("accelerate_tpu.accelerator")
    assert page == mod.render_module("accelerate_tpu.accelerator")  # deterministic
    assert "0x" not in page
    assert "build_train_step" in page and "gather_for_metrics" in page
    ops_page = mod.render_module("accelerate_tpu.ops.qdense")
    assert "QuantDense" in ops_page and "0x" not in ops_page
