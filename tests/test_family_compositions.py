"""Cross-feature composition checks for the newer model families: the
sliding-window band and qkv-bias/tied-head variants must ride the same
speculative-decoding, beam-search, and LoRA machinery as llama — these
are the compositions no single-feature suite exercises."""

import numpy as np
import pytest

from accelerate_tpu.generation import beam_search, generate
from accelerate_tpu.models import (
    GemmaConfig,
    MistralConfig,
    Qwen2Config,
    create_gemma_model,
    create_mistral_model,
    create_qwen2_model,
)
from accelerate_tpu.speculative import speculative_generate


@pytest.fixture(scope="module")
def mistral():
    return create_mistral_model(MistralConfig.tiny(sliding_window=4), seq_len=16)


def test_speculative_windowed_target_token_exact(mistral):
    """Speculative decode against a WINDOWED target: the verify/rollback
    frontier math must respect the band (the draft is an unwindowed
    llama-alike — realistic and maximally mismatched)."""
    draft = create_mistral_model(MistralConfig.tiny(sliding_window=None), seed=7, seq_len=16)
    ids = (np.arange(8) % 250).astype(np.int32)[None]
    want = np.asarray(generate(mistral, ids, max_new_tokens=8))
    for gamma in (2, 4):
        got = np.asarray(speculative_generate(mistral, draft, ids, max_new_tokens=8, gamma=gamma))
        np.testing.assert_array_equal(got, want)


def test_beam_search_windowed_beam1_equals_greedy(mistral):
    ids = (np.arange(6) % 250 + 1).astype(np.int32)[None]
    greedy = np.asarray(generate(mistral, ids, max_new_tokens=5))
    got = np.asarray(beam_search(mistral, ids, max_new_tokens=5, num_beams=1))
    np.testing.assert_array_equal(got, greedy)


@pytest.mark.parametrize(
    "factory,cfg",
    [
        (create_qwen2_model, Qwen2Config.tiny()),  # qkv bias
        (create_gemma_model, GemmaConfig.tiny()),  # tied head + head_dim + MQA
    ],
    ids=["qwen2", "gemma"],
)
def test_lora_finetune_on_new_families(factory, cfg):
    """LoRA adapters attach to the new families' projections and train
    (the adapter regexes target q/v kernels, which all families share)."""
    import jax
    import optax

    from accelerate_tpu.models.llama import causal_lm_loss
    from accelerate_tpu.utils.lora import LoRAConfig, lora_init, lora_merge

    model = factory(cfg, seq_len=16)
    lcfg = LoRAConfig(rank=4, alpha=8.0)
    lora = lora_init(jax.random.key(0), model.params, lcfg)
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(1, 250, size=(2, 16)).astype(np.int32)}

    def loss_fn(trainable):
        merged = lora_merge(model.params, trainable, lcfg)
        return causal_lm_loss(merged, batch, model.apply_fn)

    opt = optax.adam(1e-2)
    state = opt.init(lora)
    losses = []
    for _ in range(15):
        loss, grads = jax.value_and_grad(loss_fn)(lora)
        updates, state = opt.update(grads, state)
        lora = optax.apply_updates(lora, updates)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], (losses[0], losses[-1])


@pytest.mark.parametrize(
    "family",
    ["qwen3", "olmo2", "gemma3"],
)
def test_round5_families_compose_with_lora_quant_speculative(family):
    """The round-5 knobs (qk-norm, post-norms, sandwich+dual-rope) ride the
    same LoRA, quantization, and speculative machinery as llama — one
    smoke per family keeps every new structural variant composed."""
    import jax

    from accelerate_tpu.models import (
        Gemma3Config,
        Olmo2Config,
        Qwen3Config,
        create_gemma3_model,
        create_olmo2_model,
        create_qwen3_model,
    )
    from accelerate_tpu.models.llama import causal_lm_loss
    from accelerate_tpu.utils.lora import LoRAConfig, lora_init, lora_merge
    from accelerate_tpu.utils.quantization import QuantizationConfig, load_and_quantize_model

    factory, cfg = {
        "qwen3": (create_qwen3_model, Qwen3Config.tiny()),
        "olmo2": (create_olmo2_model, Olmo2Config.tiny()),
        "gemma3": (create_gemma3_model, Gemma3Config.tiny()),
    }[family]
    rng = np.random.default_rng(0)
    ids = rng.integers(1, 250, size=(1, 8)).astype(np.int32)
    model = factory(cfg, seq_len=16)

    # speculative with a same-family draft: token-exact
    want = np.asarray(generate(model, ids, max_new_tokens=5))
    got = np.asarray(speculative_generate(model, model, ids, max_new_tokens=5, gamma=2))
    np.testing.assert_array_equal(got, want)

    # LoRA step on the variant projections
    lcfg = LoRAConfig(rank=2, alpha=4.0)
    lora = lora_init(jax.random.key(0), model.params, lcfg)
    batch = {"input_ids": rng.integers(1, 250, size=(2, 16)).astype(np.int32)}

    def loss_fn(trainable):
        merged = lora_merge(model.params, trainable, lcfg)
        return causal_lm_loss(merged, batch, model.apply_fn)

    loss, grads = jax.value_and_grad(loss_fn)(lora)
    assert np.isfinite(float(loss))
    assert any(float(np.abs(np.asarray(g)).max()) > 0 for g in jax.tree.leaves(grads))

    # weight-only int8 quantization: forward stays finite
    qmodel = load_and_quantize_model(factory(cfg, seq_len=16), QuantizationConfig(bits=8, method="int8"))
    assert np.isfinite(np.asarray(qmodel(ids))).all()
