"""Qwen3-MoE family (models/qwen3_moe.py): Qwen3 attention + routed
experts through training on the expert mesh. HF importer parity lives in
test_hf_parity.py."""

import numpy as np
import pytest

from accelerate_tpu.models import Qwen3MoeConfig, create_qwen3_moe_model


@pytest.fixture(scope="module")
def tiny_moe():
    return create_qwen3_moe_model(Qwen3MoeConfig.tiny(), seq_len=16)


def test_structure(tiny_moe):
    cfg = Qwen3MoeConfig.tiny()
    layer0 = tiny_moe.params["layer_0"]
    assert layer0["attn"]["q_norm"]["scale"].shape == (cfg.head_dim,)  # qwen3 qk-norm
    assert layer0["moe"]["experts/gate_proj"].shape == (
        cfg.num_local_experts, cfg.hidden_size, cfg.moe_intermediate_size,
    )  # separate (narrow) expert width


def test_forward_finite_both_routing_conventions():
    ids = (np.arange(2 * 16).reshape(2, 16) % 200 + 1).astype(np.int32)
    for norm_topk in (True, False):
        m = create_qwen3_moe_model(Qwen3MoeConfig.tiny(norm_topk=norm_topk), seq_len=16)
        logits = np.asarray(m(ids))
        assert np.isfinite(logits).all(), norm_topk


def test_trains_on_expert_mesh():
    """Full train step with experts sharded over the expert axis, through
    the Accelerator like any user model (the Mixtral dryrun pattern)."""
    import jax
    import optax

    from accelerate_tpu import Accelerator, ParallelismPlugin
    from accelerate_tpu.models import qwen3_moe_lm_loss
    from accelerate_tpu.parallel.mesh import MeshConfig, batch_sharding, data_parallel_size

    acc = Accelerator(
        mixed_precision="bf16",
        parallelism_plugin=ParallelismPlugin(mesh_config=MeshConfig(expert=2, tensor=2, data=2)),
    )
    model = acc.prepare_model(create_qwen3_moe_model(Qwen3MoeConfig.tiny(), seq_len=16))
    acc.prepare_optimizer(optax.adamw(1e-3))
    step = acc.build_train_step(lambda p, b: qwen3_moe_lm_loss(p, b, module=model.module))
    batch = jax.device_put(
        {"input_ids": np.ones((2 * data_parallel_size(acc.mesh), 16), np.int32)},
        batch_sharding(acc.mesh),
    )
    losses = [float(step(batch)) for _ in range(3)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
