#!/usr/bin/env bash
# One-shot on-chip measurement session (VERDICT r4 #1): run every benchmark
# that needs real TPU hardware and collect JSON into benchmarks/chip_logs/.
# Safe to re-run; each step is independently timeout-guarded so a tunnel
# drop mid-session still leaves the earlier results on disk.
set -u
cd "$(dirname "$0")/.."
OUT=benchmarks/chip_logs
mkdir -p "$OUT"
stamp=$(date +%Y%m%d_%H%M%S)

probe() {
  timeout 90 python -c "import jax; print('ndev', len(jax.devices()), jax.devices()[0].device_kind)" 2>/dev/null
}

if ! probe; then
  echo "chip_session: backend unreachable; aborting" >&2
  exit 2
fi

run_step() { # name, timeout_s, cmd...
  local name=$1 tmo=$2; shift 2
  echo "=== $name ==="
  timeout "$tmo" "$@" 2>&1 | tee "$OUT/${name}_${stamp}.log"
  # the benchmark's status, not tee's (124 = hit the timeout)
  echo "rc=${PIPESTATUS[0]} -> $OUT/${name}_${stamp}.log"
}

# 1. the two headline lines the driver parses
run_step bench 2400 python bench.py

# 2. serving engine: continuous vs static batching (never had chip numbers)
run_step serving 1800 python benchmarks/serving_throughput.py

# 3. paged-attention kernel on hardware: token exactness + ms/token (the
#    ONLY hardware validation of ops/pallas_paged_attention.py)
run_step paged_check 1800 python benchmarks/paged_serving_chip_check.py

# 4. big-model inference: int8/int4 decode confirmation
run_step big_model 2400 python benchmarks/big_model_inference.py

# 5. host-offload micro-bench: step-time cost + HBM saving
run_step offload 1800 python benchmarks/offload_optimizer.py --steps 10

# 6. seq-128 attention kernel A/B (the roofline's named MFU lever)
run_step attn_ab 900 python benchmarks/attn_seq128_ab.py

echo "chip_session: done; logs in $OUT (commit the JSON into benchmarks/README.md tables)"
