#!/usr/bin/env python
"""Zero-dependency repo quality gate — a thin shim over
``accelerate_tpu.analysis`` so ``make quality`` and ``accelerate-tpu lint``
share one rule implementation (the AST tier is stdlib-only, so this script
keeps its zero-extra-dependency property):

1. **import check** (``TPU003``) — every package module imports cleanly on
   the CPU backend. This is the gate that would have caught round 1's
   ``tracking.py`` module-level NameError.
2. **AST tier** (``TPU001`` unused imports, ``TPU002`` module docstrings,
   ``TPU2xx`` TPU hazards) — delegated to
   ``accelerate_tpu.analysis.ast_lint``.

Findings print in the standard ``path:line: TPUxxx message`` format so
editors and CI annotators can parse them. Exit code is nonzero on any
error-severity finding. Run via ``make quality`` (or ``make lint`` for the
CLI equivalent plus the rule selfcheck).
"""

from __future__ import annotations

import pathlib
import sys

REPO = pathlib.Path(__file__).parent.parent
PKG = REPO / "accelerate_tpu"


def check_imports() -> list:
    """Import every package module on the forced-CPU backend (TPU003)."""
    import importlib

    from accelerate_tpu.analysis import Finding

    failures = []
    for path in sorted(PKG.rglob("*.py")):
        rel = path.relative_to(REPO)
        mod = ".".join(rel.with_suffix("").parts)
        if mod.endswith(".__init__"):
            mod = mod[: -len(".__init__")]
        try:
            importlib.import_module(mod)
        except Exception as e:  # noqa: BLE001 — report everything
            failures.append(
                Finding("TPU003", f"import {mod} failed: {type(e).__name__}: {e}", path=str(rel), line=1)
            )
    return failures


def main() -> int:
    # force the CPU platform before anything imports jax — the import check
    # must never touch (or wedge on) a real TPU
    sys.path.insert(0, str(REPO))
    from accelerate_tpu.utils.environment import force_host_platform

    force_host_platform(1)

    from accelerate_tpu.analysis import exit_code, format_finding, lint_paths

    findings = check_imports()
    print(f"[imports] {'OK' if not findings else f'{len(findings)} finding(s)'}")

    ast_findings = lint_paths([PKG])
    n_err = sum(1 for f in ast_findings if f.is_error)
    print(f"[ast lint] {'OK' if not ast_findings else f'{len(ast_findings)} finding(s), {n_err} error(s)'}")

    findings += ast_findings
    for f in findings:
        print(f"  {format_finding(f)}")
    return exit_code(findings)


if __name__ == "__main__":
    raise SystemExit(main())
