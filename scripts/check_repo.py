#!/usr/bin/env python
"""Zero-dependency repo quality gates (reference analogue: the Makefile
quality targets + utils/check_copies.py-style repo checks; the image has no
ruff/flake8, so the checks that matter are implemented directly):

1. **import check** — every package module imports cleanly on the CPU
   backend. This is the gate that would have caught round 1's
   ``tracking.py`` module-level NameError.
2. **unused-import check** — AST scan; names imported but never referenced.
3. **docstring check** — every public module opens with a docstring (the
   project convention: docstrings cite the reference file:line they cover).

Exit code is nonzero on any finding. Run via ``make quality``.
"""

from __future__ import annotations

import ast
import importlib
import pathlib
import sys

REPO = pathlib.Path(__file__).parent.parent
PKG = REPO / "accelerate_tpu"


def iter_modules():
    for path in sorted(PKG.rglob("*.py")):
        rel = path.relative_to(REPO)
        mod = ".".join(rel.with_suffix("").parts)
        if mod.endswith(".__init__"):
            mod = mod[: -len(".__init__")]
        yield mod, path


def check_imports() -> list[str]:
    failures = []
    for mod, _ in iter_modules():
        try:
            importlib.import_module(mod)
        except Exception as e:  # noqa: BLE001 — report everything
            failures.append(f"import {mod}: {type(e).__name__}: {e}")
    return failures


class _NameCollector(ast.NodeVisitor):
    def __init__(self):
        self.used: set[str] = set()

    def visit_Name(self, node):
        self.used.add(node.id)

    def visit_Attribute(self, node):
        # record the root name of dotted access (os.path -> os)
        n = node
        while isinstance(n, ast.Attribute):
            n = n.value
        if isinstance(n, ast.Name):
            self.used.add(n.id)
        self.generic_visit(node)


def check_unused_imports() -> list[str]:
    findings = []
    for _, path in iter_modules():
        tree = ast.parse(path.read_text(), filename=str(path))
        imported: dict[str, int] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = (a.asname or a.name).split(".")[0]
                    imported[name] = node.lineno
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    imported[a.asname or a.name] = node.lineno
        collector = _NameCollector()
        collector.visit(tree)
        # names re-exported via __all__ count as used
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                collector.used.add(node.value)
        is_init = path.name == "__init__.py"
        for name, lineno in imported.items():
            if name not in collector.used and not is_init:
                findings.append(f"{path.relative_to(REPO)}:{lineno}: unused import {name!r}")
    return findings


def check_docstrings() -> list[str]:
    findings = []
    for _, path in iter_modules():
        if path.name == "__init__.py" and path.stat().st_size == 0:
            continue
        tree = ast.parse(path.read_text(), filename=str(path))
        if ast.get_docstring(tree) is None:
            findings.append(f"{path.relative_to(REPO)}: missing module docstring")
    return findings


def main() -> int:
    # force the CPU platform before anything imports jax — the import check
    # must never touch (or wedge on) a real TPU
    sys.path.insert(0, str(REPO))
    from accelerate_tpu.utils.environment import force_host_platform

    force_host_platform(1)

    failures = []
    for title, check in (
        ("imports", check_imports),
        ("unused imports", check_unused_imports),
        ("module docstrings", check_docstrings),
    ):
        found = check()
        status = "OK" if not found else f"{len(found)} finding(s)"
        print(f"[{title}] {status}")
        for f in found:
            print(f"  {f}")
        failures.extend(found)
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
