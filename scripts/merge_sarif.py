"""Merge SARIF 2.1.0 documents into one multi-run file.

GitHub code scanning accepts one SARIF upload per job; each analysis tier
(`accelerate-tpu lint`, `accelerate-tpu divergence`, `flight-check`)
emits its own document, so CI merges them here: the output keeps one
``runs[]`` entry per input, tool metadata intact.

    python scripts/merge_sarif.py a.sarif b.sarif -o merged.sarif

Inputs that are missing or unparseable are skipped with a warning — a
tier that failed to run must not lose the others' findings.
"""

from __future__ import annotations

import argparse
import json
import sys

SCHEMA = "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/Schemata/sarif-schema-2.1.0.json"


def merge(paths: list[str]) -> dict:
    runs = []
    for path in paths:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"merge_sarif: skipping {path}: {e}", file=sys.stderr)
            continue
        runs.extend(doc.get("runs", []))
    return {"$schema": SCHEMA, "version": "2.1.0", "runs": runs}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("inputs", nargs="+", help="SARIF files to merge")
    ap.add_argument("-o", "--output", required=True, help="merged SARIF output path")
    args = ap.parse_args()
    doc = merge(args.inputs)
    with open(args.output, "w") as f:
        json.dump(doc, f, indent=2)
    print(f"merged {len(doc['runs'])} run(s) into {args.output}")


if __name__ == "__main__":
    main()
