"""Generate the per-symbol API reference (docs/api/*.md) from docstrings.

The reference ships a full generated API doc site (docs/source/package_reference);
this is the equivalent for the TPU framework: deterministic markdown, one
file per module, signatures + docstrings for every public symbol. Re-run
after changing public surface:

    python scripts/gen_api_docs.py [--check]

``--check`` exits nonzero if the files on disk are stale (CI guard).
"""

from __future__ import annotations

import argparse
import importlib
import inspect
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
OUT_DIR = os.path.join(REPO, "docs", "api")

MODULES = [
    "accelerate_tpu.accelerator",
    "accelerate_tpu.state",
    "accelerate_tpu.modeling",
    "accelerate_tpu.data_loader",
    "accelerate_tpu.optimizer",
    "accelerate_tpu.scheduler",
    "accelerate_tpu.generation",
    "accelerate_tpu.diffusion",
    "accelerate_tpu.serving",
    "accelerate_tpu.serving_fleet",
    "accelerate_tpu.serving_proc",
    "accelerate_tpu.serving_transport",
    "accelerate_tpu.scheduling",
    "accelerate_tpu.speculative",
    "accelerate_tpu.big_modeling",
    "accelerate_tpu.checkpointing",
    "accelerate_tpu.tracking",
    "accelerate_tpu.logging",
    "accelerate_tpu.launchers",
    "accelerate_tpu.local_sgd",
    "accelerate_tpu.parallel.mesh",
    "accelerate_tpu.parallel.sharding",
    "accelerate_tpu.parallel.pipeline",
    "accelerate_tpu.parallel.context",
    "accelerate_tpu.parallel.collectives",
    "accelerate_tpu.parallel.compression",
    "accelerate_tpu.parallel.zero",
    "accelerate_tpu.ops.attention",
    "accelerate_tpu.ops.flash_attention",
    "accelerate_tpu.ops.pallas_attention",
    "accelerate_tpu.ops.pallas_qmatmul",
    "accelerate_tpu.ops.kv_cache",
    "accelerate_tpu.ops.paged_kv",
    "accelerate_tpu.ops.pallas_paged_attention",
    "accelerate_tpu.ops.moe",
    "accelerate_tpu.ops.fp8",
    "accelerate_tpu.ops.qdense",
    "accelerate_tpu.aot",
    "accelerate_tpu.aot.cache",
    "accelerate_tpu.aot.program_cache",
    "accelerate_tpu.aot.bucketing",
    "accelerate_tpu.ft.manifest",
    "accelerate_tpu.ft.manager",
    "accelerate_tpu.ft.preemption",
    "accelerate_tpu.ft.topology",
    "accelerate_tpu.ft.crashpoints",
    "accelerate_tpu.test_utils.fault_injection",
    "accelerate_tpu.utils.retry",
    "accelerate_tpu.utils.dataclasses",
    "accelerate_tpu.utils.operations",
    "accelerate_tpu.utils.lora",
    "accelerate_tpu.utils.quantization",
    "accelerate_tpu.utils.memory",
    "accelerate_tpu.utils.random",
    "accelerate_tpu.utils.offload",
    "accelerate_tpu.analysis.rules",
    "accelerate_tpu.analysis.ast_lint",
    "accelerate_tpu.analysis.jaxpr_lint",
    "accelerate_tpu.analysis.flightcheck",
    "accelerate_tpu.analysis.costmodel",
    "accelerate_tpu.analysis.perfmodel",
    "accelerate_tpu.analysis.perf_rules",
    "accelerate_tpu.analysis.numerics",
    "accelerate_tpu.analysis.numerics_rules",
    "accelerate_tpu.analysis.ranksim",
    "accelerate_tpu.analysis.divergence",
    "accelerate_tpu.analysis.searchspace",
    "accelerate_tpu.analysis.tuner",
    "accelerate_tpu.analysis.tune_rules",
    "accelerate_tpu.analysis.pipemodel",
    "accelerate_tpu.analysis.pipe_rules",
    "accelerate_tpu.analysis.hostsim",
    "accelerate_tpu.analysis.fleet_rules",
    "accelerate_tpu.analysis.kernelmodel",
    "accelerate_tpu.analysis.kernel_rules",
    "accelerate_tpu.analysis.changed",
    "accelerate_tpu.analysis.project_config",
    "accelerate_tpu.analysis.report",
    "accelerate_tpu.kernels",
    "accelerate_tpu.kernels.contracts",
    "accelerate_tpu.kernels.reference",
    "accelerate_tpu.telemetry",
    "accelerate_tpu.telemetry.eventlog",
    "accelerate_tpu.telemetry.step",
    "accelerate_tpu.telemetry.mfu",
    "accelerate_tpu.telemetry.serving_metrics",
    "accelerate_tpu.telemetry.summarize",
    "accelerate_tpu.telemetry.nonfinite",
    "accelerate_tpu.telemetry.wire",
    "accelerate_tpu.telemetry.trace",
    "accelerate_tpu.telemetry.flightrec",
    "accelerate_tpu.telemetry.critpath",
    "accelerate_tpu.telemetry.httpd",
    "accelerate_tpu.models",
]


def _sig(obj) -> str:
    try:
        sig = str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(...)"
    # default-value reprs can embed memory addresses — strip for determinism
    return re.sub(r" at 0x[0-9a-fA-F]+", "", sig)


def _doc(obj) -> str:
    doc = inspect.getdoc(obj)
    if not doc:
        return "*(undocumented)*"
    # flax dataclass auto-docstrings embed default-object reprs w/ addresses
    return re.sub(r" at 0x[0-9a-fA-F]+", "", doc.strip())


def _public_members(mod):
    names = getattr(mod, "__all__", None)
    if names is None:
        names = [n for n in vars(mod) if not n.startswith("_")]
    out = []
    for name in sorted(names):
        obj = getattr(mod, name, None)
        if obj is None or inspect.ismodule(obj):
            continue
        if inspect.isclass(obj) or callable(obj):
            # only classes/functions defined (or re-exported) by the package
            owner = getattr(obj, "__module__", "") or ""
            if not owner.startswith("accelerate_tpu"):
                continue
        elif not name.isupper():
            # plain values have no __module__; keep only CONSTANT_CASE ones
            continue
        out.append((name, obj))
    return out


def render_module(modname: str) -> str:
    mod = importlib.import_module(modname)
    lines = [f"# `{modname}`", ""]
    if mod.__doc__:
        lines += [inspect.cleandoc(mod.__doc__), ""]
    classes, functions, other = [], [], []
    for name, obj in _public_members(mod):
        if inspect.isclass(obj):
            classes.append((name, obj))
        elif callable(obj):
            functions.append((name, obj))
        else:
            other.append((name, obj))

    for name, obj in classes:
        lines += [f"## class `{name}{_sig(obj)}`", "", _doc(obj), ""]
        for mname, meth in sorted(vars(obj).items()):
            if mname.startswith("_"):
                continue
            # descriptors are NOT callable on CPython: unwrap them explicitly
            if isinstance(meth, property):
                if meth.fget is not None:
                    lines += [f"### `{name}.{mname}` *(property)*", "", _doc(meth.fget), ""]
                continue
            fn = meth.__func__ if isinstance(meth, (classmethod, staticmethod)) else meth
            if not (inspect.isfunction(fn) or inspect.ismethod(fn)):
                continue
            kind = " *(classmethod)*" if isinstance(meth, classmethod) else ""
            lines += [f"### `{name}.{mname}{_sig(fn)}`{kind}", "", _doc(fn), ""]
    for name, obj in functions:
        lines += [f"## `{name}{_sig(obj)}`", "", _doc(obj), ""]
    if other:
        lines += ["## Constants", ""]
        for name, obj in other:
            lines += [f"- `{name}`", ""]
    return "\n".join(lines).rstrip() + "\n"


# -- rules catalogue ------------------------------------------------------

CATALOGUE_PATH = os.path.join(REPO, "docs", "usage_guides", "static_analysis.md")
CATALOGUE_START = "<!-- rules-catalogue:start (generated by scripts/gen_api_docs.py — do not edit) -->"
CATALOGUE_END = "<!-- rules-catalogue:end -->"


def render_rules_catalogue() -> str:
    """The full TPU001-TPU405 rule table, generated from the
    ``analysis.rules`` registry so the doc cannot drift from the code."""
    from accelerate_tpu.analysis.rules import RULES

    lines = [
        "| ID | Name | Severity | Tier | Catches |",
        "|---|---|---|---|---|",
    ]
    for rid in sorted(RULES):
        r = RULES[rid]
        lines.append(f"| `{r.id}` | {r.name} | {r.severity} | {r.tier} | {r.summary} |")
    return "\n".join(lines)


def embed_rules_catalogue(check: bool) -> bool:
    """Splice the generated table between the catalogue markers in
    ``static_analysis.md``. Returns True when the file was already (or is
    now) up to date; False from --check when it is stale."""
    with open(CATALOGUE_PATH) as f:
        text = f.read()
    start = text.find(CATALOGUE_START)
    end = text.find(CATALOGUE_END)
    if start < 0 or end < 0:
        raise SystemExit(f"{CATALOGUE_PATH}: rules-catalogue markers missing")
    updated = text[: start + len(CATALOGUE_START)] + "\n" + render_rules_catalogue() + "\n" + text[end:]
    if check:
        return updated == text
    if updated != text:
        with open(CATALOGUE_PATH, "w") as f:
            f.write(updated)
    return True


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--check", action="store_true", help="fail if docs on disk are stale")
    args = ap.parse_args()

    os.makedirs(OUT_DIR, exist_ok=True)
    index = ["# Generated API reference", "",
             "One page per module, generated by `scripts/gen_api_docs.py` — do not edit by hand.", ""]
    stale = []
    for modname in MODULES:
        content = render_module(modname)
        fname = modname.replace("accelerate_tpu.", "").replace(".", "_") + ".md"
        path = os.path.join(OUT_DIR, fname)
        index.append(f"- [`{modname}`]({fname})")
        if args.check:
            on_disk = open(path).read() if os.path.exists(path) else None
            if on_disk != content:
                stale.append(fname)
        else:
            with open(path, "w") as f:
                f.write(content)
    index_content = "\n".join(index) + "\n"
    index_path = os.path.join(OUT_DIR, "index.md")
    if args.check:
        if (not os.path.exists(index_path)) or open(index_path).read() != index_content:
            stale.append("index.md")
        if not embed_rules_catalogue(check=True):
            stale.append("usage_guides/static_analysis.md (rules catalogue)")
        if stale:
            print(f"STALE: {stale} — run python scripts/gen_api_docs.py", file=sys.stderr)
            raise SystemExit(1)
        print(f"api docs up to date ({len(MODULES)} modules)")
    else:
        with open(index_path, "w") as f:
            f.write(index_content)
        embed_rules_catalogue(check=False)
        print(f"wrote {len(MODULES) + 1} files to {OUT_DIR} (+ rules catalogue)")


if __name__ == "__main__":
    main()
