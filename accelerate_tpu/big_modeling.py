"""Big-model inference: abstract init, HBM-budget placement, streamed
execution, checkpoint-and-dispatch.

Reference analogue: src/accelerate/big_modeling.py (749) + utils/modeling.py
(2199) + hooks.py (765). The reference's machinery — meta device modules,
``infer_auto_device_map`` greedy packing, ``AlignDevicesHook`` pre/post
forward weight shuffling — maps to TPU as:

* meta device        -> ``jax.eval_shape`` pytrees (:func:`init_empty_weights`,
                        :func:`abstract_params`);
* device_map         -> :func:`infer_auto_device_map`: greedy packing of
                        layer groups into per-device HBM budgets, with
                        "cpu" (host RAM) and "disk" (memmap) tiers;
* AlignDevicesHook   -> :class:`StreamedExecutor`: per-layer weight
                        streaming with double-buffering — the transfer of
                        layer i+1 overlaps compute of layer i (device_put
                        is async), which replaces the reference's
                        synchronous hook H2D copies (hooks.py:328-402);
* load_checkpoint_and_dispatch -> same-named function over safetensors
                        shard indexes, loading each tensor straight to its
                        placement tier.
"""

from __future__ import annotations

import contextlib
import json
import os
import re
from typing import Any, Callable, Optional, Union

import numpy as np

from .logging import get_logger
from .utils.offload import OffloadedWeightsLoader, offload_state_dict

logger = get_logger(__name__)


# --------------------------------------------------------------------- #
# meta-device equivalents
# --------------------------------------------------------------------- #


@contextlib.contextmanager
def init_empty_weights(include_buffers: bool = False):
    """(reference: big_modeling.py:61). In JAX "empty init" is not a patch
    but the natural mode: yield a helper that eval_shapes an init function.

    Usage::

        with init_empty_weights() as empty:
            abstract = empty(module.init, rng, dummy_input)
    """

    def evaluate(init_fn, *args, **kwargs):
        import jax

        return jax.eval_shape(init_fn, *args, **kwargs)

    yield evaluate


def abstract_params(init_fn: Callable, *args, **kwargs):
    """Shape/dtype pytree of ``init_fn(*args)`` with zero FLOPs/memory."""
    import jax

    return jax.eval_shape(init_fn, *args, **kwargs)


def _walk_insertion_order(tree: Any, prefix: str = ""):
    """Yield (path, leaf) preserving dict insertion order — module
    *definition* order, which the greedy packer must honour (jax's
    tree_flatten sorts keys alphabetically and would scramble layers)."""
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield from _walk_insertion_order(v, f"{prefix}{k}/")
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            yield from _walk_insertion_order(v, f"{prefix}{i}/")
    else:
        yield prefix[:-1], tree


def compute_module_sizes(params: Any, prefix_depth: int = 1) -> dict[str, int]:
    """Bytes per top-level (or depth-N) parameter group, in definition order
    (reference: utils/modeling.py compute_module_sizes)."""
    sizes: dict[str, int] = {}
    for path, leaf in _walk_insertion_order(params):
        group = "/".join(path.split("/")[:prefix_depth])
        nbytes = int(np.prod(getattr(leaf, "shape", (1,)) or (1,))) * np.dtype(leaf.dtype).itemsize
        sizes[group] = sizes.get(group, 0) + nbytes
    return sizes


def get_max_memory(max_memory: Optional[dict] = None) -> dict:
    """Per-device HBM budgets (reference: utils/modeling.py:761 probes
    ``torch.cuda.mem_get_info``; here ``device.memory_stats``)."""
    import jax

    if max_memory is not None:
        return {k: _parse_size(v) for k, v in max_memory.items()}
    out = {}
    for i, d in enumerate(jax.local_devices()):
        try:
            stats = d.memory_stats()
            budget = int(stats.get("bytes_limit", 16 * 2**30) * 0.9) - int(stats.get("bytes_in_use", 0))
        except Exception:
            budget = int(16 * 2**30 * 0.9)
        out[i] = budget
    out["cpu"] = int(0.8 * _host_ram_bytes())
    return out


def _host_ram_bytes() -> int:
    try:
        return os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
    except (ValueError, OSError):
        return 32 * 2**30


def _parse_size(size) -> int:
    if isinstance(size, (int, float)):
        return int(size)
    m = re.fullmatch(r"([\d.]+)\s*([KMGT]?i?B)", str(size).strip(), re.IGNORECASE)
    if not m:
        raise ValueError(f"cannot parse memory size {size!r}")
    mult = {"B": 1, "KB": 10**3, "MB": 10**6, "GB": 10**9, "TB": 10**12,
            "KIB": 2**10, "MIB": 2**20, "GIB": 2**30, "TIB": 2**40}[m.group(2).upper()]
    return int(float(m.group(1)) * mult)


def infer_auto_device_map(
    params: Any,
    max_memory: Optional[dict] = None,
    no_split_module_classes=None,
    prefix_depth: int = 2,
    tied_groups: Optional[list[list[str]]] = None,
) -> dict[str, Union[int, str]]:
    """Greedy layer-group -> placement packing
    (reference: utils/modeling.py:1294-1601 incl. tied-weight accounting).

    Returns ``{group_prefix: device_index | "cpu" | "disk"}``, filling
    devices in order, then host RAM, then disk. Tied groups (weight-shared,
    e.g. embeddings/lm_head) are forced to the same placement.
    """
    budgets = get_max_memory(max_memory)
    sizes = compute_module_sizes(params, prefix_depth=prefix_depth)
    device_order = [k for k in budgets if k not in ("cpu", "disk")] + ["cpu", "disk"]
    remaining = {k: budgets.get(k, float("inf")) for k in device_order}
    remaining.setdefault("disk", float("inf"))

    tied = {}
    for group in tied_groups or []:
        for name in group:
            tied[name] = group[0]

    device_map: dict[str, Union[int, str]] = {}
    cursor = 0
    for group, nbytes in sizes.items():
        if group in tied and tied[group] in device_map:
            device_map[group] = device_map[tied[group]]
            continue
        placed = False
        while cursor < len(device_order):
            dev = device_order[cursor]
            if remaining.get(dev, 0) >= nbytes:
                device_map[group] = dev
                remaining[dev] -= nbytes
                placed = True
                break
            cursor += 1
        if not placed:
            device_map[group] = "disk"
    return device_map


def get_balanced_memory(params: Any, num_devices: int, prefix_depth: int = 2) -> dict:
    """Even split targets (reference: utils/modeling.py:935). The per-device
    budget is floored at the largest single group so one oversized block
    (typically the embedding) cannot overflow every device in turn."""
    sizes = compute_module_sizes(params, prefix_depth)
    total = sum(sizes.values())
    per = max(int(total / num_devices * 1.15), max(sizes.values(), default=0))
    return {i: per for i in range(num_devices)}


# --------------------------------------------------------------------- #
# dispatch + streamed execution
# --------------------------------------------------------------------- #


class DispatchedParams:
    """Parameters split by placement tier: device-resident jax arrays,
    host-RAM numpy, and disk-memmap lazy entries. The functional analogue
    of a ``dispatch_model``-ed module (reference: big_modeling.py:309)."""

    def __init__(self, flat: dict[str, Any], device_map: dict, offload_dir: Optional[str] = None):
        import jax

        self.device_map = dict(device_map)
        self.flat_device: dict[str, Any] = {}
        self.flat_host: dict[str, np.ndarray] = {}
        self.disk_loader: Optional[OffloadedWeightsLoader] = None
        devices = jax.local_devices()

        disk_entries = {}
        for name, value in flat.items():
            placement = self._placement_for(name)
            if placement == "disk":
                disk_entries[name] = value
            elif placement == "cpu":
                self.flat_host[name] = np.asarray(value)
            else:
                idx = int(placement) if placement is not None else 0
                self.flat_device[name] = jax.device_put(value, devices[min(idx, len(devices) - 1)])
        if disk_entries:
            if offload_dir is None:
                raise ValueError("disk placements require offload_dir")
            offload_state_dict(offload_dir, disk_entries)
            self.disk_loader = OffloadedWeightsLoader(save_folder=offload_dir)

    def _placement_for(self, name: str):
        best, best_len = None, -1
        for prefix, placement in self.device_map.items():
            if (name == prefix or name.startswith(prefix + "/")) and len(prefix) > best_len:
                best, best_len = placement, len(prefix)
        return best

    def __getitem__(self, name: str):
        if name in self.flat_device:
            return self.flat_device[name]
        if name in self.flat_host:
            return self.flat_host[name]
        if self.disk_loader is not None and name in self.disk_loader:
            return self.disk_loader[name]
        raise KeyError(name)

    def keys(self):
        keys = set(self.flat_device) | set(self.flat_host)
        if self.disk_loader is not None:
            keys |= set(self.disk_loader.all_keys)
        return sorted(keys)


class StreamedExecutor:
    """Layer-streamed forward: weights for layer i+1 prefetch (async
    ``device_put``) while layer i computes — the double-buffered
    replacement for the reference's AlignDevicesHook pre_forward H2D copy
    (hooks.py:328-371) and post_forward re-offload (:373-402).

    ``layer_params``: list of host-side pytrees (one per layer).
    ``layer_fn(params_i, carry, i)`` -> carry.
    """

    def __init__(self, layer_params: list, layer_fn: Callable, device=None, jit: bool = True):
        import jax

        self.layer_params = layer_params
        self.device = device or jax.local_devices()[0]
        self.layer_fn = jax.jit(layer_fn, static_argnums=(2,)) if jit else layer_fn

    def __call__(self, carry):
        import jax

        n = len(self.layer_params)
        if n == 0:
            return carry
        next_weights = jax.device_put(self.layer_params[0], self.device)
        for i in range(n):
            weights = next_weights
            if i + 1 < n:
                # schedule the next transfer before blocking on compute
                next_weights = jax.device_put(self.layer_params[i + 1], self.device)
            carry = self.layer_fn(weights, carry, i)
            # drop the consumed layer's device buffers eagerly
            jax.tree_util.tree_map(lambda x: x.delete() if hasattr(x, "delete") else None, weights)
        return carry


def dispatch_model(
    model,
    device_map: dict,
    offload_dir: Optional[str] = None,
    state_dict: Optional[dict] = None,
):
    """Place a Model's params per ``device_map`` and rebind its params to a
    :class:`DispatchedParams` view (reference: big_modeling.py:309-509)."""
    flat = state_dict if state_dict is not None else model.state_dict()
    dispatched = DispatchedParams(flat, device_map, offload_dir=offload_dir)
    model.dispatched_params = dispatched
    model.device_map = device_map
    return model


def shard_model(model, mesh=None, rules=None, dtype=None):
    """Mesh-shard a Model's params for multi-device inference — the TP
    answer to the reference's ``dispatch_model`` across GPUs
    (reference: big_modeling.py:309, inference.py:124-184): instead of one
    layer per device with per-layer H2D hops, every device holds a
    column/row slice of every layer (the zoo's Megatron sharding rules) and
    ``generate`` decodes in place with the KV cache laid out on the same
    mesh (ops/kv_cache.CACHE_KV_SPEC). A model larger than one chip's HBM
    fits as long as params/mesh-size does.

    ``mesh``: target mesh (default: all local devices on the ``tensor``
    axis). ``rules``: override the model's own ``sharding_rules``.
    ``dtype``: optional cast (e.g. ``jnp.bfloat16``) applied to floating
    leaves before placement.
    """
    import jax

    from .modeling import as_model
    from .parallel.mesh import MeshConfig
    from .parallel.sharding import infer_shardings

    model = as_model(model)
    if mesh is None:
        mesh = MeshConfig(data=1, tensor=len(jax.local_devices())).build()
    rules = rules if rules is not None else (model.sharding_rules or [])
    params = model.params
    if dtype is not None:
        import jax.numpy as jnp

        # dtype read from the leaf attribute only: jnp.asarray here would
        # commit every host leaf to device 0 before the sharded placement
        params = jax.tree_util.tree_map(
            lambda p: p.astype(dtype)
            if hasattr(p, "dtype") and jnp.issubdtype(p.dtype, jnp.floating)
            else p,
            params,
        )
    shardings = infer_shardings(params, rules, mesh)
    model.params = jax.device_put(params, shardings)
    model.param_shardings = shardings
    model.mesh = mesh
    return model


def load_checkpoint_in_model(
    flat_target: dict[str, Any],
    checkpoint: str,
    device_map: Optional[dict] = None,
    offload_dir: Optional[str] = None,
) -> DispatchedParams | dict:
    """Load safetensors (single file, shard index, or directory) straight to
    placement tiers (reference: utils/modeling.py:1804)."""
    state: dict[str, np.ndarray] = {}
    index_file = None
    if os.path.isdir(checkpoint):
        candidates = [f for f in os.listdir(checkpoint) if f.endswith(".safetensors.index.json")]
        if candidates:
            index_file = os.path.join(checkpoint, candidates[0])
        else:
            from safetensors.numpy import load_file

            for f in sorted(os.listdir(checkpoint)):
                if f.endswith(".safetensors"):
                    state.update(load_file(os.path.join(checkpoint, f)))
    elif checkpoint.endswith(".index.json"):
        index_file = checkpoint
    else:
        from safetensors.numpy import load_file

        state = load_file(checkpoint)

    if index_file is not None:
        from safetensors.numpy import load_file

        with open(index_file) as f:
            weight_map = json.load(f)["weight_map"]
        base = os.path.dirname(index_file)
        for shard in sorted(set(weight_map.values())):
            state.update(load_file(os.path.join(base, shard)))

    missing = [k for k in flat_target if k not in state]
    if missing:
        raise KeyError(f"checkpoint missing {len(missing)} keys, e.g. {missing[:3]}")
    if device_map is None:
        return state
    return DispatchedParams(state, device_map, offload_dir=offload_dir)


def load_checkpoint_and_dispatch(
    model,
    checkpoint: str,
    device_map: Optional[Union[str, dict]] = "auto",
    max_memory: Optional[dict] = None,
    offload_dir: Optional[str] = None,
):
    """(reference: big_modeling.py:512). ``device_map`` may be a dict, or
    "auto" (pack into measured HBM budgets) or "balanced" (even split across
    local devices via :func:`get_balanced_memory`,
    reference: utils/modeling.py:935)."""
    flat_target = {k: None for k in model.state_dict().keys()} if model.params is not None else {}
    if device_map == "balanced":
        import jax

        max_memory = get_balanced_memory(model.params, len(jax.local_devices()))
        device_map = "auto"
    if device_map == "auto":
        device_map = infer_auto_device_map(model.params, max_memory=max_memory)
    state = load_checkpoint_in_model(flat_target, checkpoint, device_map=None)
    return dispatch_model(model, device_map, offload_dir=offload_dir, state_dict=state)
