"""True multi-process serving fleet: a process supervisor over real
engine-worker subprocesses.

Everything the in-process fleet (:mod:`accelerate_tpu.serving_fleet`)
proves — the health state machine, priced token/logprob-exact failover,
``HandoffCodec`` wire blobs, chaos coverage, request tracing, flight
recording — crosses the OS process boundary here:

* **worker** (``python -m accelerate_tpu.serving_proc --worker spec.json``):
  one single-threaded :class:`~accelerate_tpu.serving.ServingEngine` per
  process, warm-started from the shared
  :class:`~accelerate_tpu.aot.ExecutableStore` (zero XLA compiles after
  the first incarnation), serving a strict request/response protocol
  over one localhost TCP connection (:mod:`accelerate_tpu.serving_transport`).
  Request/KV payloads are the PR-15 codec blobs; every status poll ships
  failover snapshots, so the supervisor always holds a recovery point
  for each in-flight request. Single-threaded on purpose: no locks, so
  the TPU9xx host-concurrency gate has nothing to price.

* **supervisor** (:class:`ProcessSupervisor`): spawns/monitors the
  workers, drives the PR-15 health machine off REAL process death —
  ``wait()``-observed exit / SIGKILL → ``dead`` with priced failover of
  the worker's in-flight snapshots to survivors, transport timeout →
  ``degraded`` → ``quarantined`` (the hung process is SIGKILLed),
  heartbeat heal — and respawns dead slots with jittered exponential
  backoff (:func:`accelerate_tpu.utils.retry.backoff_delays`) behind a
  restart-storm circuit breaker. Worker death writes a flight-recorder
  dump holding the kill. All transport IO is confined to :meth:`pump`
  (one thread); the public submit/cancel surface crosses threads through
  a command queue and published snapshots only, never a socket.

* **front door**: :func:`serve` pairs the supervisor with the PR-18
  :class:`~accelerate_tpu.telemetry.httpd.TelemetryHTTPD` extended with
  ``POST /v1/generate`` (JSON or SSE token streaming), cancellation,
  priority/SLO headers, and ``/healthz`` flipping 503 on zero LIVE
  worker processes. SIGTERM drains gracefully: in-flight requests
  complete (or migrate off a failing worker), workers shut down clean,
  exit 0.

Failover exactness across SIGKILL: a killed process cannot export, so
the supervisor recovers from the LAST POLLED snapshot — the carried
sampling-chain ``key_data`` plus deterministic decode regenerates the
lost tail token- and logprob-exactly on the survivor (with
``ProcConfig.shadow_kv`` the snapshot also carries the trimmed KV rows,
making the recovery a priced KV import whose bytes are pinned
predicted == moved, exactly like the in-process fleet).
"""

from __future__ import annotations

import dataclasses
import json
import os
import queue
import signal
import socket
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Optional

import numpy as np

from .serving_transport import (
    PeerClosedError,
    TransportError,
    WorkerError,
    encode_snapshots,
    recv_msg,
    request,
    send_msg,
)
from .utils.retry import backoff_delays

#: supervisor-side worker health states. ``spawning`` is the pre-hello
#: window of a launched process; ``healthy``/``degraded`` serve traffic
#: (mirroring ``serving_fleet.HEALTH_STATES``); ``quarantined`` means the
#: process was SIGKILLed for hanging or poisoned numerics; ``dead`` is an
#: observed process exit. The proc protocol extractor
#: (:func:`accelerate_tpu.analysis.fleet_rules.extract_proc_spec`) reads
#: this tuple — renaming a state without re-anchoring it is a TPU904.
WORKER_STATES = ("spawning", "healthy", "degraded", "quarantined", "dead")

#: states that accept routed work
SERVING_WORKER_STATES = ("healthy", "degraded")

#: env var carrying a process-level ReplicaChaos spec into ONE worker
PROC_CHAOS_ENV = "ACCELERATE_TPU_PROC_CHAOS"


@dataclasses.dataclass
class ProcConfig:
    """Supervisor + worker-fleet knobs. Everything is JSON-able: the
    worker slice of this config is written to a per-worker spec file the
    subprocess reads at boot."""

    workers: int = 2
    #: ``"module:callable"`` model factory; called with ``model_kwargs``
    #: in the worker process. MUST be deterministic (seeded init) — the
    #: cross-process exactness story requires every worker to hold
    #: bit-identical params.
    model_spec: str = "accelerate_tpu.serving_proc:default_model"
    model_kwargs: Optional[dict] = None
    #: ServingEngine kwargs (num_slots, prompt_buckets, tick_block, ...)
    engine: Optional[dict] = None
    #: run artifacts: per-worker eventlog JSONLs, worker stderr logs,
    #: flight dumps, worker spec files
    run_dir: str = "/tmp/accelerate_tpu_proc"
    #: shared ExecutableStore dir (default: ``<run_dir>/store``) — the
    #: zero-compile warm-start contract for respawns and late workers
    store_dir: Optional[str] = None
    #: prompt lengths each worker prefills at boot (plus one detached
    #: handoff paste) so steady state — including failover imports — is
    #: replay-only
    warm_prompt_lens: tuple = (4,)
    warm_max_new_tokens: int = 2
    #: status-poll cadence and the per-RPC transport timeout that drives
    #: degraded/quarantined escalation
    poll_interval_s: float = 0.02
    heartbeat_timeout_s: float = 5.0
    quarantine_after_timeouts: int = 2
    heal_after_polls: int = 8
    spawn_timeout_s: float = 180.0
    #: respawn policy: jittered exponential backoff per slot, a per-slot
    #: attempt cap, and a fleet-wide restart-storm circuit breaker
    max_respawns: int = 3
    respawn_backoff_base_s: float = 0.05
    respawn_backoff_max_s: float = 2.0
    respawn_backoff_jitter: float = 0.5
    storm_threshold: int = 5
    storm_window_s: float = 30.0
    #: include trimmed KV rows in every status-poll snapshot: SIGKILL
    #: failover becomes a priced KV import (bytes predicted == moved)
    #: instead of exact recompute, at the cost of snapshot bandwidth
    shadow_kv: bool = False
    #: flight-recorder ring capacity per worker
    flight_capacity: int = 256
    #: chaos injection: ``{"worker", "label", "action", "hits"}`` —
    #: installed (via env) into the NAMED worker incarnation only, so a
    #: respawn serves clean
    chaos: Optional[dict] = None
    #: extra env for worker processes
    worker_env: Optional[dict] = None
    #: model/engine seed (worker params + sampling chains)
    seed: int = 0

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["warm_prompt_lens"] = list(self.warm_prompt_lens)
        return d


def default_model(seq_len: int = 128, seed: int = 0, **config_overrides):
    """Default worker model factory: a seeded tiny llama (identical
    params in every process by construction). Override fields of
    :class:`~accelerate_tpu.models.LlamaConfig` via kwargs — overrides
    apply on top of ``LlamaConfig.tiny()``, never the full-size
    defaults (a worker must boot in seconds, not compile a 7B init)."""
    from .models import LlamaConfig, create_llama_model

    return create_llama_model(LlamaConfig.tiny(**config_overrides), seed=seed, seq_len=seq_len)


def _load_factory(spec: str):
    mod_name, _, fn_name = spec.partition(":")
    if not fn_name:
        raise ValueError(f"model_spec must be 'module:callable', got {spec!r}")
    import importlib

    return getattr(importlib.import_module(mod_name), fn_name)


# ===================================================================== #
# worker half (runs in the subprocess; single-threaded, lock-free)
# ===================================================================== #


class EngineWorker:
    """One engine process: builds the model/engine from a spec dict,
    warms from the shared store, connects back to the supervisor, and
    serves the transport protocol until ``shutdown`` (or death)."""

    def __init__(self, spec: dict):
        self.spec = spec
        self.name = spec["name"]
        self.slot = int(spec["slot"])
        self.token = spec["token"]
        self._draining = False
        self._fault: Optional[dict] = None
        #: done results not yet acknowledged by a supervisor status poll
        self._unacked: dict = {}
        self._reported: set = set()

        from .telemetry.eventlog import EventLog

        log_path = os.path.join(spec["run_dir"], f"events_{self.name}.jsonl")
        # rank = slot index: merge_events disambiguates the per-process
        # seq counters of concurrent workers by this id
        self.log = EventLog(log_path, rank=self.slot, main_process_only=False)

        factory = _load_factory(spec["model_spec"])
        model = factory(**(spec.get("model_kwargs") or {}))
        from .aot import ExecutableStore, ProgramCache
        from .serving import ServingEngine

        pc = ProgramCache(store=ExecutableStore(spec["store_dir"]), name=self.name)
        self.engine = ServingEngine(
            model,
            program_cache=pc,
            telemetry_log=self.log,
            seed=int(spec.get("seed", 0)),
            **(spec.get("engine") or {}),
        )
        self.engine.metrics.replica = self.name
        self._warm(spec)
        self.warm_compiles = int(pc.misses)
        self.warm_deserialized = int(pc.deserialized)
        self.log.emit(
            "event", "proc_worker_warm", worker=self.name, severity="info",
            compiles=self.warm_compiles, deserialized=self.warm_deserialized,
        )

    def _warm(self, spec: dict) -> None:
        """Prefill each warm bucket, the decode tick, and one detached
        handoff paste (the input signature failover imports hit), so a
        warm-started worker serves everything replay-only."""
        vocab = int(self.engine.model.config.vocab_size)
        lens = [int(v) for v in spec.get("warm_prompt_lens") or (4,)]
        n_new = int(spec.get("warm_max_new_tokens", 2))
        for ln in lens:
            prompt = (np.arange(1, ln + 1) % max(2, vocab - 2) + 1).astype(np.int32)
            self.engine.submit(prompt, max_new_tokens=n_new)
        self.engine.run()
        if not self.engine.paged and self.engine.draft_model is None:
            ln = min(lens) if lens else 4
            prompt = (np.arange(2, ln + 2) % max(2, vocab - 2) + 1).astype(np.int32)
            h = self.engine.prefill_detached(
                prompt, max_new_tokens=n_new, uid_key=2**30 + self.slot
            )
            self.engine.submit_prefilled(dict(h))
            self.engine.run()
        # warm results never leave the process
        self.engine.done.clear()

    # ------------------------------------------------------------------ #
    # protocol
    # ------------------------------------------------------------------ #

    def hello(self) -> dict:
        per_tok = fixed = 0
        if not self.engine.paged and self.engine.draft_model is None:
            per_tok, fixed = self.engine.kv_handoff_dims()
        return {
            "op": "hello",
            "worker": self.name,
            "slot": self.slot,
            "token": self.token,
            "pid": os.getpid(),
            "compiles": self.warm_compiles,
            "deserialized": self.warm_deserialized,
            "kv_bytes_per_token": int(per_tok),
            "kv_fixed_bytes": int(fixed),
            "max_len": int(self.engine.max_len),
            "vocab_size": int(self.engine.model.config.vocab_size),
        }

    def _busy(self) -> bool:
        return self.engine.active_count > 0 or len(self.engine.queue) > 0

    def _step(self) -> None:
        """One engine tick; engine faults become a structured report in
        the next status reply instead of a silent death. A process-level
        chaos action (SIGKILL/SIGSTOP) fires inside the tick's labeled
        crash points and never returns."""
        from .serving_fleet import NonFinitePoison

        try:
            self.engine.step()
        except NonFinitePoison as e:
            self._fault = {"kind": "poison", "detail": str(e)}
            self.log.emit(
                "event", "proc_worker_fault", worker=self.name, severity="error",
                fault="poison", detail=str(e),
            )
        except Exception as e:  # noqa: BLE001 — reported, then re-raised by status
            self._fault = {"kind": "error", "detail": f"{type(e).__name__}: {e}"}
            self.log.emit(
                "event", "proc_worker_fault", worker=self.name, severity="error",
                fault="error", detail=str(e),
            )

    def _status(self, obj: dict) -> tuple:
        for uid in obj.get("ack") or []:
            self._unacked.pop(int(uid), None)
        for uid, toks in self.engine.done.items():
            if uid in self._reported:
                continue
            self._reported.add(uid)
            self._unacked[int(uid)] = {
                "tokens": [int(t) for t in np.asarray(toks).ravel()],
                "lps": [float(v) for v in np.asarray(self.engine.logprobs(uid)).ravel()],
            }
        include_kv = bool(obj.get("shadow_kv")) and not self.engine.paged \
            and self.engine.draft_model is None
        snaps = self.engine.export_inflight(include_kv=include_kv)
        meta, blob = encode_snapshots(snaps)
        progress = {
            str(s["uid"]): {
                "tokens": [int(t) for t in s.get("out_tokens") or []],
                "lps": [float(v) for v in s.get("out_lps") or []],
            }
            for s in snaps
        }
        fault, self._fault = self._fault, None
        reply = {
            "op": "status",
            "busy": self._busy(),
            "queue": len(self.engine.queue),
            "active": int(self.engine.active_count),
            "done": {str(u): r for u, r in self._unacked.items()},
            "progress": progress,
            "snaps": meta,
            "compiles": int(self.engine.program_cache.misses),
            "deserialized": int(self.engine.program_cache.deserialized),
            "fault": fault,
            "metrics": self._metrics_snapshot(),
        }
        return reply, blob

    def _metrics_snapshot(self) -> dict:
        snap = self.engine.metrics.snapshot()
        return {
            k: (float(v) if isinstance(v, float) else int(v))
            for k, v in snap.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        }

    def _handle(self, obj: dict, blob: bytes) -> tuple:
        op = obj.get("op")
        if op == "status":
            return self._status(obj)
        if op == "submit":
            if self._draining:
                return {"err": {"kind": "draining", "detail": "worker is draining"}}, b""
            from .scheduling import ShedError

            try:
                uid = self.engine.submit(
                    np.asarray(obj["prompt"], np.int32),
                    max_new_tokens=int(obj["max_new_tokens"]),
                    stop_sequences=[tuple(s) for s in obj.get("stop_sequences") or []] or None,
                    priority=int(obj.get("priority", 0)),
                    trace=obj.get("trace"),
                )
            except ShedError as e:
                return {"err": {"kind": "shed", "detail": str(e)}}, b""
            import jax

            key = jax.random.fold_in(jax.random.key(self.engine._seed), uid)
            key_data = [int(v) for v in np.asarray(jax.random.key_data(key)).ravel()]
            return {"uid": int(uid), "key_data": key_data}, b""
        if op == "submit_prefilled":
            from .serving_fleet import HandoffCodec

            handoff = HandoffCodec.decode(blob, self.engine)
            uid = self.engine.submit_prefilled(handoff, priority=int(obj.get("priority", 0)))
            return {"uid": int(uid)}, b""
        if op == "import_snaps":
            from .serving_transport import decode_snapshots

            keep = {int(u) for u in obj.get("uids") or []}
            allow_kv = bool(obj.get("allow_kv", True))
            uids, kv_bytes = {}, {}
            for snap in decode_snapshots(blob, self.engine):
                if keep and int(snap["uid"]) not in keep:
                    continue
                if not allow_kv:
                    snap.pop("cache", None)
                    snap.pop("rows", None)
                moved = 0
                if snap.get("cache") is not None:
                    import jax

                    moved = sum(
                        np.asarray(leaf).nbytes
                        for leaf in jax.tree_util.tree_leaves(snap["cache"])
                    )
                uids[str(snap["uid"])] = int(self.engine.import_inflight(snap))
                kv_bytes[str(snap["uid"])] = int(moved)
            return {"uids": uids, "kv_bytes": kv_bytes}, b""
        if op == "export":
            include_kv = bool(obj.get("include_kv", True)) and not self.engine.paged \
                and self.engine.draft_model is None
            snaps = self.engine.export_inflight(include_kv=include_kv)
            meta, blob_out = encode_snapshots(snaps)
            return {"snaps": meta}, blob_out
        if op == "cancel":
            uid = int(obj["uid"])
            try:
                toks = self.engine.cancel(uid)
            except KeyError:
                return {"err": {"kind": "unknown_uid", "detail": f"no request {uid}"}}, b""
            self._reported.add(uid)
            self.engine.done.pop(uid, None)
            return {"tokens": [int(t) for t in np.asarray(toks).ravel()]}, b""
        if op == "drain":
            self._draining = True
            return {"ok": True}, b""
        if op == "shutdown":
            return {"op": "bye", "ok": True}, b""
        return {"err": {"kind": "bad_op", "detail": f"unknown op {op!r}"}}, b""

    def run(self, conn: socket.socket) -> int:
        """The event loop: wait for a frame, tick the engine between
        frames. Single-threaded; ``select`` is the scheduler — a read
        only starts once bytes are waiting, so an idle wait can never
        desync mid-frame."""
        import select

        from .ft.crashpoints import crash_point

        send_msg(conn, self.hello())
        self.log.emit(
            "event", "proc_worker_hello", worker=self.name, severity="info",
            pid=os.getpid(),
        )
        while True:
            wait_s = 0.001 if self._busy() else 0.05
            readable, _, _ = select.select([conn], [], [], wait_s)
            if not readable:
                if self._busy():
                    crash_point("pre_tick", replica=self.name)
                    self._step()
                continue
            conn.settimeout(None)
            try:
                obj, blob = recv_msg(conn)
            except (PeerClosedError, ConnectionError, OSError):
                # supervisor went away: nothing left to serve
                self.log.emit(
                    "event", "proc_worker_orphaned", worker=self.name,
                    severity="warning",
                )
                return 0
            try:
                reply, rblob = self._handle(obj, blob)
            except Exception as e:  # noqa: BLE001 — protocol errors stay structured
                reply, rblob = {
                    "err": {"kind": "error", "detail": f"{type(e).__name__}: {e}"}
                }, b""
            conn.settimeout(None)
            send_msg(conn, reply, rblob)
            if reply.get("op") == "bye":
                self.log.emit(
                    "event", "proc_worker_shutdown", worker=self.name, severity="info",
                )
                self.log.close()
                return 0


def worker_main(spec_path: str) -> int:
    """Subprocess entry: read the spec, build + warm the engine, install
    chaos (if this worker is the named target), connect, serve. Chaos is
    installed only AFTER the warm pass: the warm prompts run real decode
    ticks through the same labeled crash points, and an injected fault's
    ``hits`` countdown must index served traffic, not boot-time warmup."""
    with open(spec_path) as f:
        spec = json.load(f)
    from .utils.environment import force_host_platform

    force_host_platform(int(spec.get("host_devices", 1)))
    # The shared ExecutableStore is this process's zero-compile path; jax's
    # own persistent compilation cache must stay OFF here. The poison is
    # process-global: once ANY executable has been restored from that
    # cache, every LATER fresh compile in the process serializes into a
    # blob that fails to load elsewhere ("Symbols not found"), so the
    # per-compile bypass in ProgramCache cannot contain it — and a worker
    # that ships unloadable blobs silently costs every future incarnation
    # its warm start.
    os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
    import jax

    jax.config.update("jax_enable_compilation_cache", False)
    from .test_utils.fault_injection import ReplicaChaos

    worker = EngineWorker(spec)
    ReplicaChaos.install_from_env(spec["name"])
    conn = socket.create_connection(("127.0.0.1", int(spec["port"])), timeout=30.0)
    conn.settimeout(None)
    try:
        return worker.run(conn)
    finally:
        conn.close()


# ===================================================================== #
# supervisor half (parent process; IO confined to pump())
# ===================================================================== #


class ProcessSupervisor:
    """Spawns, monitors, heals, and respawns engine-worker subprocesses.

    Thread contract (linted by the TPU9xx gate): all sockets and all
    mutable fleet state belong to the thread that calls :meth:`pump`.
    Other threads (the HTTP front door) interact only through the
    command queue (``submit``/``cancel``) and the published snapshot
    (``poll``/``partial``/``health``/``prometheus_text``), which a
    single short-critical-section lock guards — no blocking call ever
    runs under it.
    """

    def __init__(self, config: Optional[ProcConfig] = None):
        self.config = config or ProcConfig()
        cfg = self.config
        self.run_dir = cfg.run_dir
        os.makedirs(self.run_dir, exist_ok=True)
        self.store_dir = cfg.store_dir or os.path.join(self.run_dir, "store")
        os.makedirs(self.store_dir, exist_ok=True)

        from .telemetry.eventlog import EventLog
        from .telemetry.trace import Tracer

        self._log = EventLog(
            os.path.join(self.run_dir, "events_supervisor.jsonl"),
            rank=0, main_process_only=False,
        )
        self._tracer = Tracer(log=self._log)
        self._log.add_tap(self._tap_worker_events)
        self._recorders: dict = {}

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(max(8, cfg.workers * 2))
        self._listener.settimeout(0.0)
        self.port = self._listener.getsockname()[1]

        self._slots: list = []
        self._reqs: dict = {}
        self._next_fuid = 0
        self._pending_fuids: set = set()
        self._cmds: "queue.Queue" = queue.Queue()
        self._pub_lock = threading.Lock()
        self._pub = {"streams": {}, "health": {}, "prom": "", "summary": {}}
        self._acct = {
            "failovers": 0, "failovers_kv": 0, "failovers_recompute": 0,
            "failovers_lost": 0, "bytes_predicted": 0, "bytes_moved": 0,
        }
        self._respawn_times: deque = deque()
        self._breaker_open = False
        self._drain_flag = threading.Event()
        self._respawns_total = 0
        self._token = f"sup-{os.getpid()}-{id(self):x}"

    # ------------------------------------------------------------------ #
    # flight recording: supervisor-side per-worker ring of every event
    # that names the worker, dumped on its death/quarantine
    # ------------------------------------------------------------------ #

    def _tap_worker_events(self, rec: dict) -> None:
        fr = self._recorders.get(rec.get("worker"))
        if fr is not None:
            fr.record(rec)

    # ------------------------------------------------------------------ #
    # spawn / lifecycle (pump-thread only)
    # ------------------------------------------------------------------ #

    def start(self, wait: bool = True) -> None:
        """Spawn every slot; with ``wait``, pump until all workers said
        hello (or the spawn deadline passes, which marks them dead and
        schedules respawns)."""
        for i in range(self.config.workers):
            self._slots.append(self._new_slot(i))
            self._spawn_slot(self._slots[i])
        if wait:
            deadline = time.monotonic() + self.config.spawn_timeout_s
            while time.monotonic() < deadline:
                self.pump()
                if all(s["health"] != "spawning" for s in self._slots):
                    break
                time.sleep(0.02)
        self._publish()

    def _new_slot(self, i: int) -> dict:
        return {
            "slot": i, "name": f"w{i}", "proc": None, "conn": None,
            "health": "spawning", "reason": "initial spawn",
            "timeouts": 0, "clean": 0, "respawns": 0,
            "hello": None, "shadow": None, "uids": {},
            "next_spawn_at": None, "spawn_deadline": None,
            "next_poll_at": 0.0, "gave_up": False, "acked": [],
        }

    def _spawn_slot(self, slot: dict) -> None:
        cfg = self.config
        name = slot["name"]
        spec = {
            "name": name,
            "slot": slot["slot"],
            "port": self.port,
            "token": self._token,
            "run_dir": self.run_dir,
            "store_dir": self.store_dir,
            "model_spec": cfg.model_spec,
            "model_kwargs": cfg.model_kwargs or {},
            "engine": cfg.engine or {},
            "warm_prompt_lens": list(cfg.warm_prompt_lens),
            "warm_max_new_tokens": cfg.warm_max_new_tokens,
            "seed": cfg.seed,
            "host_devices": 1,
        }
        spec_path = os.path.join(self.run_dir, f"worker_{name}.json")
        with open(spec_path, "w") as f:
            json.dump(spec, f)
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        env.pop(PROC_CHAOS_ENV, None)
        if cfg.chaos and cfg.chaos.get("worker") == name:
            env[PROC_CHAOS_ENV] = json.dumps(cfg.chaos)
        if cfg.worker_env:
            env.update(cfg.worker_env)
        log_path = os.path.join(self.run_dir, f"worker_{name}.log")
        with open(log_path, "ab") as out:
            slot["proc"] = subprocess.Popen(
                [sys.executable, "-m", "accelerate_tpu.serving_proc", "--worker", spec_path],
                stdout=out, stderr=subprocess.STDOUT, env=env,
            )
        slot["health"] = "spawning"
        slot["reason"] = "spawned"
        slot["conn"] = None
        slot["hello"] = None
        slot["spawn_deadline"] = time.monotonic() + cfg.spawn_timeout_s
        from .telemetry.flightrec import FlightRecorder

        self._recorders[name] = FlightRecorder(cfg.flight_capacity, name=name)
        self._log.emit(
            "event", "proc_spawn", worker=name, severity="info",
            slot=slot["slot"], pid=slot["proc"].pid, incarnation=slot["respawns"],
        )

    def _accept_hellos(self) -> None:
        """Non-blocking accept of worker callbacks; a completed hello
        promotes its slot to healthy."""
        while True:
            try:
                conn, _addr = self._listener.accept()
            except (BlockingIOError, socket.timeout):
                return
            try:
                conn.settimeout(self.config.heartbeat_timeout_s)
                hello, _ = recv_msg(conn)
            except (TransportError, OSError):
                conn.close()
                continue
            if hello.get("op") != "hello" or hello.get("token") != self._token:
                conn.close()
                continue
            matched = None
            for slot in self._slots:
                if slot["name"] == hello.get("worker") and slot["health"] == "spawning":
                    matched = slot
                    break
            if matched is None:
                conn.close()
                continue
            matched["conn"] = conn
            matched["hello"] = hello
            matched["timeouts"] = 0
            matched["clean"] = 0
            self._set_health(matched, "healthy", "hello")
            self._log.emit(
                "event", "proc_hello", worker=matched["name"], severity="info",
                pid=hello.get("pid"), compiles=hello.get("compiles"),
                deserialized=hello.get("deserialized"),
            )

    # ------------------------------------------------------------------ #
    # health machine (extraction-anchored: extract_proc_spec reads the
    # _set_health targets and thresholds out of these methods by AST)
    # ------------------------------------------------------------------ #

    def _set_health(self, slot: dict, state: str, reason: str) -> None:
        if state not in WORKER_STATES:
            raise ValueError(f"unknown worker state {state!r}")
        prev = slot["health"]
        slot["health"] = state
        slot["reason"] = reason
        if state in ("healthy", "spawning"):
            slot["timeouts"] = 0
            slot["clean"] = 0
        self._log.emit(
            "event", "proc_health", worker=slot["name"], severity="warning"
            if state in ("quarantined", "dead") else "info",
            prev=prev, state=state, reason=reason,
        )
        if state in ("quarantined", "dead") and prev not in ("quarantined", "dead"):
            self._flight_dump(slot, reason)

    def _on_worker_exit(self, slot: dict, returncode: int) -> None:
        """REAL process death: SIGKILL shows up as a negative returncode
        (the signal number); either way the worker is gone — migrate its
        snapshots and schedule a respawn."""
        sig = -returncode if returncode is not None and returncode < 0 else 0
        self._log.emit(
            "event", "proc_exit", worker=slot["name"], severity="error",
            returncode=returncode, signal=sig,
            killed=bool(sig == signal.SIGKILL),
        )
        self._close_conn(slot)
        self._set_health(slot, "dead", f"process exit rc={returncode}")
        self._migrate_worker(slot, kind="crash", allow_kv=True)
        self._schedule_respawn(slot)

    def _on_worker_timeout(self, slot: dict) -> None:
        """Transport/heartbeat timeout: degrade, then quarantine (and
        SIGKILL — a hung process holds no consistency we can trust to a
        graceful stop) once the threshold trips."""
        slot["timeouts"] += 1
        slot["clean"] = 0
        self._log.emit(
            "event", "proc_timeout", worker=slot["name"], severity="warning",
            timeouts=slot["timeouts"],
        )
        if slot["timeouts"] >= self.config.quarantine_after_timeouts:
            self._kill_slot(slot)
            self._set_health(slot, "quarantined", "heartbeat timeouts")
            self._migrate_worker(slot, kind="timeout", allow_kv=True)
            self._schedule_respawn(slot)
        else:
            self._set_health(slot, "degraded", "heartbeat timeout")

    def _on_worker_poison(self, slot: dict, detail: str) -> None:
        """Non-finite poison reported by the worker: numerics are
        suspect, so quarantine, kill, and fail over WITHOUT trusting its
        KV snapshots (recompute only)."""
        self._kill_slot(slot)
        self._set_health(slot, "quarantined", f"poison: {detail}")
        self._migrate_worker(slot, kind="poison", allow_kv=False)
        self._schedule_respawn(slot)

    def _on_worker_clean(self, slot: dict) -> None:
        """A clean status poll; enough of them heal a degraded worker."""
        slot["timeouts"] = 0
        if slot["health"] == "degraded":
            slot["clean"] += 1
            if slot["clean"] >= self.config.heal_after_polls:
                self._set_health(slot, "healthy", "healed")

    def _schedule_respawn(self, slot: dict) -> None:
        """Jittered-backoff respawn with a per-slot attempt cap and the
        fleet-wide restart-storm circuit breaker."""
        cfg = self.config
        if slot["respawns"] >= cfg.max_respawns:
            slot["gave_up"] = True
            self._log.emit(
                "event", "proc_respawn_giveup", worker=slot["name"],
                severity="error", respawns=slot["respawns"],
            )
            return
        now = time.monotonic()
        while self._respawn_times and now - self._respawn_times[0] > cfg.storm_window_s:
            self._respawn_times.popleft()
        if len(self._respawn_times) >= cfg.storm_threshold:
            self._breaker_open = True
            slot["gave_up"] = True
            self._log.emit(
                "event", "proc_respawn_storm", worker=slot["name"], severity="error",
                respawns_in_window=len(self._respawn_times),
                window_s=cfg.storm_window_s,
            )
            return
        self._respawn_times.append(now)
        delays = list(
            backoff_delays(
                attempts=slot["respawns"] + 2,
                base_delay=cfg.respawn_backoff_base_s,
                max_delay=cfg.respawn_backoff_max_s,
                jitter=cfg.respawn_backoff_jitter,
            )
        )
        delay = delays[-1] if delays else cfg.respawn_backoff_base_s
        slot["respawns"] += 1
        self._respawns_total += 1
        slot["name"] = f"w{slot['slot']}.{slot['respawns']}"
        slot["uids"] = {}
        slot["shadow"] = None
        slot["acked"] = []
        slot["next_spawn_at"] = now + delay
        self._log.emit(
            "event", "proc_respawn_scheduled", worker=slot["name"], severity="info",
            slot=slot["slot"], delay_s=round(delay, 4), attempt=slot["respawns"],
        )

    # ------------------------------------------------------------------ #
    # failover (priced; snapshots are the recovery points)
    # ------------------------------------------------------------------ #

    def _migrate_worker(self, slot: dict, kind: str, allow_kv: bool) -> None:
        """Fail the dead/quarantined worker's in-flight requests over to
        survivors from its last polled snapshots — priced BEFORE the
        import, bytes pinned predicted == moved after. Requests with no
        snapshot (submitted after the last poll) rebuild from the
        supervisor's own request record; no routable survivor means
        lost-with-reason, never silence."""
        victims = {
            fuid: r for fuid, r in self._reqs.items()
            if r["state"] == "routed" and r["slot"] is slot
        }
        if not victims:
            return
        meta_by_uid = {}
        blob = b""
        if slot["shadow"] is not None:
            meta_list, blob = slot["shadow"]
            meta_by_uid = {int(m["uid"]): m for m in meta_list}
        hello = slot["hello"] or {}
        per_tok = int(hello.get("kv_bytes_per_token", 0))
        fixed = int(hello.get("kv_fixed_bytes", 0))
        for fuid, r in victims.items():
            survivor = self._route(exclude=slot)
            if survivor is None:
                r["state"] = "lost"
                r["lost_reason"] = f"no routable survivor after {kind}"
                self._acct["failovers_lost"] += 1
                self._log.emit(
                    "event", "proc_failover_lost", worker=slot["name"],
                    severity="error", fuid=fuid, failure=kind,
                )
                self._finish_trace(r, "lost")
                continue
            m = meta_by_uid.get(r["uid"])
            use_kv = bool(allow_kv and m is not None and m.get("has_kv"))
            predicted = (int(m["rows"]) * per_tok + fixed) if use_kv else 0
            moved = 0
            try:
                if m is not None:
                    reply, _ = request(
                        survivor["conn"],
                        {
                            "op": "import_snaps",
                            "uids": [r["uid"]],
                            "allow_kv": bool(allow_kv),
                        },
                        blob,
                        timeout=self.config.heartbeat_timeout_s,
                    )
                    new_uid = int(reply["uids"][str(r["uid"])])
                    moved = int(reply.get("kv_bytes", {}).get(str(r["uid"]), 0))
                else:
                    new_uid = self._resubmit_snapshotless(survivor, r)
            except (TransportError, OSError) as e:
                # the survivor failed mid-failover: its own health event
                # fires on the next pump; this request is lost only if no
                # OTHER survivor remains
                self._log.emit(
                    "event", "proc_failover_retry", worker=slot["name"],
                    severity="warning", fuid=fuid, survivor=survivor["name"],
                    detail=str(e),
                )
                r["state"] = "lost"
                r["lost_reason"] = f"failover import failed: {e}"
                self._acct["failovers_lost"] += 1
                self._finish_trace(r, "lost")
                continue
            r["slot"] = survivor
            r["uid"] = new_uid
            survivor["uids"][new_uid] = fuid
            self._acct["failovers"] += 1
            if use_kv and moved:
                self._acct["failovers_kv"] += 1
                self._acct["bytes_predicted"] += predicted
                self._acct["bytes_moved"] += moved
            else:
                self._acct["failovers_recompute"] += 1
            self._tracer.seg(
                r.get("trace"), "failover", src=slot["name"], dst=survivor["name"],
                failure=kind, predicted_bytes=predicted, moved_bytes=moved,
            )
            self._log.emit(
                "event", "proc_failover", worker=slot["name"], severity="warning",
                fuid=fuid, dst=survivor["name"], failure=kind, kv=use_kv,
                predicted_bytes=predicted, moved_bytes=moved,
            )
        slot["uids"] = {}

    def _resubmit_snapshotless(self, survivor: dict, r: dict) -> int:
        """A request the dead worker never reported a snapshot for:
        rebuild the snapshot from the supervisor's own record (the
        sampling ``key_data`` captured at submit keeps the stream
        exact) and import it on the survivor."""
        snap = {
            "uid": r["uid"],
            "prompt": np.asarray(r["prompt"], np.int32),
            "max_new_tokens": r["max_new"],
            "out_tokens": [],
            "out_lps": [],
            "stop_sequences": tuple(tuple(s) for s in r["stops"]),
            "priority": r["priority"],
            "trace": r.get("trace"),
            "key_data": np.asarray(r["key_data"], np.uint32),
        }
        _meta, blob = encode_snapshots([snap])
        reply, _ = request(
            survivor["conn"],
            {"op": "import_snaps", "uids": [r["uid"]], "allow_kv": False},
            blob,
            timeout=self.config.heartbeat_timeout_s,
        )
        return int(reply["uids"][str(r["uid"])])

    # ------------------------------------------------------------------ #
    # pump (the single IO thread)
    # ------------------------------------------------------------------ #

    def pump(self) -> None:
        """One supervision iteration: accept hellos, serve queued
        commands, poll worker status, observe process exits, respawn due
        slots, publish. Call in a loop (``serve``'s main loop, or a test
        harness's)."""
        self._accept_hellos()
        self._serve_commands()
        now = time.monotonic()
        for slot in self._slots:
            if slot["health"] in SERVING_WORKER_STATES and now >= slot["next_poll_at"]:
                slot["next_poll_at"] = now + self.config.poll_interval_s
                self._poll_slot(slot)
        self._reap_exits()
        self._respawn_due()
        self._publish()

    def _poll_slot(self, slot: dict) -> None:
        try:
            reply, blob = request(
                slot["conn"],
                {"op": "status", "ack": slot["acked"], "shadow_kv": self.config.shadow_kv},
                timeout=self.config.heartbeat_timeout_s,
            )
        except socket.timeout:
            self._on_worker_timeout(slot)
            return
        except (TransportError, OSError):
            # a dropped connection almost always means the process just
            # died (SIGKILL mid-frame); the exit can lag the socket close
            # by a scheduler beat, so give the kernel a moment to make it
            # reapable — misclassifying a real death as a transport
            # timeout would quarantine-dump without the kill evidence
            rc = slot["proc"].poll()
            if rc is None:
                try:
                    rc = slot["proc"].wait(timeout=0.25)
                except subprocess.TimeoutExpired:
                    rc = None
            if rc is not None:
                self._on_worker_exit(slot, rc)
            else:
                self._on_worker_timeout(slot)
            return
        slot["acked"] = []
        fault = reply.get("fault")
        if fault and fault.get("kind") == "poison":
            self._on_worker_poison(slot, fault.get("detail", ""))
            return
        if fault:
            self._log.emit(
                "event", "proc_worker_error", worker=slot["name"], severity="error",
                detail=fault.get("detail", ""),
            )
        self._on_worker_clean(slot)
        slot["status"] = {
            "queue": reply.get("queue", 0), "active": reply.get("active", 0),
            "busy": reply.get("busy", False), "compiles": reply.get("compiles", 0),
            "deserialized": reply.get("deserialized", 0),
            "metrics": reply.get("metrics", {}),
        }
        # progress → published streams
        for uid_s, prog in (reply.get("progress") or {}).items():
            fuid = slot["uids"].get(int(uid_s))
            if fuid is None:
                continue
            r = self._reqs[fuid]
            r["tokens"] = list(prog.get("tokens") or [])
            r["lps"] = list(prog.get("lps") or [])
        # done results
        for uid_s, res in (reply.get("done") or {}).items():
            uid = int(uid_s)
            slot["acked"].append(uid)
            fuid = slot["uids"].pop(uid, None)
            if fuid is None:
                continue
            r = self._reqs[fuid]
            r["state"] = "done"
            r["final"] = list(res.get("tokens") or [])
            r["lps"] = list(res.get("lps") or [])
            r["tokens"] = r["final"][len(r["prompt"]):]
            self._finish_trace(r, "ok")
            self._log.emit(
                "event", "proc_done", worker=slot["name"], severity="info",
                fuid=fuid, tokens=len(r["tokens"]),
            )
        # fresh failover snapshots (the recovery points)
        snaps_meta = reply.get("snaps")
        if snaps_meta is not None:
            slot["shadow"] = (snaps_meta, blob)

    def _reap_exits(self) -> None:
        for slot in self._slots:
            proc = slot["proc"]
            if proc is None or slot["health"] == "dead":
                continue
            rc = proc.poll()
            if rc is None:
                continue
            if slot["health"] == "quarantined":
                # already handled (we killed it); just observe the exit
                self._log.emit(
                    "event", "proc_exit", worker=slot["name"], severity="info",
                    returncode=rc, after="quarantine",
                )
                slot["proc"] = None
                continue
            self._on_worker_exit(slot, rc)

    def _respawn_due(self) -> None:
        now = time.monotonic()
        for slot in self._slots:
            if slot["health"] == "spawning" and slot["spawn_deadline"] is not None \
                    and now > slot["spawn_deadline"] and slot["hello"] is None:
                self._log.emit(
                    "event", "proc_spawn_timeout", worker=slot["name"], severity="error",
                )
                self._kill_slot(slot)
                self._set_health(slot, "dead", "spawn timeout")
                self._schedule_respawn(slot)
                continue
            if (
                slot["health"] in ("dead", "quarantined")
                and slot["next_spawn_at"] is not None
                and now >= slot["next_spawn_at"]
                and not self._breaker_open
                and not slot["gave_up"]
            ):
                slot["next_spawn_at"] = None
                self._spawn_slot(slot)

    # ------------------------------------------------------------------ #
    # command surface (any thread): queue in, published snapshot out
    # ------------------------------------------------------------------ #

    def submit(
        self,
        prompt_ids,
        max_new_tokens: int = 16,
        stop_sequences=None,
        priority: int = 0,
        wait: bool = False,
        timeout: float = 30.0,
    ) -> int:
        """Route one request to the fleet; returns the fleet-wide id.
        ``wait=True`` blocks until the pump thread actually routed (or
        shed) it and raises the structured failure."""
        fuid = self._mint_fuid()
        reply: Optional[queue.Queue] = queue.Queue(maxsize=1) if wait else None
        self._cmds.put(
            {
                "op": "submit", "fuid": fuid,
                "prompt": [int(t) for t in np.asarray(prompt_ids).ravel()],
                "max_new_tokens": int(max_new_tokens),
                "stops": [list(s) for s in (stop_sequences or [])],
                "priority": int(priority),
                "reply": reply,
            }
        )
        if reply is not None:
            result = reply.get(timeout=timeout)
            if result.get("err"):
                raise FleetRequestError(fuid, result["err"])
        return fuid

    def cancel(self, fuid: int, timeout: float = 30.0) -> list:
        """Cancel a request; returns its tokens so far."""
        reply: "queue.Queue" = queue.Queue(maxsize=1)
        self._cmds.put({"op": "cancel", "fuid": int(fuid), "reply": reply})
        result = reply.get(timeout=timeout)
        if result.get("err"):
            raise KeyError(f"request {fuid}: {result['err']}")
        return result.get("tokens", [])

    def _mint_fuid(self) -> int:
        # itertools-free so the counter survives pickling of configs;
        # CPython attribute int += is GIL-atomic enough for a counter
        # only ever read for uniqueness, but take the pub lock anyway to
        # keep the cross-thread write explicit and lint-clean
        with self._pub_lock:
            fuid = self._next_fuid
            self._next_fuid += 1
            # Visible as "queued" to readers until the pump thread routes the
            # command and the next publish carries the real state — without
            # this, a poll racing the pump sees KeyError ("unknown request")
            # for a fuid submit() just handed out.
            self._pending_fuids.add(fuid)
        return fuid

    def _serve_commands(self) -> None:
        while True:
            try:
                cmd = self._cmds.get_nowait()
            except queue.Empty:
                return
            if cmd["op"] == "submit":
                self._cmd_submit(cmd)
            elif cmd["op"] == "cancel":
                self._cmd_cancel(cmd)

    def _reply(self, cmd: dict, result: dict) -> None:
        q = cmd.get("reply")
        if q is not None:
            q.put(result)

    def _cmd_submit(self, cmd: dict) -> None:
        fuid = cmd["fuid"]
        if self._drain_flag.is_set():
            self._reqs[fuid] = {"state": "shed", "prompt": cmd["prompt"], "tokens": []}
            self._reply(cmd, {"err": "supervisor draining"})
            return
        slot = self._route()
        if slot is None:
            self._reqs[fuid] = {"state": "shed", "prompt": cmd["prompt"], "tokens": []}
            self._log.emit(
                "event", "proc_shed", severity="warning", fuid=fuid,
                reason="zero routable workers",
            )
            self._reply(cmd, {"err": "zero routable workers"})
            return
        trace = self._tracer.start(fuid=fuid, prompt_len=len(cmd["prompt"]))
        try:
            reply, _ = request(
                slot["conn"],
                {
                    "op": "submit", "prompt": cmd["prompt"],
                    "max_new_tokens": cmd["max_new_tokens"],
                    "stop_sequences": cmd["stops"], "priority": cmd["priority"],
                    "trace": trace,
                },
                timeout=self.config.heartbeat_timeout_s,
            )
        except WorkerError as e:
            self._reqs[fuid] = {"state": "shed", "prompt": cmd["prompt"], "tokens": []}
            self._tracer.finish(trace, status="shed")
            self._reply(cmd, {"err": f"{e.kind}: {e}"})
            return
        except (TransportError, OSError):
            # the routed worker failed at submit time: its health event
            # fires on the next poll; tell the caller to retry
            self._reqs[fuid] = {"state": "shed", "prompt": cmd["prompt"], "tokens": []}
            self._tracer.finish(trace, status="error")
            self._reply(cmd, {"err": "worker transport failure; retry"})
            return
        uid = int(reply["uid"])
        self._reqs[fuid] = {
            "fuid": fuid, "state": "routed", "slot": slot, "uid": uid,
            "prompt": cmd["prompt"], "max_new": cmd["max_new_tokens"],
            "stops": cmd["stops"], "priority": cmd["priority"],
            "trace": trace, "key_data": reply.get("key_data") or [0, 0],
            "tokens": [], "lps": [], "final": None,
        }
        slot["uids"][uid] = fuid
        self._log.emit(
            "event", "proc_submit", worker=slot["name"], severity="info",
            fuid=fuid, uid=uid, prompt_len=len(cmd["prompt"]),
            max_new_tokens=cmd["max_new_tokens"], trace=trace,
        )
        self._reply(cmd, {"ok": True, "worker": slot["name"]})

    def _cmd_cancel(self, cmd: dict) -> None:
        r = self._reqs.get(cmd["fuid"])
        if r is None:
            self._reply(cmd, {"err": "unknown request"})
            return
        if r["state"] != "routed":
            self._reply(cmd, {"tokens": r.get("tokens", [])})
            return
        slot = r["slot"]
        try:
            reply, _ = request(
                slot["conn"], {"op": "cancel", "uid": r["uid"]},
                timeout=self.config.heartbeat_timeout_s,
            )
            tokens = reply.get("tokens", [])
        except (TransportError, OSError):
            tokens = r.get("tokens", [])
        slot["uids"].pop(r["uid"], None)
        r["state"] = "cancelled"
        r["final"] = tokens
        r["tokens"] = tokens[len(r["prompt"]):] if len(tokens) >= len(r["prompt"]) else tokens
        self._finish_trace(r, "cancelled")
        self._log.emit(
            "event", "proc_cancel", worker=slot["name"], severity="info",
            fuid=cmd["fuid"],
        )
        self._reply(cmd, {"tokens": tokens})

    def _route(self, exclude: Optional[dict] = None) -> Optional[dict]:
        """Least-outstanding routable worker (real liveness: a slot whose
        process died is never routable, whatever its last status said)."""
        best = None
        for slot in self._slots:
            if slot is exclude or slot["health"] not in SERVING_WORKER_STATES:
                continue
            if slot["conn"] is None:
                continue
            if best is None or len(slot["uids"]) < len(best["uids"]):
                best = slot
        return best

    # ------------------------------------------------------------------ #
    # published read surface (any thread; lock-guarded dict copies)
    # ------------------------------------------------------------------ #

    def _publish(self) -> None:
        streams = {}
        for fuid, r in self._reqs.items():
            streams[fuid] = {
                "state": r["state"],
                "tokens": list(r.get("tokens") or []),
                "lps": list(r.get("lps") or []),
                "final": None if r.get("final") is None else list(r["final"]),
                "lost_reason": r.get("lost_reason"),
            }
        health = {
            slot["name"]: {
                "health": slot["health"], "reason": slot["reason"],
                "slot": slot["slot"], "respawns": slot["respawns"],
                "pid": slot["proc"].pid if slot["proc"] else None,
                "outstanding": len(slot["uids"]),
                "compiles": (slot.get("status") or {}).get("compiles"),
                "deserialized": (slot.get("status") or {}).get("deserialized"),
                "draining": self._drain_flag.is_set(),
            }
            for slot in self._slots
        }
        summary = {
            "requests": len(self._reqs),
            "done": sum(1 for r in self._reqs.values() if r["state"] == "done"),
            "routed": sum(1 for r in self._reqs.values() if r["state"] == "routed"),
            "lost": sum(1 for r in self._reqs.values() if r["state"] == "lost"),
            "breaker_open": self._breaker_open,
            "respawns_total": self._respawns_total,
            "accounting": dict(self._acct),
        }
        prom = self._prometheus(health, summary)
        with self._pub_lock:
            # Minted fuids whose submit command the pump has now served show
            # up in streams; drop them from the pending set. The rest are
            # still in the command queue — keep them visible as queued.
            self._pending_fuids.difference_update(streams)
            for fuid in self._pending_fuids:
                streams[fuid] = {
                    "state": "queued", "tokens": [], "lps": [],
                    "final": None, "lost_reason": None,
                }
            self._pub["streams"] = streams
            self._pub["health"] = health
            self._pub["summary"] = summary
            self._pub["prom"] = prom

    def _prometheus(self, health: dict, summary: dict) -> str:
        lines = [
            "# HELP proc_worker_state worker health (0 healthy, 1 degraded, "
            "2 quarantined, 3 dead, 4 spawning)",
            "# TYPE proc_worker_state gauge",
        ]
        level = {"healthy": 0, "degraded": 1, "quarantined": 2, "dead": 3, "spawning": 4}
        for name, h in sorted(health.items()):
            lines.append(
                f'proc_worker_state{{worker="{name}"}} {level.get(h["health"], -1)}'
            )
        lines += [
            "# HELP proc_worker_outstanding requests routed to the worker",
            "# TYPE proc_worker_outstanding gauge",
        ]
        for name, h in sorted(health.items()):
            lines.append(f'proc_worker_outstanding{{worker="{name}"}} {h["outstanding"]}')
        for key in ("requests", "done", "routed", "lost", "respawns_total"):
            lines.append(f"# TYPE proc_{key} gauge")
            lines.append(f"proc_{key} {summary[key]}")
        for key, val in sorted(summary["accounting"].items()):
            lines.append(f"# TYPE proc_{key}_total counter")
            lines.append(f"proc_{key}_total {val}")
        lines.append("# TYPE proc_breaker_open gauge")
        lines.append(f"proc_breaker_open {int(summary['breaker_open'])}")
        return "\n".join(lines) + "\n"

    def health(self) -> dict:
        with self._pub_lock:
            return dict(self._pub["health"])

    def summary(self) -> dict:
        with self._pub_lock:
            return dict(self._pub["summary"])

    def prometheus_text(self) -> str:
        with self._pub_lock:
            return self._pub["prom"]

    def failover_accounting(self) -> dict:
        with self._pub_lock:
            return dict(self._acct)

    def _stream(self, fuid: int) -> dict:
        with self._pub_lock:
            s = self._pub["streams"].get(int(fuid))
            if s is None and int(fuid) in self._pending_fuids:
                # Minted but not yet published: the submit command is still
                # in the pump's queue. Report it queued instead of unknown.
                s = {
                    "state": "queued", "tokens": [], "lps": [],
                    "final": None, "lost_reason": None,
                }
        if s is None:
            raise KeyError(f"unknown request {fuid}")
        return s

    def poll(self, fuid: int):
        """Finished [prompt + generated] tokens, or None while pending.
        Lost/shed requests raise their structured reason."""
        s = self._stream(fuid)
        if s["state"] in ("lost", "shed"):
            raise FleetRequestError(fuid, s.get("lost_reason") or s["state"])
        if s["state"] in ("done", "cancelled") and s["final"] is not None:
            return np.asarray(s["final"], np.int64)
        return None

    def partial(self, fuid: int) -> np.ndarray:
        """Generated-so-far tokens (streaming read)."""
        s = self._stream(fuid)
        return np.asarray(s["tokens"], np.int64)

    def logprobs(self, fuid: int) -> np.ndarray:
        s = self._stream(fuid)
        return np.asarray(s["lps"], np.float64)

    def request_state(self, fuid: int) -> str:
        return self._stream(fuid)["state"]

    # ------------------------------------------------------------------ #
    # drain / shutdown (pump-owner thread)
    # ------------------------------------------------------------------ #

    def request_drain(self) -> None:
        """Stop accepting new work (SIGTERM handler sets this; it is the
        only supervisor method that is async-signal safe)."""
        self._drain_flag.set()

    def draining(self) -> bool:
        return self._drain_flag.is_set()

    def drained(self) -> bool:
        return self._drain_flag.is_set() and not any(
            r["state"] == "routed" for r in self._reqs.values()
        )

    def drain_worker(self, name: str) -> dict:
        """Gracefully remove ONE live worker: export its full in-flight
        state (KV included), migrate to survivors, shut it down. The
        planned-maintenance twin of crash failover; same pricing
        discipline."""
        slot = next((s for s in self._slots if s["name"] == name), None)
        if slot is None or slot["health"] not in SERVING_WORKER_STATES:
            raise KeyError(f"no live worker {name!r}")
        reply, blob = request(
            slot["conn"], {"op": "export", "include_kv": True},
            timeout=self.config.heartbeat_timeout_s,
        )
        slot["shadow"] = (reply.get("snaps") or [], blob)
        self._set_health(slot, "dead", "drained")
        self._migrate_worker(slot, kind="drain", allow_kv=True)
        self._shutdown_slot(slot)
        self._publish()
        return {"migrated": len(reply.get("snaps") or [])}

    def _work_remaining(self) -> bool:
        return any(r["state"] == "routed" for r in self._reqs.values())

    def run_until_drained(self, timeout_s: float = 300.0) -> bool:
        """Pump until every routed request resolved; the SIGTERM drain
        path of :func:`serve`."""
        deadline = time.monotonic() + timeout_s
        while self._work_remaining() and time.monotonic() < deadline:
            self.pump()
            time.sleep(0.002)
        return not self._work_remaining()

    def shutdown(self) -> None:
        """Stop everything: polite shutdown RPC per live worker, then
        SIGKILL stragglers, close the logs."""
        for slot in self._slots:
            self._shutdown_slot(slot)
        self._listener.close()
        self._log.emit(
            "event", "proc_supervisor_shutdown", severity="info",
            accounting=dict(self._acct), respawns=self._respawns_total,
        )
        self._log.close()

    def _shutdown_slot(self, slot: dict) -> None:
        if slot["conn"] is not None:
            try:
                request(slot["conn"], {"op": "shutdown"}, timeout=2.0)
            except (TransportError, OSError):
                pass
            self._close_conn(slot)
        proc = slot["proc"]
        if proc is not None and proc.poll() is None:
            try:
                proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5.0)
        slot["proc"] = proc

    def _kill_slot(self, slot: dict) -> None:
        self._close_conn(slot)
        proc = slot["proc"]
        if proc is not None and proc.poll() is None:
            proc.kill()
            try:
                proc.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                self._log.emit(
                    "event", "proc_kill_stuck", worker=slot["name"], severity="error",
                )

    def _close_conn(self, slot: dict) -> None:
        if slot["conn"] is not None:
            try:
                slot["conn"].close()
            except OSError:
                pass
            slot["conn"] = None

    def _flight_dump(self, slot: dict, reason: str) -> None:
        fr = self._recorders.get(slot["name"])
        if fr is None:
            return
        inflight = [
            {"fuid": fuid, "uid": r["uid"], "generated": len(r.get("tokens") or []),
             "trace": r.get("trace")}
            for fuid, r in self._reqs.items()
            if r["state"] == "routed" and r["slot"] is slot
        ]
        path = os.path.join(self.run_dir, f"flight_{slot['name']}.json")
        fr.dump(reason=reason, inflight=inflight, path=path)
        self._log.emit(
            "event", "proc_flight_dump", worker=slot["name"], severity="info",
            path=path, reason=reason,
        )

    def _finish_trace(self, r: dict, status: str) -> None:
        if r.get("trace") is not None:
            self._tracer.finish(r["trace"], status=status)
            r["trace_closed"] = True

class FleetRequestError(RuntimeError):
    """Structured terminal failure for one fleet request (lost to a
    failover dead-end, or shed at the supervisor edge)."""

    def __init__(self, fuid: int, detail):
        super().__init__(f"request {fuid}: {detail}")
        self.fuid = int(fuid)
        self.detail = detail


# ===================================================================== #
# serve(): supervisor + HTTP/SSE front door + signal-driven drain
# ===================================================================== #


def serve(
    config: Optional[ProcConfig] = None,
    http_host: str = "127.0.0.1",
    http_port: int = 0,
    ready_file: Optional[str] = None,
    max_runtime_s: Optional[float] = None,
) -> int:
    """Run the multi-process fleet behind the HTTP front door until
    SIGTERM/SIGINT, then drain gracefully: stop accepting, let in-flight
    requests finish (or migrate off failing workers), shut workers down,
    exit 0. ``ready_file`` (written once serving) and ``max_runtime_s``
    exist for test harnesses."""
    from .telemetry.httpd import TelemetryHTTPD

    sup = ProcessSupervisor(config)
    sup.start(wait=True)
    httpd = TelemetryHTTPD.for_supervisor(sup, host=http_host, port=http_port)
    httpd.start()

    def _term(_signum, _frame):
        sup.request_drain()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    if ready_file:
        with open(ready_file, "w") as f:
            json.dump({"http_port": httpd.port, "pid": os.getpid()}, f)
    deadline = None if max_runtime_s is None else time.monotonic() + max_runtime_s
    while not sup.draining():
        sup.pump()
        time.sleep(0.002)
        if deadline is not None and time.monotonic() > deadline:
            sup.request_drain()
    drained = sup.run_until_drained()
    httpd.stop()
    sup.shutdown()
    return 0 if drained else 1


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser("accelerate_tpu.serving_proc")
    ap.add_argument("--worker", default=None, help="worker spec JSON (subprocess entry)")
    args = ap.parse_args(argv)
    if args.worker:
        return worker_main(args.worker)
    ap.error("this module is the worker entry point; use `accelerate-tpu serve`")
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
