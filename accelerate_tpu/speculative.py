"""Speculative decoding: draft-proposed tokens verified by the target in
one forward — fewer target passes per emitted token, token-exact output.

No reference analogue (the reference delegates generation); parity-plus
inference performance surface alongside quantized decode and continuous
batching. Greedy acceptance: the draft proposes ``gamma`` tokens
autoregressively, the target scores all of them in ONE forward, the
longest prefix where the draft matched the target's own argmax is
accepted, and the target's argmax at the first mismatch is emitted as
the correction — so every iteration emits ``accepted + 1`` tokens for
one target forward, and the output equals plain greedy decode of the
target exactly.

Cache bookkeeping uses the same frontier argument as the serving
engine's padded prefill: rejected positions leave stale rows in both
models' caches, but the write index is reset to the accepted frontier,
and every stale row is overwritten by the next iteration's tokens
before the causal frontier reaches it — verified token-exact in
``tests/test_speculative.py``.

Both models run inside a handful of fixed-shape jitted programs (one
per (prompt_bucket, gamma)); the host loop only reads the per-iteration
accept count.

A load-bearing corollary of greedy acceptance: the emitted stream is the
target's argmax stream for ANY draft behavior — a cold, stale, or even
garbage draft cache can only lower the acceptance rate, never change a
token. The serving scheduler's per-priority speculative gating
(``SchedulerConfig.speculative_priorities``) leans on exactly this: a
tick whose decode set includes a non-speculative priority class runs the
plain target tick and leaves the draft caches stale, and the next
speculative tick is still token-exact.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def _jax():
    import jax

    return jax




def build_spec_step(t_apply, d_apply, gamma: int):
    """The draft-propose / target-verify core shared by
    :func:`speculative_generate` (batch-1 host loop) and the serving
    engine's speculative tick (vmapped over slots):

    ``(t_params, d_params, t_cache, d_cache, last_tok, pos) ->
    (t_cache, d_cache, emit [gamma+1], lps [gamma+1], n_emit)``

    ``pos`` is the cache frontier (= valid entries in BOTH caches;
    ``last_tok`` is emitted-but-not-yet-cached). The draft proposes
    ``gamma`` tokens autoregressively, one target forward scores them
    all, the longest prefix matching the target's own argmax is accepted
    and the target's correction (or bonus) token appended — so
    ``n_emit = accepted + 1`` and the emitted stream equals plain greedy
    target decode. ``lps`` are the target's f32 log-softmax of each
    emitted token. Both cache frontiers are reset to ``pos + n_emit``;
    stale speculative rows beyond are overwritten before the causal
    frontier reaches them (serving.py's padded-prefill argument)."""
    jax = _jax()
    jnp = jax.numpy
    from .ops.kv_cache import reset_cache_index

    g = gamma

    def spec_step(t_params, d_params, t_cache, d_cache, last_tok, pos):
        def draft_one(carry, _):
            d_cache, tok, p = carry
            logits, d_cache = d_apply(
                d_params, tok.reshape(1, 1), positions=p.reshape(1, 1), decode=True, cache=d_cache
            )
            nxt = jnp.argmax(logits[0, -1].astype(jnp.float32)).astype(jnp.int32)
            return (d_cache, nxt, p + 1), nxt

        (d_cache, d_last, _), drafts = jax.lax.scan(
            draft_one, (d_cache, last_tok, pos), None, length=g
        )  # drafts [g] = tokens for positions pos+1..pos+g
        # one extra draft pass caches d_last's own row (needed when every
        # draft is accepted — the next iteration's frontier includes it)
        _, d_cache = d_apply(
            d_params, d_last.reshape(1, 1), positions=(pos + g).reshape(1, 1),
            decode=True, cache=d_cache,
        )

        # target scores last_tok + ALL g drafts in ONE pass: logits[j] is
        # the target's token for position pos+j+1, so t_argmax[g] is the
        # bonus token when every draft matches
        fed = jnp.concatenate([last_tok[None], drafts])  # [g+1]
        positions = (pos + jnp.arange(g + 1))[None]
        t_logits, t_cache = t_apply(
            t_params, fed[None], positions=positions, decode=True, cache=t_cache
        )
        rows = t_logits[0].astype(jnp.float32)  # [g+1, V]
        t_argmax = jnp.argmax(rows, axis=-1).astype(jnp.int32)

        matches = drafts == t_argmax[:g]
        n_acc = jnp.argmin(jnp.concatenate([matches, jnp.array([False])])).astype(jnp.int32)
        emit = jnp.where(
            jnp.arange(g + 1) < n_acc, jnp.concatenate([drafts, jnp.zeros((1,), jnp.int32)]), 0
        )
        emit = emit.at[n_acc].set(t_argmax[n_acc])
        n_emit = n_acc + 1
        lps = jax.vmap(lambda r, t: jax.nn.log_softmax(r)[t])(rows, emit)

        new_frontier = pos + n_emit
        t_cache = reset_cache_index(t_cache, new_frontier)
        d_cache = reset_cache_index(d_cache, new_frontier)
        return t_cache, d_cache, emit, lps, n_emit

    return spec_step


def speculative_generate(
    target_model,
    draft_model,
    input_ids,
    max_new_tokens: int = 32,
    gamma: int = 4,
    eos_token_id: Optional[int] = None,
    return_stats: bool = False,
):
    """Greedy speculative decode of ``input_ids`` [1, S] (batch 1).

    ``draft_model`` must share the target's vocabulary (typically a
    smaller model of the same family). Returns int32 [1, S + n] with
    n <= max_new_tokens (exactly max_new_tokens without EOS). With
    ``return_stats``: (tokens, {"target_forwards", "accept_rate", ...}).
    """
    jax = _jax()
    jnp = jax.numpy

    input_ids = jnp.asarray(input_ids, jnp.int32)
    if input_ids.ndim != 2 or input_ids.shape[0] != 1:
        raise ValueError(f"speculative_generate is batch-1 ([1, S]); got {input_ids.shape}")
    prompt_len = input_ids.shape[1]
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    cap = min(
        target_model.config.max_position_embeddings,
        draft_model.config.max_position_embeddings,
    )
    # +gamma headroom: the last iteration may write gamma speculative rows
    # past the budget before the host truncates
    if prompt_len + max_new_tokens + gamma > cap:
        raise ValueError(
            f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) + gamma "
            f"({gamma}) exceeds the smaller cache (max_position_embeddings={cap})"
        )

    from .generation import _params_mesh, _shard_batch, _trace_ctx

    mesh = _params_mesh(target_model.params)
    if mesh is not None:
        input_ids = _shard_batch(input_ids, mesh)
    mesh_key = None if mesh is None else tuple(sorted(mesh.shape.items()))
    key = ("spec", prompt_len, gamma, mesh_key)
    runners = target_model.__dict__.setdefault("_generate_runners", {})
    # the jitted closures capture the DRAFT's apply_fn: a cache hit is only
    # valid for the same draft function (id() of a dead model can be
    # recycled, so the value itself carries the identity check)
    hit = runners.get(key)
    if hit is None or hit[2] is not draft_model.apply_fn:
        t_apply, d_apply = target_model.apply_fn, draft_model.apply_fn

        @jax.jit
        def prefill(t_params, d_params, ids):
            positions = jnp.broadcast_to(jnp.arange(prompt_len), (1, prompt_len))
            t_logits, t_cache = t_apply(t_params, ids, positions=positions, decode=True, cache=None)
            _, d_cache = d_apply(d_params, ids, positions=positions, decode=True, cache=None)
            first = jnp.argmax(t_logits[0, -1].astype(jnp.float32)).astype(jnp.int32)
            return first, t_cache, d_cache

        _core = build_spec_step(t_apply, d_apply, gamma)

        @jax.jit
        def spec_step(t_params, d_params, t_cache, d_cache, last_tok, pos):
            """One iteration at frontier ``pos`` (shared core; the batch-1
            host loop discards the logprob tail). Returns
            (tokens [gamma+1], n_emit, t_cache, d_cache)."""
            t_cache, d_cache, emit, _, n_emit = _core(
                t_params, d_params, t_cache, d_cache, last_tok, pos
            )
            return emit, n_emit, t_cache, d_cache

        runners[key] = (prefill, spec_step, d_apply)
    prefill, spec_step, _ = runners[key]

    with _trace_ctx(mesh):
        first, t_cache, d_cache = prefill(target_model.params, draft_model.params, input_ids)
    out = [int(first)]
    target_forwards = 1
    pos = prompt_len
    last = first
    accepted_total = 0
    n_steps = 0
    while len(out) < max_new_tokens and (eos_token_id is None or out[-1] != eos_token_id):
        with _trace_ctx(mesh):
            emit, n_emit, t_cache, d_cache = spec_step(
                target_model.params, draft_model.params, t_cache, d_cache, last, jnp.int32(pos)
            )
        target_forwards += 1
        n_steps += 1
        n = int(n_emit)
        toks = np.asarray(emit)[:n].tolist()
        if eos_token_id is not None and eos_token_id in toks:
            toks = toks[: toks.index(eos_token_id) + 1]
            out.extend(toks)
            break
        out.extend(toks)
        pos += n
        last = jnp.int32(out[-1])

    out = out[:max_new_tokens]
    tokens = jnp.concatenate([input_ids, jnp.asarray(out, jnp.int32)[None]], axis=1)
    if not return_stats:
        return tokens
    # stats count only USABLE tokens (post eos/budget truncation): each spec
    # step contributes one correction; everything else it kept was accepted
    accepted_usable = max(0, len(out) - 1 - n_steps)
    stats = {
        "target_forwards": target_forwards,
        "emitted": len(out),
        "tokens_per_target_forward": len(out) / target_forwards,
        "accept_rate": accepted_usable / max(1, n_steps * gamma),
    }
    return tokens, stats
