"""In-package test utilities (shipped, like the reference package's
src/accelerate/test_utils): fixtures, decorators, self-checking scripts."""

from .training import RegressionDataset, RegressionModel, linear_loss_fn
from .fault_injection import CrashPoint, SimulatedCrash, corrupt_file
from .testing import (
    AccelerateTestCase,
    TempDirTestCase,
    execute_subprocess_async,
    require_multi_device,
    require_tpu,
    skip,
)
