"""Tiny fixtures for framework tests
(reference: src/accelerate/test_utils/training.py — RegressionModel /
RegressionDataset, a 1-parameter linear model used by every distributed
correctness test)."""

from __future__ import annotations

import numpy as np

from ..modeling import Model


class RegressionDataset:
    """y = a*x + b + noise (reference: test_utils/training.py RegressionDataset)."""

    def __init__(self, a=2.0, b=3.0, length=64, seed=42):
        rng = np.random.default_rng(seed)
        self.length = length
        self.x = rng.normal(size=(length,)).astype(np.float32)
        self.y = (a * self.x + b + rng.normal(scale=0.1, size=(length,))).astype(np.float32)

    def __len__(self):
        return self.length

    def __getitem__(self, i):
        return {"x": self.x[i], "y": self.y[i]}


def regression_apply(params, x):
    return params["a"] * x + params["b"]


def RegressionModel(a=0.0, b=0.0) -> Model:
    """(reference: test_utils/training.py RegressionModel — torch module with
    scalar weight+bias; here an apply_fn + 2-leaf pytree)."""
    params = {"a": np.float32(a), "b": np.float32(b)}
    return Model(regression_apply, params, name="RegressionModel")


def linear_loss_fn(params, batch):
    pred = regression_apply(params, batch["x"])
    return ((pred - batch["y"]) ** 2).mean()
