"""Self-checking gradient-accumulation / sync-semantics script.

Reference analogue: src/accelerate/test_utils/scripts/test_sync.py (410 LoC)
— asserts grads are (not) applied at the right steps under ``accumulate``/
``no_sync``. On TPU there are no DDP hooks to toggle; the observable
contract is *when the optimizer actually updates params*, which is what
this script checks. Asserts internally, exits nonzero on failure.
"""

from __future__ import annotations

import numpy as np


def _flat(params):
    import jax

    return np.concatenate([np.asarray(l).ravel() for l in jax.tree.leaves(params)])


def check_accumulate_applies_on_boundary(accelerator):
    """With accumulation=2: step 1 buffers (params frozen), step 2 applies."""
    import optax

    from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel

    model = accelerator.prepare_model(RegressionModel())
    opt = accelerator.prepare_optimizer(optax.sgd(0.05))
    ds = RegressionDataset(length=16, seed=1)
    batches = [
        {"x": np.stack([ds[i]["x"], ds[i + 1]["x"]]), "y": np.stack([ds[i]["y"], ds[i + 1]["y"]])}
        for i in range(0, 8, 2)
    ]

    def loss_fn(params, batch):
        pred = model.apply_fn(params, batch["x"])
        return ((pred - batch["y"]) ** 2).mean()

    p0 = _flat(model.params)
    with accelerator.accumulate(model):
        accelerator.backward(loss_fn, batches[0])
        opt.step()
    assert not accelerator.sync_gradients
    # step_was_skipped stays False: it reports fp16 overflow only
    # (reference: optimizer.py:188 _is_overflow), not accumulation no-ops
    assert not opt.step_was_skipped
    np.testing.assert_array_equal(_flat(model.params), p0)  # frozen mid-accumulation

    with accelerator.accumulate(model):
        accelerator.backward(loss_fn, batches[1])
        opt.step()
    assert accelerator.sync_gradients
    assert not opt.step_was_skipped
    assert np.abs(_flat(model.params) - p0).max() > 0  # applied on boundary
    accelerator.print("accumulate boundary OK")
    return model, opt, loss_fn, batches


def check_accumulated_equals_fused(accelerator, model, opt, loss_fn, batches):
    """Two accumulated half-batches must step like one fused batch."""
    import jax

    p_before = jax.tree.map(np.asarray, model.params)
    for b in batches[2:4]:
        with accelerator.accumulate(model):
            accelerator.backward(loss_fn, b)
            opt.step()
    p_accum = _flat(model.params)

    # rebuild at the same start and take one fused step
    model.params = jax.tree.map(np.asarray, p_before)
    fused = {
        "x": np.concatenate([batches[2]["x"], batches[3]["x"]]),
        "y": np.concatenate([batches[2]["y"], batches[3]["y"]]),
    }
    with accelerator.no_sync(model):
        pass  # no-op body: exercises the context manager
    accelerator.gradient_state._set_sync_gradients(True)
    accelerator._zero_grad_buffer()
    accelerator.backward(loss_fn, fused)
    accelerator.backward(loss_fn, fused)  # /accum(2) twice == one full grad
    opt.step()
    np.testing.assert_allclose(p_accum, _flat(model.params), atol=1e-5, rtol=1e-5)
    accelerator.print("accumulated == fused OK")


def check_no_sync_never_applies(accelerator):
    import optax

    from accelerate_tpu.test_utils.training import RegressionModel

    model = accelerator.prepare_model(RegressionModel())
    opt = accelerator.prepare_optimizer(optax.sgd(0.1))

    def loss_fn(params, batch):
        return ((model.apply_fn(params, batch["x"]) - batch["y"]) ** 2).mean()

    batch = {"x": np.ones((2, 1), np.float32), "y": np.ones((2, 1), np.float32)}
    p0 = _flat(model.params)
    for _ in range(3):
        with accelerator.no_sync(model):
            accelerator.backward(loss_fn, batch)
            opt.step()
    np.testing.assert_array_equal(_flat(model.params), p0)
    accelerator.print("no_sync OK")


def _reset_singletons():
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


def check_fast_path_accumulation(accelerator):
    """build_train_step with accum=2: params frozen off-boundary, and the
    2-microbatch result equals one fused batch (the jitted mirror of the
    imperative checks; reference: test_sync.py:455 step_model parity)."""
    import jax
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel, linear_loss_fn

    _reset_singletons()
    acc = Accelerator(gradient_accumulation_steps=2)
    ds = RegressionDataset(length=8, seed=3)
    model = acc.prepare_model(RegressionModel())
    acc.prepare_optimizer(optax.sgd(0.05))
    step = acc.build_train_step(linear_loss_fn)

    half_a = {"x": ds.x[:4], "y": ds.y[:4]}
    half_b = {"x": ds.x[4:], "y": ds.y[4:]}
    p0 = jax.tree.map(np.asarray, model.params)
    step(half_a)
    np.testing.assert_array_equal(_flat(model.params), _flat(p0))  # buffered
    step(half_b)
    p_accum = _flat(model.params)
    assert np.abs(p_accum - _flat(p0)).max() > 0  # applied on the boundary

    # fused single step at accum=1 from the same start
    _reset_singletons()
    acc2 = Accelerator()
    model2 = acc2.prepare_model(RegressionModel())
    acc2.prepare_optimizer(optax.sgd(0.05))
    step2 = acc2.build_train_step(linear_loss_fn)
    step2({"x": ds.x, "y": ds.y})
    np.testing.assert_allclose(p_accum, _flat(model2.params), atol=1e-5, rtol=1e-5)
    print("fast-path accumulation OK")


def check_end_of_dataloader_forces_sync(accelerator):
    """The LAST batch of an epoch applies the update even mid-accumulation
    window (reference sync_with_dataloader semantics: accelerator.py:1123 +
    GradientState end_of_dataloader)."""
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel, linear_loss_fn

    _reset_singletons()
    acc = Accelerator(gradient_accumulation_steps=2)
    # exactly 3 global batches (odd: the last lands mid-accumulation-window)
    ds = RegressionDataset(length=3 * acc.num_data_shards, seed=4)
    model = acc.prepare_model(RegressionModel())
    acc.prepare_optimizer(optax.sgd(0.05))
    loader = acc.prepare_data_loader(ds)
    loader.batch_size = 1  # per-shard
    step = acc.build_train_step(linear_loss_fn)
    assert len(loader) == 3, len(loader)

    p_after_two = None
    for i, batch in enumerate(loader):
        step(batch)
        if i == 1:
            p_after_two = _flat(model.params)
    # batch 3 is both off-boundary (micro 1 of 2) AND end-of-epoch: the
    # update must still apply
    assert np.abs(_flat(model.params) - p_after_two).max() > 0, (
        "end-of-dataloader did not force a gradient sync"
    )
    print("end-of-dataloader sync OK")


def check_scheduler_steps_with_optimizer(accelerator):
    """AcceleratedScheduler advances only when the optimizer really steps
    (reference: scheduler.py:54-84)."""
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel, linear_loss_fn

    _reset_singletons()
    acc = Accelerator(gradient_accumulation_steps=2)
    ds = RegressionDataset(length=8, seed=5)
    model = acc.prepare_model(RegressionModel())
    acc.prepare_optimizer(optax.sgd(optax.linear_schedule(0.1, 0.0, 10)))
    sched = acc.prepare_scheduler(optax.linear_schedule(0.1, 0.0, 10))
    step = acc.build_train_step(linear_loss_fn)
    half_a = {"x": ds.x[:4], "y": ds.y[:4]}
    half_b = {"x": ds.x[4:], "y": ds.y[4:]}
    assert sched.step_count == 0
    step(half_a)  # buffered: no optimizer step -> no scheduler step
    assert sched.step_count == 0, sched.step_count
    step(half_b)  # boundary: both step (scaled by the data-parallel degree,
    # reference scheduler.py:54-84 scales by num_processes)
    assert sched.step_count == acc.num_data_shards, sched.step_count
    print("scheduler-with-optimizer OK")


def main():
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import GradientAccumulationPlugin

    accelerator = Accelerator(
        gradient_accumulation_plugin=GradientAccumulationPlugin(num_steps=2)
    )
    model, opt, loss_fn, batches = check_accumulate_applies_on_boundary(accelerator)
    check_accumulated_equals_fused(accelerator, model, opt, loss_fn, batches)
    check_no_sync_never_applies(accelerator)
    check_fast_path_accumulation(accelerator)
    check_end_of_dataloader_forces_sync(accelerator)
    check_scheduler_steps_with_optimizer(accelerator)
    print("test_sync: ALL OK")


if __name__ == "__main__":
    main()
