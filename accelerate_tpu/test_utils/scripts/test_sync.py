"""Self-checking gradient-accumulation / sync-semantics script.

Reference analogue: src/accelerate/test_utils/scripts/test_sync.py (410 LoC)
— asserts grads are (not) applied at the right steps under ``accumulate``/
``no_sync``. On TPU there are no DDP hooks to toggle; the observable
contract is *when the optimizer actually updates params*, which is what
this script checks. Asserts internally, exits nonzero on failure.
"""

from __future__ import annotations

import numpy as np


def _flat(params):
    import jax

    return np.concatenate([np.asarray(l).ravel() for l in jax.tree.leaves(params)])


def check_accumulate_applies_on_boundary(accelerator):
    """With accumulation=2: step 1 buffers (params frozen), step 2 applies."""
    import optax

    from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel

    model = accelerator.prepare_model(RegressionModel())
    opt = accelerator.prepare_optimizer(optax.sgd(0.05))
    ds = RegressionDataset(length=16, seed=1)
    batches = [
        {"x": np.stack([ds[i]["x"], ds[i + 1]["x"]]), "y": np.stack([ds[i]["y"], ds[i + 1]["y"]])}
        for i in range(0, 8, 2)
    ]

    def loss_fn(params, batch):
        pred = model.apply_fn(params, batch["x"])
        return ((pred - batch["y"]) ** 2).mean()

    p0 = _flat(model.params)
    with accelerator.accumulate(model):
        accelerator.backward(loss_fn, batches[0])
        opt.step()
    assert not accelerator.sync_gradients
    # step_was_skipped stays False: it reports fp16 overflow only
    # (reference: optimizer.py:188 _is_overflow), not accumulation no-ops
    assert not opt.step_was_skipped
    np.testing.assert_array_equal(_flat(model.params), p0)  # frozen mid-accumulation

    with accelerator.accumulate(model):
        accelerator.backward(loss_fn, batches[1])
        opt.step()
    assert accelerator.sync_gradients
    assert not opt.step_was_skipped
    assert np.abs(_flat(model.params) - p0).max() > 0  # applied on boundary
    accelerator.print("accumulate boundary OK")
    return model, opt, loss_fn, batches


def check_accumulated_equals_fused(accelerator, model, opt, loss_fn, batches):
    """Two accumulated half-batches must step like one fused batch."""
    import jax

    p_before = jax.tree.map(np.asarray, model.params)
    for b in batches[2:4]:
        with accelerator.accumulate(model):
            accelerator.backward(loss_fn, b)
            opt.step()
    p_accum = _flat(model.params)

    # rebuild at the same start and take one fused step
    model.params = jax.tree.map(np.asarray, p_before)
    fused = {
        "x": np.concatenate([batches[2]["x"], batches[3]["x"]]),
        "y": np.concatenate([batches[2]["y"], batches[3]["y"]]),
    }
    with accelerator.no_sync(model):
        pass  # no-op body: exercises the context manager
    accelerator.gradient_state._set_sync_gradients(True)
    accelerator._zero_grad_buffer()
    accelerator.backward(loss_fn, fused)
    accelerator.backward(loss_fn, fused)  # /accum(2) twice == one full grad
    opt.step()
    np.testing.assert_allclose(p_accum, _flat(model.params), atol=1e-5, rtol=1e-5)
    accelerator.print("accumulated == fused OK")


def check_no_sync_never_applies(accelerator):
    import optax

    from accelerate_tpu.test_utils.training import RegressionModel

    model = accelerator.prepare_model(RegressionModel())
    opt = accelerator.prepare_optimizer(optax.sgd(0.1))

    def loss_fn(params, batch):
        return ((model.apply_fn(params, batch["x"]) - batch["y"]) ** 2).mean()

    batch = {"x": np.ones((2, 1), np.float32), "y": np.ones((2, 1), np.float32)}
    p0 = _flat(model.params)
    for _ in range(3):
        with accelerator.no_sync(model):
            accelerator.backward(loss_fn, batch)
            opt.step()
    np.testing.assert_array_equal(_flat(model.params), p0)
    accelerator.print("no_sync OK")


def main():
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import GradientAccumulationPlugin

    accelerator = Accelerator(
        gradient_accumulation_plugin=GradientAccumulationPlugin(num_steps=2)
    )
    model, opt, loss_fn, batches = check_accumulate_applies_on_boundary(accelerator)
    check_accumulated_equals_fused(accelerator, model, opt, loss_fn, batches)
    check_no_sync_never_applies(accelerator)
    accelerator.print("test_sync: ALL OK")


if __name__ == "__main__":
    main()
