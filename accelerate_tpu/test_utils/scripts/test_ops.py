"""Self-checking collective-ops script.

Reference analogue: src/accelerate/test_utils/scripts/test_ops.py (181 LoC)
— gather / broadcast / reduce / pad correctness on real collectives. Runs
single- or multi-process (the launcher's jax.distributed rendezvous);
asserts internally and exits nonzero on failure.
"""

from __future__ import annotations

import numpy as np


def check_gather(accelerator):
    from accelerate_tpu.utils import gather

    n = accelerator.num_processes
    local = np.full((2, 3), accelerator.process_index, np.float32)
    out = gather(local)
    assert out.shape == (2 * n, 3), out.shape
    assert sorted(set(out[:, 0].tolist())) == list(range(n))
    # structure preservation
    nested = gather({"a": local, "b": [local + 1]})
    assert nested["a"].shape == (2 * n, 3)
    assert nested["b"][0].shape == (2 * n, 3)
    accelerator.print("gather OK")


def check_gather_object(accelerator):
    from accelerate_tpu.utils import gather_object

    objs = gather_object([{"rank": accelerator.process_index}])
    ranks = sorted(o["rank"] for o in objs)
    assert ranks == list(range(accelerator.num_processes)), ranks
    accelerator.print("gather_object OK")


def check_broadcast(accelerator):
    from accelerate_tpu.utils import broadcast, broadcast_object_list

    value = np.arange(4, dtype=np.float32) * (accelerator.process_index + 1)
    out = broadcast(value, from_process=0)
    np.testing.assert_array_equal(np.asarray(out), np.arange(4, dtype=np.float32))

    objs = ["payload" if accelerator.is_main_process else None]
    objs = broadcast_object_list(objs, from_process=0)
    assert objs[0] == "payload"
    accelerator.print("broadcast OK")


def check_scatter_object(accelerator):
    from accelerate_tpu.utils.operations import scatter_object

    n = accelerator.num_processes
    payloads = [{"for": p, "rows": list(range(p * 2, p * 2 + 2))} for p in range(n)]
    mine = scatter_object(payloads if accelerator.is_main_process else None, from_process=0)
    assert mine["for"] == accelerator.process_index, mine
    assert mine["rows"] == [accelerator.process_index * 2, accelerator.process_index * 2 + 1]
    # repeated calls must stay in lockstep (sequence tags advance together)
    for round_ in range(3):
        got = scatter_object(
            [f"r{round_}p{p}" for p in range(n)] if accelerator.is_main_process else None,
            from_process=0,
        )
        assert got == f"r{round_}p{accelerator.process_index}", got
    accelerator.print("scatter_object OK")


def check_dispatch_loader(accelerator):
    """Dispatch-mode loader: process 0 reads, everyone gets its slice only
    (reference: DataLoaderDispatcher data_loader.py:704)."""
    import numpy as np

    from accelerate_tpu.data_loader import DataLoaderDispatcher, DataLoaderShard

    n = accelerator.num_processes
    data = [{"x": np.array([float(i)], np.float32)} for i in range(8 * n)]
    inner = DataLoaderShard(data, batch_size=2, device_placement=True)
    loader = DataLoaderDispatcher(inner)
    seen = 0
    for batch in loader:
        # the global batch is assembled from per-process slices
        assert batch["x"].shape[0] == 2 * accelerator.num_data_shards
        local = sum(np.asarray(s.data).size for s in batch["x"].addressable_shards)
        assert local * n == batch["x"].shape[0] or n == 1
        seen += 1
    assert seen == len(loader), (seen, len(loader))
    accelerator.print("dispatch loader OK")


def check_reduce(accelerator):
    from accelerate_tpu.utils import reduce

    n = accelerator.num_processes
    local = np.full((3,), float(accelerator.process_index + 1), np.float32)
    summed = reduce(local, reduction="sum")
    np.testing.assert_allclose(np.asarray(summed), np.full(3, n * (n + 1) / 2))
    mean = reduce(local, reduction="mean")
    np.testing.assert_allclose(np.asarray(mean), np.full(3, (n + 1) / 2))
    accelerator.print("reduce OK")


def check_pad_across_processes(accelerator):
    from accelerate_tpu.utils import pad_across_processes

    # each rank holds a different-length row; pad must equalise to the max
    length = 2 + accelerator.process_index
    local = np.ones((1, length), np.float32)
    padded = pad_across_processes(local, dim=1)
    max_len = 2 + accelerator.num_processes - 1
    assert padded.shape == (1, max_len), padded.shape
    np.testing.assert_array_equal(np.asarray(padded)[0, :length], np.ones(length))
    if length < max_len:
        np.testing.assert_array_equal(np.asarray(padded)[0, length:], np.zeros(max_len - length))
    accelerator.print("pad_across_processes OK")


def main():
    from accelerate_tpu import Accelerator

    accelerator = Accelerator()
    check_gather(accelerator)
    check_gather_object(accelerator)
    check_broadcast(accelerator)
    check_scatter_object(accelerator)
    check_dispatch_loader(accelerator)
    check_reduce(accelerator)
    check_pad_across_processes(accelerator)
    accelerator.print("test_ops: ALL OK")


if __name__ == "__main__":
    main()
