"""Multi-process checkpoint round-trip: every process participates in the
orbax sharded save, per-rank RNG files are written, and load_state
restores identical params on every rank.

Reference analogue: tests/test_state_checkpointing.py (444 LoC,
save/load round-trip equality) — but run as a REAL 2-process group
through the launcher, which the reference only does for its external-deps
checkpointing script. Self-checking: exits nonzero on failure.

The target directory comes from ``ACCELERATE_TEST_CKPT_DIR`` (all
processes must see the same filesystem — true for localhost groups and
for pods with NFS-mounted checkpoints).
"""

from __future__ import annotations

import os

import numpy as np


def main():
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.test_utils import RegressionDataset, RegressionModel, linear_loss_fn
    from accelerate_tpu.utils import set_seed
    from accelerate_tpu.utils.operations import gather_object

    ckpt_dir = os.environ.get("ACCELERATE_TEST_CKPT_DIR")
    assert ckpt_dir, "set ACCELERATE_TEST_CKPT_DIR to a shared directory"

    set_seed(123)
    acc = Accelerator()
    model = acc.prepare_model(RegressionModel())
    acc.prepare_optimizer(optax.sgd(0.1))
    loader = acc.prepare_data_loader(RegressionDataset(length=64), batch_size=4, shuffle=True, seed=9)
    step = acc.build_train_step(linear_loss_fn)

    for batch in loader:
        step(batch)
    saved_a = float(np.asarray(model.params["a"]))
    saved_step = acc.step
    acc.save_state(ckpt_dir)

    # keep training past the checkpoint, then restore
    for batch in loader:
        step(batch)
    assert float(np.asarray(model.params["a"])) != saved_a
    acc.load_state(ckpt_dir)

    restored_a = float(np.asarray(model.params["a"]))
    assert restored_a == saved_a, f"restore mismatch: {restored_a} vs {saved_a}"
    assert acc.step == saved_step, (acc.step, saved_step)

    # every rank restored the same value (orbax shards + replication agree)
    all_a = gather_object([restored_a])
    assert all(abs(v - saved_a) < 1e-12 for v in all_a), all_a

    # per-rank RNG files exist for every process in the group
    if acc.is_main_process:
        for rank in range(acc.num_processes):
            assert os.path.exists(os.path.join(ckpt_dir, f"rng_state_{rank}.pkl")), rank

    # async save in a process group: device->host copies now, background
    # writes drained by wait_for_checkpoint on every rank, then reload
    async_dir = ckpt_dir + "_async"
    for batch in loader:
        step(batch)
    async_a = float(np.asarray(model.params["a"]))
    acc.save_state(async_dir, async_save=True)
    for batch in loader:
        step(batch)
    acc.wait_for_checkpoint()
    acc.load_state(async_dir)
    assert float(np.asarray(model.params["a"])) == async_a

    # restored state still trains
    for batch in loader:
        step(batch)
    acc.wait_for_everyone()
    acc.print("test_checkpoint_resume: ALL OK")


if __name__ == "__main__":
    main()
