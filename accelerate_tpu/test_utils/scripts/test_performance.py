"""Per-strategy accuracy regression gates.

Reference analogue: test_utils/scripts/external_deps/test_performance.py
(298 LoC — trains MRPC under each strategy and asserts minimum
accuracy/F1 so a strategy that silently corrupts training fails CI, not
just crashes). Here every reference "strategy" is a mesh layout, so the
gate trains the same model/data under each layout and asserts the same
accuracy floor — plus cross-layout agreement, which the reference cannot
check (different backends) but one sharding engine can.

Self-checking: exits nonzero on failure. Run via
``python -m accelerate_tpu.test_utils.scripts.test_performance`` on the
8-device fake mesh or through ``accelerate-tpu launch``.
"""

from __future__ import annotations

import numpy as np

ACCURACY_FLOOR = 0.95  # planted-signal task: every healthy layout hits 1.0 with the warmup schedule
CROSS_LAYOUT_TOLERANCE = 0.08  # layouts see different batch shards; small drift allowed


def make_dataset(n=256, seq_len=32, vocab_size=256, seed=0):
    """Binary classification with a planted signal token (the shape of
    examples/nlp_example.py's SyntheticMRPC)."""
    rng = np.random.default_rng(seed)
    ids = rng.integers(5, vocab_size, size=(n, seq_len)).astype(np.int32)
    labels = rng.integers(0, 2, size=(n,)).astype(np.int32)
    ids[labels == 1, 3] = 4

    class DS:
        def __len__(self):
            return n

        def __getitem__(self, i):
            return {
                "input_ids": ids[i],
                "attention_mask": np.ones((seq_len,), np.bool_),
                "labels": labels[i],
            }

    return DS()


def run_layout(name: str, mesh_kwargs: dict, epochs: int = 14, precision: str = "bf16", loss_trace: int = 0):
    import jax.numpy as jnp
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.models import BertConfig, bert_classification_loss, create_bert_model
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils import set_seed
    from accelerate_tpu.utils.dataclasses import MeshConfig, ParallelismPlugin

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    set_seed(42)

    acc = Accelerator(
        mixed_precision=precision,
        parallelism_plugin=ParallelismPlugin(mesh_config=MeshConfig(**mesh_kwargs)),
    )
    cfg = BertConfig.tiny(num_labels=2)
    dataset = make_dataset(vocab_size=cfg.vocab_size)
    model = acc.prepare_model(create_bert_model(cfg, seq_len=32))
    acc.prepare_optimizer(optax.adamw(optax.linear_schedule(0.0, 1.5e-3, 8)))
    loader = acc.prepare_data_loader(dataset, batch_size=max(1, 32 // acc.num_data_shards), shuffle=True, seed=7)
    step = acc.build_train_step(lambda p, b: bert_classification_loss(p, b, model.apply_fn))
    eval_step = acc.build_eval_step(lambda p, ids, mask: model.apply_fn(p, ids, mask))

    losses = []
    for epoch in range(epochs):
        loader.set_epoch(epoch)
        for batch in loader:
            loss = step(batch)
            if loss_trace and len(losses) < loss_trace:
                losses.append(float(loss))
    if loss_trace:
        return losses

    correct = total = 0
    for batch in loader:
        logits = eval_step(batch["input_ids"], batch["attention_mask"])
        preds = acc.gather_for_metrics(jnp.argmax(logits, -1))
        labels = acc.gather_for_metrics(batch["labels"])
        correct += int((np.asarray(preds) == np.asarray(labels)).sum())
        total += len(np.asarray(labels))
    accuracy = correct / total
    acc.print(f"test_performance [{name}] accuracy={accuracy:.3f} mesh={dict(acc.mesh.shape)}")
    return accuracy


def run_moe_trace(mesh_kwargs: dict, steps: int = 8):
    """fp32 loss trajectory of a tiny Mixtral under a mesh layout — the
    expert-axis analogue of the BERT gate (routing + all-to-all dispatch
    must compute the same global math as pure dp)."""
    import jax
    import numpy as np
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.models.mixtral import MixtralConfig, create_mixtral_model, mixtral_lm_loss
    from accelerate_tpu.parallel.mesh import batch_sharding, data_parallel_size
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils import set_seed
    from accelerate_tpu.utils.dataclasses import MeshConfig, ParallelismPlugin

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    set_seed(11)
    acc = Accelerator(
        mixed_precision="no",
        parallelism_plugin=ParallelismPlugin(mesh_config=MeshConfig(**mesh_kwargs)),
    )
    seq_len = 16
    model = acc.prepare_model(create_mixtral_model(MixtralConfig.tiny(), seed=5, seq_len=seq_len))
    acc.prepare_optimizer(optax.adamw(1e-3))
    step = acc.build_train_step(lambda p, b: mixtral_lm_loss(p, b, module=model.module))
    rng = np.random.default_rng(3)
    global_batch = 16  # fixed GLOBAL batch so every layout sees identical data
    assert global_batch % data_parallel_size(acc.mesh) == 0
    losses = []
    for _ in range(steps):
        ids = rng.integers(0, 250, size=(global_batch, seq_len)).astype(np.int32)
        batch = jax.device_put({"input_ids": ids}, batch_sharding(acc.mesh))
        losses.append(float(step(batch)))
    return losses


def run_pipe_trace(mesh_kwargs: dict, steps: int = 8):
    """fp32 loss trajectory of a stacked-MLP regression trained through
    ``pipeline_apply`` — pipe=1 falls back to the plain layer scan, so the
    {pipe: k} trace vs {data: n} trace is exactly 'pipelining must not
    change the math'."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from accelerate_tpu.parallel.mesh import MeshConfig, batch_sharding
    from accelerate_tpu.parallel.pipeline import pipeline_apply, stage_sharding

    mesh = MeshConfig(**mesh_kwargs).build()
    width, layers, batch = 16, 4, 16
    ks = jax.random.split(jax.random.key(0), 2)
    params = {
        "w": jax.random.normal(ks[0], (layers, width, width)) * 0.1,
        "b": jnp.zeros((layers, width)),
    }
    n_pipe = mesh.shape.get("pipe", 1)
    sharding = stage_sharding(mesh) if n_pipe > 1 else NamedSharding(mesh, P())
    params = jax.tree.map(lambda l: jax.device_put(l, sharding), params)

    def layer_fn(p, h):
        return jnp.tanh(h @ p["w"] + p["b"]) + h

    opt = optax.adamw(1e-2)
    opt_state = opt.init(params)
    rng = np.random.default_rng(4)

    def loss_fn(p, x):
        return jnp.mean((pipeline_apply(layer_fn, p, x, mesh=mesh, num_microbatches=2) - 1.0) ** 2)

    @jax.jit
    def train_step(p, s, x):
        loss, g = jax.value_and_grad(loss_fn)(p, x)
        up, s = opt.update(g, s, p)
        return optax.apply_updates(p, up), s, loss

    losses = []
    for _ in range(steps):
        x = jax.device_put(
            rng.standard_normal((batch, width)).astype(np.float32), batch_sharding(mesh)
        )
        params, opt_state, loss = train_step(params, opt_state, x)
        losses.append(float(loss))
    return losses


def run_llama_trace(mesh_kwargs: dict, steps: int = 6):
    """Decoder-LM training trace (VERDICT r4 weak #3: the BERT gate can't
    see flash-bwd/remat/ring regressions): tiny llama with its production
    defaults — scan-over-layers, remat, GQA, auto attention dispatch (ring
    on seq-sharded meshes) — same data, fp32, per layout."""
    import jax
    import jax.numpy as jnp
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.models import LlamaConfig, causal_lm_loss, create_llama_model
    from accelerate_tpu.parallel.mesh import MeshConfig, batch_sharding
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils import set_seed
    from accelerate_tpu.utils.dataclasses import ParallelismPlugin

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    set_seed(42)
    acc = Accelerator(
        mixed_precision="no",
        parallelism_plugin=ParallelismPlugin(mesh_config=MeshConfig(**mesh_kwargs)),
    )
    seq_len = 32
    model = acc.prepare_model(create_llama_model(LlamaConfig.tiny(), seq_len=seq_len))
    acc.prepare_optimizer(optax.adamw(1e-3))
    step = acc.build_train_step(lambda p, b: causal_lm_loss(p, b, model.apply_fn))
    rng = np.random.default_rng(11)
    ids = rng.integers(5, 250, size=(16, seq_len)).astype(np.int32)  # fixed global batch
    batch = jax.device_put({"input_ids": ids}, batch_sharding(acc.mesh))
    losses = []
    for _ in range(steps):
        loss = step(batch)
        losses.append(float(jnp.asarray(loss)))
    return losses


def main():
    import jax

    n_dev = len(jax.devices())
    layouts = {"dp": {"data": -1}}
    if n_dev >= 8:
        layouts["fsdp"] = {"fsdp": 8}
        layouts["dp_x_tp"] = {"data": 4, "tensor": 2}
        layouts["hybrid_dp_fsdp_tp"] = {"data": 2, "fsdp": 2, "tensor": 2}
    elif n_dev >= 2:
        layouts["fsdp"] = {"fsdp": n_dev}

    scores = {}
    for name, mesh_kwargs in layouts.items():
        scores[name] = run_layout(name, mesh_kwargs)

    # The stronger invariant only one sharding engine can promise: in fp32
    # every layout computes the SAME global-batch math, so short loss
    # trajectories must agree bitwise-closely across layouts (bf16 is
    # excluded: reduction order legitimately perturbs rounding).
    traces = {
        name: run_layout(name, mesh_kwargs, epochs=2, precision="no", loss_trace=8)
        for name, mesh_kwargs in layouts.items()
    }
    base = traces.pop("dp")
    for name, trace in traces.items():
        np.testing.assert_allclose(trace, base, rtol=1e-5, err_msg=f"fp32 trajectory of {name} diverged from dp")

    # pipe and expert axes: same identical-trajectory contract, on the
    # programs that actually use them (GPipe schedule; MoE dispatch)
    if n_dev >= 8:
        moe_dp = run_moe_trace({"data": 8})
        for name, mesh_kwargs in {
            "dp_x_ep": {"expert": 2, "data": 4},
            "ep_x_tp": {"expert": 2, "tensor": 2, "data": 2},
        }.items():
            np.testing.assert_allclose(
                run_moe_trace(mesh_kwargs), moe_dp, rtol=2e-4,
                err_msg=f"fp32 MoE trajectory of {name} diverged from dp",
            )
        print(f"test_performance: MoE expert-axis trajectories match dp {moe_dp[:3]}...")

        llama_dp = run_llama_trace({"data": 8})
        for name, mesh_kwargs in {
            "llama_fsdp": {"fsdp": 8},
            "llama_dp_x_tp": {"data": 4, "tensor": 2},
            "llama_dp_x_sp": {"data": 2, "seq": 4},  # ring attention in training
        }.items():
            np.testing.assert_allclose(
                run_llama_trace(mesh_kwargs), llama_dp, rtol=2e-4,
                err_msg=f"fp32 decoder trajectory of {name} diverged from dp",
            )
        print(f"test_performance: llama decoder trajectories match dp {llama_dp[:3]}...")

        pipe_dp = run_pipe_trace({"data": 8})
        for name, mesh_kwargs in {
            "dp_x_pp2": {"pipe": 2, "data": 4},
            "pp4": {"pipe": 4, "data": 2},
        }.items():
            np.testing.assert_allclose(
                run_pipe_trace(mesh_kwargs), pipe_dp, rtol=1e-5,
                err_msg=f"fp32 pipeline trajectory of {name} diverged from dp",
            )
        print(f"test_performance: GPipe pipe-axis trajectories match dp {pipe_dp[:3]}...")

    failures = [f"{k}: {v:.3f} < {ACCURACY_FLOOR}" for k, v in scores.items() if v < ACCURACY_FLOOR]
    assert not failures, f"accuracy regression: {failures}"
    spread = max(scores.values()) - min(scores.values())
    assert spread <= CROSS_LAYOUT_TOLERANCE, (
        f"layouts disagree beyond tolerance: {scores} (spread {spread:.3f})"
    )
    print(f"test_performance: ALL OK {scores}")


if __name__ == "__main__":
    main()
