"""Self-checking multi-process (DCN) legs: orbax checkpoint save->load
(including reshard-on-load), DataLoaderDispatcher scatter, and ring
attention on a mesh that spans processes.

Reference analogue: the tier-2 pattern (SURVEY §4) where
test_utils/scripts/test_script.py runs under the real launcher
(reference: tests/test_multigpu.py:49-53). Round-4 VERDICT weak #4: these
three paths were only exercised single-process on the fake mesh — this
script runs them across a REAL 2-process JAX distributed mesh:

    accelerate-tpu launch --num_processes 2 --cpu --fake_devices 4 \
        -m accelerate_tpu.test_utils.scripts.test_dcn --tmpdir /tmp/x

Asserts internally; prints ``test_dcn: ALL OK`` on success.
"""

from __future__ import annotations

import argparse
import os

import numpy as np


def check_dispatcher(accelerator):
    """Process 0 reads every global batch; worker ranks receive their slice
    over DCN (reference: data_loader.py:704 dispatch mode)."""
    from accelerate_tpu.data_loader import prepare_data_loader
    from accelerate_tpu.utils.operations import gather_object

    class DS:
        def __len__(self):
            return 64  # divisible by the global batch: no uneven tail here

        def __getitem__(self, i):
            return {"x": np.float32(i)}

    loader = prepare_data_loader(
        DS(), batch_size=4, dispatch_batches=True, put_on_device=False, shuffle=False
    )
    global_bs = loader.total_batch_size
    seen = []
    for batch in loader:
        rows = [float(v) for v in np.asarray(batch["x"]).ravel()]
        # each process must hold its per-rank slice, not the global batch
        assert len(rows) == global_bs // accelerator.num_processes, (len(rows), global_bs)
        seen.extend(rows)
    all_rows = sorted(x for chunk in gather_object([seen]) for x in chunk)
    assert all_rows == [float(i) for i in range(64)], all_rows
    accelerator.print("dispatcher scatter OK")


def check_checkpoint_roundtrip(accelerator, tmpdir: str):
    """Multi-host orbax save -> perturb -> load (every host participates),
    then reshard-on-load into a DIFFERENT mesh layout."""
    import jax
    import jax.numpy as jnp
    import optax

    from accelerate_tpu.modeling import Model

    def apply(p, x):
        return x @ p["w"]

    r = np.random.default_rng(7)
    w0 = r.normal(0, 0.1, (16, 64)).astype(np.float32)
    model = accelerator.prepare_model(Model(apply, {"w": w0.copy()}, name="m"))
    accelerator.prepare_optimizer(optax.sgd(0.1))
    step = accelerator.build_train_step(lambda p, b: jnp.mean((apply(p, b["x"]) - 1.0) ** 2))
    from accelerate_tpu.parallel.mesh import batch_sharding

    batch = {"x": np.ones((4 * accelerator.num_data_shards, 16), np.float32)}
    batch = jax.device_put(batch, batch_sharding(accelerator.mesh))
    float(step(batch))
    want = np.asarray(jax.device_get(model.params["w"]))

    ckpt = os.path.join(tmpdir, "dcn_ckpt")
    accelerator.save_state(ckpt)
    model.params = jax.tree_util.tree_map(lambda l: l * 0, model.params)
    accelerator.load_state(ckpt)
    got = np.asarray(jax.device_get(model.params["w"]))
    np.testing.assert_allclose(got, want, rtol=1e-6)
    accelerator.print("checkpoint save/load across hosts OK")
    return want, ckpt


def check_checkpoint_reshard(want, ckpt):
    """Load the same checkpoint into an fsdp-sharded layout (reshard-on-load
    over DCN; the reference needs FULL_STATE_DICT or merge tooling)."""
    import jax
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.modeling import Model
    from accelerate_tpu.parallel.mesh import MeshConfig
    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.utils.dataclasses import ParallelismPlugin

    AcceleratorState._reset_state()
    GradientState._reset_state()

    def apply(p, x):
        return x @ p["w"]

    acc2 = Accelerator(
        parallelism_plugin=ParallelismPlugin(
            mesh_config=MeshConfig(data=2, fsdp=-1),
            # force the fsdp split for this small kernel
            sharding_rules=[(r"^w$", jax.sharding.PartitionSpec(None, "fsdp"))],
        )
    )
    model2 = acc2.prepare_model(Model(apply, {"w": np.zeros((16, 64), np.float32)}, name="m"))
    acc2.prepare_optimizer(optax.sgd(0.1))
    acc2.load_state(ckpt)
    assert not model2.param_shardings["w"].is_fully_replicated, "fsdp split did not apply"
    for shard in model2.params["w"].addressable_shards:
        np.testing.assert_allclose(np.asarray(shard.data), want[shard.index], rtol=1e-6)
    acc2.print("checkpoint reshard-on-load (replicated -> fsdp) OK")


def check_ring_attention(accelerator):
    """Ring attention on a seq axis spanning BOTH processes vs the dense
    single-device reference computed redundantly on every host."""
    import jax

    from accelerate_tpu.ops.attention import dot_product_attention
    from accelerate_tpu.parallel.context import context_parallel_attention, sequence_sharding
    from accelerate_tpu.parallel.mesh import MeshConfig

    n_dev = len(jax.devices())
    mesh = MeshConfig(seq=n_dev).build()
    b, s, h, d = 2, 8 * n_dev, 4, 16
    r = np.random.default_rng(3)
    q, k, v = (r.normal(0, 1, (b, s, h, d)).astype(np.float32) for _ in range(3))
    ref = np.asarray(dot_product_attention(jax.numpy.asarray(q), jax.numpy.asarray(k), jax.numpy.asarray(v), causal=True, use_flash=False))

    shard = sequence_sharding(mesh)
    def put(x):
        return jax.make_array_from_callback(x.shape, shard, lambda idx: x[idx])

    out = context_parallel_attention(put(q), put(k), put(v), mesh=mesh, causal=True, method="ring")
    for sh in out.addressable_shards:
        np.testing.assert_allclose(np.asarray(sh.data), ref[sh.index], atol=3e-5, rtol=3e-5)
    accelerator.print("ring attention across processes OK")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tmpdir", default=os.environ.get("ACCELERATE_TEST_TMPDIR", "/tmp"))
    args = ap.parse_args()

    from accelerate_tpu import Accelerator
    from accelerate_tpu.parallel.mesh import MeshConfig
    from accelerate_tpu.utils.dataclasses import ParallelismPlugin

    accelerator = Accelerator(
        parallelism_plugin=ParallelismPlugin(mesh_config=MeshConfig(data=-1))
    )
    assert accelerator.num_processes >= 2, (
        f"test_dcn needs a real multi-process launch, got {accelerator.num_processes}"
    )
    check_dispatcher(accelerator)
    want, ckpt = check_checkpoint_roundtrip(accelerator, args.tmpdir)
    check_checkpoint_reshard(want, ckpt)
    check_ring_attention(accelerator)
    accelerator.print("test_dcn: ALL OK")


if __name__ == "__main__":
    main()
