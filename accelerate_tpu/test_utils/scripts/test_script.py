"""The canonical self-checking "does distributed work" script, run by
``accelerate-tpu test`` through the real launcher.

Reference analogue: src/accelerate/test_utils/scripts/test_script.py
(952 LoC; run by ``accelerate test``, commands/test.py:45). Sections mirror
the reference's: process control (:94), RNG/shuffle sync (:175), dataloader
sharding (:193,253), end-to-end training parity vs a single-device baseline
(:455 training_check), split_between_processes (:666). Asserts internally
and exits nonzero on failure.
"""

from __future__ import annotations

import numpy as np


def check_process_control(accelerator):
    state = accelerator.state
    assert state.process_index == accelerator.process_index
    accelerator.wait_for_everyone()
    with accelerator.main_process_first():
        pass
    executed = []
    accelerator.on_main_process(lambda: executed.append("main"))()
    if accelerator.is_main_process:
        assert executed == ["main"]
    with accelerator.split_between_processes(list(range(10))) as chunk:
        assert len(chunk) >= 10 // max(1, accelerator.num_processes)
    accelerator.print("process control OK")


def check_dataloader_sharding(accelerator):
    from accelerate_tpu.data_loader import DataLoaderShard

    class DS:
        def __len__(self):
            return 40

        def __getitem__(self, i):
            return {"x": np.float32(i)}

    dl = DataLoaderShard(DS(), batch_size=2)
    seen = []
    for batch in dl:
        assert batch["x"].shape[0] == dl.total_batch_size
        seen.extend(np.asarray(batch["x"]).ravel().tolist())
    # all real samples appear; the padded tail duplicates batch-start rows
    assert set(range(40)) <= set(int(v) for v in seen)
    # shuffled loaders agree across processes (same seed -> same order)
    dl_a = DataLoaderShard(DS(), batch_size=2, shuffle=True, seed=5)
    dl_b = DataLoaderShard(DS(), batch_size=2, shuffle=True, seed=5)
    order = lambda d: [v for b in d for v in np.asarray(b["x"]).ravel().tolist()]
    assert order(dl_a) == order(dl_b)
    accelerator.print("dataloader sharding OK")


def check_training_parity(accelerator):
    """Distributed fast-path training must match the single-device loop
    (reference training_check: test_script.py:455)."""
    import jax
    import optax

    from accelerate_tpu.test_utils import RegressionDataset, RegressionModel, linear_loss_fn

    ds = RegressionDataset(length=64)
    model = accelerator.prepare_model(RegressionModel())
    optimizer = accelerator.prepare_optimizer(optax.sgd(0.1))
    loader = accelerator.prepare_data_loader(ds)
    loader.batch_size = max(1, 16 // accelerator.num_data_shards)
    step = accelerator.build_train_step(linear_loss_fn)
    for _ in range(2):
        for batch in loader:
            step(batch)

    # single-device baseline
    params = {"a": np.float32(0.0), "b": np.float32(0.0)}
    tx = optax.sgd(0.1)
    opt_state = tx.init(params)
    i = 0
    for _ in range(2):
        for _ in range(len(loader)):
            idx = np.arange(i, i + 16) % 64
            i += 16
            batch = {"x": ds.x[idx], "y": ds.y[idx]}
            g = jax.grad(linear_loss_fn)(params, batch)
            updates, opt_state = tx.update(g, opt_state, params)
            params = optax.apply_updates(params, updates)

    a_dist, a_base = float(model.params["a"]), float(params["a"])
    assert abs(a_dist - a_base) < 1e-4, f"training diverged: {a_dist} vs {a_base}"
    accelerator.print("training parity OK")


def check_gather_ops(accelerator):
    import jax.numpy as jnp

    x = jnp.arange(8.0)
    gathered = accelerator.gather(x)
    assert gathered.shape[0] >= 8
    reduced = accelerator.reduce(jnp.ones(4), "mean")
    np.testing.assert_allclose(np.asarray(reduced), np.ones(4))
    objs = accelerator.gather_for_metrics([accelerator.process_index], use_gather_object=True)
    assert accelerator.process_index in objs
    accelerator.print("gather ops OK")


def main():
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import set_seed

    set_seed(42)
    accelerator = Accelerator()
    accelerator.print(f"state: mesh={dict(accelerator.mesh.shape)} procs={accelerator.num_processes}")
    check_process_control(accelerator)
    check_dataloader_sharding(accelerator)
    check_gather_ops(accelerator)
    check_training_parity(accelerator)
    accelerator.print("ALL CHECKS PASSED")


if __name__ == "__main__":
    main()
