"""The canonical self-checking "does distributed work" script, run by
``accelerate-tpu test`` through the real launcher.

Reference analogue: src/accelerate/test_utils/scripts/test_script.py
(952 LoC; run by ``accelerate test``, commands/test.py:45). Sections mirror
the reference's: process control (:94), RNG/shuffle sync (:175), dataloader
sharding (:193,253), end-to-end training parity vs a single-device baseline
(:455 training_check), split_between_processes (:666). Asserts internally
and exits nonzero on failure.
"""

from __future__ import annotations

import numpy as np


def check_process_control(accelerator):
    state = accelerator.state
    assert state.process_index == accelerator.process_index
    accelerator.wait_for_everyone()
    with accelerator.main_process_first():
        pass
    executed = []
    accelerator.on_main_process(lambda: executed.append("main"))()
    if accelerator.is_main_process:
        assert executed == ["main"]
    with accelerator.split_between_processes(list(range(10))) as chunk:
        assert len(chunk) >= 10 // max(1, accelerator.num_processes)
    accelerator.print("process control OK")


def _local_order(dl):
    """Values yielded to THIS process, in order (loaders built with
    device_placement=False so rows stay host-local numpy — safe under
    multi-process where placed arrays span non-addressable devices)."""
    return [float(v) for b in dl for v in np.asarray(b["x"]).ravel()]


def make_ds(length: int):
    """Toy dict-dataset: sample i is {"x": float(i)}."""

    class DS:
        def __len__(self):
            return length

        def __getitem__(self, i):
            return {"x": np.float32(i)}

    return DS()


def check_dataloader_sharding(accelerator):
    from accelerate_tpu.data_loader import DataLoaderShard
    from accelerate_tpu.utils.operations import gather_object

    DS = lambda: make_ds(40)
    pc = max(1, accelerator.num_processes)
    dl = DataLoaderShard(DS(), batch_size=2, device_placement=False)
    seen = []
    for batch in dl:
        assert batch["x"].shape[0] == dl.total_batch_size // pc
        seen.extend(np.asarray(batch["x"]).ravel().tolist())
    # all real samples appear globally; the padded tail duplicates rows
    global_seen = [v for chunk in gather_object([seen]) for v in chunk]
    assert set(range(40)) <= set(int(v) for v in global_seen)
    # same seed -> every process derives the same global permutation
    dl_a = DataLoaderShard(DS(), batch_size=2, shuffle=True, seed=5, device_placement=False)
    dl_b = DataLoaderShard(DS(), batch_size=2, shuffle=True, seed=5, device_placement=False)
    assert _local_order(dl_a) == _local_order(dl_b)
    accelerator.print("dataloader sharding OK")


def _single_device_baseline(ds, n_steps_per_epoch, epochs=2, lr=0.1, global_batch=16, skipped=()):
    """The fp32 single-device reference loop every distributed mode must
    match. ``skipped``: step indices the distributed run's fp16 GradScaler
    rejected (overflow while the scale calibrates — torch GradScaler does
    the same); the baseline must drop those batches too for step-for-step
    parity."""
    import jax
    import optax

    from accelerate_tpu.test_utils import linear_loss_fn

    params = {"a": np.float32(0.0), "b": np.float32(0.0)}
    tx = optax.sgd(lr)
    opt_state = tx.init(params)
    i = 0
    step_idx = 0
    for _ in range(epochs):
        for _ in range(n_steps_per_epoch):
            idx = np.arange(i, i + global_batch) % len(ds)
            i += global_batch
            if step_idx in skipped:
                step_idx += 1
                continue
            step_idx += 1
            batch = {"x": ds.x[idx], "y": ds.y[idx]}
            g = jax.grad(linear_loss_fn)(params, batch)
            updates, opt_state = tx.update(g, opt_state, params)
            params = optax.apply_updates(params, updates)
    return params


def _fresh_accelerator(**kwargs):
    """Reset the borg singletons and build a new Accelerator — the script's
    equivalent of the reference constructing one Accelerator per
    training_check mode (test_script.py:455)."""
    from accelerate_tpu import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    return Accelerator(**kwargs)


def check_training_parity(accelerator):
    """Distributed fast-path training must match the single-device loop in
    every precision mode (reference training_check: test_script.py:455
    covers fp32/bf16/fp16)."""
    import optax

    from accelerate_tpu.test_utils import RegressionDataset, RegressionModel, linear_loss_fn

    # tolerance per dtype policy: fp32 exact-ish; bf16/fp16 compute rounds
    # the matmul but the 2-param regression still lands within ~1e-2
    for mixed_precision, tol in (("no", 1e-4), ("bf16", 2e-2), ("fp16", 2e-2)):
        acc = _fresh_accelerator(mixed_precision=mixed_precision)
        ds = RegressionDataset(length=64)
        model = acc.prepare_model(RegressionModel())
        acc.prepare_optimizer(optax.sgd(0.1))
        loader = acc.prepare_data_loader(ds)
        loader.batch_size = max(1, 16 // acc.num_data_shards)
        step = acc.build_train_step(linear_loss_fn)
        optimizer = acc._optimizers[-1]
        skipped = set()
        step_idx = 0
        for _ in range(2):
            for batch in loader:
                step(batch)
                if optimizer.step_was_skipped:
                    skipped.add(step_idx)
                step_idx += 1

        params = _single_device_baseline(ds, n_steps_per_epoch=len(loader), skipped=skipped)
        a_dist, a_base = float(model.params["a"]), float(params["a"])
        b_dist, b_base = float(model.params["b"]), float(params["b"])
        assert abs(a_dist - a_base) < tol and abs(b_dist - b_base) < tol, (
            f"[{mixed_precision}] training diverged: a {a_dist} vs {a_base}, b {b_dist} vs {b_base}"
        )
        acc.print(f"training parity [{mixed_precision}] OK")


def check_split_batches(accelerator):
    """``split_batches=True``: batch_size is the GLOBAL batch (each shard
    sees batch_size // n rows); False: per-shard (global = batch_size * n).
    Reference semantics: data_loader.py:110 BatchSamplerShard + the
    split_batches field (dataclasses.py:773)."""
    from accelerate_tpu.data_loader import DataLoaderShard

    n = max(1, accelerator.num_data_shards)
    pc = max(1, accelerator.num_processes)
    if 16 % n:
        accelerator.print("split batches SKIPPED (mesh does not divide 16)")
        return
    DS = lambda: make_ds(64)
    dl_split = DataLoaderShard(DS(), batch_size=16, split_batches=True, device_placement=False)
    assert dl_split.total_batch_size == 16, dl_split.total_batch_size
    batch = next(iter(dl_split))
    assert batch["x"].shape[0] == 16 // pc  # this process's rows of the global 16

    dl_grow = DataLoaderShard(DS(), batch_size=16, split_batches=False)
    assert dl_grow.total_batch_size == 16 * n
    accelerator.print("split batches OK")


def check_uneven_gather_exactness(accelerator):
    """gather_for_metrics on a dataset length coprime with the mesh must
    return EXACTLY the dataset — padded-tail rows dropped, no duplicates
    (reference: accelerator.py:2799-2871 remainder truncation;
    external_deps/test_metrics.py asserts sklearn-exactness on MRPC)."""
    length = 61  # prime: never divides evenly into any mesh batch
    acc = _fresh_accelerator()

    loader = acc.prepare_data_loader(make_ds(length))
    loader.batch_size = max(1, 8 // max(1, acc.num_data_shards))
    seen = []
    for batch in loader:
        seen.append(np.asarray(acc.gather_for_metrics(batch["x"])))
    flat = np.concatenate(seen)
    assert len(flat) == length, f"expected exactly {length} rows, got {len(flat)}"
    assert sorted(int(v) for v in flat) == list(range(length)), "gathered rows are not the dataset"
    acc.print("uneven gather exactness OK")


def check_epoch_reshuffle(accelerator):
    """set_epoch reshuffles (different order per epoch) while staying
    deterministic for a given (seed, epoch) — the reference's
    SeedableRandomSampler contract (data_loader.py:73, test_script.py:364)."""
    from accelerate_tpu.data_loader import DataLoaderShard

    DS = lambda: make_ds(32)
    dl = DataLoaderShard(DS(), batch_size=2, shuffle=True, seed=7, device_placement=False)
    dl.set_epoch(0)
    e0 = _local_order(dl)
    dl.set_epoch(1)
    e1 = _local_order(dl)
    assert e0 != e1, "epochs must reshuffle"

    dl2 = DataLoaderShard(DS(), batch_size=2, shuffle=True, seed=7, device_placement=False)
    dl2.set_epoch(1)
    assert _local_order(dl2) == e1, "same (seed, epoch) must give the same order on every process"
    accelerator.print("epoch reshuffle OK")


def check_trigger(accelerator):
    """Early-stop flag semantics (reference: accelerator.py:2583-2640
    set_trigger/check_trigger — a flag all-reduced across processes)."""
    assert accelerator.check_trigger() is False
    if accelerator.process_index == accelerator.num_processes - 1:
        accelerator.set_trigger()
    fired = accelerator.check_trigger()
    assert fired is True, "trigger set on one rank must be visible on all"
    assert accelerator.check_trigger() is False, "check_trigger must reset the flag"
    accelerator.print("trigger OK")


def check_gather_ops(accelerator):
    import jax.numpy as jnp

    x = jnp.arange(8.0)
    gathered = accelerator.gather(x)
    assert gathered.shape[0] >= 8
    reduced = accelerator.reduce(jnp.ones(4), "mean")
    np.testing.assert_allclose(np.asarray(reduced), np.ones(4))
    objs = accelerator.gather_for_metrics([accelerator.process_index], use_gather_object=True)
    assert accelerator.process_index in objs
    accelerator.print("gather ops OK")


def main():
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import set_seed

    set_seed(42)
    accelerator = Accelerator()
    accelerator.print(f"state: mesh={dict(accelerator.mesh.shape)} procs={accelerator.num_processes}")
    check_process_control(accelerator)
    check_dataloader_sharding(accelerator)
    check_split_batches(accelerator)
    check_epoch_reshuffle(accelerator)
    check_gather_ops(accelerator)
    check_trigger(accelerator)
    # the singleton-resetting checks run last (they rebuild the Accelerator)
    check_uneven_gather_exactness(accelerator)
    check_training_parity(accelerator)
    accelerator.print("ALL CHECKS PASSED")


if __name__ == "__main__":
    main()
