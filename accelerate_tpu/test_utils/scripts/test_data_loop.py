"""Self-checking distributed data-loop script.

Reference analogue: src/accelerate/test_utils/scripts/
test_distributed_data_loop.py (410 LoC) — dispatch-vs-shard loader
equivalence, uneven batches under both ``even_batches`` policies, and
mid-epoch resume. Run through the real launcher (single- and
multi-process); asserts internally and exits nonzero on failure.
"""

from __future__ import annotations

import numpy as np


def make_ds(length: int):
    class DS:
        def __len__(self):
            return length

        def __getitem__(self, i):
            return {"x": np.float32(i)}

    return DS()


def check_shard_vs_dispatch(accelerator):
    """Dispatch mode (process 0 reads, scatters row slices) must deliver the
    same global content as shard mode (every host reads its own rows)
    (reference: DataLoaderDispatcher data_loader.py:704 vs DataLoaderShard
    :500)."""
    from accelerate_tpu.data_loader import prepare_data_loader

    def collect(dispatch):
        loader = prepare_data_loader(
            make_ds(24),
            batch_size=max(1, 4 // max(1, accelerator.num_data_shards)),
            dispatch_batches=dispatch,
        )
        out = []
        for batch in loader:
            gathered = accelerator.gather_for_metrics(batch["x"])
            out.append(sorted(float(v) for v in np.asarray(gathered).ravel()))
        return out

    shard_seq = collect(False)
    dispatch_seq = collect(True)
    assert shard_seq == dispatch_seq, f"shard {shard_seq} != dispatch {dispatch_seq}"
    accelerator.print("shard vs dispatch OK")


def check_uneven_batch_policies(accelerator):
    """even_batches=True pads the tail to the full global batch;
    even_batches=False pads only to a shard multiple (never ragged —
    static shapes). Reference: data_loader.py:878-916."""
    from accelerate_tpu.data_loader import DataLoaderShard

    n = max(1, accelerator.num_data_shards)
    dl_even = DataLoaderShard(make_ds(10), batch_size=4)
    sizes_even = [b["x"].shape[0] for b in dl_even]
    assert all(s == 4 * n for s in sizes_even), sizes_even

    dl_min = DataLoaderShard(make_ds(10), batch_size=4, even_batches=False)
    sizes_min = [b["x"].shape[0] for b in dl_min]
    assert sizes_min[:-1] == [4 * n] * (len(sizes_min) - 1), sizes_min
    assert sizes_min[-1] % n == 0, sizes_min
    accelerator.print("uneven batch policies OK")


def check_skip_first_batches_resume(accelerator):
    """skip_first_batches(loader, k) must reproduce the uninterrupted run's
    batches k..end (reference: data_loader.py:1371)."""
    from accelerate_tpu.data_loader import prepare_data_loader, skip_first_batches

    def batch_values(loader):
        return [
            sorted(float(v) for v in np.asarray(accelerator.gather_for_metrics(b["x"])).ravel())
            for b in loader
        ]

    loader = prepare_data_loader(
        make_ds(32), batch_size=max(1, 4 // max(1, accelerator.num_data_shards))
    )
    full = batch_values(loader)
    resumed = batch_values(skip_first_batches(loader, 3))
    assert resumed == full[3:], f"{resumed} != {full[3:]}"
    accelerator.print("skip_first_batches resume OK")


def check_iteration_counts_equal(accelerator):
    """Every process must see the same number of batches — the reference
    needs join_uneven_inputs for this (accelerator.py:1194); static padded
    shapes give it by construction."""
    from accelerate_tpu.data_loader import prepare_data_loader
    from accelerate_tpu.utils.operations import gather_object

    loader = prepare_data_loader(
        make_ds(13), batch_size=max(1, 2 // max(1, accelerator.num_data_shards))
    )
    count = sum(1 for _ in loader)
    counts = gather_object([count])
    assert len(set(counts)) == 1, f"batch counts diverge across processes: {counts}"
    accelerator.print("iteration counts OK")


def main():
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import set_seed

    set_seed(7)
    accelerator = Accelerator()
    check_shard_vs_dispatch(accelerator)
    check_uneven_batch_policies(accelerator)
    check_skip_first_batches_resume(accelerator)
    check_iteration_counts_equal(accelerator)
    accelerator.print("test_data_loop: ALL OK")


if __name__ == "__main__":
    main()
