"""Self-checking gradient-compression script across REAL processes.

Reference done-bar: the DDP comm hooks' compressed allreduce must converge
like the uncompressed one across process boundaries (reference:
utils/dataclasses.py:130-226). Run via
``accelerate-tpu launch --num_processes 2 ...`` — the shard_map reduction
then crosses the jax.distributed transport, the multi-host path the
feature exists for. Asserts internally; exits nonzero on failure.
"""

from __future__ import annotations

import numpy as np


def train(compression, steps=32):
    import optax

    from accelerate_tpu import Accelerator, MeshConfig, ParallelismPlugin
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.test_utils import RegressionDataset, RegressionModel, linear_loss_fn

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    acc = Accelerator(
        parallelism_plugin=ParallelismPlugin(
            mesh_config=MeshConfig(data=-1), grad_compression=compression
        )
    )
    model = acc.prepare_model(RegressionModel())
    acc.prepare_optimizer(optax.sgd(0.1))
    step = acc.build_train_step(linear_loss_fn)
    ds = RegressionDataset(length=64, seed=0)
    losses = []
    for s in range(steps):
        idx = np.arange(s * 16, (s + 1) * 16) % 64
        losses.append(float(step({"x": ds.x[idx], "y": ds.y[idx]})))
    params = {k: float(np.asarray(v).ravel()[0]) for k, v in model.params.items()}
    return losses, params, acc


def main():
    from accelerate_tpu.parallel.compression import wire_bytes

    plain_losses, plain_params, acc = train(None)
    for method, tol in (("bf16", 0.02), ("int8", 0.03)):
        losses, params, acc = train(method)
        assert losses[-1] < 0.05, (method, losses[-5:])
        np.testing.assert_allclose(losses, plain_losses, atol=tol, rtol=0.1,
                                   err_msg=f"{method} trajectory diverged")
        for k, v in plain_params.items():
            assert abs(params[k] - v) < 0.1, (method, k, params[k], v)
        acc.print(f"compression[{method}] OK (wire bytes per reduction: "
                  f"{wire_bytes(acc._models[-1].params, method)} vs f32 "
                  f"{wire_bytes(acc._models[-1].params, None)})")
    acc.print("test_compression: ALL OK")


if __name__ == "__main__":
    main()
