"""Crash-point fault injection for the checkpoint save path.

The save protocol (``checkpointing.save_accelerator_state``) calls
:func:`accelerate_tpu.ft.crashpoints.crash_point` at every state
transition; this module installs hooks that kill the save there —
driving the crash-at-every-point matrix in
``tests/test_fault_tolerance.py`` that proves ``load_state()``
auto-resume always lands on a valid checkpoint::

    with CrashPoint("pre_rename"):
        with pytest.raises(SimulatedCrash):
            accelerator.save_state()        # dies mid-commit
    accelerator.load_state()                # resumes from the last GOOD one

``CrashPoint(..., action="kill")`` hard-kills the process with
``os._exit`` (no atexit, no finally blocks — the closest in-process
approximation of a SIGKILL'd pod) for subprocess-driven tests.
:func:`corrupt_file` truncates/garbles committed files to exercise the
manifest's size/crc32 detection.
"""

from __future__ import annotations

import json
import os
import signal
import time
from pathlib import Path

from ..ft.crashpoints import (
    ALL_CRASH_POINTS,
    CRASH_POINTS,
    RESTORE_CRASH_POINTS,
    SERVING_CRASH_POINTS,
    set_crash_hook,
)

__all__ = [
    "SimulatedCrash",
    "CrashPoint",
    "ReplicaChaos",
    "corrupt_file",
    "CRASH_POINTS",
    "RESTORE_CRASH_POINTS",
    "SERVING_CRASH_POINTS",
    "ALL_CRASH_POINTS",
]


class SimulatedCrash(RuntimeError):
    """Raised by :class:`CrashPoint` — deliberately NOT an ``OSError`` so
    the checkpoint path's IO retry decorator never absorbs it (a real
    kill isn't retryable either)."""


class CrashPoint:
    """Context manager that crashes the save at a labeled point.

    ``label`` must be one of
    :data:`~accelerate_tpu.ft.crashpoints.ALL_CRASH_POINTS` (save-path
    ``CRASH_POINTS`` or restore-path ``RESTORE_CRASH_POINTS``). ``hits``
    delays the crash to the Nth time the label is reached (e.g.
    the second model's pytree write). ``action``: ``"raise"`` (default)
    raises :class:`SimulatedCrash`; ``"kill"`` calls ``os._exit(17)``.
    The hook is process-wide and cleared on exit; ``fired`` records
    whether the crash actually triggered."""

    EXIT_CODE = 17

    def __init__(self, label: str, action: str = "raise", hits: int = 1):
        if label not in ALL_CRASH_POINTS:
            raise ValueError(f"unknown crash point {label!r}; choose from {ALL_CRASH_POINTS}")
        if action not in ("raise", "kill"):
            raise ValueError(f"action must be raise|kill, got {action!r}")
        self.label = label
        self.action = action
        self.hits = max(1, int(hits))
        self.fired = False
        self._seen = 0

    def _hook(self, label: str, **ctx):
        if label != self.label:
            return
        self._seen += 1
        if self._seen < self.hits:
            return
        self.fired = True
        if self.action == "kill":
            os._exit(self.EXIT_CODE)
        raise SimulatedCrash(f"simulated crash at checkpoint save point {self.label!r}")

    def __enter__(self):
        set_crash_hook(self._hook)
        return self

    def __exit__(self, *exc):
        set_crash_hook(None)
        return False


class ReplicaChaos:
    """Chaos controller for the serving fleet — the serving twin of
    :class:`CrashPoint`, driving the crash-at-every-point failover matrix
    in ``tests/test_fleet.py``.

    Targets one labeled serving point
    (:data:`~accelerate_tpu.ft.crashpoints.SERVING_CRASH_POINTS`:
    ``pre_tick``/``mid_prefill``/``mid_decode`` inside
    ``ServingEngine.step`` and ``pre_handoff`` in the router's
    disaggregated dispatch), optionally on ONE named replica of a fleet
    (serving crash points pass ``replica=<name>`` context; ``replica=None``
    matches any). ``action``:

    * ``"crash"``   — raise :class:`SimulatedCrash` (the replica process
      died; its KV may still be exportable)
    * ``"poison"``  — raise ``serving_fleet.NonFinitePoison`` (the PR-9
      non-finite watchdog tripped: numerics are suspect, so the router
      quarantines and fails over by recompute only, never trusting the
      replica's KV)
    * ``"hang"``    — sleep ``hang_s`` (drives tick-timeout degradation)
    * ``"latency"`` — sleep ``latency_s`` (slow-replica jitter)

    ``hits`` delays firing to the Nth matching visit; with ``repeat`` the
    hook keeps firing on every later visit too (a persistently sick
    replica), otherwise it fires once. ``fired``/``count`` record what
    happened. Like :class:`CrashPoint`, both raise actions use exception
    types that are deliberately NOT ``OSError`` — the failover handoff
    leg's ``utils.retry`` wrapper must never absorb a simulated death.

    **Process-level actions** (the multi-process fleet's REAL faults,
    installed into one engine-worker subprocess via
    :meth:`install_from_env` at boot):

    * ``"sigkill"``  — ``os.kill(os.getpid(), SIGKILL)``: the kernel
      removes the process mid-tick; the supervisor observes a ``-9``
      exit and fails the worker's in-flight snapshots over
    * ``"sigstop"``  — ``os.kill(os.getpid(), SIGSTOP)``: the process
      freezes (a real hang, not a sleep); the supervisor's heartbeat
      timeouts escalate degraded → quarantined and SIGKILL it
    """

    #: actions that end (or freeze) the whole process rather than raise
    PROCESS_ACTIONS = ("sigkill", "sigstop")

    def __init__(
        self,
        label: str,
        replica: str = None,
        action: str = "crash",
        hits: int = 1,
        repeat: bool = False,
        latency_s: float = 0.005,
        hang_s: float = 0.05,
    ):
        if label not in SERVING_CRASH_POINTS:
            raise ValueError(
                f"unknown serving crash point {label!r}; choose from {SERVING_CRASH_POINTS}"
            )
        if action not in ("crash", "poison", "hang", "latency") + self.PROCESS_ACTIONS:
            raise ValueError(
                "action must be crash|poison|hang|latency|sigkill|sigstop, "
                f"got {action!r}"
            )
        self.label = label
        self.replica = replica
        self.action = action
        self.hits = max(1, int(hits))
        self.repeat = bool(repeat)
        self.latency_s = float(latency_s)
        self.hang_s = float(hang_s)
        self.fired = False
        self.count = 0
        self._seen = 0

    def _hook(self, label: str, **ctx):
        if label != self.label:
            return
        if self.replica is not None and ctx.get("replica") != self.replica:
            return
        self._seen += 1
        if self._seen < self.hits or (self.fired and not self.repeat):
            return
        self.fired = True
        self.count += 1
        if self.action == "hang":
            time.sleep(self.hang_s)
            return
        if self.action == "latency":
            time.sleep(self.latency_s)
            return
        if self.action == "sigkill":
            os.kill(os.getpid(), signal.SIGKILL)
            return  # unreachable: SIGKILL is not deliverable-to-self-later
        if self.action == "sigstop":
            os.kill(os.getpid(), signal.SIGSTOP)
            return  # resumes here only if something SIGCONTs the process
        where = f"{self.label!r}" + (f" on replica {self.replica!r}" if self.replica else "")
        if self.action == "poison":
            from ..serving_fleet import NonFinitePoison

            raise NonFinitePoison(f"simulated non-finite poison at {where}")
        raise SimulatedCrash(f"simulated replica crash at {where}")

    def __enter__(self):
        set_crash_hook(self._hook)
        return self

    def __exit__(self, *exc):
        set_crash_hook(None)
        return False

    # ------------------------------------------------------------------ #
    # process-level installation (engine-worker subprocesses)
    # ------------------------------------------------------------------ #

    def to_env_spec(self, worker: str) -> str:
        """Serialize this chaos for ONE named worker process as the JSON
        the ``ACCELERATE_TPU_PROC_CHAOS`` env var carries."""
        return json.dumps(
            {
                "worker": worker,
                "label": self.label,
                "action": self.action,
                "hits": self.hits,
                "repeat": self.repeat,
                "hang_s": self.hang_s,
                "latency_s": self.latency_s,
            }
        )

    @classmethod
    def install_from_env(cls, worker: str, env_var: str = "ACCELERATE_TPU_PROC_CHAOS"):
        """Worker-boot hook: if the env var names THIS worker, build the
        chaos and install its hook permanently (no context manager — the
        process lives inside the chaos until it dies). Returns the
        installed instance or None. The supervisor only sets the var on
        the targeted incarnation, so a respawn boots clean."""
        spec = os.environ.get(env_var)
        if not spec:
            return None
        cfg = json.loads(spec)
        if cfg.get("worker") not in (None, worker):
            return None
        chaos = cls(
            cfg["label"],
            replica=worker,
            action=cfg.get("action", "sigkill"),
            hits=int(cfg.get("hits", 1)),
            repeat=bool(cfg.get("repeat", False)),
            latency_s=float(cfg.get("latency_s", 0.005)),
            hang_s=float(cfg.get("hang_s", 0.05)),
        )
        set_crash_hook(chaos._hook)
        return chaos


def corrupt_file(path, mode: str = "truncate", nbytes: int = 16) -> str:
    """Damage a checkpoint file in place to exercise integrity checks.

    ``mode``: ``"truncate"`` chops ``nbytes`` off the end (size mismatch),
    ``"garbage"`` flips bytes in place keeping the size (crc32 mismatch),
    ``"delete"`` removes the file (missing-file detection). Returns the
    path for chaining."""
    p = Path(path)
    if mode == "delete":
        p.unlink()
        return str(p)
    data = p.read_bytes()
    if mode == "truncate":
        p.write_bytes(data[: max(0, len(data) - nbytes)])
    elif mode == "garbage":
        if not data:
            raise ValueError(f"cannot garble empty file {p}")
        n = min(nbytes, len(data))
        head = bytes((b ^ 0xFF) for b in data[:n])
        p.write_bytes(head + data[n:])
    else:
        raise ValueError(f"mode must be truncate|garbage|delete, got {mode!r}")
    return str(p)
