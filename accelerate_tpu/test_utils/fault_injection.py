"""Crash-point fault injection for the checkpoint save path.

The save protocol (``checkpointing.save_accelerator_state``) calls
:func:`accelerate_tpu.ft.crashpoints.crash_point` at every state
transition; this module installs hooks that kill the save there —
driving the crash-at-every-point matrix in
``tests/test_fault_tolerance.py`` that proves ``load_state()``
auto-resume always lands on a valid checkpoint::

    with CrashPoint("pre_rename"):
        with pytest.raises(SimulatedCrash):
            accelerator.save_state()        # dies mid-commit
    accelerator.load_state()                # resumes from the last GOOD one

``CrashPoint(..., action="kill")`` hard-kills the process with
``os._exit`` (no atexit, no finally blocks — the closest in-process
approximation of a SIGKILL'd pod) for subprocess-driven tests.
:func:`corrupt_file` truncates/garbles committed files to exercise the
manifest's size/crc32 detection.
"""

from __future__ import annotations

import os
from pathlib import Path

from ..ft.crashpoints import ALL_CRASH_POINTS, CRASH_POINTS, RESTORE_CRASH_POINTS, set_crash_hook

__all__ = [
    "SimulatedCrash",
    "CrashPoint",
    "corrupt_file",
    "CRASH_POINTS",
    "RESTORE_CRASH_POINTS",
    "ALL_CRASH_POINTS",
]


class SimulatedCrash(RuntimeError):
    """Raised by :class:`CrashPoint` — deliberately NOT an ``OSError`` so
    the checkpoint path's IO retry decorator never absorbs it (a real
    kill isn't retryable either)."""


class CrashPoint:
    """Context manager that crashes the save at a labeled point.

    ``label`` must be one of
    :data:`~accelerate_tpu.ft.crashpoints.ALL_CRASH_POINTS` (save-path
    ``CRASH_POINTS`` or restore-path ``RESTORE_CRASH_POINTS``). ``hits``
    delays the crash to the Nth time the label is reached (e.g.
    the second model's pytree write). ``action``: ``"raise"`` (default)
    raises :class:`SimulatedCrash`; ``"kill"`` calls ``os._exit(17)``.
    The hook is process-wide and cleared on exit; ``fired`` records
    whether the crash actually triggered."""

    EXIT_CODE = 17

    def __init__(self, label: str, action: str = "raise", hits: int = 1):
        if label not in ALL_CRASH_POINTS:
            raise ValueError(f"unknown crash point {label!r}; choose from {ALL_CRASH_POINTS}")
        if action not in ("raise", "kill"):
            raise ValueError(f"action must be raise|kill, got {action!r}")
        self.label = label
        self.action = action
        self.hits = max(1, int(hits))
        self.fired = False
        self._seen = 0

    def _hook(self, label: str):
        if label != self.label:
            return
        self._seen += 1
        if self._seen < self.hits:
            return
        self.fired = True
        if self.action == "kill":
            os._exit(self.EXIT_CODE)
        raise SimulatedCrash(f"simulated crash at checkpoint save point {self.label!r}")

    def __enter__(self):
        set_crash_hook(self._hook)
        return self

    def __exit__(self, *exc):
        set_crash_hook(None)
        return False


def corrupt_file(path, mode: str = "truncate", nbytes: int = 16) -> str:
    """Damage a checkpoint file in place to exercise integrity checks.

    ``mode``: ``"truncate"`` chops ``nbytes`` off the end (size mismatch),
    ``"garbage"`` flips bytes in place keeping the size (crc32 mismatch),
    ``"delete"`` removes the file (missing-file detection). Returns the
    path for chaining."""
    p = Path(path)
    if mode == "delete":
        p.unlink()
        return str(p)
    data = p.read_bytes()
    if mode == "truncate":
        p.write_bytes(data[: max(0, len(data) - nbytes)])
    elif mode == "garbage":
        if not data:
            raise ValueError(f"cannot garble empty file {p}")
        n = min(nbytes, len(data))
        head = bytes((b ^ 0xFF) for b in data[:n])
        p.write_bytes(head + data[n:])
    else:
        raise ValueError(f"mode must be truncate|garbage|delete, got {mode!r}")
    return str(p)
