"""Test harness utilities shipped in-package
(reference: src/accelerate/test_utils/testing.py, 870 LoC — require_*
decorators :151-585, AccelerateTestCase :639, TempDirTestCase :606,
execute_subprocess_async :753)."""

from __future__ import annotations

import os
import shutil
import sys
import tempfile
import unittest
from pathlib import Path

from ..utils.imports import is_tpu_available


def skip(reason="Test was skipped"):
    import unittest

    return unittest.skip(reason)


def require_tpu(test_case):
    """(reference: testing.py:346 require_tpu)."""
    return unittest.skipUnless(is_tpu_available(), "test requires TPU")(test_case)


def require_multi_device(test_case):
    import jax

    return unittest.skipUnless(len(jax.devices()) > 1, "test requires multiple devices")(test_case)


def require_cpu_only(test_case):
    import jax

    return unittest.skipUnless(jax.default_backend() == "cpu", "test requires CPU backend")(test_case)


def require_device_count(n: int):
    """Skip unless at least ``n`` devices are attached (reference analogue:
    require_multi_device/require_multi_gpu with counts, testing.py:151+)."""

    def decorator(test_case):
        import jax

        return unittest.skipUnless(len(jax.devices()) >= n, f"test requires >= {n} devices")(test_case)

    return decorator


def require_package(name: str, import_name: str | None = None):
    """Generic availability gate (the reference ships ~60 hand-written
    require_* decorators, testing.py:151-585; one factory covers them)."""
    import importlib.util

    def decorator(test_case):
        found = importlib.util.find_spec(import_name or name) is not None
        return unittest.skipUnless(found, f"test requires {name}")(test_case)

    return decorator


require_transformers = require_package("transformers")
require_safetensors = require_package("safetensors")
require_orbax = require_package("orbax-checkpoint", "orbax.checkpoint")
require_tensorboard = require_package("tensorboard")
require_wandb = require_package("wandb")
require_torch = require_package("torch")


def slow(test_case):
    """Gate long tests behind ACCELERATE_RUN_SLOW=1 (reference:
    testing.py slow decorator)."""
    from ..utils.environment import parse_flag_from_env

    return unittest.skipUnless(parse_flag_from_env("ACCELERATE_RUN_SLOW"), "slow test; set ACCELERATE_RUN_SLOW=1")(
        test_case
    )


class AccelerateTestCase(unittest.TestCase):
    """Resets singleton state between tests (reference: testing.py:639-651)."""

    def tearDown(self):
        super().tearDown()
        from ..state import AcceleratorState, GradientState, PartialState

        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()


class TempDirTestCase(AccelerateTestCase):
    """Class-scoped temp dir, wiped between tests (reference: testing.py:606)."""

    clear_on_setup = True

    @classmethod
    def setUpClass(cls):
        cls.tmpdir = Path(tempfile.mkdtemp())

    @classmethod
    def tearDownClass(cls):
        if cls.tmpdir.exists():
            shutil.rmtree(cls.tmpdir, ignore_errors=True)

    def setUp(self):
        super().setUp()
        if self.clear_on_setup:
            for path in self.tmpdir.glob("**/*"):
                if path.is_file():
                    path.unlink()
                elif path.is_dir():
                    shutil.rmtree(path, ignore_errors=True)


def execute_subprocess_async(cmd: list, env=None, timeout: int = 600) -> "SubprocessResult":
    """Run a command, stream+capture output, raise on failure
    (reference: testing.py:753)."""
    import subprocess

    env = env if env is not None else os.environ.copy()
    result = subprocess.run(
        [str(c) for c in cmd], capture_output=True, text=True, env=env, timeout=timeout
    )
    if result.returncode != 0:
        raise RuntimeError(
            f"command {' '.join(map(str, cmd))!r} failed (rc={result.returncode})\n"
            f"stdout:\n{result.stdout}\nstderr:\n{result.stderr}"
        )
    return result


def get_launch_command(num_processes: int = 1) -> list:
    """(reference: testing.py:110 DEFAULT_LAUNCH_COMMAND)."""
    return [sys.executable, "-m", "accelerate_tpu.commands.launch", "--num_processes", str(num_processes)]
