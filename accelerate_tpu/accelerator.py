"""The ``Accelerator``: prepare / train-step / gather / checkpoint engine.

Reference analogue: src/accelerate/accelerator.py (4015 LoC, class at :184).
The contract preserved: a user writes a plain training loop, calls
``prepare()`` once, and gets sharding + mixed precision + grad accumulation +
checkpointing + tracking for free. What changes is *how*: the reference
dispatches to per-strategy wrapper branches (DDP/FSDP/DeepSpeed/Megatron,
accelerator.py:1447-2285); here ``prepare`` lays parameters out on one mesh
with ``NamedSharding``s and the whole hot loop (forward/backward/allreduce/
optimizer — reference call stack §3.4) becomes **one jitted function** with
gradient accumulation folded in as a branchless on-device buffer.

Two ways to drive training:

* **fast path** — ``step = accelerator.build_train_step(loss_fn)``; call
  ``step(batch)`` per dataloader batch. One XLA program per step; grad sync
  is an XLA-inserted reduction over the batch axes.
* **imperative parity path** — ``accumulate()`` / ``backward(loss_fn,
  batch)`` / ``optimizer.step()`` / ``clip_grad_norm_`` mirror the
  reference's eager API; each piece is itself jit-cached so the cost over
  the fast path is only the python between calls.
"""

from __future__ import annotations

import contextlib
import os
from typing import Any, Callable, Optional

import numpy as np

from .data_loader import BaseDataLoader, prepare_data_loader, skip_first_batches as _skip_first_batches
from .logging import get_logger
from .modeling import Model, as_model
from .optimizer import AcceleratedOptimizer
from .parallel.mesh import data_parallel_size
from .parallel.sharding import fsdp_rules_for, infer_shardings
from .scheduler import AcceleratedScheduler
from .state import AcceleratorState, GradientState
from .utils.dataclasses import (
    AutocastKwargs,
    DataLoaderConfiguration,
    DistributedInitKwargs,
    DistributedType,
    GradientAccumulationPlugin,
    GradScalerKwargs,
    ParallelismPlugin,
    ProfileKwargs,
    ProjectConfiguration,
)
from .utils.operations import gather, gather_object, pad_across_processes, reduce

logger = get_logger(__name__)


def _jax():
    import jax

    return jax


def _jnp():
    import jax.numpy as jnp

    return jnp


class Accelerator:
    """(reference: accelerator.py:184)."""

    def __init__(
        self,
        device_placement: bool = True,
        split_batches: bool = False,
        mixed_precision: Optional[str] = None,
        gradient_accumulation_steps: int = 1,
        cpu: bool = False,
        dataloader_config: Optional[DataLoaderConfiguration] = None,
        log_with=None,
        project_dir: Optional[str] = None,
        project_config: Optional[ProjectConfiguration] = None,
        gradient_accumulation_plugin: Optional[GradientAccumulationPlugin] = None,
        parallelism_plugin: Optional[ParallelismPlugin] = None,
        rng_types: Optional[list] = None,
        kwargs_handlers: Optional[list] = None,
        step_scheduler_with_optimizer: bool = True,
    ):
        # kwargs handlers (reference: accelerator.py:415-452)
        from .utils.dataclasses import CompileKwargs, FaultToleranceKwargs, TelemetryKwargs

        self.autocast_handler = AutocastKwargs()
        self.scaler_handler = GradScalerKwargs()
        self.profile_handler = ProfileKwargs()
        self.init_handler = DistributedInitKwargs()
        self.telemetry_handler = TelemetryKwargs()
        self.ft_handler = FaultToleranceKwargs()
        self.compile_handler = CompileKwargs()
        # opt-in behaviors (signal handlers, tracker retries) only activate
        # when the user passed the handler explicitly
        self._ft_explicit = False
        self._compile_explicit = False
        self.fp8_recipe_handler = None
        for handler in kwargs_handlers or []:
            if isinstance(handler, AutocastKwargs):
                self.autocast_handler = handler
            elif isinstance(handler, GradScalerKwargs):
                self.scaler_handler = handler
            elif isinstance(handler, ProfileKwargs):
                self.profile_handler = handler
            elif isinstance(handler, DistributedInitKwargs):
                self.init_handler = handler
            elif isinstance(handler, TelemetryKwargs):
                self.telemetry_handler = handler
            elif isinstance(handler, FaultToleranceKwargs):
                self.ft_handler = handler
                self._ft_explicit = True
            elif isinstance(handler, CompileKwargs):
                self.compile_handler = handler
                self._compile_explicit = True
            else:
                from .utils.dataclasses import Fp8RecipeKwargs, MixedPrecisionPolicy

                if isinstance(handler, Fp8RecipeKwargs):
                    self.fp8_recipe_handler = handler
                elif isinstance(handler, MixedPrecisionPolicy):
                    # full dtype-policy override (e.g. softmax_dtype="bfloat16"
                    # — the HBM-bandwidth lever, see the policy's docstring)
                    self._dtype_policy_override = handler

        if gradient_accumulation_plugin is None:
            env_steps = int(os.environ.get("ACCELERATE_GRADIENT_ACCUMULATION_STEPS", gradient_accumulation_steps))
            gradient_accumulation_plugin = GradientAccumulationPlugin(num_steps=env_steps)
        elif gradient_accumulation_steps != 1:
            raise ValueError("Pass either gradient_accumulation_steps or a GradientAccumulationPlugin, not both")

        self.project_configuration = project_config or ProjectConfiguration(project_dir=project_dir)
        if project_dir is not None and self.project_configuration.project_dir is None:
            self.project_configuration.set_directories(project_dir)

        init_kwargs = {}
        if self.init_handler.coordinator_address is not None:
            init_kwargs = dict(
                coordinator_address=self.init_handler.coordinator_address,
                num_processes=self.init_handler.num_processes,
                process_id=self.init_handler.process_id,
                local_device_ids=self.init_handler.local_device_ids,
            )
        self.state = AcceleratorState(
            mixed_precision=mixed_precision,
            cpu=cpu,
            parallelism_plugin=parallelism_plugin,
            _from_accelerator=True,
            **init_kwargs,
        )
        if getattr(self, "_dtype_policy_override", None) is not None:
            # the handler must AGREE with mixed_precision on the core dtype
            # fields — a wholesale override that silently flips them (e.g.
            # dropping fp8, or bf16 compute under mixed_precision="no")
            # would be a footgun for users adding the handler just for
            # softmax_dtype
            derived, override = self.state.dtype_policy, self._dtype_policy_override
            for field_name in ("param_dtype", "compute_dtype", "output_dtype", "fp8"):
                if getattr(override, field_name) != getattr(derived, field_name):
                    raise ValueError(
                        f"MixedPrecisionPolicy({field_name}={getattr(override, field_name)!r}) "
                        f"conflicts with mixed_precision={self.state.mixed_precision!r} "
                        f"(which implies {field_name}={getattr(derived, field_name)!r}); "
                        f"set the field to match, or change mixed_precision"
                    )
            self.state.dtype_policy = override
        self.gradient_state = GradientState(gradient_accumulation_plugin)
        if getattr(self.state.dtype_policy, "fp8", False):
            # attach the recipe where trace-time code (the zoo's dense
            # factory) can reach it: the globally-visible dtype policy.
            # Delayed scaling is OPT-IN via an explicit Fp8RecipeKwargs —
            # bare mixed_precision="fp8" keeps the stateless dynamic recipe
            # (delayed needs the fp8 collection threaded as model.state,
            # which plain generate()/loss paths don't do)
            from .utils.dataclasses import Fp8RecipeKwargs

            self.state.dtype_policy.fp8_recipe = self.fp8_recipe_handler or Fp8RecipeKwargs(
                delayed_scaling=False
            )
        self.device_placement = device_placement
        self.step_scheduler_with_optimizer = step_scheduler_with_optimizer
        self.rng_types = rng_types or ["numpy", "python"]

        self.dataloader_config = dataloader_config or DataLoaderConfiguration(split_batches=split_batches)
        if split_batches:
            self.dataloader_config.split_batches = True

        # registries (reference keeps the same lists: accelerator.py:520-540)
        self._models: list[Model] = []
        self._optimizers: list[AcceleratedOptimizer] = []
        self._schedulers: list[AcceleratedScheduler] = []
        self._dataloaders: list[BaseDataLoader] = []
        self._custom_objects: list = []
        self._save_model_hooks: list = []
        self._load_model_hooks: list = []

        # imperative-path machinery — gradient buffers are per-model
        # (multi-model setups like GANs must not share one buffer)
        self.step = 0
        self._grad_buffers: dict[int, Any] = {}
        self._grad_count = 0
        self._clip_max_norm = None
        self._last_grad_norm = None
        self._jit_cache: dict = {}
        self._trigger_flag = False

        # fp16 dynamic loss scale (host-side; bf16 needs none of this —
        # reference scaler: accelerator.py:551-604)
        self._loss_scale = self.scaler_handler.init_scale if self.mixed_precision == "fp16" else 1.0
        self._scale_growth_tracker = 0

        self.trackers: list = []
        self._log_with = log_with

        # runtime telemetry (lazy — see the `telemetry` property)
        self._telemetry = None

        # compile management (docs/usage_guides/compilation.md): the shared
        # ProgramCache + persistent caches activate when a CompileKwargs
        # handler was passed or ACCELERATE_COMPILE_CACHE_DIR is set — a
        # bare Accelerator() must never start writing cache files
        self._program_cache = None
        if self._compile_explicit or os.environ.get("ACCELERATE_COMPILE_CACHE_DIR"):
            from .aot import ExecutableStore, ProgramCache, configure_persistent_cache, resolve_cache_dir

            ch = self.compile_handler
            cache_dir = resolve_cache_dir(
                ch.cache_dir, self.project_dir, self.project_configuration.compile_cache_dir_name
            )
            store = None
            if cache_dir is not None and ch.executable_store:
                store = ExecutableStore(os.path.join(cache_dir, "executables"))
            self._program_cache = ProgramCache(store=store)
            if cache_dir is not None and ch.persistent_xla_cache:
                configure_persistent_cache(os.path.join(cache_dir, "xla"), ch.min_compile_time_secs)

        # fault tolerance (docs/usage_guides/fault_tolerance.md): the
        # checkpoint a run resumed from (protected from pruning), the
        # one-final-checkpoint latch, and the preemption handler
        self._resumed_from: Optional[str] = None
        self._preempt_checkpointed = False
        self._preempt_agreed = False
        self._preemption = None
        if self._ft_explicit and self.ft_handler.handle_preemption:
            from .ft.preemption import PreemptionHandler

            def _on_preempt(signame: str):
                if self._telemetry is not None:
                    self._telemetry.log.event("preempt", severity="warning", signal=signame)

            self._preemption = PreemptionHandler(
                signals=self.ft_handler.preemption_signals, on_preempt=_on_preempt
            )
            self._preemption.install()

        self.flag_tensor = None

    # ------------------------------------------------------------------ #
    # topology / state passthroughs (reference: accelerator.py:600-1030)
    # ------------------------------------------------------------------ #

    @property
    def mesh(self):
        return self.state.mesh

    @property
    def device(self):
        return self.state.device

    @property
    def distributed_type(self) -> DistributedType:
        return self.state.distributed_type

    @property
    def num_processes(self) -> int:
        return self.state.num_processes

    @property
    def process_index(self) -> int:
        return self.state.process_index

    @property
    def local_process_index(self) -> int:
        return self.state.local_process_index

    @property
    def is_main_process(self) -> bool:
        return self.state.is_main_process

    @property
    def is_local_main_process(self) -> bool:
        return self.state.is_local_main_process

    @property
    def is_last_process(self) -> bool:
        return self.state.is_last_process

    @property
    def mixed_precision(self) -> str:
        return self.state.mixed_precision

    @property
    def use_distributed(self) -> bool:
        return self.state.use_distributed

    @property
    def num_data_shards(self) -> int:
        return data_parallel_size(self.mesh)

    @property
    def sync_gradients(self) -> bool:
        return self.gradient_state.sync_gradients

    @property
    def gradient_accumulation_steps(self) -> int:
        return self.gradient_state.num_steps

    @gradient_accumulation_steps.setter
    def gradient_accumulation_steps(self, value: int):
        self.gradient_state.plugin_kwargs.update({"num_steps": value})

    @property
    def project_dir(self):
        return self.project_configuration.project_dir

    @property
    def logging_dir(self):
        return self.project_configuration.logging_dir

    @property
    def save_iteration(self):
        return self.project_configuration.iteration

    def print(self, *args, **kwargs):
        self.state.print(*args, **kwargs)

    def wait_for_everyone(self):
        self.state.wait_for_everyone()

    def split_between_processes(self, inputs, apply_padding: bool = False):
        return self.state.split_between_processes(inputs, apply_padding=apply_padding)

    def on_main_process(self, function):
        return self.state.on_main_process(function)

    def on_local_main_process(self, function):
        return self.state.on_local_main_process(function)

    def on_process(self, function=None, process_index=None):
        return self.state.on_process(function, process_index)

    def on_last_process(self, function):
        return self.state.on_last_process(function)

    def main_process_first(self):
        return self.state.main_process_first()

    def local_main_process_first(self):
        return self.state.local_main_process_first()

    # ------------------------------------------------------------------ #
    # prepare (reference: accelerator.py:1316)
    # ------------------------------------------------------------------ #

    def _is_model_like(self, obj) -> bool:
        if isinstance(obj, Model):
            return True
        if self._is_optimizer_like(obj):  # optax tx is itself a 2-tuple
            return False
        return isinstance(obj, tuple) and len(obj) == 2 and (hasattr(obj[0], "apply") or callable(obj[0]))

    def _is_optimizer_like(self, obj) -> bool:
        if isinstance(obj, AcceleratedOptimizer):
            return True
        return hasattr(obj, "init") and hasattr(obj, "update") and not hasattr(obj, "apply")

    def _is_dataloader_like(self, obj) -> bool:
        if isinstance(obj, BaseDataLoader):
            return True
        try:
            import torch.utils.data as tud

            if isinstance(obj, tud.DataLoader):
                return True
        except ImportError:
            pass
        return False

    def prepare(self, *args, device_placement=None):
        """Shard/wrap models, optimizers, dataloaders, schedulers; returns
        them in the same order (reference: accelerator.py:1316).

        Two-pass like the reference (scheduler after optimizer,
        accelerator.py:1456-1459) so a scheduler can bind to its prepared
        optimizer. Idempotent via the ``_is_accelerate_prepared`` marker
        (reference: accelerator.py:1470-1475).
        """
        staged = {}
        # models first (argument order must not matter: an optimizer passed
        # before its model still binds to it), then optimizers/loaders,
        # then schedulers — mirrors the reference's two-pass ordering.
        for i, obj in enumerate(args):
            if getattr(obj, "_is_accelerate_prepared", False):
                staged[i] = obj
            elif self._is_model_like(obj):
                staged[i] = self.prepare_model(obj)
        for i, obj in enumerate(args):
            if i in staged:
                continue
            if self._is_optimizer_like(obj):
                staged[i] = self.prepare_optimizer(obj)
            elif (
                self._is_dataloader_like(obj)
                or hasattr(obj, "__iter__")
                or (hasattr(obj, "__getitem__") and hasattr(obj, "__len__"))
            ):
                staged[i] = self.prepare_data_loader(obj)
        for i, obj in enumerate(args):
            if i in staged:
                continue
            staged[i] = self.prepare_scheduler(obj)
        if self._telemetry is not None:
            # telemetry already live: mark the prepare so the timeline can
            # attribute the layout/device_put cost (never force-create it —
            # prepare() must not start writing files as a side effect)
            self._telemetry.log.event(
                "prepare",
                models=len(self._models),
                optimizers=len(self._optimizers),
                dataloaders=len(self._dataloaders),
                schedulers=len(self._schedulers),
                mesh={k: int(v) for k, v in dict(self.mesh.shape).items()},
                mixed_precision=self.mixed_precision,
            )
        result = [staged[i] for i in range(len(args))]
        return result[0] if len(result) == 1 else tuple(result)

    def _sharding_rules_for(self, model: Model):
        plugin = self.state.parallelism_plugin
        if plugin.sharding_rules is not None:
            return list(plugin.sharding_rules)
        rules = list(model.sharding_rules or [])
        if self.mesh.shape.get("fsdp", 1) > 1:
            rules = rules + list(fsdp_rules_for(model.params, self.mesh))
        return rules

    def prepare_model(self, model, device_placement: Optional[bool] = None, evaluation_mode: bool = False) -> Model:
        """(reference: accelerator.py:1549). Cast params to the fp32 master
        dtype, compute per-param shardings from the layout rules, and
        ``device_put`` — the DDP/FSDP/TP wrap branches (reference
        :1647-1750) all reduce to the sharding choice."""
        model = as_model(model)
        if model._is_accelerate_prepared:
            return model
        jax = _jax()
        jnp = _jnp()
        if device_placement is None:
            device_placement = self.device_placement

        param_dtype = jnp.dtype(self.state.dtype_policy.param_dtype)

        def cast(p):
            if hasattr(p, "dtype") and jnp.issubdtype(np.asarray(p).dtype if not hasattr(p, "dtype") else p.dtype, jnp.floating):
                return np.asarray(p, dtype=param_dtype) if isinstance(p, np.ndarray) else p.astype(param_dtype)
            return p

        params = jax.tree_util.tree_map(cast, model.params)
        if device_placement:
            rules = self._sharding_rules_for(model)
            shardings = infer_shardings(params, rules, self.mesh)
            params = jax.device_put(params, shardings)
            model.param_shardings = shardings
        model.params = params
        model._is_accelerate_prepared = True
        model.accelerator = self
        if not evaluation_mode:
            self._models.append(model)
        return model

    def prepare_optimizer(self, optimizer, device_placement: Optional[bool] = None) -> AcceleratedOptimizer:
        """(reference: accelerator.py:2464). The optax state is created
        *from sharded params* inside jit, so XLA propagates param layouts
        into the optimizer moments — ZeRO/FSDP optimizer-state sharding
        with no extra code (this replaces the reference's FSDP2
        optimizer-param-swap dance, accelerator.py:1479-1547)."""
        if isinstance(optimizer, AcceleratedOptimizer):
            if not optimizer._is_accelerate_prepared:
                optimizer._is_accelerate_prepared = True
                optimizer.accelerator = self
                self._optimizers.append(optimizer)
            return optimizer
        opt = AcceleratedOptimizer(optimizer, accelerator=self)
        self._ensure_opt_state(opt)
        opt._is_accelerate_prepared = True
        self._optimizers.append(opt)
        return opt

    def _ensure_opt_state(self, opt: AcceleratedOptimizer, model: Optional[Model] = None):
        """Bind the optimizer to a prepared model and init its (sharded)
        state. Deferred when no model has been prepared yet, so argument
        order in ``prepare()`` doesn't matter.

        With ``ParallelismPlugin(shard_optimizer_state=True)`` (ZeRO-1/2;
        reference: utils/deepspeed.py:253-294) the state is born sharded
        over the ``data`` axis via ``out_shardings`` — params stay
        replicated, per-device optimizer memory divides by the dp degree.

        With ``ParallelismPlugin(offload_optimizer=True)`` (ZeRO-offload /
        FSDP cpu-offload analogue; reference: utils/dataclasses.py:1100-1180
        ``offload_optimizer_device``, accelerator.py:1694-1750 cpu_offload)
        the state is *born on* ``pinned_host`` memory-kind shardings — it
        never materialises in HBM — and the jitted step streams it through
        the device around the update (``_offload_transfers``). Composes
        with ZeRO: the host copy keeps the data-axis layout."""
        if opt.opt_state is not None:
            return
        model = model or getattr(opt, "_model", None) or (self._models[-1] if self._models else None)
        if model is None:
            return
        jax = _jax()
        zero1_fallback = None
        if self._zero1_active():
            # ZeRO-1's flat-segment update is only correct for transforms
            # that treat every parameter element independently; a factored
            # / coupled state (adafactor's row-col moments) would compute
            # a DIFFERENT update on the flat segments than on the real
            # leaves. Detect it structurally and fall back LOUDLY to the
            # passive shard_optimizer_state layout instead of silently
            # changing the optimizer's semantics.
            zero1_fallback = _nonelementwise_state_nodes(opt.optimizer)
            if zero1_fallback:
                names = ", ".join(sorted(zero1_fallback))
                if names not in _ZERO1_FALLBACK_WARNED:
                    _ZERO1_FALLBACK_WARNED.add(names)
                    logger.warning(
                        "zero_stage=1 requires an elementwise optax transform, but this "
                        "optimizer's state couples elements within a leaf (%s); falling "
                        "back to the passive shard_optimizer_state layout — the optimizer "
                        "state is GSPMD-sharded over the data axis but the update wire "
                        "stays the replicated all-reduce (no reduce-scatter/all-gather "
                        "split, no quantized update legs)",
                        names,
                    )
                opt._zero1_fallback = tuple(sorted(zero1_fallback))
        if self._zero1_active() and not zero1_fallback:
            layout = self._zero1_layout_for(model)
            if layout is not None:
                # ZeRO-1 explicit mode: the state is created over the FLAT
                # padded parameter vector and *born sharded* over the data
                # axes (jit + out_shardings) — per-device optimizer HBM is
                # 1/n from step 0, never materialised replicated
                def init_flat(p):
                    return opt.optimizer.init(layout.flatten_pad(p))

                state_shapes = jax.eval_shape(init_flat, model.params)
                shardings = layout.state_shardings(state_shapes, self.mesh)
                opt.opt_state = jax.jit(init_flat, out_shardings=shardings)(model.params)
                opt._zero_shardings = shardings
                opt._zero1_layout = layout
                # per-state-leaf true sizes: what elastic restore needs to
                # re-pad a shard checkpoint onto a different mesh
                opt._zero1_state_sizes = layout.state_true_sizes(state_shapes)
                opt._model = model
                return
        shardings = self._zero_state_shardings(opt.optimizer, model, force=bool(zero1_fallback))
        init_shardings = shardings
        plugin = self.state.parallelism_plugin
        offload = plugin is not None and getattr(plugin, "offload_optimizer", False)
        if offload:
            from .utils.compat import supports_memory_kind

            if not supports_memory_kind("pinned_host"):
                logger.warning(
                    "offload_optimizer requested but the %s backend has no pinned_host "
                    "memory; optimizer state stays in device memory",
                    jax.default_backend(),
                )
                offload = False
        if offload:
            from .parallel.sharding import zero_optimizer_shardings

            state_shapes = jax.eval_shape(opt.optimizer.init, model.params)
            base = shardings
            if base is None:  # param-matched layout, no ZeRO split
                base = zero_optimizer_shardings(
                    state_shapes, getattr(model, "param_shardings", None), self.mesh, axis=None
                )
            # scalar leaves (adam's step count) stay in device memory: XLA's
            # SPMD partitioner rejects pinned_host placement on scalars
            # ("Side-effect HLO must have sharding"), and they're 4 bytes
            opt._offload_shardings = jax.tree_util.tree_map(
                lambda s, shape: s if getattr(shape, "ndim", 0) == 0 else s.with_memory_kind("pinned_host"),
                base,
                state_shapes,
            )
        opt.opt_state = jax.jit(opt.optimizer.init, out_shardings=init_shardings)(model.params)
        if getattr(opt, "_offload_shardings", None) is not None:
            # move to the pinned_host home OUTSIDE jit: memory-kind
            # out_shardings on init trip XLA's SPMD partitioner on the
            # constant scalar leaves ("Side-effect HLO must have sharding").
            # The transient HBM copy is just-born state (zeros for adam).
            opt.opt_state = jax.device_put(opt.opt_state, opt._offload_shardings)
        opt._zero_shardings = shardings
        opt._model = model

    def _offload_transfers(self, opt: AcceleratedOptimizer):
        """``(pull, push)`` for a host-offloaded optimizer state, or
        ``(None, None)`` when offload is off.

        ``pull`` runs INSIDE the jitted step, at its top level (never inside
        ``lax.cond`` — host-offload transfers are not legal in every
        control-flow position): a host->device stream XLA's latency-hiding
        scheduler can overlap with the forward/backward. ``push`` runs
        OUTSIDE jit, after the step returns: XLA's CPU backend has no
        device->pinned_host placement lowering inside a program (the
        ``annotate_device_placement`` custom call is unimplemented for Host
        targets, and the SPMD partitioner rejects it besides), while a plain
        ``jax.device_put`` after the fact is an async D2H copy on every
        backend. The updated state's device buffers are freed as soon as the
        copy lands, restoring the between-steps HBM saving."""
        host = getattr(opt, "_offload_shardings", None)
        if host is None:
            return None, None
        jax = _jax()
        kind = jax.devices()[0].default_memory().kind

        def pull(st):
            # per-leaf: only host-resident leaves transfer; scalar leaves
            # (device-kind home) pass through untouched
            return jax.tree_util.tree_map(
                lambda x, s: (
                    jax.device_put(x, s.with_memory_kind(kind)) if s.memory_kind == "pinned_host" else x
                ),
                st,
                host,
            )

        return pull, (lambda st: jax.device_put(st, host))

    def _zero_state_shardings(self, optax_tx, model: Model, force: bool = False):
        """ZeRO-1/2 ``NamedSharding`` pytree for ``optax_tx``'s state, or
        None when ``shard_optimizer_state`` is off / no data axis.
        ``force`` takes the passive layout regardless of the plugin flag
        (the zero_stage=1 non-elementwise fallback)."""
        plugin = self.state.parallelism_plugin
        if not force and (plugin is None or not getattr(plugin, "shard_optimizer_state", False)):
            return None
        from .parallel.mesh import data_parallel_size

        if data_parallel_size(self.mesh) <= 1:
            return None
        jax = _jax()
        from .parallel.sharding import zero_optimizer_shardings

        state_shapes = jax.eval_shape(optax_tx.init, model.params)
        return zero_optimizer_shardings(
            state_shapes, getattr(model, "param_shardings", None), self.mesh
        )

    def _zero1_active(self) -> bool:
        plugin = self.state.parallelism_plugin
        return plugin is not None and getattr(plugin, "zero_stage", 0) == 1

    def zero1_fallback_reason(self, optimizer) -> Optional[tuple]:
        """The offending optax state node names if ``zero_stage=1`` fell
        back to the passive layout for this (prepared) optimizer, else
        None."""
        return getattr(optimizer, "_zero1_fallback", None)

    def _zero1_layout_for(self, model: Model):
        """The :class:`~accelerate_tpu.parallel.zero.Zero1Layout` for this
        model on this mesh, or ``None`` when the data-parallel degree is 1
        (ZeRO-1 degenerates to the replicated update — nothing to shard).
        Validates the mode's preconditions: the only non-trivial mesh axes
        are the batch axes, and params are replicated over them."""
        from .parallel.mesh import BATCH_AXES
        from .parallel.zero import Zero1Layout, zero1_axes

        axes = zero1_axes(self.mesh)
        if not axes:
            return None
        bad = [a for a, s in dict(self.mesh.shape).items() if s > 1 and a not in BATCH_AXES]
        if bad:
            raise ValueError(
                f"zero_stage=1 shards the update over the batch axes only; "
                f"shard-bearing axes {bad} would need their own update semantics"
            )
        shardings = getattr(model, "param_shardings", None)
        if shardings is not None:
            import jax as _j

            for kp, s in _j.tree_util.tree_flatten_with_path(shardings)[0]:
                spec_axes = {
                    a
                    for entry in tuple(getattr(s, "spec", s) or ())
                    if entry is not None
                    for a in (entry if isinstance(entry, tuple) else (entry,))
                }
                used = spec_axes & set(axes)
                if used:
                    from .parallel.sharding import path_str

                    raise ValueError(
                        f"zero_stage=1 needs params replicated over the data axes, but "
                        f"{path_str(kp)} is sharded over {sorted(used)} — use plain FSDP "
                        "(ZeRO-3 layout) for parameter sharding instead"
                    )
        return Zero1Layout(model.params, self.mesh, axes=axes)

    def prepare_data_loader(
        self, data_loader, device_placement: Optional[bool] = None, slice_fn_for_dispatch=None, **kwargs
    ):
        """Extra ``kwargs`` (``batch_size``, ``shuffle``, ``seed``,
        ``collate_fn``, ``drop_last``) pass through to
        :func:`~accelerate_tpu.data_loader.prepare_data_loader` when the
        input is a raw dataset rather than a built loader."""
        if isinstance(data_loader, BaseDataLoader):
            if data_loader not in self._dataloaders:
                self._dataloaders.append(data_loader)
            return data_loader
        prepared = prepare_data_loader(
            data_loader,
            put_on_device=device_placement if device_placement is not None else self.device_placement,
            data_loader_config=self.dataloader_config,
            rng_types=self.rng_types,
            **kwargs,
        )
        self._dataloaders.append(prepared)
        return prepared

    def prepare_scheduler(self, scheduler) -> AcceleratedScheduler:
        if isinstance(scheduler, AcceleratedScheduler):
            return scheduler
        prepared = AcceleratedScheduler(
            scheduler,
            optimizers=self._optimizers,
            step_with_optimizer=self.step_scheduler_with_optimizer,
            split_batches=self.dataloader_config.split_batches,
        )
        prepared._is_accelerate_prepared = True
        self._schedulers.append(prepared)
        return prepared

    # ------------------------------------------------------------------ #
    # the jitted train step (fast path)
    # ------------------------------------------------------------------ #

    def _matmul_precision_ctx(self):
        """``mixed_precision="no"`` must mean REAL fp32: JAX's DEFAULT
        matmul precision decomposes fp32 operands into bf16 passes (TPU
        MXU and oneDNN CPU alike), which silently injects ~1e-3 relative
        error into every matmul. Tracing the jitted step inside this
        context pins fp32-mode matmuls to full precision; bf16/fp16
        policies keep the fast default. (The reference's fp32 is torch
        fp32 — true fp32 — so this is a parity requirement, not a
        preference.)"""
        import contextlib

        if self.mixed_precision == "no":
            return _jax().default_matmul_precision("highest")
        return contextlib.nullcontext()

    def _compute_cast(self, params):
        """fp32 master -> compute dtype, keeping norm-like params in fp32
        (the autocast policy; reference: accelerator.py:1590-1601)."""
        jnp = _jnp()
        jax = _jax()
        compute = jnp.dtype(self.state.dtype_policy.compute_dtype)
        if compute == jnp.float32 or not self.autocast_handler.enabled:
            return params
        from .parallel.sharding import path_str

        keep = tuple(self.autocast_handler.keep_fp32_patterns)

        def cast(kp, p):
            if not hasattr(p, "dtype") or not jnp.issubdtype(p.dtype, jnp.floating):
                return p
            path = path_str(kp).lower()
            if any(pat in path for pat in keep):
                return p
            return p.astype(compute)

        return jax.tree_util.tree_map_with_path(cast, params)

    def build_eval_step(self, eval_fn: Callable, model: Optional[Model] = None) -> Callable:
        """Jitted inference counterpart of :meth:`build_train_step`.

        ``eval_fn(params, *args)`` — or ``eval_fn(params, state, *args)``
        when the model carries mutable state (BatchNorm). Returns
        ``step(*args)`` reading the model's CURRENT params/state each call.
        The reference's eval loop just calls the module (torch eager is
        fine there); in JAX an unjitted forward dispatches op-by-op, which
        is pathological on TPU — always evaluate through a jitted step.
        """
        jax = _jax()
        model = model or self._models[-1]
        compute_cast = self._compute_cast
        jitted = jax.jit(lambda p, *args, **kwargs: eval_fn(compute_cast(p), *args, **kwargs))
        if self._program_cache is not None and self.compile_handler.aot_train_step:
            jitted = self._program_cache.wrap_jit(jitted, name="eval_step")
        ctx = self._matmul_precision_ctx

        def run(*args, **kwargs):
            with ctx():
                if getattr(model, "state", None) is not None:
                    return jitted(model.params, model.state, *args, **kwargs)
                return jitted(model.params, *args, **kwargs)

        return run

    def lint(
        self,
        step_fn: Callable,
        *sample_args,
        donate_argnums=(),
        in_shardings=None,
        ignore=(),
        divergence: bool = True,
    ):
        """Statically lint ``step_fn`` against this accelerator's mesh
        *before* paying a multi-chip compile (tier-1 jaxpr analysis:
        collective axis names, silent bf16/fp8->f32 promotion, buffer
        donation, output sharding constraints — see
        docs/usage_guides/static_analysis.md for the rule catalogue).

        ``sample_args`` are traced abstractly (``jax.ShapeDtypeStruct``s
        or real arrays — nothing executes, nothing compiles); concrete
        arrays contribute their ``NamedSharding`` to the TPU104 check.

        With ``divergence=True`` (the default) the multi-host divergence
        analyzer (TPU4xx, ``analysis.divergence``) also runs over the
        *calling module's* source: collectives or barriers that not every
        rank reaches, rank-divergent loop trip counts, unguarded host
        writes — the deadlocks a single-program trace cannot see.

        Returns the list of :class:`~accelerate_tpu.analysis.Finding`;
        error-severity findings are also logged. Suppress individual rules
        with ``ignore=("TPU103",)``.
        """
        from .analysis import lint_step, render_text

        findings = lint_step(
            step_fn,
            *sample_args,
            mesh=self.mesh,
            donate_argnums=donate_argnums,
            in_shardings=in_shardings,
            ignore=ignore,
        )
        if divergence:
            findings += self._lint_calling_module(ignore=ignore, depth=2)
        if any(f.is_error for f in findings):
            logger.warning("lint found issues in %s:\n%s", getattr(step_fn, "__name__", "step_fn"), render_text(findings))
        return findings

    def _lint_calling_module(self, ignore=(), depth: int = 1):
        """Run the TPU4xx divergence analyzer over the source file of the
        caller ``depth`` frames up. Quietly returns ``[]`` when the caller
        has no readable ``.py`` source (REPL, notebook, frozen app)."""
        import sys

        try:
            frame = sys._getframe(depth)
        except ValueError:
            return []
        path = frame.f_globals.get("__file__") if frame is not None else None
        if not path or not str(path).endswith(".py") or not os.path.exists(path):
            return []
        from .analysis.divergence import analyze_file
        from .analysis.project_config import load_project_config

        cfg = load_project_config(os.path.dirname(os.path.abspath(path)))
        try:
            findings = analyze_file(path, n_ranks=max(3, cfg.resolve_ranks(None)), ignore=cfg.merge_ignore(ignore))
        except (OSError, RecursionError):
            return []
        return cfg.apply_suppressions(findings)

    def flight_check(
        self,
        step_fn: Callable,
        *sample_args,
        donate_argnums=(),
        in_shardings=None,
        generation: str = "v5e",
        ignore=(),
    ):
        """Static SPMD flight-check of ``step_fn`` against this
        accelerator's mesh, *before* paying a multi-chip compile: a
        per-device peak-HBM estimate (liveness walk with donated-buffer
        reuse and sharding-aware byte counts), the collective traffic bill
        (bytes on wire, ICI vs DCN, per-step totals), and the TPU3xx
        safety rules — collective under value-dependent ``cond``/``while``
        (deadlock), implicit reshards, donation defeated by a late read.

        Same calling convention as :meth:`lint`; returns a
        :class:`~accelerate_tpu.analysis.FlightReport` (``.render_text()``
        for the human report, ``.as_dict()`` for tooling,
        ``.fits(hbm_gb)`` for a go/no-go). Error-severity findings are
        logged. See ``docs/usage_guides/static_analysis.md``.
        """
        from .analysis import flight_check as _flight_check
        from .analysis import render_text

        report = _flight_check(
            step_fn,
            *sample_args,
            mesh=self.mesh,
            donate_argnums=donate_argnums,
            in_shardings=in_shardings,
            generation=generation,
            ignore=ignore,
        )
        if not report.ok:
            logger.warning(
                "flight-check found issues in %s:\n%s",
                getattr(step_fn, "__name__", "step_fn"),
                render_text(report.findings),
            )
        if self._telemetry is not None and report.peak_hbm_bytes:
            # seed the runtime HBM drift check with the static prediction
            self._telemetry.set_static_hbm_estimate(report.peak_hbm_bytes)
        return report

    def perf_check(
        self,
        step_fn: Callable,
        *sample_args,
        in_shardings=None,
        dcn=None,
        generation: Optional[str] = None,
        ignore=(),
    ):
        """Static roofline of ``step_fn`` against this accelerator's mesh,
        *before* paying a multi-chip compile: per-op FLOPs / HBM bytes /
        bytes-on-wire, compute/memory/comms-bound classification, the
        predicted step time and MFU upper bound for the attached
        generation, plus the TPU5xx efficiency rules (MXU tile
        misalignment, redundant collectives, latency-bound small DCN
        collectives, missed collective/compute overlap, f32 matmuls that
        are safely bf16).

        Same calling convention as :meth:`flight_check`; returns a
        :class:`~accelerate_tpu.analysis.PerfReport` (``.render_text()``
        for the human report, ``.as_dict()`` for tooling /
        ``accelerate-tpu perf-check --baseline`` diffs). Error-severity
        findings are logged. When telemetry is live
        (:class:`~accelerate_tpu.utils.TelemetryKwargs`), the predicted
        step time seeds the runtime ``perf_model_drift`` cross-check —
        the measured steady-state step split is compared against this
        static prediction so the model stays honest. See
        ``docs/usage_guides/static_analysis.md`` and
        ``docs/usage_guides/performance.md``.
        """
        from .analysis import render_text
        from .analysis.perfmodel import perf_check as _perf_check

        report = _perf_check(
            step_fn,
            *sample_args,
            mesh=self.mesh,
            in_shardings=in_shardings,
            dcn=dcn,
            generation=generation,
            ignore=ignore,
        )
        if not report.ok:
            logger.warning(
                "perf-check found issues in %s:\n%s",
                getattr(step_fn, "__name__", "step_fn"),
                render_text(report.findings),
            )
        if self._telemetry is not None and report.predicted_step_ms > 0:
            # seed the runtime perf-model drift check with the prediction
            self._telemetry.set_static_step_estimate(report.predicted_step_ms)
        return report

    def pipe_check(
        self,
        target,
        *sample_args,
        num_microbatches: Optional[int] = None,
        axis_name: str = "pipe",
        interleave: int = 1,
        remat: bool = False,
        stage_layers=None,
        dcn=None,
        generation: Optional[str] = None,
        hbm_gb: Optional[float] = None,
        ignore=(),
    ):
        """Static pipeline-schedule analysis of ``target`` *before*
        paying a multi-chip compile: per-stage rooflines and remat-aware
        peak HBM, bubble fraction vs the ideal ``(S-1)/(M+S-1)``,
        exposed-vs-hidden handoff time under ``interleave``, and the
        bubble-adjusted predicted step time ``(M+S-1) x max-stage tick``,
        plus the TPU8xx schedule rules (pipeline cut on the fast link
        while DCN exists, stage imbalance, bubble over threshold with
        the covering ``num_microbatches`` priced, collectives over the
        pipe axis inside the tick body — error severity — and per-stage
        activations over the HBM budget).

        ``target`` is a step function whose trace contains the
        ``parallel.pipeline`` schedule (analyzed against this
        accelerator's mesh), a
        :class:`~accelerate_tpu.analysis.PipelineSpec`, or a
        :class:`~accelerate_tpu.parallel.pipeline.PipelinedModel` (plus
        its sample inputs) — specs and models carry their own mesh.
        Returns a :class:`~accelerate_tpu.analysis.PipeReport`
        (``.render_text()`` / ``.as_dict()``). Error-severity findings
        are logged. When telemetry is live, the bubble-adjusted
        prediction seeds the runtime ``perf_model_drift`` cross-check,
        same as :meth:`perf_check`. See
        ``docs/usage_guides/pipeline.md`` and
        ``docs/usage_guides/static_analysis.md``.
        """
        from .analysis import render_text
        from .analysis.pipemodel import PipelineSpec, pipe_check as _pipe_check
        from .parallel.pipeline import PipelinedModel

        report = _pipe_check(
            target,
            *sample_args,
            mesh=None if isinstance(target, (PipelineSpec, PipelinedModel)) else self.mesh,
            num_microbatches=num_microbatches,
            axis_name=axis_name,
            interleave=interleave,
            remat=remat,
            stage_layers=stage_layers,
            dcn=dcn,
            generation=generation,
            hbm_gb=hbm_gb,
            ignore=ignore,
        )
        if not report.ok:
            logger.warning(
                "pipe-check found issues in %s:\n%s",
                report.fn_name,
                render_text(report.findings),
            )
        if self._telemetry is not None and report.predicted_step_ms > 0:
            # the bubble-adjusted prediction seeds the drift watchdog
            self._telemetry.set_static_step_estimate(report.predicted_step_ms)
        return report

    def kernel_check(
        self,
        step_fn: Callable,
        *sample_args,
        generation: Optional[str] = None,
        probe: bool = True,
        ignore=(),
    ):
        """Static Pallas kernel analysis of ``step_fn`` against this
        accelerator's mesh, *before* paying a compile: every
        ``pl.pallas_call`` site is extracted from the traced jaxpr (grid,
        BlockSpecs, concretely re-evaluated index maps, in/out aliases)
        and checked with the TPU10xx rules — per-block VMEM occupancy vs
        the generation's capacity, MXU/VPU tile alignment, index-map
        coverage/races, grid-loop-carried alias hazards, and the
        registered :class:`~accelerate_tpu.kernels.KernelCostSpec`
        contracts (an unregistered call is TPU1005 error-severity; a
        declaration drifting from the interpret-mode count is TPU1006).
        On CPU the kernels are also executed in Pallas interpret mode as
        a finiteness probe.

        Same calling convention as :meth:`flight_check`; returns a
        :class:`~accelerate_tpu.analysis.KernelReport`
        (``.render_text()`` / ``.as_dict()``). Error-severity findings
        are logged. See ``docs/usage_guides/kernels.md`` and
        ``docs/usage_guides/static_analysis.md``.
        """
        from .analysis import render_text
        from .analysis.kernelmodel import kernel_check as _kernel_check

        report = _kernel_check(
            step_fn,
            *sample_args,
            mesh=self.mesh,
            generation=generation,
            probe=probe,
            ignore=ignore,
        )
        if not report.ok:
            logger.warning(
                "kernel-check found issues in %s:\n%s",
                getattr(step_fn, "__name__", "step_fn"),
                render_text(report.findings),
            )
        return report

    def numerics_check(
        self,
        step_fn: Callable,
        *sample_args,
        assume=None,
        ignore=(),
    ):
        """Static numerics & precision analysis of ``step_fn`` against
        this accelerator's mesh, *before* paying a multi-chip compile:
        a value-interval + dtype-provenance abstract interpretation of
        the traced jaxpr (widening through ``scan``/``while``, joins
        across ``cond`` branches, relational softmax refinements) plus
        the TPU6xx precision rules — low-precision accumulation over
        long axes, provable fp16/fp8 overflow, unguarded div/log/rsqrt
        over zero, weight updates below the param ulp, PRNG key reuse,
        and compressed collectives without error feedback. Every finding
        prices its impact (relative-error bound, overflow margin, or
        lost-update ulp).

        ``assume=(lo, hi)`` states the input-value assumption the proofs
        are relative to (default ±16). Same calling convention as
        :meth:`perf_check`; returns a
        :class:`~accelerate_tpu.analysis.NumericsReport`
        (``.render_text()`` for the human report, ``.as_dict()`` for
        tooling). Error-severity findings are logged. The runtime
        counterpart is the opt-in telemetry
        :class:`~accelerate_tpu.telemetry.NonFiniteWatchdog`
        (``TelemetryKwargs(nonfinite_every=N)``). See
        ``docs/usage_guides/static_analysis.md`` and
        ``docs/usage_guides/low_precision.md``.
        """
        from .analysis import render_text
        from .analysis.numerics import numerics_check as _numerics_check

        report = _numerics_check(
            step_fn,
            *sample_args,
            mesh=self.mesh,
            assume=assume,
            ignore=ignore,
        )
        if not report.ok:
            logger.warning(
                "numerics-check found issues in %s:\n%s",
                getattr(step_fn, "__name__", "step_fn"),
                render_text(report.findings),
            )
        return report

    def tune(
        self,
        workload: Callable,
        *sample_args,
        space=None,
        generation: Optional[str] = None,
        hbm_gb: Optional[float] = None,
        top_k: int = 3,
        confirm: bool = False,
        confirm_steps: int = 8,
        shape_histogram=None,
        optimizer=None,
        ignore=(),
    ):
        """Search configuration space for the fastest feasible config of
        ``workload`` with the static analyzers as the oracle — ROADMAP
        item 4 paid off: every candidate the
        :class:`~accelerate_tpu.analysis.SearchSpace` enumerates is
        constraint-pruned, flight-checked (static peak HBM vs the
        generation's capacity — the TPU701 feasibility prune), and
        rooflined (:meth:`perf_check`'s predicted step time / MFU bound,
        costmodel wire bytes as the tiebreak), all statically, in
        milliseconds per candidate, before anything compiles.

        ``workload`` is a plain step function (``sample_args`` traced
        abstractly; the mesh/bucket knobs vary around it) or a workload
        factory — any callable with a truthy ``tune_factory`` attribute,
        called as ``workload(point) -> (step_fn, sample_args)`` per
        candidate. ``space=None`` searches the default neighborhood over
        this accelerator's device pool
        (:func:`~accelerate_tpu.analysis.default_space`). With
        ``confirm=True`` the top-``top_k`` candidates are measured with
        short :class:`~accelerate_tpu.telemetry.StepTelemetry` runs and
        the report carries predicted-vs-measured rank agreement.

        Returns a :class:`~accelerate_tpu.analysis.TuneReport`
        (``.render_text()``, ``.as_dict()``, ``.winner``,
        ``.chosen_toml()`` — the ``[tune.chosen]`` block to commit into
        ``.tpulint.toml``; ``analysis.load_chosen()`` +
        ``ConfigPoint.parallelism_kwargs()`` feed it back into
        :class:`~accelerate_tpu.utils.ParallelismPlugin`). The winner is
        logged. See ``docs/usage_guides/autotuning.md``.
        """
        from .analysis import default_space
        from .analysis.tuner import tune as _tune

        jax = _jax()
        if space is None:
            space = default_space(len(jax.devices()))
        report = _tune(
            workload,
            space,
            *sample_args,
            base_mesh=self.mesh,
            generation=generation,
            hbm_gb=hbm_gb,
            top_k=top_k,
            confirm=confirm,
            confirm_steps=confirm_steps,
            shape_histogram=shape_histogram,
            optimizer=optimizer,
            ignore=ignore,
        )
        if report.winner is not None:
            logger.info(
                "tune: winner %s — predicted %.3f ms (of %d candidates, %d pruned, %d infeasible)",
                report.winner.label,
                (report.winner.predicted_step_us or 0.0) / 1000.0,
                len(report.candidates),
                report.pruned_count,
                report.infeasible_count,
            )
        else:
            logger.warning("tune: no feasible candidate (of %d)", len(report.candidates))
        return report

    def build_train_step(
        self,
        loss_fn: Callable,
        model: Optional[Model] = None,
        optimizer: Optional[AcceleratedOptimizer] = None,
        scheduler: Optional[AcceleratedScheduler] = None,
        has_aux: bool = False,
        has_state: bool = False,
        donate: bool = True,
    ) -> Callable:
        """Build the single jitted train step (reference hot loop §3.4
        collapsed into one XLA program).

        ``loss_fn(params, batch) -> loss`` (or ``(loss, aux)``). The
        returned ``step(batch)`` mutates the prepared model/optimizer in
        place (their pytrees are swapped each call) and returns the loss
        (plus aux), keeping per-step python under a microsecond-scale
        dispatch. Gradient accumulation runs as a branchless on-device
        buffer: every call accumulates; on sync boundaries the update
        applies and the buffer zeroes — ``1/accum``-weighted so the applied
        gradient is the mean over microbatches.

        ``has_state=True`` threads non-trainable mutable collections
        (flax ``batch_stats`` et al.) through the step: ``model.state`` is
        passed as the second argument — ``loss_fn(params, state, batch[,
        rng])`` — and the loss_fn returns ``(loss, new_state)`` (or
        ``(loss, (new_state, aux))`` with ``has_aux``). The state updates
        every microbatch, gradient-free. The reference has no analogue
        (torch BN mutates buffers in place); in JAX the state is explicit.

        With ``ParallelismPlugin(zero_stage=1)`` the grad-pmean →
        replicated-update wire is replaced by reduce-scatter grads →
        per-replica 1/n flat-segment optimizer update (state born
        sharded) → all-gather updates, optionally with int8/fp8/bf16
        quantized legs carrying error feedback
        (``grad_compression``) — see
        ``docs/usage_guides/zero_redundancy.md``. fp32 parity with the
        replicated path is bit-exact; ``do_sync`` turns static (two
        compiled variants, the offload pattern).
        """
        jax = _jax()
        jnp = _jnp()
        model = model or self._models[-1]
        optimizer = optimizer or (self._optimizers[-1] if self._optimizers else None)
        if optimizer is None:
            raise ValueError("prepare() an optimizer before building a train step")
        self._ensure_opt_state(optimizer, model)
        scheduler = scheduler or (self._schedulers[-1] if self._schedulers else None)
        accum = self.gradient_accumulation_steps
        use_fp16 = self.mixed_precision == "fp16"
        compute_cast = self._compute_cast
        apply_gradients = self._make_gradient_applier(optimizer.optimizer)
        # loss_fn(params, batch) or loss_fn(params, batch, rng) — the rng
        # variant gets a per-step folded key (dropout etc.). With has_state
        # the state slots in before batch: loss_fn(params, state, batch[, rng]).
        # Opt-in is by arity (a required positional beyond batch) OR by a
        # parameter literally named ``rng`` (covers optional-rng losses like
        # functools.partial(bert_classification_loss, apply_fn=...), whose
        # ``rng=None`` is keyword-with-default). Bound keyword arguments
        # from partial must NOT count toward arity.
        import inspect

        try:
            sig_params = inspect.signature(loss_fn).parameters
            n_loss_args = sum(
                1
                for p in sig_params.values()
                if p.default is inspect.Parameter.empty
                and p.kind in (inspect.Parameter.POSITIONAL_ONLY, inspect.Parameter.POSITIONAL_OR_KEYWORD)
            )
            has_rng_param = "rng" in sig_params
        except (TypeError, ValueError):  # builtins / C callables
            n_loss_args, has_rng_param = (3 if has_state else 2), False
        if n_loss_args >= (4 if has_state else 3):
            rng_mode = "positional"
        elif has_rng_param:
            # optional rng must go by keyword: a partial that bound an
            # earlier parameter by keyword rejects extra positionals
            rng_mode = "keyword"
        else:
            rng_mode = "none"

        def call_loss(p, mstate, batch, rng):
            lead = (p, mstate, batch) if has_state else (p, batch)
            if rng_mode == "positional":
                return loss_fn(*lead, rng)
            if rng_mode == "keyword":
                return loss_fn(*lead, rng=rng)
            return loss_fn(*lead)

        h = self.scaler_handler
        growth_factor = float(getattr(h, "growth_factor", 2.0))
        backoff_factor = float(getattr(h, "backoff_factor", 0.5))
        growth_interval = int(getattr(h, "growth_interval", 2000))

        def update_scale_state(scale_state, finite, do_sync):
            """The fp16 dynamic-loss-scale transition (torch GradScaler
            semantics, applied only on sync boundaries) — shared by the
            replicated, compressed, and ZeRO-1 paths."""
            if not use_fp16:
                return scale_state
            loss_scale = scale_state["scale"]
            grown = scale_state["growth"] + 1
            do_grow = grown >= growth_interval
            upd_scale = jnp.where(
                finite,
                jnp.where(do_grow, loss_scale * growth_factor, loss_scale),
                jnp.maximum(1.0, loss_scale * backoff_factor),
            )
            upd_growth = jnp.where(finite & ~do_grow, grown, 0)
            return {
                "scale": jnp.where(do_sync, upd_scale, loss_scale),
                "growth": jnp.where(do_sync, upd_growth, scale_state["growth"]),
            }

        compress_method = getattr(self.state.parallelism_plugin, "grad_compression", None)
        zero_layout = getattr(optimizer, "_zero1_layout", None)
        psgd_rank = None
        if compress_method is not None and zero_layout is None:
            bad = [a for a, s in dict(self.mesh.shape).items() if s > 1 and a != "data"]
            if bad:
                raise ValueError(
                    f"grad_compression reduces over the 'data' axis only; shard-bearing axes {bad} "
                    "would need their own reduction semantics (or compose with zero_stage=1, "
                    "which shards the update over the batch axes)"
                )
            from .parallel.compression import powersgd_rank

            psgd_rank = powersgd_rank(compress_method)

        def parse_out(out, mstate_in):
            """Normalise a loss_fn return to ``(loss, new_state, aux)``
            under the has_state/has_aux contract — shared by the implicit
            path, the compressed-psum path, and the ZeRO-1 path (one
            definition, so the three can never disagree on the protocol)."""
            if has_state:
                loss, rest = out
                new_state, aux = rest if has_aux else (rest, None)
            else:
                loss, aux = out if has_aux else (out, None)
                new_state = mstate_in
            return loss, new_state, aux

        offload_pull, offload_push = self._offload_transfers(optimizer)

        zero_fns = None
        if zero_layout is not None:
            # ZeRO-1 explicit wire: reduce-scatter grads -> per-segment
            # optimizer update -> all-gather updates, the whole update
            # inside ONE shard_map over the batch axes. Two compiled
            # variants keyed on a STATIC do_sync (the offload pattern):
            # the non-sync microbatch program is grads + reduce-scatter +
            # accumulate only, and no collective ever sits under a
            # value-dependent cond (TPU301).
            from jax.sharding import PartitionSpec as P

            from .parallel.collectives import pmean_floats
            from .parallel.zero import (
                all_gather_updates,
                reduce_scatter_grads,
                shard_index,
                sharded_global_norm,
                zero1_comp_specs,
            )
            from .utils.compat import shard_map as _shard_map

            zaxes, z_n = zero_layout.axes, zero_layout.n
            z_tx = optimizer.optimizer
            inv_n = 1.0 / z_n  # powers of two stay exact scalings
            opt_specs = zero_layout.state_specs(optimizer.opt_state)
            buf_specs = jax.tree_util.tree_unflatten(
                zero_layout.treedef, [zero_layout.flat_spec()] * len(zero_layout.padded)
            )
            comp_specs = zero1_comp_specs(zero_layout, compress_method)

            def zero_body(sync):
                def body(params, opt_local, buf_local, mstate_in, local_batch, ls, key, clip, cstate):
                    def local_loss(q):
                        out = call_loss(compute_cast(q), mstate_in, local_batch, key)
                        loss, new_state, aux = parse_out(out, mstate_in)
                        return loss.astype(jnp.float32) * ls, (loss, new_state, aux)

                    g, (loss, new_state, aux) = jax.grad(local_loss, has_aux=True)(params)
                    # 1/n BEFORE the wire: local losses are means over the
                    # LOCAL shard, so the reduce-scatter sum of g/n equals
                    # the baseline's implicit global pmean — and the later
                    # /denom lands AFTER the reduction, exactly where the
                    # replicated path divides (bit-exact fp32 parity)
                    if compress_method is None:
                        g = jax.tree_util.tree_map(lambda l: l.astype(jnp.float32) * inv_n, g)
                        g_shard, _ = reduce_scatter_grads(
                            zero_layout.flatten_pad(g), zaxes, z_n, None, None
                        )
                        denom = jnp.maximum(ls, 1.0) * accum
                        g_shard = jax.tree_util.tree_map(lambda l: l / denom, g_shard)
                        new_cstate = cstate
                    else:
                        # unscale BEFORE quantizing: the error-feedback
                        # residual must live in true gradient units, or a
                        # dynamic loss-scale change mis-weights the carry.
                        # The scaler clamps the scale at >= 1 (backoff
                        # floor), so the maximum() is an exact no-op that
                        # makes the division provably guarded (TPU603)
                        g = jax.tree_util.tree_map(
                            lambda l: l.astype(jnp.float32) / jnp.maximum(ls, 1.0) * inv_n, g
                        )
                        rs_err = jax.tree_util.tree_map(lambda e: e[0], cstate["rs_error"])
                        if use_fp16:
                            # one overflowed microbatch must not poison the
                            # carried residual (the PowerSGD discipline):
                            # keep the old carry and hand NaN shards to the
                            # sync-boundary finite gate
                            ok = jnp.bool_(True)
                            for l in jax.tree_util.tree_leaves(g):
                                ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(l)))
                            ok = jax.lax.psum(ok.astype(jnp.int32), zaxes) == jax.lax.psum(1, zaxes)
                        g_shard, new_rs = reduce_scatter_grads(
                            zero_layout.flatten_pad(g), zaxes, z_n, compress_method, rs_err
                        )
                        if use_fp16:
                            g_shard = jax.tree_util.tree_map(
                                lambda l: jnp.where(ok, l, jnp.float32(jnp.nan)), g_shard
                            )
                            new_rs = jax.tree_util.tree_map(
                                lambda new, old: jnp.where(ok, new, old), new_rs, rs_err
                            )
                        g_shard = jax.tree_util.tree_map(lambda l: l / accum, g_shard)
                        new_cstate = {
                            "rs_error": jax.tree_util.tree_map(lambda e: e[None], new_rs),
                            "ag_error": cstate["ag_error"],
                        }
                    buf_local = jax.tree_util.tree_map(lambda b, s: b + s, buf_local, g_shard)
                    loss = jax.lax.pmean(loss, zaxes)
                    new_state = pmean_floats(new_state, zaxes)
                    aux = pmean_floats(aux, zaxes)
                    if not sync:
                        return (
                            params, opt_local, buf_local, new_state, loss,
                            jnp.float32(0.0), jnp.bool_(True), aux, new_cstate,
                        )
                    # sync boundary: the global norm is a psum of local
                    # partial sums over the shards — never a gather
                    gnorm = sharded_global_norm(buf_local, zaxes)
                    cscale = jnp.where(clip >= 0, jnp.minimum(1.0, clip / (gnorm + 1e-6)), 1.0)
                    gbuf = jax.tree_util.tree_map(lambda t: t * cscale, buf_local)
                    finite = jnp.isfinite(gnorm)
                    idx = shard_index(zaxes, zero_layout.mesh_shape)
                    p_local = zero_layout.local_slice(zero_layout.flatten_pad(params), idx)

                    def do_update(_):
                        return z_tx.update(gbuf, opt_local, p_local)

                    def hold(_):
                        return jax.tree_util.tree_map(jnp.zeros_like, gbuf), opt_local

                    if use_fp16:
                        updates, new_opt = jax.lax.cond(finite, do_update, hold, operand=None)
                    else:
                        updates, new_opt = do_update(None)
                    if compress_method is None:
                        # exact path: apply the update to the param segment
                        # INSIDE the shard body — the add fuses with the
                        # optimizer chain exactly as the replicated path's
                        # does (same FMA opportunities, bit-exact fp32
                        # parity) — and all-gather the new segments
                        new_seg = jax.tree_util.tree_map(
                            lambda p, u: p + u.astype(p.dtype), p_local, updates
                        )
                        p_full, _ = all_gather_updates(new_seg, zaxes, z_n, None, None)
                        new_params = zero_layout.unflatten(p_full)
                    else:
                        # quantized path: gather the quantized UPDATES (not
                        # params — update deltas are small-range and carry
                        # per-rank error feedback; every replica applies the
                        # IDENTICAL decoded vector, so params never drift)
                        ag_err = cstate["ag_error"]
                        if use_fp16:
                            # a held step must not flush the pending
                            # residual into the params
                            ag_err = jax.tree_util.tree_map(
                                lambda e: jnp.where(finite, e, jnp.zeros_like(e)), ag_err
                            )
                        u_full, new_ag = all_gather_updates(
                            updates, zaxes, z_n, compress_method, ag_err
                        )
                        if use_fp16:
                            new_ag = jax.tree_util.tree_map(
                                lambda a, b: jnp.where(finite, a, b), new_ag, cstate["ag_error"]
                            )
                        new_cstate = {**new_cstate, "ag_error": new_ag}
                        new_params = jax.tree_util.tree_map(
                            lambda p, u: p + u.astype(p.dtype), params, zero_layout.unflatten(u_full)
                        )
                    zero_buf = jax.tree_util.tree_map(jnp.zeros_like, buf_local)
                    return new_params, new_opt, zero_buf, new_state, loss, gnorm, finite, aux, new_cstate

                return _shard_map(
                    body,
                    mesh=self.mesh,
                    in_specs=(P(), opt_specs, buf_specs, P(), P(zaxes), P(), P(), P(), comp_specs),
                    out_specs=(P(), opt_specs, buf_specs, P(), P(), P(), P(), P(), comp_specs),
                    check_vma=False,
                )

            zero_fns = {True: zero_body(True), False: zero_body(False)}

        def step_fn(params, opt_state, grad_buf, mstate, batch, scale_state, do_sync, rng, clip_norm, comp_state):
            # With offload, do_sync is a STATIC python bool (two compiled
            # variants): a non-sync microbatch's program never touches the
            # host-resident state, so grad accumulation amortizes the
            # host<->HBM stream to once per sync boundary instead of
            # multiplying it. Without offload it stays a traced scalar.
            static_sync = isinstance(do_sync, bool)
            if offload_pull is not None and (not static_sync or do_sync):
                # host->HBM stream at the top of the program (not inside the
                # sync cond — see _offload_transfers)
                opt_state = offload_pull(opt_state)
            loss_scale = scale_state["scale"]
            new_comp_state = comp_state

            if zero_fns is not None:
                # the whole reduce-scatter/update/all-gather step runs in
                # one shard_map; do_sync is static (see zero_fns above)
                (new_params, new_opt, new_buf, new_state, loss, gnorm, finite, aux, new_comp_state) = (
                    zero_fns[bool(do_sync)](
                        params, opt_state, grad_buf, mstate, batch, loss_scale, rng, clip_norm, comp_state
                    )
                )
                return (
                    new_params, new_opt, new_buf, new_state, loss, gnorm, finite, aux,
                    update_scale_state(scale_state, finite, do_sync), new_comp_state,
                )

            def scaled_loss(p):
                out = call_loss(compute_cast(p), mstate, batch, rng)
                loss, new_state, aux = parse_out(out, mstate)
                return loss.astype(jnp.float32) * loss_scale, (loss, new_state, aux)

            if compress_method is not None:
                # explicit per-shard grads + compressed psum (the DDP comm
                # hook analogue) instead of XLA's implicit f32 reduction.
                # Mutable state / aux ride along per microbatch: each shard
                # computes them on its local batch and the float leaves are
                # pmean'd (cross-replica BatchNorm-sync semantics — the
                # closest SPMD analogue of the implicit path's global-batch
                # statistics).
                from jax.sharding import PartitionSpec as P

                from .parallel.collectives import pmean_floats
                from .parallel.compression import compressed_psum_mean, powersgd_psum_mean

                def local_grads(p, mstate_in, local_batch, ls, key, cstate):
                    def local_loss(q):
                        out = call_loss(compute_cast(q), mstate_in, local_batch, key)
                        loss, new_state, aux = parse_out(out, mstate_in)
                        return loss.astype(jnp.float32) * ls, (loss, new_state, aux)

                    g, (local_l, new_state, aux) = jax.grad(local_loss, has_aux=True)(p)
                    local_l = jax.lax.pmean(local_l, "data")
                    new_state = pmean_floats(new_state, "data")
                    aux = pmean_floats(aux, "data")
                    # unscale BEFORE compressing: the PowerSGD residual (and
                    # the int8 quantization error) must live in true gradient
                    # units, or every dynamic loss-scale change mis-weights
                    # the carried/rounded feedback by scale_old/scale_new
                    g = jax.tree_util.tree_map(lambda l: l.astype(jnp.float32) / ls, g)
                    if psgd_rank is None:
                        g = compressed_psum_mean(g, "data", compress_method)
                        return g, local_l, new_state, aux, cstate
                    # PowerSGD: one non-finite microbatch (fp16 overflow)
                    # must not poison the carried residual/Q — keep the old
                    # state and let the non-finite reduced gradient trip the
                    # sync-boundary finite gate (params held, buffer zeroed,
                    # scale backed off) exactly like the uncompressed path
                    ok = jnp.bool_(True)
                    for l in jax.tree_util.tree_leaves(g):
                        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(l)))
                    ok = jax.lax.psum(ok.astype(jnp.int32), "data") == jax.lax.psum(1, "data")
                    local = {
                        "error": jax.tree_util.tree_map(lambda e: e[0], cstate["error"]),
                        "q": cstate["q"],
                    }
                    g, new_local = powersgd_psum_mean(g, "data", local, psgd_rank)
                    new_local = jax.tree_util.tree_map(
                        lambda new, old: jnp.where(ok, new, old), new_local, local
                    )
                    new_cstate = {
                        "error": jax.tree_util.tree_map(lambda e: e[None], new_local["error"]),
                        "q": new_local["q"],
                    }
                    return g, local_l, new_state, aux, new_cstate

                comp_spec = {"error": P("data"), "q": P()} if psgd_rank is not None else {}
                from .utils.compat import shard_map as _shard_map

                sm = _shard_map(
                    local_grads,
                    mesh=self.mesh,
                    in_specs=(P(), P(), P(("data", "fsdp")), P(), P(), comp_spec),
                    out_specs=(P(), P(), P(), P(), comp_spec),
                    check_vma=False,
                )
                grads, loss, new_state, aux, new_comp_state = sm(
                    params, mstate, batch, loss_scale, rng, comp_state
                )
            else:
                grads, (loss, new_state, aux) = jax.grad(scaled_loss, has_aux=True)(params)
            # compressed grads are already unscaled inside local_grads.
            # The scaler clamps the loss scale at >= 1 (backoff floor), so
            # the maximum() is an exact no-op that encodes the invariant —
            # and makes the division provably guarded (numerics TPU603)
            denom = accum if compress_method is not None else (jnp.maximum(loss_scale, 1.0) * accum)
            grads = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32) / denom, grads)
            grad_buf = jax.tree_util.tree_map(lambda b, g: b + g, grad_buf, grads)

            def hold(operand):
                params, opt_state, grad_buf = operand
                return params, opt_state, grad_buf, jnp.float32(0.0), jnp.bool_(True)

            if accum == 1 or (static_sync and do_sync):
                new_params, new_opt, new_buf, gnorm, finite = apply_gradients(
                    (params, opt_state, grad_buf), clip_norm
                )
            elif static_sync:  # non-sync microbatch, compiled without the update
                new_params, new_opt, new_buf, gnorm, finite = hold((params, opt_state, grad_buf))
            else:
                new_params, new_opt, new_buf, gnorm, finite = jax.lax.cond(
                    do_sync,
                    lambda op: apply_gradients(op, clip_norm),
                    hold,
                    (params, opt_state, grad_buf),
                )
            applied = accum == 1 or not static_sync or do_sync
            if zero_shardings is not None:
                # pin the ZeRO-1/2 layout so XLA keeps moments (and the
                # accumulation buffer: ZeRO-2) data-sharded across steps.
                # Skip the (unchanged, possibly host-resident) state on a
                # static non-sync program — the constraint would force a
                # pointless transfer.
                if applied:
                    new_opt = jax.lax.with_sharding_constraint(new_opt, zero_shardings)
                new_buf = jax.lax.with_sharding_constraint(new_buf, buf_shardings)

            # dynamic loss scale lives ON DEVICE (torch GradScaler
            # semantics, applied only on sync boundaries): no host
            # round-trip per boundary — the 5 MB/s-tunnel/stall fix
            new_scale_state = update_scale_state(scale_state, finite, do_sync)
            return new_params, new_opt, new_buf, new_state, loss, gnorm, finite, aux, new_scale_state, new_comp_state

        zero_shardings = None if zero_layout is not None else getattr(optimizer, "_zero_shardings", None)
        buf_shardings = None
        if zero_layout is not None:
            # the accumulation buffer lives in the flat 1/n-per-device
            # layout (the ZeRO-2 flavour rides along for free: grads are
            # reduce-scattered every microbatch, so the buffer never
            # materialises replicated)
            buf_shardings = zero_layout.flat_shardings(self.mesh)
        elif zero_shardings is not None:
            from .parallel.sharding import zero_optimizer_shardings

            buf_shardings = zero_optimizer_shardings(
                model.params, getattr(model, "param_shardings", None), self.mesh
            )

        donate_args = ((0, 1, 2, 3) if has_state else (0, 1, 2)) if donate else ()
        if donate and (psgd_rank is not None or (zero_layout is not None and compress_method is not None)):
            donate_args = donate_args + (9,)  # the params-sized error-feedback carry
        if offload_pull is not None:
            # the host-resident state can't be donated to device outputs
            # (memory-kind mismatch); its buffers are replaced by the push.
            # do_sync turns static (two program variants) so non-sync
            # microbatches never stream the state — see step_fn.
            donate_args = tuple(i for i in donate_args if i != 1)
            jitted = jax.jit(step_fn, donate_argnums=donate_args, static_argnums=(6,))
            step_statics = (6,)
        elif zero_layout is not None:
            # static do_sync: two program variants, no collective under a
            # value-dependent cond (see zero_fns)
            jitted = jax.jit(step_fn, donate_argnums=donate_args, static_argnums=(6,))
            step_statics = (6,)
        else:
            jitted = jax.jit(step_fn, donate_argnums=donate_args)
            step_statics = ()
        if self._program_cache is not None and self.compile_handler.aot_train_step:
            # AOT warm-start: dispatch goes signature -> executable through
            # the shared ProgramCache, so a restarted process re-creating
            # this step deserializes from the store instead of recompiling
            # (the wrapper keeps `_cache_size` for the recompile watchdog)
            jitted = self._program_cache.wrap_jit(
                jitted, name="train_step", static_argnums=step_statics
            )

        if zero_layout is not None:
            # flat-padded buffer leaves, born 1/n-per-device
            grad_buf = jax.jit(
                lambda p: jax.tree_util.tree_map(
                    lambda x: jnp.zeros_like(x, dtype=jnp.float32), zero_layout.flatten_pad(p)
                ),
                out_shardings=buf_shardings,
            )(model.params)
        else:
            grad_buf = jax.jit(
                lambda p: jax.tree_util.tree_map(lambda x: jnp.zeros_like(x, dtype=jnp.float32), p),
                out_shardings=buf_shardings,
            )(model.params)
        if not hasattr(self, "_fast_scale_boxes"):
            self._fast_scale_boxes = []
        comp_state0 = {}
        if zero_layout is not None and compress_method is not None:
            from .parallel.zero import zero1_comp_shardings, zero1_comp_template

            template = zero1_comp_template(zero_layout, compress_method)
            # build the residual carries ALREADY sharded (jit +
            # out_shardings): the rs_error carry is n x params f32 global —
            # materializing it replicated first would put all of it on one
            # device
            comp_state0 = jax.jit(
                lambda: jax.tree_util.tree_map(
                    lambda x: jnp.zeros(x.shape, jnp.float32), template
                ),
                out_shardings=zero1_comp_shardings(zero_layout, compress_method, self.mesh),
            )()
        elif psgd_rank is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from .parallel.compression import powersgd_init_state

            n_data = int(dict(self.mesh.shape).get("data", 1))
            # build the params-sized error carry ALREADY sharded (jit +
            # out_shardings, the grad_buf pattern above): materializing it
            # replicated first would put n_data x params f32 on one device
            comp_state0 = jax.jit(
                lambda p: powersgd_init_state(p, psgd_rank, n_data),
                out_shardings={
                    "error": jax.tree_util.tree_map(
                        lambda _: NamedSharding(self.mesh, P("data")), model.params
                    ),
                    "q": jax.tree_util.tree_map(
                        lambda _: NamedSharding(self.mesh, P()), model.params
                    ),
                },
            )(model.params)
        from jax.sharding import NamedSharding as _NS, PartitionSpec as _PS

        state_box = {
            "grad_buf": grad_buf,
            "micro": 0,
            # fp16 dynamic loss scale as carried device arrays (no host
            # fetch per boundary); refreshed to the host copy every
            # _SCALE_REFRESH boundaries for introspection/checkpointing.
            # Committed mesh-replicated UP FRONT: after the first step the
            # carried scale comes back replicated over the whole mesh, and
            # a device-0-committed initial value would give the program a
            # second (then third, with static do_sync variants) cache
            # entry — a recompile the watchdog rightly flags
            "scale_state": jax.device_put(
                {
                    "scale": jnp.float32(self._loss_scale),
                    "growth": jnp.int32(self._scale_growth_tracker),
                },
                _NS(self.mesh, _PS()),
            ),
            "boundaries": 0,
            # PowerSGD error-feedback + warm-start factors (empty unless
            # grad_compression="powersgd[:r]")
            "comp_state": comp_state0,
        }
        self._fast_scale_boxes.append(state_box)
        _SCALE_REFRESH = 64

        def step(batch):
            # sync on the accumulation boundary OR at end-of-dataloader
            # (reference sync_with_dataloader semantics: accelerator.py:1123)
            do_sync = (state_box["micro"] + 1) % accum == 0
            if (
                self.gradient_state.sync_with_dataloader
                and self.gradient_state.in_dataloader
                and self.gradient_state.end_of_dataloader
            ):
                do_sync = True
            self.gradient_state._set_sync_gradients(do_sync)
            from .utils.random import key_for_step

            with self._matmul_precision_ctx():
                new_params, new_opt, new_buf, new_state, loss, gnorm, finite, aux, new_scale_state, new_comp = jitted(
                    model.params,
                    optimizer.opt_state,
                    state_box["grad_buf"],
                    getattr(model, "state", None) if has_state else None,
                    batch,
                    state_box["scale_state"],
                    bool(do_sync) if (offload_push is not None or zero_layout is not None) else jnp.bool_(do_sync),
                    key_for_step(self.step),
                    jnp.float32(-1.0 if self._clip_max_norm is None else self._clip_max_norm),
                    state_box["comp_state"],
                )
            model.params = new_params
            if has_state:
                model.state = new_state
            if offload_push is None:
                optimizer.opt_state = new_opt
            elif do_sync:
                optimizer.opt_state = offload_push(new_opt)
            # offload + non-sync: the state passed through the program
            # untouched (and unstreamed) — nothing to write back
            state_box["grad_buf"] = new_buf
            state_box["scale_state"] = new_scale_state
            state_box["comp_state"] = new_comp
            state_box["micro"] = 0 if do_sync else state_box["micro"] + 1
            self.step += 1
            self._last_grad_norm = gnorm
            # opt-in runtime finiteness probe (TelemetryKwargs
            # nonfinite_every=N) — the runtime counterpart of the static
            # TPU602 overflow proof. Gated inside observe(): off-cadence
            # steps coerce nothing, so no host sync is added
            if self._telemetry is not None and self._telemetry.nonfinite.enabled:
                self._telemetry.nonfinite.observe(
                    self.step,
                    loss=loss,
                    grad_norm=gnorm,
                    loss_scale=new_scale_state["scale"] if use_fp16 else None,
                    # the fp16 scaler skips the update and backs off on a
                    # grad overflow — that's calibration, not divergence
                    scaler_handled=use_fp16,
                )
            if do_sync:
                if use_fp16:
                    # device value, coerced lazily by the property — reading
                    # step_was_skipped is what forces the fetch, not the step
                    optimizer._step_was_skipped = jnp.logical_not(finite)
                    state_box["boundaries"] += 1
                    if state_box["boundaries"] % _SCALE_REFRESH == 0:
                        self._loss_scale = float(new_scale_state["scale"])
                        self._scale_growth_tracker = int(new_scale_state["growth"])
                if scheduler is not None:
                    scheduler.step()
            return (loss, aux) if has_aux else loss

        step._jitted = jitted
        return step

    def _make_gradient_applier(self, optax_tx):
        """The shared clip + finite-check + update + zero-buffer body used by
        both the fast path and the imperative path — one definition so the
        two paths can never diverge.

        ``clip_norm`` is a *traced* scalar (negative = clipping disabled,
        0.0 = zero all gradients, torch semantics), not a build-time
        constant: calling ``clip_grad_norm_`` inside the training loop —
        the reference idiom (accelerator.py:2677) — takes effect on the
        very next step without rebuilding the jitted program."""
        jax = _jax()
        jnp = _jnp()
        use_fp16 = self.mixed_precision == "fp16"

        def apply_gradients(operand, clip_norm):
            params, opt_state, grad_buf = operand
            g = grad_buf
            gnorm = optax_global_norm(g)
            # clip_norm < 0 = clipping disabled; 0.0 zeroes gradients
            # (torch clip_grad_norm_ semantics)
            scale = jnp.where(clip_norm >= 0, jnp.minimum(1.0, clip_norm / (gnorm + 1e-6)), 1.0)
            g = jax.tree_util.tree_map(lambda t: t * scale, g)
            finite = jnp.isfinite(gnorm)

            def do_update(_):
                updates, new_opt = optax_tx.update(g, opt_state, params)
                new_params = jax.tree_util.tree_map(lambda p, u: p + u.astype(p.dtype), params, updates)
                return new_params, new_opt

            if use_fp16:
                new_params, new_opt = jax.lax.cond(finite, do_update, lambda _: (params, opt_state), operand=None)
            else:
                new_params, new_opt = do_update(None)
            zero_buf = jax.tree_util.tree_map(jnp.zeros_like, grad_buf)
            return new_params, new_opt, zero_buf, gnorm, finite

        return apply_gradients

    def _update_loss_scale(self, finite: bool):
        h = self.scaler_handler
        if not finite:
            self._loss_scale = max(1.0, self._loss_scale * h.backoff_factor)
            self._scale_growth_tracker = 0
        else:
            self._scale_growth_tracker += 1
            if self._scale_growth_tracker >= h.growth_interval:
                self._loss_scale *= h.growth_factor
                self._scale_growth_tracker = 0

    # ------------------------------------------------------------------ #
    # imperative parity path (reference: accumulate/backward/step §3.4)
    # ------------------------------------------------------------------ #

    def _do_sync(self):
        """(reference: accelerator.py:1123-1131)."""
        if self.gradient_state.sync_with_dataloader and self.gradient_state.end_of_dataloader:
            self.step = 0
            self.gradient_state._set_sync_gradients(True)
        else:
            self.step += 1
            sync = (self.step % self.gradient_accumulation_steps) == 0
            sync = sync or self.gradient_state.plugin_kwargs.get("sync_each_batch", False)
            self.gradient_state._set_sync_gradients(sync)

    @contextlib.contextmanager
    def accumulate(self, *models):
        """(reference: accelerator.py:1149). Gradient-sync bookkeeping for
        the imperative path: inside the context, ``backward`` accumulates;
        ``optimizer.step()`` applies only on sync boundaries.

        When telemetry is live (the ``telemetry`` property has been
        accessed), each ``accumulate`` block is recorded as one step on
        the runtime timeline, fenced on the active model's params — the
        imperative twin of ``telemetry.wrap(step)``."""
        self._do_sync()
        if self._telemetry is None:
            yield
            return
        with self._telemetry.steps.step() as handle:
            yield
            target = (models[0] if models else None) or (self._models[-1] if self._models else None)
            handle.done(getattr(target, "params", None))

    @contextlib.contextmanager
    def no_sync(self, model=None):
        """(reference: accelerator.py:1033). Forces accumulation-only for
        the body. On TPU there is no DDP hook to disable — the flag simply
        gates the buffered apply."""
        old = self.gradient_state.sync_gradients
        self.gradient_state._set_sync_gradients(False)
        try:
            yield
        finally:
            self.gradient_state._set_sync_gradients(old)

    @contextlib.contextmanager
    def join_uneven_inputs(self, joinables, even_batches: Optional[bool] = None):
        """(reference: accelerator.py:1194). Uneven batches never reach the
        step on TPU (padding+mask in the dataloader), so this is a
        compatibility context that optionally overrides ``even_batches``."""
        loaders = [dl for dl in self._dataloaders if hasattr(dl, "even_batches")]
        old = [dl.even_batches for dl in loaders]
        if even_batches is not None:
            for dl in loaders:
                dl.even_batches = even_batches
        try:
            yield
        finally:
            for dl, val in zip(loaders, old):
                dl.even_batches = val

    def backward(self, loss_fn: Callable, batch=None, model: Optional[Model] = None, **kwargs):
        """Imperative gradient computation + accumulation
        (reference: accelerator.py:2549).

        JAX cannot differentiate an already-computed loss value, so the
        imperative contract takes the *loss function* plus the batch:
        ``accelerator.backward(loss_fn, batch)`` computes
        ``grad(loss_fn)(params, batch)``, scales by
        ``1/gradient_accumulation_steps`` (reference :2571), and adds into
        the on-device gradient buffer.
        """
        jax = _jax()
        jnp = _jnp()
        model = model or self._models[-1]
        accum = self.gradient_accumulation_steps
        # the cache entry holds a strong reference to loss_fn: a freed
        # lambda's id() can be reused, so identity is re-checked on hit
        cache_key = ("backward", id(loss_fn), id(model), accum)
        entry = self._jit_cache.get(cache_key)
        if entry is None or entry[0] is not loss_fn:
            compute_cast = self._compute_cast

            def grad_step(params, grad_buf, batch, loss_scale):
                def scaled(p):
                    loss = loss_fn(compute_cast(p), batch)
                    return loss.astype(jnp.float32) * loss_scale, loss

                grads, loss = jax.grad(scaled, has_aux=True)(params)
                grads = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32) / (loss_scale * accum), grads)
                new_buf = jax.tree_util.tree_map(lambda b, g: b + g, grad_buf, grads)
                return new_buf, loss

            entry = (loss_fn, jax.jit(grad_step, donate_argnums=(1,)))
            self._jit_cache[cache_key] = entry
        if self._grad_buffers.get(id(model)) is None:
            self._grad_buffers[id(model)] = jax.jit(
                lambda p: jax.tree_util.tree_map(lambda x: jnp.zeros_like(x, dtype=jnp.float32), p)
            )(model.params)
        with self._matmul_precision_ctx():
            self._grad_buffers[id(model)], loss = entry[1](
                model.params, self._grad_buffers[id(model)], batch, jnp.float32(self._loss_scale)
            )
        self._grad_count += 1
        return loss

    def _buffer_for(self, model: Optional[Model] = None):
        """The gradient buffer for ``model`` (default: the single active
        buffer, or the last prepared model's)."""
        if model is not None:
            return id(model), self._grad_buffers.get(id(model))
        if len(self._grad_buffers) == 1:
            return next(iter(self._grad_buffers.items()))
        if self._models:
            mid = id(self._models[-1])
            return mid, self._grad_buffers.get(mid)
        return None, None

    def _zero_grad_buffer(self, model: Optional[Model] = None):
        jax = _jax()
        jnp = _jnp()
        keys = [id(model)] if model is not None else list(self._grad_buffers)
        for k in keys:
            if self._grad_buffers.get(k) is not None:
                self._grad_buffers[k] = jax.tree_util.tree_map(lambda x: jnp.zeros_like(x), self._grad_buffers[k])
        self._grad_count = 0

    def _apply_accumulated_gradients(self, opt: AcceleratedOptimizer) -> bool:
        """Apply the imperative-path gradient buffer through the optimizer.
        Returns False when skipped (non-finite, fp16)."""
        jax = _jax()
        jnp = _jnp()
        model = getattr(opt, "_model", None) or self._models[-1]
        self._ensure_opt_state(opt, model)
        if getattr(opt, "_zero1_layout", None) is not None:
            raise NotImplementedError(
                "zero_stage=1 shards the update across replicas inside the jitted fast "
                "path; drive training through build_train_step (the imperative "
                "backward/step path would need a replicated optimizer state)"
            )
        _, grad_buffer = self._buffer_for(model)
        if grad_buffer is None:
            return True
        cache_key = ("apply", id(opt))
        if cache_key not in self._jit_cache:
            apply_gradients = self._make_gradient_applier(opt.optimizer)
            pull, _ = self._offload_transfers(opt)

            def _apply(params, opt_state, grad_buf, clip):
                if pull is not None:
                    opt_state = pull(opt_state)
                return apply_gradients((params, opt_state, grad_buf), clip)

            donate = (0, 2) if pull is not None else (0, 1, 2)
            self._jit_cache[cache_key] = jax.jit(_apply, donate_argnums=donate)
        with self._matmul_precision_ctx():
            new_params, new_opt, zero_buf, gnorm, finite = self._jit_cache[cache_key](
                model.params,
                opt.opt_state,
                grad_buffer,
                _jnp().float32(-1.0 if self._clip_max_norm is None else self._clip_max_norm),
            )
        model.params = new_params
        _, push = self._offload_transfers(opt)
        opt.opt_state = new_opt if push is None else push(new_opt)
        self._grad_buffers[id(model)] = zero_buf
        self._grad_count = 0
        self._last_grad_norm = gnorm
        ok = bool(finite)
        if self.mixed_precision == "fp16":
            self._update_loss_scale(ok)
        return ok

    def clip_grad_norm_(self, parameters=None, max_norm: float = 1.0, norm_type: float = 2.0):
        """(reference: accelerator.py:2677). Sets the max norm consumed by
        the next gradient apply — the norm is a traced input of the jitted
        step, so calling this inside the loop (the reference idiom) takes
        effect immediately on both the fast and imperative paths. On the
        imperative path the current buffer is also clipped in place and its
        pre-clip norm returned."""
        if norm_type != 2.0:
            raise NotImplementedError("only the L2 global norm is supported on TPU")
        self._clip_max_norm = max_norm
        model = parameters if isinstance(parameters, Model) else None
        key, buf = self._buffer_for(model)
        if buf is not None:
            jax = _jax()
            gnorm = optax_global_norm(buf)
            scale = _jnp().minimum(1.0, max_norm / (gnorm + 1e-6))
            self._grad_buffers[key] = jax.tree_util.tree_map(lambda t: t * scale, buf)
            self._last_grad_norm = gnorm
            return gnorm
        return self._last_grad_norm

    def clip_grad_value_(self, parameters, clip_value: float):
        """(reference: accelerator.py:2754)."""
        model = parameters if isinstance(parameters, Model) else None
        key, buf = self._buffer_for(model)
        if buf is not None:
            jax = _jax()
            jnp = _jnp()
            self._grad_buffers[key] = jax.tree_util.tree_map(lambda t: jnp.clip(t, -clip_value, clip_value), buf)

    # ------------------------------------------------------------------ #
    # metrics / gathering (reference: accelerator.py:2799-2871)
    # ------------------------------------------------------------------ #

    def gather(self, tensor):
        return gather(tensor)

    def gather_for_metrics(self, input_data, use_gather_object: bool = False):
        """Gather + drop the duplicated tail of the final uneven batch
        (reference: accelerator.py:2799; remainder from
        data_loader.py:365-405)."""
        if use_gather_object or not _has_array_leaves(input_data):
            data = gather_object(input_data if isinstance(input_data, list) else [input_data])
        else:
            data = gather(input_data)
        if self.gradient_state.end_of_dataloader and self.gradient_state.remainder > 0:
            rem = self.gradient_state.remainder

            def trunc(x):
                return x[:rem] if hasattr(x, "shape") and getattr(x, "ndim", 0) >= 1 else x

            import jax

            return jax.tree_util.tree_map(trunc, data)
        return data

    def reduce(self, tensor, reduction: str = "mean", scale: float = 1.0):
        return reduce(tensor, reduction, scale)

    def pad_across_processes(self, tensor, dim: int = 0, pad_index: int = 0, pad_first: bool = False):
        return pad_across_processes(tensor, dim, pad_index, pad_first)

    # ------------------------------------------------------------------ #
    # precision helpers
    # ------------------------------------------------------------------ #

    @contextlib.contextmanager
    def autocast(self, autocast_handler: Optional[AutocastKwargs] = None):
        """(reference: accelerator.py:3832). Compute-dtype casting is baked
        into the jitted step (``_compute_cast``); this context exists for
        API parity and temporarily overrides the policy for code that calls
        :meth:`cast_to_compute`."""
        old = self.autocast_handler
        if autocast_handler is not None:
            self.autocast_handler = autocast_handler
        try:
            yield
        finally:
            self.autocast_handler = old

    def cast_to_compute(self, tree):
        return self._compute_cast(tree)

    # ------------------------------------------------------------------ #
    # triggers (reference: accelerator.py:2583-2640)
    # ------------------------------------------------------------------ #

    def set_trigger(self):
        self._trigger_flag = True

    def check_trigger(self) -> bool:
        flags = gather_object([self._trigger_flag])
        fired = any(flags)
        if fired:
            self._trigger_flag = False
        return fired

    # ------------------------------------------------------------------ #
    # model export / unwrap
    # ------------------------------------------------------------------ #

    def unwrap_model(self, model, keep_fp32_wrapper: bool = True):
        """(reference: accelerator.py:2744 via utils/other.py:217). Models
        are never wrapped on TPU; returns as-is."""
        return model

    def free_memory(self, *objects):
        """(reference: accelerator.py:3633)."""
        self._models.clear()
        self._optimizers.clear()
        self._schedulers.clear()
        self._dataloaders.clear()
        self._grad_buffers.clear()
        self._jit_cache.clear()
        self.step = 0
        from .utils.memory import release_memory

        return release_memory(*objects)

    def clear(self, *objects):
        return self.free_memory(*objects)

    # ------------------------------------------------------------------ #
    # checkpointing (reference: accelerator.py:3308/3474)
    # ------------------------------------------------------------------ #

    def register_for_checkpointing(self, *objects):
        """(reference: accelerator.py:3795)."""
        invalid = [o for o in objects if not (hasattr(o, "state_dict") and hasattr(o, "load_state_dict"))]
        if invalid:
            raise ValueError(f"Objects must expose state_dict/load_state_dict: {invalid}")
        self._custom_objects.extend(objects)

    def register_save_state_pre_hook(self, hook):
        self._save_model_hooks.append(hook)
        return _RemovableHandle(self._save_model_hooks, hook)

    def register_load_state_pre_hook(self, hook):
        self._load_model_hooks.append(hook)
        return _RemovableHandle(self._load_model_hooks, hook)

    def _sync_loss_scale_to_host(self):
        """Pull the fast path's on-device fp16 scale into the host mirror
        (the periodic refresh may lag by up to _SCALE_REFRESH boundaries —
        a checkpoint must persist the TRUE current scale)."""
        boxes = getattr(self, "_fast_scale_boxes", None)
        if boxes and self.mixed_precision == "fp16":
            ss = boxes[-1]["scale_state"]
            self._loss_scale = float(ss["scale"])
            self._scale_growth_tracker = int(ss["growth"])

    def _seed_loss_scale_to_device(self):
        """Push the host scale into every built train step's carried device
        state (load_state must take effect on steps built BEFORE the load).
        Mesh-replicated like the build-time init, so re-seeding never
        hands the jitted step a differently-committed scale (= recompile)."""
        jax = _jax()
        jnp = _jnp()
        from jax.sharding import NamedSharding, PartitionSpec

        for box in getattr(self, "_fast_scale_boxes", []) or []:
            box["scale_state"] = jax.device_put(
                {
                    "scale": jnp.float32(self._loss_scale),
                    "growth": jnp.int32(self._scale_growth_tracker),
                },
                NamedSharding(self.mesh, PartitionSpec()),
            )

    def save_state(self, output_dir: Optional[str] = None, **save_model_func_kwargs):
        """Atomic checkpoint save (tmp-dir write -> barrier -> manifest ->
        rename; see ``docs/usage_guides/fault_tolerance.md``).

        ``async_save=True`` returns once device->host copies finish;
        disk writes AND the commit continue in the background (drained by
        :meth:`wait_for_checkpoint` or the next save/load). Under
        preemption the async request is demoted to a synchronous save —
        the grace window is for committing, not for queueing."""
        from .checkpointing import save_accelerator_state

        if self.preempted:
            save_model_func_kwargs.pop("async_save", None)
        self._sync_loss_scale_to_host()
        out = save_accelerator_state(self, output_dir, **save_model_func_kwargs)
        if self.preempted:
            self._preempt_checkpointed = True
        return out

    def wait_for_checkpoint(self):
        """Block until pending ``save_state(async_save=True)`` writes commit."""
        from .checkpointing import wait_for_checkpoint

        wait_for_checkpoint()

    def load_state(self, input_dir: Optional[str] = None, **load_model_func_kwargs):
        """Restore a checkpoint. With ``input_dir=None``, **auto-resume**:
        find the newest checkpoint whose integrity manifest verifies under
        ``{project_dir}/checkpoints`` (walking back past corrupt or
        uncommitted ones), restore it, and continue the ``checkpoint_N``
        numbering from there."""
        from .checkpointing import load_accelerator_state

        out = load_accelerator_state(self, input_dir, **load_model_func_kwargs)
        self._seed_loss_scale_to_device()
        if self._program_cache is not None:
            # warm-start after (elastic) restore: the restored trainer's
            # step programs should deserialize from the executable store
            # instead of recompiling — surface how warm that store is so
            # a resume that DID recompile is explainable from telemetry
            stats = self._program_cache.stats()
            if self._telemetry is not None:
                self._telemetry.log.event("compile_cache_warmstart", **stats)
            logger.info(
                "compile cache at resume: %s stored executable(s), %s deserialized this process",
                stats.get("store_entries", 0), stats.get("deserialized", 0),
            )
        return out

    @property
    def checkpoint_manager(self):
        """A :class:`~accelerate_tpu.ft.CheckpointManager` over this
        project's automatic-naming checkpoint directory (``None`` without
        a ``project_dir``)."""
        if self.project_dir is None:
            return None
        from .ft.manager import CheckpointManager

        return CheckpointManager(
            os.path.join(self.project_dir, self.project_configuration.checkpoints_dir_name)
        )

    # ------------------------------------------------------------------ #
    # preemption (docs/usage_guides/fault_tolerance.md; no reference
    # analogue — the reference dies with the SIGTERM)
    # ------------------------------------------------------------------ #

    @property
    def preemption_handler(self):
        """The installed :class:`~accelerate_tpu.ft.PreemptionHandler`, or
        ``None`` (pass ``FaultToleranceKwargs()`` to install one)."""
        return self._preemption

    @property
    def preempted(self) -> bool:
        """True once SIGTERM/SIGINT was received (always False without a
        preemption handler)."""
        return self._preemption is not None and self._preemption.preempted

    def _preempted_everywhere(self) -> bool:
        """The fleet-wide preemption flag. Multi-host, a SIGTERM usually
        lands on a SUBSET of hosts; every rank runs the same max-reduce
        of its local flag here (``parallel.collectives.agree_preempt_max``)
        so the flag flips on all ranks in the same step and the fleet
        takes one coherent final checkpoint. Called unconditionally by
        ``should_checkpoint``/``should_stop`` — never guard a call to
        those behind rank-divergent state. Latches after the first
        agreed-True so later checks are free; single-process runs skip
        the collective entirely."""
        if self._preemption is None:
            return False
        if self._preempt_agreed:
            return True
        local = self._preemption.preempted
        if self.num_processes == 1 or not self.ft_handler.agree_preemption:
            return local
        from .parallel.collectives import agree_preempt_max

        agreed = bool(agree_preempt_max(1 if local else 0))
        if agreed:
            self._preempt_agreed = True
            if not local:
                # this rank never saw the signal: latch its handler so
                # telemetry/logging and `preempted` agree fleet-wide
                self._preemption.mark_remote()
        return agreed

    @property
    def should_checkpoint(self) -> bool:
        """True when a preemption signal arrived — on ANY host (see
        :meth:`_preempted_everywhere`) — and the final synchronous
        checkpoint has not been taken yet; check after each step::

            if accelerator.should_checkpoint:
                accelerator.save_state()   # drains async saves, saves sync
            if accelerator.should_stop:
                break

        Every rank must read this at the same step boundary: multi-host it
        performs the preemption-agreement collective."""
        return self._preempted_everywhere() and not self._preempt_checkpointed

    @property
    def should_stop(self) -> bool:
        """True once preemption was signalled anywhere in the fleet: exit
        the training loop at the next step boundary (after the
        :attr:`should_checkpoint` save)."""
        return self._preempted_everywhere()

    def save_model(self, model, save_directory: str, max_shard_size="10GB", safe_serialization: bool = True):
        from .checkpointing import save_model as _save_model

        return _save_model(model, save_directory, max_shard_size=max_shard_size, safe_serialization=safe_serialization)

    def skip_first_batches(self, dataloader, num_batches: int = 0):
        """(reference: accelerator.py:3929)."""
        return _skip_first_batches(dataloader, num_batches)

    # ------------------------------------------------------------------ #
    # runtime telemetry (no reference analogue; docs/usage_guides/telemetry.md)
    # ------------------------------------------------------------------ #

    @property
    def telemetry(self):
        """The run's :class:`~accelerate_tpu.telemetry.Telemetry` facade
        (created on first access from the ``TelemetryKwargs`` handler).

        Typical use — instrument the fast path and let everything else
        happen automatically (event log under ``logging_dir``, HBM
        sampling, recompile watchdog, tracker forwarding)::

            step = accelerator.telemetry.wrap(accelerator.build_train_step(loss_fn))

        The imperative path needs no call at all: ``accumulate()`` blocks
        are timed as steps once telemetry has been touched. Pass
        ``TelemetryKwargs(enabled=False)`` to keep even explicit accesses
        event-log-free (in-memory records still accumulate, so
        ``telemetry.summary()`` keeps working)."""
        if self._telemetry is None:
            from .telemetry import Telemetry, default_path

            h = self.telemetry_handler
            path = None
            if h.enabled:
                path = h.output_path or default_path(self.logging_dir)
            self._telemetry = Telemetry(
                path,
                rank=self.process_index,
                main_process_only=h.main_process_only,
                warmup_steps=h.warmup_steps,
                fence=h.fence,
                watchdog=h.recompile_watchdog,
                n_devices=self.state.num_devices,
                hbm_sample_every=h.hbm_sample_every,
                forward_fn=(lambda values, step: self.log(values, step=step)),
                forward_every=h.forward_to_trackers_every,
                nonfinite_every=h.nonfinite_every,
            )
            if self._program_cache is not None:
                # compile_cache_* events land in the same run JSONL as the
                # step timeline, so a summarize pass explains both
                self._program_cache.log = self._telemetry.log
        return self._telemetry

    @property
    def program_cache(self):
        """The shared :class:`~accelerate_tpu.aot.ProgramCache` (``None``
        unless a :class:`~accelerate_tpu.utils.CompileKwargs` handler was
        passed or ``ACCELERATE_COMPILE_CACHE_DIR`` is set). When active,
        ``build_train_step`` routes program dispatch through it, so a
        restarted process deserializes the step executable instead of
        recompiling — see ``docs/usage_guides/compilation.md``."""
        return self._program_cache

    # ------------------------------------------------------------------ #
    # tracking (reference: accelerator.py:3002-3114)
    # ------------------------------------------------------------------ #

    def init_trackers(self, project_name: str, config: Optional[dict] = None, init_kwargs: dict = {}):
        from .tracking import filter_trackers

        self.trackers = filter_trackers(self._log_with, self.logging_dir, project_name, config, init_kwargs)

    def get_tracker(self, name: str, unwrap: bool = False):
        """(reference: accelerator.py:3069). With NO active trackers,
        returns a no-op blank ``GeneralTracker`` (reference behavior) so
        user code can call ``get_tracker(...).log(...)`` unconditionally;
        the ``ValueError`` is kept only for a *named* tracker genuinely
        missing among active ones."""
        if self.trackers:
            for tracker in self.trackers:
                if tracker.name == name:
                    return tracker.tracker if unwrap else tracker
            raise ValueError(f"{name} is not an active tracker: {[t.name for t in self.trackers]}")
        from .tracking import GeneralTracker

        return GeneralTracker(_blank=True)

    def log(self, values: dict, step: Optional[int] = None, log_kwargs: dict = {}):
        if not self.is_main_process:
            return
        retries = self.ft_handler.tracker_retries if self._ft_explicit else 1
        for tracker in self.trackers:
            kw = log_kwargs.get(tracker.name, {})
            if retries <= 1:
                tracker.log(values, step=step, **kw)
                continue
            # FT mode: a tracker backend hiccup (wandb 5xx, mlflow timeout)
            # is retried with backoff and, on giveup, logged and swallowed —
            # metrics loss must not kill a multi-hour run
            from .utils.retry import retry_call

            def _on_retry(attempt, delay, exc, _name=tracker.name):
                if self._telemetry is not None:
                    self._telemetry.log.event(
                        "tracker_retry", severity="warning", tracker=_name,
                        attempt=attempt, delay_s=round(delay, 3), error=str(exc),
                    )

            try:
                retry_call(
                    tracker.log, values, step=step,
                    attempts=retries,
                    base_delay=self.ft_handler.retry_base_delay,
                    max_delay=self.ft_handler.retry_max_delay,
                    exceptions=(Exception,),
                    on_retry=_on_retry,
                    **kw,
                )
            except Exception as e:
                logger.warning(f"tracker {tracker.name}.log failed after {retries} attempts: {e}")
                if self._telemetry is not None:
                    self._telemetry.log.event(
                        "tracker_giveup", severity="error", tracker=tracker.name, error=str(e)
                    )

    def _media_trackers(self, method: str):
        """Active trackers that override ``method`` beyond the base class
        (the base raises NotImplementedError); others are skipped with a
        one-line note so mixed tracker sets don't error on media calls."""
        from .tracking import GeneralTracker

        capable = []
        for tracker in self.trackers:
            if getattr(type(tracker), method) is getattr(GeneralTracker, method):
                logger.debug("%s does not support %s; skipping", tracker.name, method)
            else:
                capable.append(tracker)
        return capable

    def log_images(self, values: dict, step: Optional[int] = None, log_kwargs: dict = {}):
        """Route ``{name: [images]}`` to every active tracker with media
        support (reference: per-tracker ``log_images``, tracking.py:272/:373;
        the reference has no Accelerator-level helper — this closes the
        round-4 media-parity gap with one call)."""
        if self.is_main_process:
            for tracker in self._media_trackers("log_images"):
                tracker.log_images(values, step=step, **log_kwargs.get(tracker.name, {}))

    def log_table(
        self,
        table_name: str,
        columns: Optional[list] = None,
        data: Optional[list] = None,
        dataframe=None,
        step: Optional[int] = None,
        log_kwargs: dict = {},
    ):
        """Route a table to every active tracker with table support
        (reference: tracking.py:392 WandB / :1016 ClearML)."""
        if self.is_main_process:
            for tracker in self._media_trackers("log_table"):
                tracker.log_table(
                    table_name, columns=columns, data=data, dataframe=dataframe, step=step,
                    **log_kwargs.get(tracker.name, {}),
                )

    def end_training(self):
        if self.is_main_process:
            for tracker in self.trackers:
                tracker.finish()
        self.wait_for_everyone()

    # ------------------------------------------------------------------ #
    # profiling (reference: accelerator.py:3859)
    # ------------------------------------------------------------------ #

    @contextlib.contextmanager
    def profile(self, profile_handler: Optional[ProfileKwargs] = None):
        """Trace the body with ``jax.profiler``. Every ``ProfileKwargs``
        field is honoured as far as the installed jax allows:
        ``create_perfetto_link``/``create_perfetto_trace`` go straight to
        ``start_trace``; the tracer levels ride on profiler options when
        this jax exposes them (``jax.profiler.ProfileOptions``, jax>=0.5)
        and are otherwise DROPPED with a one-time warning naming exactly
        which knobs were ignored."""
        if isinstance(profile_handler, str):  # path shorthand
            profile_handler = ProfileKwargs(output_trace_dir=profile_handler)
        handler = profile_handler or self.profile_handler
        import inspect
        import jax

        trace_dir = handler.output_trace_dir or os.path.join(self.logging_dir or ".", "profile")
        start_params = inspect.signature(jax.profiler.start_trace).parameters
        kwargs = {}
        if "create_perfetto_trace" in start_params:
            kwargs["create_perfetto_trace"] = handler.create_perfetto_trace
        if "create_perfetto_link" in start_params:
            kwargs["create_perfetto_link"] = handler.create_perfetto_link
        elif handler.create_perfetto_link:
            _warn_dropped_profile_options(["create_perfetto_link"])
        defaults = ProfileKwargs()
        tracer_fields = ("host_tracer_level", "python_tracer_level", "device_tracer_level")
        requested = [f for f in tracer_fields if getattr(handler, f) != getattr(defaults, f)]
        options_cls = getattr(jax.profiler, "ProfileOptions", None)
        if options_cls is not None and "profiler_options" in start_params:
            options = options_cls()
            for f in tracer_fields:
                setattr(options, f, getattr(handler, f))
            kwargs["profiler_options"] = options
        elif requested:
            _warn_dropped_profile_options(requested)
        jax.profiler.start_trace(trace_dir, **kwargs)
        try:
            yield
        finally:
            jax.profiler.stop_trace()
            if handler.on_trace_ready is not None:
                handler.on_trace_ready(trace_dir)

    def __repr__(self):
        return f"Accelerator(mesh={dict(self.mesh.shape)}, mixed_precision={self.mixed_precision!r})"


#: zero_stage=1 non-elementwise fallbacks already warned about (one
#: warning per offending state-node set per process)
_ZERO1_FALLBACK_WARNED: set = set()


def _nonelementwise_state_nodes(optax_tx) -> set:
    """Names of optax state nodes whose leaves couple elements within a
    parameter leaf — the structural probe behind the zero_stage=1
    fallback. An elementwise transform's state leaves are scalars (step
    counts) or param-shaped (adam moments); anything else (adafactor's
    ``(rows,)``/``(cols,)`` factored moments) proves the update reads
    across elements, which the flat-segment ZeRO-1 update would break.
    Probed via ``eval_shape`` on a tiny 2-D template — nothing runs.
    Shape-preserving couplings (a per-leaf trust ratio) are outside what
    a structural probe can see; those transforms keep their documented
    ``shard_optimizer_state`` contract."""
    jax = _jax()
    jnp = _jnp()
    probe_shape = (4, 6)
    try:
        state = jax.eval_shape(optax_tx.init, {"w": jax.ShapeDtypeStruct(probe_shape, jnp.float32)})
    except Exception:
        return set()  # unprobeable init: leave the explicit-layout path to its own validation
    bad: set = set()

    def walk(node, owner: str):
        if isinstance(node, tuple) and hasattr(node, "_fields"):
            for v in node:
                walk(v, type(node).__name__)
        elif isinstance(node, (list, tuple)):
            for v in node:
                walk(v, owner)
        elif isinstance(node, dict):
            for v in node.values():
                walk(v, owner)
        else:
            shape = getattr(node, "shape", None)
            if shape is not None and tuple(shape) not in ((), probe_shape):
                bad.add(owner or "optax state")

    walk(state, "")
    return bad


_dropped_profile_options_warned = False


def _warn_dropped_profile_options(fields):
    """One warning per process for ProfileKwargs knobs this jax version
    cannot honour (accepting-and-ignoring them silently was the old bug)."""
    global _dropped_profile_options_warned
    if _dropped_profile_options_warned:
        return
    _dropped_profile_options_warned = True
    import jax

    logger.warning(
        "ProfileKwargs option(s) %s are not supported by jax %s's profiler "
        "and were ignored (profiler options need jax>=0.5)",
        ", ".join(fields),
        jax.__version__,
    )


class _RemovableHandle:
    def __init__(self, hooks_list, hook):
        self._list = hooks_list
        self._hook = hook

    def remove(self):
        if self._hook in self._list:
            self._list.remove(self._hook)


def optax_global_norm(tree):
    import jax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves))


def _has_array_leaves(data) -> bool:
    import jax

    return any(hasattr(l, "shape") for l in jax.tree_util.tree_leaves(data))
