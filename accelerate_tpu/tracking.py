"""Experiment trackers.

Reference analogue: src/accelerate/tracking.py (1326 LoC): ``GeneralTracker``
ABC (:101-181, contract: ``name``/``requires_logging_directory``/``start``/
``store_init_configuration``/``log``/``finish``, main-process gating via the
``on_main_process`` decorator :77) + nine hosted-service integrations.

The ABC and the TensorBoard/WandB/MLflow/Aim/CometML/ClearML trackers are
kept (import-gated); a dependency-free ``JSONLTracker`` is the default so
tracking works on a bare TPU VM.
"""

from __future__ import annotations

import functools
import json
import os
import time
from typing import Optional

from .logging import get_logger
from .state import PartialState
from .utils.dataclasses import LoggerType
from .utils.imports import (
    is_aim_available,
    is_clearml_available,
    is_comet_ml_available,
    is_dvclive_available,
    is_mlflow_available,
    is_swanlab_available,
    is_tensorboard_available,
    is_trackio_available,
    is_wandb_available,
)

logger = get_logger(__name__)


def on_main_process(function):
    """Method decorator: run only on the main process (reference:
    tracking.py:77)."""

    @functools.wraps(function)
    def execute_on_main_process(self, *args, **kwargs):
        if getattr(self, "main_process_only", True) and not PartialState().is_main_process:
            return None
        return function(self, *args, **kwargs)

    return execute_on_main_process


class GeneralTracker:
    """(reference: tracking.py:101). Subclass contract: class attrs ``name``
    and ``requires_logging_directory``; methods ``store_init_configuration``
    and ``log``; optionally ``start``, ``finish`` and a ``tracker`` property.

    Lifecycle (reference: tracking.py:318): ``__init__`` only records
    arguments; the backend (wandb run, SummaryWriter, ...) is created in
    ``start()``, which ``Accelerator.init_trackers`` calls on the main
    process. Constructing a tracker on a worker rank is therefore free and
    side-effect-less."""

    main_process_only = True

    def __init__(self, _blank: bool = False):
        """``_blank=True`` builds a NO-OP tracker (reference:
        tracking.py:110 + ``Accelerator.get_tracker`` with no active
        trackers) — every method accepts its arguments and does nothing,
        so user code can call ``get_tracker(...).log(...)``
        unconditionally."""
        self._blank = _blank
        if _blank:
            self.name = ""
            self.requires_logging_directory = False
            return
        for attr in ("name", "requires_logging_directory"):
            if not hasattr(self, attr):
                raise NotImplementedError(f"Tracker subclass must define `{attr}`")

    def start(self):
        """Initialise the tracking backend. Idempotence is the subclass's
        concern; ``filter_trackers``/``init_trackers`` call it exactly once."""

    @property
    def tracker(self):
        if getattr(self, "_blank", False):
            return None
        raise NotImplementedError

    def store_init_configuration(self, values: dict):
        if getattr(self, "_blank", False):
            return None
        raise NotImplementedError

    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        if getattr(self, "_blank", False):
            return None
        raise NotImplementedError

    def log_images(self, values: dict, step: Optional[int] = None, **kwargs):
        """Log ``{name: [HWC uint8/float arrays or PIL images]}`` media
        (reference: tracking.py:272/:373/:666/:998 — per-tracker
        ``log_images``). Base raises: ``Accelerator.log_images`` dispatches
        only to trackers that override this."""
        raise NotImplementedError(f"{type(self).__name__} does not support image logging")

    def log_table(
        self,
        table_name: str,
        columns: Optional[list] = None,
        data: Optional[list] = None,
        dataframe=None,
        step: Optional[int] = None,
        **kwargs,
    ):
        """Log a table from ``columns``+``data`` rows or a ``dataframe``
        (reference: tracking.py:392/:1016)."""
        raise NotImplementedError(f"{type(self).__name__} does not support table logging")

    def finish(self):
        pass


def _as_hwc_uint8(image):
    """Normalise one image (PIL / [H,W] / [H,W,C] float-or-int array) to an
    HWC uint8 numpy array — the common currency every media sink accepts.
    Floats are assumed in [0, 1] (the diffusion example's output range)."""
    import numpy as np

    if hasattr(image, "mode"):  # PIL.Image duck-type
        return np.asarray(image.convert("RGB"))
    arr = np.asarray(image)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if np.issubdtype(arr.dtype, np.floating):
        arr = (np.clip(arr, 0.0, 1.0) * 255).astype(np.uint8)
    elif arr.dtype != np.uint8:  # int16/32/64 pixels are already 0-255
        arr = np.clip(arr, 0, 255).astype(np.uint8)
    return arr


class JSONLTracker(GeneralTracker):
    """Dependency-free default: one JSON object per log call, appended to
    ``{logging_dir}/{run_name}/metrics.jsonl``. No reference analogue —
    exists so a bare TPU VM always has a tracker."""

    name = "jsonl"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str = ".", **kwargs):
        super().__init__()
        self.run_name = run_name
        self.dir = os.path.join(logging_dir, run_name)
        self.path = os.path.join(self.dir, "metrics.jsonl")

    @on_main_process
    def start(self):
        os.makedirs(self.dir, exist_ok=True)

    @property
    def tracker(self):
        return self.path

    @on_main_process
    def store_init_configuration(self, values: dict):
        with open(os.path.join(self.dir, "config.json"), "w") as f:
            json.dump(values, f, indent=2, default=str)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        record = {"_time": time.time()}
        if step is not None:
            record["_step"] = step
        record.update(values)
        with open(self.path, "a") as f:
            f.write(json.dumps(record, default=float) + "\n")

    @on_main_process
    def log_images(self, values: dict, step: Optional[int] = None, **kwargs):
        """PNGs under ``{run}/media/`` (PIL; falls back to .npy without it),
        with their paths appended to the metrics stream."""
        media_dir = os.path.join(self.dir, "media")
        os.makedirs(media_dir, exist_ok=True)
        paths = {}
        for k, images in values.items():
            paths[k] = []
            for i, image in enumerate(images):
                arr = _as_hwc_uint8(image)
                stem = f"{k.replace('/', '_')}_{step if step is not None else 'x'}_{i}"
                try:
                    from PIL import Image

                    path = os.path.join(media_dir, stem + ".png")
                    Image.fromarray(arr.squeeze() if arr.shape[-1] == 1 else arr).save(path)
                except ImportError:
                    import numpy as np

                    path = os.path.join(media_dir, stem + ".npy")
                    np.save(path, arr)
                paths[k].append(path)
        self.log({f"_images/{k}": v for k, v in paths.items()}, step=step)

    @on_main_process
    def log_table(self, table_name, columns=None, data=None, dataframe=None, step=None, **kwargs):
        if dataframe is not None:
            columns = list(dataframe.columns)
            data = dataframe.values.tolist()
        elif data is None:
            raise ValueError("log_table needs `data` (with optional `columns`) or `dataframe`")
        self.log({f"_table/{table_name}": {"columns": columns, "data": data}}, step=step)


class TensorBoardTracker(GeneralTracker):
    """(reference: tracking.py:182). Uses tensorboardX or
    torch.utils.tensorboard, whichever is importable."""

    name = "tensorboard"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str = ".", **kwargs):
        super().__init__()
        self.run_name = run_name
        self.logging_dir = os.path.join(logging_dir, run_name)
        self._init_kwargs = kwargs

    @on_main_process
    def start(self):
        try:
            from torch.utils.tensorboard import SummaryWriter
        except ImportError:
            from tensorboardX import SummaryWriter
        self.writer = SummaryWriter(self.logging_dir, **self._init_kwargs)

    @property
    def tracker(self):
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.writer.add_hparams(
            {k: v for k, v in values.items() if isinstance(v, (int, float, str, bool))}, metric_dict={}
        )
        self.writer.flush()

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        for k, v in values.items():
            if isinstance(v, (int, float)):
                self.writer.add_scalar(k, v, global_step=step, **kwargs)
            elif isinstance(v, str):
                self.writer.add_text(k, v, global_step=step, **kwargs)
            elif isinstance(v, dict):
                self.writer.add_scalars(k, v, global_step=step, **kwargs)
        self.writer.flush()

    @on_main_process
    def log_images(self, values: dict, step: Optional[int] = None, **kwargs):
        """(reference: tracking.py:272). Accepts ``{name: [images]}``; images
        are normalised to a stacked NHWC uint8 batch (the JAX-native layout —
        the reference's torch default is NCHW)."""
        import numpy as np

        for k, v in values.items():
            imgs = [_as_hwc_uint8(image) for image in v]
            # a batch may mix grayscale/RGB/RGBA inputs — stack needs one
            # depth: drop alpha, broadcast grayscale
            imgs = [
                i[..., :3] if i.shape[-1] >= 3 else np.repeat(i[..., :1], 3, axis=-1)
                for i in imgs
            ]
            self.writer.add_images(k, np.stack(imgs), global_step=step, dataformats="NHWC", **kwargs)
        self.writer.flush()

    @on_main_process
    def finish(self):
        self.writer.close()


class WandBTracker(GeneralTracker):
    """(reference: tracking.py:297)."""

    name = "wandb"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__()
        self.run_name = run_name
        self._init_kwargs = kwargs

    @on_main_process
    def start(self):
        import wandb

        self.run = wandb.init(project=self.run_name, **self._init_kwargs)

    @property
    def tracker(self):
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict):
        import os

        import wandb

        offline = os.environ.get("WANDB_MODE") == "offline" or self._init_kwargs.get("mode") == "offline"
        if offline:
            # offline runs can't mutate config after init — restart the run
            # with the config baked in (reference: tracking.py:343-352);
            # merge over any config the tracker was constructed with
            if getattr(self, "run", None):
                self.run.finish()
            init_kwargs = dict(self._init_kwargs)
            base = init_kwargs.pop("config", None)
            config = {**base, **values} if isinstance(base, dict) else values
            self.run = wandb.init(project=self.run_name, config=config, **init_kwargs)
            return
        wandb.config.update(values, allow_val_change=True)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        self.run.log(values, step=step, **kwargs)

    @on_main_process
    def log_images(self, values: dict, step: Optional[int] = None, **kwargs):
        """(reference: tracking.py:373)."""
        import wandb

        for k, v in values.items():
            self.log({k: [wandb.Image(image) for image in v]}, step=step, **kwargs)

    @on_main_process
    def log_table(self, table_name, columns=None, data=None, dataframe=None, step=None, **kwargs):
        """(reference: tracking.py:392)."""
        import wandb

        if data is None and dataframe is None:
            raise ValueError("log_table needs `data` (with optional `columns`) or `dataframe`")
        self.log({table_name: wandb.Table(columns=columns, data=data, dataframe=dataframe)}, step=step, **kwargs)

    @on_main_process
    def finish(self):
        self.run.finish()


class MLflowTracker(GeneralTracker):
    """(reference: tracking.py:705)."""

    name = "mlflow"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, logging_dir: Optional[str] = None, **kwargs):
        super().__init__()
        self.run_name = run_name
        self.logging_dir = logging_dir
        self._init_kwargs = kwargs

    @on_main_process
    def start(self):
        import mlflow

        # file-store support (reference: tracking.py:705 MLflowTracker uses
        # MLFLOW_TRACKING_URI / the logging dir): a logging_dir routes runs
        # to a local file store; ``experiment_name`` selects/creates the
        # experiment before the run starts.
        if self.logging_dir:
            mlflow.set_tracking_uri("file://" + os.path.abspath(self.logging_dir))
        init_kwargs = dict(self._init_kwargs)
        experiment = init_kwargs.pop("experiment_name", None)
        if experiment:
            mlflow.set_experiment(experiment)
        self.active_run = mlflow.start_run(run_name=self.run_name, **init_kwargs)

    @property
    def tracker(self):
        return self.active_run

    @on_main_process
    def store_init_configuration(self, values: dict):
        import mlflow

        for chunk_start in range(0, len(values), 100):
            mlflow.log_params(dict(list(values.items())[chunk_start : chunk_start + 100]))

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        import mlflow

        mlflow.log_metrics({k: v for k, v in values.items() if isinstance(v, (int, float))}, step=step)

    @on_main_process
    def finish(self):
        import mlflow

        mlflow.end_run()


class AimTracker(GeneralTracker):
    """(reference: tracking.py:602)."""

    name = "aim"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str = ".", **kwargs):
        super().__init__()
        self.run_name = run_name
        self.logging_dir = logging_dir
        self._init_kwargs = kwargs

    @on_main_process
    def start(self):
        from aim import Run

        self.writer = Run(repo=self.logging_dir, **self._init_kwargs)
        self.writer.name = self.run_name

    @property
    def tracker(self):
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.writer["hparams"] = values

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        for k, v in values.items():
            self.writer.track(v, name=k, step=step, **kwargs)

    @on_main_process
    def log_images(self, values: dict, step: Optional[int] = None, **kwargs):
        """(reference: tracking.py:666). Values may be ``(image, caption)``
        tuples; ``kwargs`` may carry ``aim_image`` / ``track`` sub-dicts."""
        import aim

        aim_image_kw = (kwargs or {}).get("aim_image", {})
        track_kw = (kwargs or {}).get("track", {})
        for k, v in values.items():
            # a key maps to one image, one (image, caption) tuple, or a list
            for image in (v if isinstance(v, list) else [v]):
                if isinstance(image, tuple):
                    img, caption = image
                    aim_img = aim.Image(img, caption=caption, **aim_image_kw)
                else:
                    aim_img = aim.Image(image, **aim_image_kw)
                self.writer.track(aim_img, name=k, step=step, **track_kw)

    @on_main_process
    def finish(self):
        self.writer.close()


class CometMLTracker(GeneralTracker):
    """(reference: tracking.py:508)."""

    name = "comet_ml"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__()
        self.run_name = run_name
        self._init_kwargs = kwargs

    @on_main_process
    def start(self):
        from comet_ml import Experiment

        self.writer = Experiment(project_name=self.run_name, **self._init_kwargs)

    @property
    def tracker(self):
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.writer.log_parameters(values)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        if step is not None:
            self.writer.set_step(step)
        self.writer.log_metrics(values, step=step, **kwargs)

    @on_main_process
    def log_images(self, values: dict, step: Optional[int] = None, **kwargs):
        """comet_ml ``Experiment.log_image`` per image (named ``{key}_{i}``)."""
        for k, v in values.items():
            for i, image in enumerate(v):
                self.writer.log_image(_as_hwc_uint8(image), name=f"{k}_{i}", step=step, **kwargs)

    @on_main_process
    def log_table(self, table_name, columns=None, data=None, dataframe=None, step=None, **kwargs):
        """comet_ml ``Experiment.log_table`` (csv filename + tabular data)."""
        if step is not None:
            self.writer.set_step(step)
        filename = table_name if table_name.endswith((".csv", ".tsv")) else f"{table_name}.csv"
        if dataframe is not None:
            self.writer.log_table(filename, tabular_data=dataframe, **kwargs)
        else:
            if data is None:
                raise ValueError("log_table needs `data` (with optional `columns`) or `dataframe`")
            self.writer.log_table(filename, tabular_data=data, headers=columns if columns is not None else False, **kwargs)

    @on_main_process
    def finish(self):
        self.writer.end()


class ClearMLTracker(GeneralTracker):
    """(reference: tracking.py:912)."""

    name = "clearml"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__()
        self.run_name = run_name
        self._init_kwargs = kwargs

    @on_main_process
    def start(self):
        from clearml import Task

        self.task = Task.init(project_name=self.run_name, **self._init_kwargs)

    @property
    def tracker(self):
        return self.task

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.task.connect_configuration(values)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        clearml_logger = self.task.get_logger()
        for k, v in values.items():
            if isinstance(v, (int, float)):
                clearml_logger.report_single_value(name=k, value=v) if step is None else clearml_logger.report_scalar(
                    title=k, series=k, value=v, iteration=step
                )

    @on_main_process
    def log_images(self, values: dict, step: Optional[int] = None, **kwargs):
        """(reference: tracking.py:998) ``Logger.report_image`` per image."""
        clearml_logger = self.task.get_logger()
        for k, v in values.items():
            for i, image in enumerate(v):
                clearml_logger.report_image(
                    title=k, series=str(i), iteration=step, image=_as_hwc_uint8(image), **kwargs
                )

    @on_main_process
    def log_table(self, table_name, columns=None, data=None, dataframe=None, step=None, **kwargs):
        """(reference: tracking.py:1016) ``Logger.report_table``. Reference
        semantics when ``columns`` is omitted: the FIRST data row is the
        header row (unlike wandb/comet, which treat every row as data)."""
        to_report = dataframe
        if dataframe is None:
            if data is None:
                raise ValueError("log_table needs `data` (with optional `columns`) or `dataframe`")
            to_report = [columns] + list(data) if columns else data
        self.task.get_logger().report_table(
            title=table_name, series=table_name, table_plot=to_report, iteration=step, **kwargs
        )

    @on_main_process
    def finish(self):
        self.task.close()


class TrackioTracker(GeneralTracker):
    """(reference: tracking.py:431). HF trackio — wandb-compatible API."""

    name = "trackio"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__()
        self.run_name = run_name
        self._init_kwargs = kwargs

    @on_main_process
    def start(self):
        import trackio

        self.run = trackio.init(project=self.run_name, **self._init_kwargs)

    @property
    def tracker(self):
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict):
        import trackio

        trackio.config.update(values) if hasattr(trackio, "config") else self.run.config.update(values)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        import trackio

        trackio.log({**values, **({"step": step} if step is not None else {})})

    @on_main_process
    def finish(self):
        import trackio

        trackio.finish()


class DVCLiveTracker(GeneralTracker):
    """(reference: tracking.py:1045)."""

    name = "dvclive"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, live=None, **kwargs):
        super().__init__()
        self._live = live
        self._init_kwargs = kwargs

    @on_main_process
    def start(self):
        from dvclive import Live

        self.live = self._live if self._live is not None else Live(**self._init_kwargs)

    @property
    def tracker(self):
        return self.live

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.live.log_params(values)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        if step is not None:
            self.live.step = step
        for k, v in values.items():
            self.live.log_metric(k, v, **kwargs)
        self.live.next_step()

    @on_main_process
    def finish(self):
        self.live.end()


class SwanLabTracker(GeneralTracker):
    """(reference: LoggerType dataclasses.py:696-721 swanlab entry)."""

    name = "swanlab"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__()
        self.run_name = run_name
        self._init_kwargs = kwargs

    @on_main_process
    def start(self):
        import swanlab

        self.run = swanlab.init(project=self.run_name, **self._init_kwargs)

    @property
    def tracker(self):
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.run.config.update(values)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        import swanlab

        swanlab.log(values, step=step)

    @on_main_process
    def log_images(self, values: dict, step: Optional[int] = None, **kwargs):
        import swanlab

        swanlab.log({k: [swanlab.Image(image, **kwargs) for image in v] for k, v in values.items()}, step=step)

    @on_main_process
    def finish(self):
        import swanlab

        swanlab.finish()


LOGGER_TYPE_TO_CLASS = {
    "jsonl": JSONLTracker,
    "tensorboard": TensorBoardTracker,
    "wandb": WandBTracker,
    "mlflow": MLflowTracker,
    "aim": AimTracker,
    "comet_ml": CometMLTracker,
    "clearml": ClearMLTracker,
    "trackio": TrackioTracker,
    "dvclive": DVCLiveTracker,
    "swanlab": SwanLabTracker,
}

_AVAILABILITY = {
    "jsonl": lambda: True,
    "tensorboard": is_tensorboard_available,
    "wandb": is_wandb_available,
    "mlflow": is_mlflow_available,
    "aim": is_aim_available,
    "comet_ml": is_comet_ml_available,
    "clearml": is_clearml_available,
    "trackio": is_trackio_available,
    "dvclive": is_dvclive_available,
    "swanlab": is_swanlab_available,
}


def filter_trackers(log_with, logging_dir=None, project_name: str = "accelerate_tpu", config=None, init_kwargs=None):
    """Resolve requested trackers to instantiated, available ones
    (reference: tracking.py:1271 + Accelerator.init_trackers
    accelerator.py:3002)."""
    init_kwargs = init_kwargs or {}
    if log_with is None:
        requested = ["jsonl"]
    elif not isinstance(log_with, (list, tuple)):
        requested = [log_with]
    else:
        requested = list(log_with)

    names = []
    for item in requested:
        if isinstance(item, GeneralTracker):
            names.append(item)
            continue
        value = str(LoggerType(item) if not isinstance(item, LoggerType) else item)
        if value == "all":
            names.extend([n for n, avail in _AVAILABILITY.items() if avail()])
        else:
            names.append(value)

    trackers = []
    def main_process_event(tracker, method, *event_args):
        # start()/store_init_configuration() are main-process-only events
        # for main_process_only trackers — enforced here so custom
        # subclasses get the guarantee without decorating their methods
        if getattr(tracker, "main_process_only", True) and not PartialState().is_main_process:
            return
        getattr(tracker, method)(*event_args)

    seen = set()
    for item in names:
        if isinstance(item, GeneralTracker):
            main_process_event(item, "start")
            if config:
                main_process_event(item, "store_init_configuration", config)
            trackers.append(item)
            continue
        if item in seen:
            continue
        seen.add(item)
        if not _AVAILABILITY.get(item, lambda: False)():
            logger.warning(f"Tracker {item!r} requested but its package is not installed; skipping.")
            continue
        cls = LOGGER_TYPE_TO_CLASS[item]
        kwargs = dict(init_kwargs.get(item, {}))
        if cls.requires_logging_directory:
            kwargs.setdefault("logging_dir", logging_dir or ".")
        tracker = cls(project_name, **kwargs)
        # reference lifecycle (tracking.py:318): backend comes up in start(),
        # not __init__ — so worker-rank construction stays side-effect-free
        main_process_event(tracker, "start")
        if config:
            main_process_event(tracker, "store_init_configuration", config)
        trackers.append(tracker)
    return trackers
