"""Experiment trackers.

Reference analogue: src/accelerate/tracking.py (1326 LoC): ``GeneralTracker``
ABC (:101-181, contract: ``name``/``requires_logging_directory``/``start``/
``store_init_configuration``/``log``/``finish``, main-process gating via the
``on_main_process`` decorator :77) + nine hosted-service integrations.

The ABC and the TensorBoard/WandB/MLflow/Aim/CometML/ClearML trackers are
kept (import-gated); a dependency-free ``JSONLTracker`` is the default so
tracking works on a bare TPU VM.
"""

from __future__ import annotations

import functools
import json
import os
import time
from typing import Optional

from .logging import get_logger
from .state import PartialState
from .utils.dataclasses import LoggerType
from .utils.imports import (
    is_aim_available,
    is_clearml_available,
    is_comet_ml_available,
    is_dvclive_available,
    is_mlflow_available,
    is_swanlab_available,
    is_tensorboard_available,
    is_trackio_available,
    is_wandb_available,
)

logger = get_logger(__name__)


def on_main_process(function):
    """Method decorator: run only on the main process (reference:
    tracking.py:77)."""

    @functools.wraps(function)
    def execute_on_main_process(self, *args, **kwargs):
        if getattr(self, "main_process_only", True) and not PartialState().is_main_process:
            return None
        return function(self, *args, **kwargs)

    return execute_on_main_process


class GeneralTracker:
    """(reference: tracking.py:101). Subclass contract: class attrs ``name``
    and ``requires_logging_directory``; methods ``store_init_configuration``
    and ``log``; optionally ``start``, ``finish`` and a ``tracker`` property.

    Lifecycle (reference: tracking.py:318): ``__init__`` only records
    arguments; the backend (wandb run, SummaryWriter, ...) is created in
    ``start()``, which ``Accelerator.init_trackers`` calls on the main
    process. Constructing a tracker on a worker rank is therefore free and
    side-effect-less."""

    main_process_only = True

    def __init__(self, _blank: bool = False):
        if not _blank:
            for attr in ("name", "requires_logging_directory"):
                if not hasattr(self, attr):
                    raise NotImplementedError(f"Tracker subclass must define `{attr}`")

    def start(self):
        """Initialise the tracking backend. Idempotence is the subclass's
        concern; ``filter_trackers``/``init_trackers`` call it exactly once."""

    @property
    def tracker(self):
        raise NotImplementedError

    def store_init_configuration(self, values: dict):
        raise NotImplementedError

    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        raise NotImplementedError

    def finish(self):
        pass


class JSONLTracker(GeneralTracker):
    """Dependency-free default: one JSON object per log call, appended to
    ``{logging_dir}/{run_name}/metrics.jsonl``. No reference analogue —
    exists so a bare TPU VM always has a tracker."""

    name = "jsonl"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str = ".", **kwargs):
        super().__init__()
        self.run_name = run_name
        self.dir = os.path.join(logging_dir, run_name)
        self.path = os.path.join(self.dir, "metrics.jsonl")

    @on_main_process
    def start(self):
        os.makedirs(self.dir, exist_ok=True)

    @property
    def tracker(self):
        return self.path

    @on_main_process
    def store_init_configuration(self, values: dict):
        with open(os.path.join(self.dir, "config.json"), "w") as f:
            json.dump(values, f, indent=2, default=str)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        record = {"_time": time.time()}
        if step is not None:
            record["_step"] = step
        record.update(values)
        with open(self.path, "a") as f:
            f.write(json.dumps(record, default=float) + "\n")


class TensorBoardTracker(GeneralTracker):
    """(reference: tracking.py:182). Uses tensorboardX or
    torch.utils.tensorboard, whichever is importable."""

    name = "tensorboard"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str = ".", **kwargs):
        super().__init__()
        self.run_name = run_name
        self.logging_dir = os.path.join(logging_dir, run_name)
        self._init_kwargs = kwargs

    @on_main_process
    def start(self):
        try:
            from torch.utils.tensorboard import SummaryWriter
        except ImportError:
            from tensorboardX import SummaryWriter
        self.writer = SummaryWriter(self.logging_dir, **self._init_kwargs)

    @property
    def tracker(self):
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.writer.add_hparams(
            {k: v for k, v in values.items() if isinstance(v, (int, float, str, bool))}, metric_dict={}
        )
        self.writer.flush()

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        for k, v in values.items():
            if isinstance(v, (int, float)):
                self.writer.add_scalar(k, v, global_step=step, **kwargs)
            elif isinstance(v, str):
                self.writer.add_text(k, v, global_step=step, **kwargs)
            elif isinstance(v, dict):
                self.writer.add_scalars(k, v, global_step=step, **kwargs)
        self.writer.flush()

    @on_main_process
    def finish(self):
        self.writer.close()


class WandBTracker(GeneralTracker):
    """(reference: tracking.py:297)."""

    name = "wandb"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__()
        self.run_name = run_name
        self._init_kwargs = kwargs

    @on_main_process
    def start(self):
        import wandb

        self.run = wandb.init(project=self.run_name, **self._init_kwargs)

    @property
    def tracker(self):
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict):
        import os

        import wandb

        offline = os.environ.get("WANDB_MODE") == "offline" or self._init_kwargs.get("mode") == "offline"
        if offline:
            # offline runs can't mutate config after init — restart the run
            # with the config baked in (reference: tracking.py:343-352);
            # merge over any config the tracker was constructed with
            if getattr(self, "run", None):
                self.run.finish()
            init_kwargs = dict(self._init_kwargs)
            base = init_kwargs.pop("config", None)
            config = {**base, **values} if isinstance(base, dict) else values
            self.run = wandb.init(project=self.run_name, config=config, **init_kwargs)
            return
        wandb.config.update(values, allow_val_change=True)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        self.run.log(values, step=step, **kwargs)

    @on_main_process
    def finish(self):
        self.run.finish()


class MLflowTracker(GeneralTracker):
    """(reference: tracking.py:705)."""

    name = "mlflow"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, logging_dir: Optional[str] = None, **kwargs):
        super().__init__()
        self.run_name = run_name
        self.logging_dir = logging_dir
        self._init_kwargs = kwargs

    @on_main_process
    def start(self):
        import mlflow

        # file-store support (reference: tracking.py:705 MLflowTracker uses
        # MLFLOW_TRACKING_URI / the logging dir): a logging_dir routes runs
        # to a local file store; ``experiment_name`` selects/creates the
        # experiment before the run starts.
        if self.logging_dir:
            mlflow.set_tracking_uri("file://" + os.path.abspath(self.logging_dir))
        init_kwargs = dict(self._init_kwargs)
        experiment = init_kwargs.pop("experiment_name", None)
        if experiment:
            mlflow.set_experiment(experiment)
        self.active_run = mlflow.start_run(run_name=self.run_name, **init_kwargs)

    @property
    def tracker(self):
        return self.active_run

    @on_main_process
    def store_init_configuration(self, values: dict):
        import mlflow

        for chunk_start in range(0, len(values), 100):
            mlflow.log_params(dict(list(values.items())[chunk_start : chunk_start + 100]))

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        import mlflow

        mlflow.log_metrics({k: v for k, v in values.items() if isinstance(v, (int, float))}, step=step)

    @on_main_process
    def finish(self):
        import mlflow

        mlflow.end_run()


class AimTracker(GeneralTracker):
    """(reference: tracking.py:602)."""

    name = "aim"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str = ".", **kwargs):
        super().__init__()
        self.run_name = run_name
        self.logging_dir = logging_dir
        self._init_kwargs = kwargs

    @on_main_process
    def start(self):
        from aim import Run

        self.writer = Run(repo=self.logging_dir, **self._init_kwargs)
        self.writer.name = self.run_name

    @property
    def tracker(self):
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.writer["hparams"] = values

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        for k, v in values.items():
            self.writer.track(v, name=k, step=step, **kwargs)

    @on_main_process
    def finish(self):
        self.writer.close()


class CometMLTracker(GeneralTracker):
    """(reference: tracking.py:508)."""

    name = "comet_ml"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__()
        self.run_name = run_name
        self._init_kwargs = kwargs

    @on_main_process
    def start(self):
        from comet_ml import Experiment

        self.writer = Experiment(project_name=self.run_name, **self._init_kwargs)

    @property
    def tracker(self):
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.writer.log_parameters(values)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        if step is not None:
            self.writer.set_step(step)
        self.writer.log_metrics(values, step=step, **kwargs)

    @on_main_process
    def finish(self):
        self.writer.end()


class ClearMLTracker(GeneralTracker):
    """(reference: tracking.py:912)."""

    name = "clearml"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__()
        self.run_name = run_name
        self._init_kwargs = kwargs

    @on_main_process
    def start(self):
        from clearml import Task

        self.task = Task.init(project_name=self.run_name, **self._init_kwargs)

    @property
    def tracker(self):
        return self.task

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.task.connect_configuration(values)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        clearml_logger = self.task.get_logger()
        for k, v in values.items():
            if isinstance(v, (int, float)):
                clearml_logger.report_single_value(name=k, value=v) if step is None else clearml_logger.report_scalar(
                    title=k, series=k, value=v, iteration=step
                )

    @on_main_process
    def finish(self):
        self.task.close()


class TrackioTracker(GeneralTracker):
    """(reference: tracking.py:431). HF trackio — wandb-compatible API."""

    name = "trackio"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__()
        self.run_name = run_name
        self._init_kwargs = kwargs

    @on_main_process
    def start(self):
        import trackio

        self.run = trackio.init(project=self.run_name, **self._init_kwargs)

    @property
    def tracker(self):
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict):
        import trackio

        trackio.config.update(values) if hasattr(trackio, "config") else self.run.config.update(values)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        import trackio

        trackio.log({**values, **({"step": step} if step is not None else {})})

    @on_main_process
    def finish(self):
        import trackio

        trackio.finish()


class DVCLiveTracker(GeneralTracker):
    """(reference: tracking.py:1045)."""

    name = "dvclive"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, live=None, **kwargs):
        super().__init__()
        self._live = live
        self._init_kwargs = kwargs

    @on_main_process
    def start(self):
        from dvclive import Live

        self.live = self._live if self._live is not None else Live(**self._init_kwargs)

    @property
    def tracker(self):
        return self.live

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.live.log_params(values)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        if step is not None:
            self.live.step = step
        for k, v in values.items():
            self.live.log_metric(k, v, **kwargs)
        self.live.next_step()

    @on_main_process
    def finish(self):
        self.live.end()


class SwanLabTracker(GeneralTracker):
    """(reference: LoggerType dataclasses.py:696-721 swanlab entry)."""

    name = "swanlab"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__()
        self.run_name = run_name
        self._init_kwargs = kwargs

    @on_main_process
    def start(self):
        import swanlab

        self.run = swanlab.init(project=self.run_name, **self._init_kwargs)

    @property
    def tracker(self):
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.run.config.update(values)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        import swanlab

        swanlab.log(values, step=step)

    @on_main_process
    def finish(self):
        import swanlab

        swanlab.finish()


LOGGER_TYPE_TO_CLASS = {
    "jsonl": JSONLTracker,
    "tensorboard": TensorBoardTracker,
    "wandb": WandBTracker,
    "mlflow": MLflowTracker,
    "aim": AimTracker,
    "comet_ml": CometMLTracker,
    "clearml": ClearMLTracker,
    "trackio": TrackioTracker,
    "dvclive": DVCLiveTracker,
    "swanlab": SwanLabTracker,
}

_AVAILABILITY = {
    "jsonl": lambda: True,
    "tensorboard": is_tensorboard_available,
    "wandb": is_wandb_available,
    "mlflow": is_mlflow_available,
    "aim": is_aim_available,
    "comet_ml": is_comet_ml_available,
    "clearml": is_clearml_available,
    "trackio": is_trackio_available,
    "dvclive": is_dvclive_available,
    "swanlab": is_swanlab_available,
}


def filter_trackers(log_with, logging_dir=None, project_name: str = "accelerate_tpu", config=None, init_kwargs=None):
    """Resolve requested trackers to instantiated, available ones
    (reference: tracking.py:1271 + Accelerator.init_trackers
    accelerator.py:3002)."""
    init_kwargs = init_kwargs or {}
    if log_with is None:
        requested = ["jsonl"]
    elif not isinstance(log_with, (list, tuple)):
        requested = [log_with]
    else:
        requested = list(log_with)

    names = []
    for item in requested:
        if isinstance(item, GeneralTracker):
            names.append(item)
            continue
        value = str(LoggerType(item) if not isinstance(item, LoggerType) else item)
        if value == "all":
            names.extend([n for n, avail in _AVAILABILITY.items() if avail()])
        else:
            names.append(value)

    trackers = []
    def main_process_event(tracker, method, *event_args):
        # start()/store_init_configuration() are main-process-only events
        # for main_process_only trackers — enforced here so custom
        # subclasses get the guarantee without decorating their methods
        if getattr(tracker, "main_process_only", True) and not PartialState().is_main_process:
            return
        getattr(tracker, method)(*event_args)

    seen = set()
    for item in names:
        if isinstance(item, GeneralTracker):
            main_process_event(item, "start")
            if config:
                main_process_event(item, "store_init_configuration", config)
            trackers.append(item)
            continue
        if item in seen:
            continue
        seen.add(item)
        if not _AVAILABILITY.get(item, lambda: False)():
            logger.warning(f"Tracker {item!r} requested but its package is not installed; skipping.")
            continue
        cls = LOGGER_TYPE_TO_CLASS[item]
        kwargs = dict(init_kwargs.get(item, {}))
        if cls.requires_logging_directory:
            kwargs.setdefault("logging_dir", logging_dir or ".")
        tracker = cls(project_name, **kwargs)
        # reference lifecycle (tracking.py:318): backend comes up in start(),
        # not __init__ — so worker-rank construction stays side-effect-free
        main_process_event(tracker, "start")
        if config:
            main_process_event(tracker, "store_init_configuration", config)
        trackers.append(tracker)
    return trackers
