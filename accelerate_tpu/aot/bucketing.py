"""ShapeBucketer: pad ragged dims to a learned bucket set.

The PR-3 recompile watchdog can only *warn* that a drifting batch/sequence
dim is recompiling every step; this module closes the loop. A
:class:`ShapeBucketer` maps any observed size to a covering bucket —
powers of two to seed, refined online from the observed-size histogram —
so a stream of ragged shapes runs at most ``len(buckets)`` programs
instead of one per distinct size, and the watchdog goes silent after the
bucket set is warm.

Invariants (the ones tests pin):

* ``bucket(n) >= n`` always — padding never truncates;
* the bucket returned is the **minimal** covering bucket in the current
  set;
* the set is **grow-only** ("never shrinks"): refinement may add a
  tighter bucket (one extra compile buys less steady-state padding) but
  never removes one, so an already-compiled program is never orphaned
  and the mapping for any ``n`` is monotonically non-increasing in pad
  waste over time;
* every bucket is a multiple of ``multiple_of`` (the data-shard count for
  batch dims — a pad target must still split evenly over the mesh).

``pad_batch_tree`` is the companion: wrap-pad a host batch pytree's
leading dim up to the bucket (the same wrap-around semantics
``even_batches`` uses for the tail batch, so downstream ``remainder``
bookkeeping already knows how to truncate).
"""

from __future__ import annotations

import collections
from typing import Optional

import numpy as np


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (n >= 1)."""
    return 1 << max(0, int(n - 1).bit_length())


def round_up_to(n: int, multiple: int) -> int:
    """Smallest multiple of ``multiple`` >= n — the pad target for an
    alignment constraint (MXU tiles in ``analysis.perf_rules``, shard
    counts here)."""
    return -(-n // multiple) * multiple


_round_up = round_up_to  # historical private alias


class ShapeBucketer:
    """Learned covering-bucket set for one ragged dimension.

    ``seed_buckets`` start the set (rounded up to ``multiple_of``);
    sizes beyond the largest bucket grow the set by rounded powers of
    two up to ``max_size`` (when given, sizes above it raise — the
    caller's capacity bound, e.g. a serving engine's ``max_len``).
    Every ``refine_every`` observations the histogram is consulted: if
    an existing bucket's mean pad waste exceeds ``waste_threshold``,
    the most frequent observed size under it is promoted to its own
    bucket (bounded by ``max_buckets`` — each bucket is one compile).
    """

    def __init__(
        self,
        seed_buckets=(),
        *,
        multiple_of: int = 1,
        max_buckets: int = 16,
        max_size: Optional[int] = None,
        refine_every: int = 64,
        waste_threshold: float = 0.25,
    ):
        if multiple_of < 1:
            raise ValueError(f"multiple_of must be >= 1, got {multiple_of}")
        if max_buckets < 1:
            raise ValueError(f"max_buckets must be >= 1, got {max_buckets}")
        self.multiple_of = int(multiple_of)
        self.max_buckets = int(max_buckets)
        self.max_size = int(max_size) if max_size is not None else None
        self.refine_every = max(1, int(refine_every))
        self.waste_threshold = float(waste_threshold)
        self._buckets: set[int] = set()
        self.histogram: collections.Counter = collections.Counter()
        self._observations = 0
        for b in seed_buckets:
            self._add(int(b))

    # ------------------------------------------------------------------ #

    def _add(self, b: int) -> int:
        b = _round_up(max(1, b), self.multiple_of)
        if self.max_size is not None:
            b = min(b, _round_up(self.max_size, self.multiple_of))
        self._buckets.add(b)
        return b

    @property
    def buckets(self) -> tuple:
        """Current bucket set, ascending (grow-only)."""
        return tuple(sorted(self._buckets))

    def lookup(self, n: int) -> Optional[int]:
        """Minimal covering bucket from the CURRENT set, or None — no
        learning, no growth (the chunked-prefill path uses this so long
        remainders don't mint unbounded buckets)."""
        covering = [b for b in self._buckets if b >= n]
        return min(covering) if covering else None

    def bucket(self, n: int) -> int:
        """Minimal covering bucket for ``n``, recording the observation
        and growing the set when nothing covers. Never returns < n."""
        n = int(n)
        if n < 1:
            raise ValueError(f"size must be >= 1, got {n}")
        if self.max_size is not None and n > self.max_size:
            raise ValueError(f"size {n} exceeds max_size {self.max_size}")
        self.histogram[n] += 1
        self._observations += 1
        got = self.lookup(n)
        if got is None:
            got = self._add(next_pow2(n))
            if got < n:  # max_size clamp undershot the need
                got = self._add(n)
        if self._observations % self.refine_every == 0:
            self.refine()
            got = self.lookup(n) or got
        return got

    def refine(self) -> list:
        """One histogram-driven refinement pass; returns the buckets it
        added (possibly empty). Grow-only and bounded by ``max_buckets``."""
        added = []
        buckets = self.buckets
        for b in buckets:
            if len(self._buckets) + len(added) >= self.max_buckets:
                break
            lower = max((x for x in buckets if x < b), default=0)
            sizes = {n: c for n, c in self.histogram.items() if lower < n <= b}
            total = sum(sizes.values())
            if not total:
                continue
            waste = sum((b - n) * c for n, c in sizes.items()) / (b * total)
            if waste <= self.waste_threshold:
                continue
            candidate = _round_up(max(sizes, key=lambda n: (sizes[n], n)), self.multiple_of)
            if candidate not in self._buckets and candidate < b:
                self._add(candidate)
                added.append(candidate)
        return added

    def stats(self) -> dict:
        return {
            "buckets": list(self.buckets),
            "observations": self._observations,
            "distinct_sizes": len(self.histogram),
        }


def pad_batch_tree(batch, target: int, current: Optional[int] = None):
    """Wrap-pad every array leaf's leading dim up to ``target`` rows
    (repeating from the start, the ``even_batches`` tail semantics —
    padded rows are real samples, so a loss over them stays finite and
    ``remainder``-based truncation recovers exactness). Non-array leaves
    and leaves whose leading dim differs from the batch dim pass through
    untouched."""
    if current is None:
        sizes = [
            leaf.shape[0]
            for leaf in _tree_leaves(batch)
            if hasattr(leaf, "shape") and getattr(leaf, "ndim", 0) >= 1
        ]
        current = max(sizes) if sizes else 0
    if target <= current or current == 0:
        return batch

    def pad(leaf):
        if not hasattr(leaf, "shape") or getattr(leaf, "ndim", 0) < 1 or leaf.shape[0] != current:
            return leaf
        x = np.asarray(leaf)
        parts, need = [x], target - x.shape[0]
        while need > 0:
            take = min(need, x.shape[0])
            parts.append(x[:take])
            need -= take
        return np.concatenate(parts, axis=0)

    return _tree_map(pad, batch)


def _tree_leaves(tree):
    out = []
    _tree_map(out.append, tree)
    return out


def _tree_map(fn, tree):
    """Minimal pytree map over dict/list/tuple (no jax import — host-side
    batches are plain containers of numpy arrays)."""
    if isinstance(tree, dict):
        return {k: _tree_map(fn, v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return type(tree)(_tree_map(fn, v) for v in tree)
    return fn(tree)
