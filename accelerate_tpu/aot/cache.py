"""On-disk executable store + content keys: the persistence layer under
:class:`~accelerate_tpu.aot.ProgramCache`.

Two caches cooperate to kill repeat compiles, and they answer different
questions:

* **jax's persistent compilation cache** (:func:`configure_persistent_cache`)
  keys on XLA's own fingerprint and saves the *compile* — a second
  ``jit`` of the same program still pays tracing + lowering + a cache
  probe inside XLA, but not optimization. It is transparent and safe to
  leave on everywhere.
* the **executable store** here keys on OUR content key and saves the
  *executable*: ``jit(fn).lower(...).compile()`` results serialized via
  ``jax.experimental.serialize_executable``, so a *different process* —
  a new serving replica, or a preemption-resumed trainer — deserializes
  and runs with **zero** XLA compiles. This is the AOT warm-start path.

The content key is a sha256 over everything that makes two programs
interchangeable: the lowered StableHLO text (which bakes in the jaxpr,
input avals, shardings, and donation), the backend platform, the device
count, and the jax + jaxlib versions. Any drift — a new jax, a different
mesh, a changed shape — lands on a different key, so a stale entry can
never be replayed. Entries additionally carry a crc32-guarded header;
a truncated or poisoned entry fails validation and is rejected (and
healed) instead of feeding XLA garbage.

Entry layout (one file per program, ``<key>.aotx``)::

    ATPX1\\n
    {"key": ..., "name": ..., "crc32": ..., "size": ..., "jax": ...}\\n
    <pickled (xla payload, in_tree, out_tree)>

Writes are atomic (tmp + rename) so a killed process never publishes a
half-written entry.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import tempfile
import time
import zlib
from typing import Optional

_MAGIC = b"ATPX1"
_SUFFIX = ".aotx"


class CorruptEntryError(Exception):
    """The entry bytes fail structural/crc validation (poisoned cache)."""


class StaleEntryError(Exception):
    """The entry was written by a different jax/jaxlib/backend and must
    not be deserialized into this process."""


def _versions() -> dict:
    import jax

    try:
        import jaxlib

        jaxlib_v = getattr(jaxlib, "__version__", "")
    except Exception:  # pragma: no cover - jaxlib always ships with jax
        jaxlib_v = ""
    return {"jax": jax.__version__, "jaxlib": jaxlib_v}


def backend_descriptor() -> dict:
    """``{"platform", "ndev"}`` for the live backend — part of the content
    key because a serialized executable is only loadable onto the same
    platform with the same device population."""
    import jax

    devices = jax.devices()
    return {"platform": devices[0].platform, "ndev": len(devices)}


def content_key(lowered, extra=()) -> str:
    """Content key for a ``jax.jit(fn).lower(...)`` result.

    The StableHLO text already pins the jaxpr, the input avals, the input/
    output shardings (and therefore the mesh layout), and the donation
    plan; versions + backend + ``extra`` salt ride along so upgrades and
    topology changes invalidate naturally instead of deserializing an
    incompatible executable.
    """
    h = hashlib.sha256()
    h.update(lowered.as_text().encode())
    v = _versions()
    b = backend_descriptor()
    for part in (v["jax"], v["jaxlib"], b["platform"], str(b["ndev"]), *extra):
        h.update(b"\x00" + str(part).encode())
    return h.hexdigest()


def serialize_compiled(compiled) -> bytes:
    """A compiled executable -> storable bytes (XLA payload + the arg
    pytree defs ``deserialize_and_load`` needs on the other side)."""
    from jax.experimental import serialize_executable

    payload, in_tree, out_tree = serialize_executable.serialize(compiled)
    return pickle.dumps((payload, in_tree, out_tree), protocol=4)


def deserialize_compiled(blob: bytes):
    """Inverse of :func:`serialize_compiled`: bytes -> a loaded, callable
    executable (no XLA compile happens here)."""
    from jax.experimental import serialize_executable

    payload, in_tree, out_tree = pickle.loads(blob)
    return serialize_executable.deserialize_and_load(payload, in_tree, out_tree)


class ExecutableStore:
    """Content-addressed directory of serialized executables.

    ``get`` raises :class:`CorruptEntryError` / :class:`StaleEntryError`
    rather than returning bad bytes — the caller (ProgramCache) treats
    both as a miss, deletes the offender, and recompiles; a poisoned
    cache degrades to a cold one, never to wrong execution.
    """

    def __init__(self, path: str):
        self.path = str(path)
        os.makedirs(self.path, exist_ok=True)

    # ------------------------------------------------------------------ #
    # entry IO
    # ------------------------------------------------------------------ #

    def _entry_path(self, key: str) -> str:
        return os.path.join(self.path, key + _SUFFIX)

    def put(self, key: str, blob: bytes, name: str = "program", meta: Optional[dict] = None) -> str:
        header = {
            "key": key,
            "name": name,
            "crc32": zlib.crc32(blob),
            "size": len(blob),
            "created": time.time(),
            **_versions(),
            **backend_descriptor(),
        }
        if meta:
            header.update(meta)
        final = self._entry_path(key)
        fd, tmp = tempfile.mkstemp(dir=self.path, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(_MAGIC + b"\n")
                f.write(json.dumps(header, sort_keys=True).encode() + b"\n")
                f.write(blob)
            os.replace(tmp, final)  # atomic publish
        except BaseException:
            if os.path.exists(tmp):
                os.remove(tmp)
            raise
        return final

    def read_header(self, key: str) -> Optional[dict]:
        path = self._entry_path(key)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            magic = f.readline().rstrip(b"\n")
            if magic != _MAGIC:
                raise CorruptEntryError(f"{path}: bad magic {magic!r}")
            try:
                return json.loads(f.readline())
            except json.JSONDecodeError as e:
                raise CorruptEntryError(f"{path}: unreadable header ({e})") from e

    def get(self, key: str) -> Optional[bytes]:
        path = self._entry_path(key)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            magic = f.readline().rstrip(b"\n")
            if magic != _MAGIC:
                raise CorruptEntryError(f"{path}: bad magic {magic!r}")
            try:
                header = json.loads(f.readline())
            except json.JSONDecodeError as e:
                raise CorruptEntryError(f"{path}: unreadable header ({e})") from e
            blob = f.read()
        # version gate BEFORE the crc: a stale entry may be perfectly
        # intact, but deserializing another jax's executable is undefined
        v = _versions()
        for field in ("jax", "jaxlib"):
            if header.get(field) != v[field]:
                raise StaleEntryError(
                    f"{path}: written by {field}={header.get(field)!r}, running {v[field]!r}"
                )
        if header.get("size") != len(blob) or header.get("crc32") != zlib.crc32(blob):
            raise CorruptEntryError(f"{path}: crc/size mismatch (truncated or poisoned)")
        return blob

    def remove(self, key: str) -> bool:
        path = self._entry_path(key)
        if os.path.exists(path):
            os.remove(path)
            return True
        return False

    # ------------------------------------------------------------------ #
    # bulk surface (CLI stats / clear / export)
    # ------------------------------------------------------------------ #

    def keys(self) -> list[str]:
        return sorted(
            f[: -len(_SUFFIX)] for f in os.listdir(self.path) if f.endswith(_SUFFIX)
        )

    def entries(self) -> list[dict]:
        """Header dicts for every entry (corrupt headers reported with an
        ``"error"`` field instead of raising — stats must always print)."""
        out = []
        for key in self.keys():
            try:
                header = self.read_header(key) or {}
            except CorruptEntryError as e:
                header = {"key": key, "error": str(e)}
            header["file_bytes"] = os.path.getsize(self._entry_path(key))
            out.append(header)
        return out

    def total_bytes(self) -> int:
        return sum(
            os.path.getsize(os.path.join(self.path, f))
            for f in os.listdir(self.path)
            if f.endswith(_SUFFIX)
        )

    def clear(self) -> int:
        n = 0
        for key in self.keys():
            self.remove(key)
            n += 1
        return n

    def export_archive(self, out_path: str, keys: Optional[list] = None) -> int:
        """Bundle entries into a ``.tar.gz`` a replica fleet can ship
        around (the ``aot_export`` surface). Returns the entry count."""
        import tarfile

        keys = list(keys) if keys is not None else self.keys()
        os.makedirs(os.path.dirname(os.path.abspath(out_path)) or ".", exist_ok=True)
        with tarfile.open(out_path, "w:gz") as tar:
            for key in keys:
                path = self._entry_path(key)
                if os.path.exists(path):
                    tar.add(path, arcname=key + _SUFFIX)
        return len(keys)

    def import_archive(self, in_path: str) -> int:
        """Unpack an :meth:`export_archive` bundle into this store. Each
        entry is validated (magic + header) before it is published; junk
        members are skipped. Returns the imported entry count."""
        import tarfile

        n = 0
        with tarfile.open(in_path, "r:gz") as tar:
            for member in tar.getmembers():
                base = os.path.basename(member.name)
                if not (member.isfile() and base.endswith(_SUFFIX)):
                    continue
                blob = tar.extractfile(member).read()
                head, _, _ = blob.partition(b"\n")
                if head != _MAGIC:
                    continue
                key = base[: -len(_SUFFIX)]
                fd, tmp = tempfile.mkstemp(dir=self.path, suffix=".tmp")
                with os.fdopen(fd, "wb") as f:
                    f.write(blob)
                os.replace(tmp, self._entry_path(key))
                n += 1
        return n


def resolve_cache_dir(
    explicit: Optional[str] = None,
    project_dir: Optional[str] = None,
    dir_name: str = "compile_cache",
) -> Optional[str]:
    """The ONE precedence rule for where the executable store lives:
    explicit argument > ``ACCELERATE_COMPILE_CACHE_DIR`` > the project's
    ``ProjectConfiguration`` dir (``{project_dir}/{dir_name}``) > None
    (memory-only cache, no persistence)."""
    if explicit:
        return explicit
    env = os.environ.get("ACCELERATE_COMPILE_CACHE_DIR")
    if env:
        return env
    if project_dir:
        return os.path.join(project_dir, dir_name)
    return None


_persistent_configured: list = []  # one-shot latch (per process)


def configure_persistent_cache(cache_dir: str, min_compile_time_secs: float = 0.0) -> bool:
    """Point jax's persistent XLA compilation cache at ``cache_dir``.

    Respects an existing configuration: if the process (or the
    environment via ``JAX_COMPILATION_CACHE_DIR``) already chose a cache
    dir, that choice wins — silently re-pointing a shared cache
    mid-process would split the warm set. Returns True when THIS call
    did the configuring."""
    import jax

    already = getattr(jax.config, "jax_compilation_cache_dir", None)
    if already or os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        return False
    if _persistent_configured:
        return False
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    try:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", float(min_compile_time_secs))
    except Exception:  # older jax: flag spelled differently; dir alone still works
        pass
    _persistent_configured.append(cache_dir)
    return True
