"""Compile management: persistent executable cache, AOT warm-start, and
shape auto-bucketing.

On TPU, XLA *is* the delegated execution layer — which makes JIT latency
a first-class cost this framework manages instead of an accident the
user eats. Three coupled pieces (see
``docs/usage_guides/compilation.md``):

* :class:`ExecutableStore` / :func:`configure_persistent_cache` — the
  persistence layer: jax's own on-disk compilation cache plus a
  content-keyed store of serialized executables;
* :class:`ProgramCache` — the shared compile-or-fetch front-end
  (``Accelerator.build_train_step``, ``ServingEngine`` buckets, and the
  ``accelerate-tpu compile-cache`` CLI all route through it), with
  ``compile_cache_*`` telemetry on every hit/miss/deserialize;
* :class:`ShapeBucketer` / :func:`pad_batch_tree` — pad ragged
  batch/sequence dims to a learned bucket set so the PR-3 recompile
  watchdog's warning becomes a one-time pad, not a compile storm.
"""

from .bucketing import ShapeBucketer, next_pow2, pad_batch_tree, round_up_to
from .cache import (
    CorruptEntryError,
    ExecutableStore,
    StaleEntryError,
    backend_descriptor,
    configure_persistent_cache,
    content_key,
    deserialize_compiled,
    resolve_cache_dir,
    serialize_compiled,
)
from .program_cache import ProgramCache, default_program_cache

__all__ = [
    "CorruptEntryError",
    "ExecutableStore",
    "ProgramCache",
    "ShapeBucketer",
    "StaleEntryError",
    "backend_descriptor",
    "configure_persistent_cache",
    "content_key",
    "default_program_cache",
    "deserialize_compiled",
    "next_pow2",
    "pad_batch_tree",
    "resolve_cache_dir",
    "round_up_to",
    "serialize_compiled",
]
