"""ProgramCache: the shared front-end every compiled program goes through.

One object answers "give me the executable for this function at these
avals" three ways, cheapest first:

1. **memory** — same process already built it: return it;
2. **disk** — another process built it (:class:`~accelerate_tpu.aot.cache.
   ExecutableStore`): deserialize instead of compiling — the warm-start
   path a restarted trainer or a new serving replica takes;
3. **compile** — ``lowered.compile()``, then serialize into the store so
   the NEXT process hits (2).

Every outcome lands in telemetry: ``compile_cache_hit`` (with
``source: "memory"|"disk"`` and ``deserialize_ms``), ``compile_cache_miss``
(with ``compile_ms``), ``compile_cache_store``, and ``compile_cache_reject``
for a poisoned/stale entry that was healed. Counters mirror onto the
instance (``hits`` / ``misses`` / ``deserialized`` / ``rejected``) so code
with no event log still has the numbers.

:meth:`wrap_jit` is the bridge for functions whose input avals are only
known at call time (``build_train_step``): it shadows ``jax.jit``'s
dispatch with a signature-keyed executable table, so a restarted process
re-creating the same step function dispatches straight into deserialized
executables — 0 XLA compiles, recompile watchdog silent.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Optional

from .cache import (
    CorruptEntryError,
    ExecutableStore,
    StaleEntryError,
    content_key,
    deserialize_compiled,
    resolve_cache_dir,
    serialize_compiled,
)


def _jax():
    import jax

    return jax


def _noop_log():
    from ..telemetry.eventlog import EventLog

    return EventLog(None)


class ProgramCache:
    """Compile-or-fetch for jitted programs, with an optional persistent
    executable store and full telemetry.

    ``store=None`` keeps the cache memory-only (still deduplicates and
    still counts); pass an :class:`ExecutableStore` (or use
    :meth:`from_env`) to make executables survive the process.
    """

    def __init__(self, store: Optional[ExecutableStore] = None, log=None, name: str = "programs"):
        self.store = store
        self.log = log if log is not None else _noop_log()
        self.name = name
        self._mem: dict = {}
        self.hits = 0
        self.misses = 0
        self.deserialized = 0
        self.rejected = 0
        self._serialize_broken = False  # backend can't serialize; warn once

    @classmethod
    def from_env(cls, log=None, project_dir: Optional[str] = None, name: str = "programs") -> "ProgramCache":
        """A cache whose store follows ``ACCELERATE_COMPILE_CACHE_DIR``
        (or ``{project_dir}/compile_cache``); memory-only when neither is
        set — the zero-config construction serving/CLI paths use."""
        cache_dir = resolve_cache_dir(project_dir=project_dir)
        return cls(store=ExecutableStore(cache_dir) if cache_dir else None, log=log, name=name)

    # ------------------------------------------------------------------ #
    # compile-or-fetch
    # ------------------------------------------------------------------ #

    def compile(
        self,
        fn: Callable,
        *avals,
        name: str = "program",
        donate_argnums=(),
        static_argnums=(),
        key_salt=(),
    ):
        """``jit(fn).lower(*avals)`` then :meth:`compile_lowered` — the
        explicit-avals path (AOT prepare, CLI ``warm``, serving buckets)."""
        jax = _jax()
        jit_kwargs = {}
        if donate_argnums:
            jit_kwargs["donate_argnums"] = tuple(donate_argnums)
        if static_argnums:
            jit_kwargs["static_argnums"] = tuple(static_argnums)
        lowered = jax.jit(fn, **jit_kwargs).lower(*avals)
        return self.compile_lowered(lowered, name=name, key_salt=key_salt)

    def compile_lowered(self, lowered, name: str = "program", key_salt=()):
        """Memory -> disk -> compile for an already-lowered program.
        Returns the loaded executable; never returns a stale or corrupt
        deserialization (those entries are deleted and recompiled)."""
        key = content_key(lowered, extra=key_salt)
        cached = self._mem.get(key)
        if cached is not None:
            self.hits += 1
            self.log.event("compile_cache_hit", program=name, key=key[:16], source="memory")
            return cached

        if self.store is not None:
            blob = None
            try:
                blob = self.store.get(key)
            except (CorruptEntryError, StaleEntryError) as e:
                # poisoned/stale entry: reject cleanly, heal, fall through
                self.rejected += 1
                self.store.remove(key)
                self.log.event(
                    "compile_cache_reject", severity="warning", program=name, key=key[:16],
                    reason=type(e).__name__, detail=str(e)[:200],
                )
            if blob is not None:
                t0 = time.perf_counter()
                try:
                    compiled = deserialize_compiled(blob)
                except Exception as e:  # undeserializable payload = poison too
                    self.rejected += 1
                    self.store.remove(key)
                    self.log.event(
                        "compile_cache_reject", severity="warning", program=name, key=key[:16],
                        reason=type(e).__name__, detail=str(e)[:200],
                    )
                else:
                    ms = (time.perf_counter() - t0) * 1000.0
                    self.hits += 1
                    self.deserialized += 1
                    self._mem[key] = compiled
                    self.log.event(
                        "compile_cache_hit", program=name, key=key[:16], source="disk",
                        deserialize_ms=round(ms, 3),
                    )
                    self.log.counter("compile_cache.deserialize_ms", round(ms, 3), program=name)
                    return compiled

        t0 = time.perf_counter()
        compiled = self._compile_fresh(lowered)
        ms = (time.perf_counter() - t0) * 1000.0
        self.misses += 1
        self._mem[key] = compiled
        self.log.event("compile_cache_miss", program=name, key=key[:16], compile_ms=round(ms, 3))
        self.log.counter("compile_cache.compile_ms", round(ms, 3), program=name)
        if self.store is not None and not self._serialize_broken:
            try:
                self.store.put(key, serialize_compiled(compiled), name=name)
                self.log.event("compile_cache_store", program=name, key=key[:16])
            except Exception as e:
                # some backends can't serialize every executable; the cache
                # degrades to memory-only rather than failing the compile
                self._serialize_broken = True
                self.log.event(
                    "compile_cache_store_failed", severity="warning", program=name,
                    reason=type(e).__name__, detail=str(e)[:200],
                )
        return compiled

    def _compile_fresh(self, lowered):
        """``lowered.compile()``, bypassing jax's persistent XLA cache when
        an executable store is attached: XLA:CPU executables *restored
        from that disk cache* serialize into blobs that fail to load
        ("Symbols not found" at deserialize) — only a fresh compile
        yields a serializable executable. The one-time cost (no XLA-cache
        shortcut on the very first build of a program) buys every later
        process a zero-compile deserialize, which is strictly cheaper
        than the XLA cache hit it forgoes."""
        if self.store is None or self._serialize_broken:
            return lowered.compile()
        jax = _jax()
        try:
            prev = bool(jax.config.jax_enable_compilation_cache)
        except AttributeError:  # ancient jax: no flag, nothing to bypass
            return lowered.compile()
        if not prev:
            return lowered.compile()
        jax.config.update("jax_enable_compilation_cache", False)
        try:
            return lowered.compile()
        finally:
            jax.config.update("jax_enable_compilation_cache", True)

    # ------------------------------------------------------------------ #
    # call-time dispatch (avals unknown until the first call)
    # ------------------------------------------------------------------ #

    def wrap_jit(self, jitted, name: str = "step", static_argnums=()):
        """Shadow a ``jax.jit`` function's dispatch with this cache.

        The wrapper keys on the concrete input signature (treedef +
        per-leaf shape/dtype/sharding + the static arg values) and keeps
        one executable per signature: a first-seen signature lowers and
        goes through :meth:`compile_lowered` (so a restarted process
        deserializes instead of compiling), later calls dispatch straight
        to the executable. Exposes ``_cache_size`` so the PR-3 recompile
        watchdog's jit-cache probe keeps working through the wrapper."""
        jax = _jax()
        statics = tuple(static_argnums)
        table: dict = {}

        def leaf_sig(x):
            shape = getattr(x, "shape", None)
            dtype = getattr(x, "dtype", None)
            if shape is None or dtype is None:
                return ("py", type(x).__name__, x if isinstance(x, (bool, int, float, str)) else None)
            sharding = getattr(x, "sharding", None)
            weak = getattr(x, "weak_type", False)
            return (tuple(shape), str(dtype), sharding, bool(weak))

        def dispatch(*args, **kwargs):
            if kwargs and statics:
                # keyword args + positional statics don't compose in the
                # AOT call convention; fall back to plain jit dispatch
                return jitted(*args, **kwargs)
            dyn = tuple(a for i, a in enumerate(args) if i not in statics)
            stat = tuple(args[i] for i in statics)
            leaves, treedef = jax.tree_util.tree_flatten((dyn, kwargs))
            sig = (treedef, tuple(leaf_sig(l) for l in leaves), stat)
            compiled = table.get(sig)
            if compiled is None:
                lowered = jitted.lower(*args, **kwargs)
                compiled = self.compile_lowered(lowered, name=name)
                table[sig] = compiled
            return compiled(*dyn, **kwargs)

        dispatch._cache_size = lambda: len(table)
        dispatch._program_cache = self
        dispatch.__wrapped__ = jitted
        return dispatch

    # ------------------------------------------------------------------ #
    # explicit AOT surface + stats
    # ------------------------------------------------------------------ #

    def aot_export(self, out_path: str, keys=None) -> int:
        """Bundle the store's executables into a portable archive (ship to
        a replica fleet, bake into an image). Requires a store."""
        if self.store is None:
            raise ValueError("aot_export needs a persistent store (set ACCELERATE_COMPILE_CACHE_DIR or CompileKwargs.cache_dir)")
        n = self.store.export_archive(out_path, keys=keys)
        self.log.event("compile_cache_export", path=out_path, entries=n)
        return n

    def aot_load(self, in_path: str) -> int:
        """Import an :meth:`aot_export` archive into the store; programs
        built afterwards deserialize instead of compiling."""
        if self.store is None:
            raise ValueError("aot_load needs a persistent store (set ACCELERATE_COMPILE_CACHE_DIR or CompileKwargs.cache_dir)")
        n = self.store.import_archive(in_path)
        self.log.event("compile_cache_import", path=in_path, entries=n)
        return n

    def stats(self) -> dict:
        out = {
            "hits": self.hits,
            "misses": self.misses,
            "deserialized": self.deserialized,
            "rejected": self.rejected,
            "in_memory": len(self._mem),
        }
        if self.store is not None:
            out["store_dir"] = self.store.path
            out["store_entries"] = len(self.store.keys())
            out["store_bytes"] = self.store.total_bytes()
        return out


def default_program_cache(log=None, project_dir: Optional[str] = None) -> Optional[ProgramCache]:
    """A :class:`ProgramCache` when the environment opted into persistence
    (``ACCELERATE_COMPILE_CACHE_DIR`` set), else None — the hook cheap
    call sites (ServingEngine's default) use without forcing a cache on
    every user."""
    if not os.environ.get("ACCELERATE_COMPILE_CACHE_DIR") and not project_dir:
        return None
    return ProgramCache.from_env(log=log, project_dir=project_dir)
