"""Labeled crash points inside the checkpoint save path.

``save_accelerator_state`` calls :func:`crash_point` at every state
transition of the atomic commit protocol. In production the calls are
free (one ``is None`` check); under test,
:class:`accelerate_tpu.test_utils.fault_injection.CrashPoint` installs a
hook that raises (or kills the process) at a chosen label — driving the
crash-at-every-point matrix that proves resume always lands on a valid
checkpoint.

The save labels, in save order:

* ``pre_write``   — before anything touches disk (no ``.tmp`` dir yet)
* ``mid_pytree``  — after the first sharded pytree write (tmp dir holds
  a partial array set)
* ``pre_manifest``— all data written, barrier passed, manifest not yet
  written (tmp dir complete but uncommitted)
* ``pre_rename``  — manifest written (COMMITTED) but the tmp dir not yet
  renamed to its final name (recoverable by ``CheckpointManager.gc``)
* ``mid_prune``   — new checkpoint visible, ``total_limit`` pruning in
  progress

``load_accelerator_state`` is instrumented the same way (a kill
mid-restore must leave the checkpoint untouched so a fresh auto-resume
lands on it again). The restore labels, in restore order:

* ``pre_restore``       — checkpoint located (and any elastic-topology
  decision made), nothing restored yet
* ``mid_restore_arrays``— after the first orbax pytree restore (model
  params in memory, optimizer state not yet)
* ``pre_restore_rng``   — arrays/schedulers/samplers restored, host RNG
  not yet touched

The serving fleet is instrumented with the same mechanism
(``test_utils.fault_injection.ReplicaChaos`` drives the serving chaos
matrix). Every serving label sits at a state-consistent boundary — the
engine's host bookkeeping (queue, slot state, sampling keys, KV frontier)
is exact at each one, so a crash there is always failover-recoverable:

* ``pre_tick``    — top of ``ServingEngine.step`` (nothing this tick ran)
* ``mid_prefill`` — a prefill slot about to advance (its chunk/bucket
  state untouched; the pre-sample key still in the slot state)
* ``mid_decode``  — decode slots about to run the jitted K-step tick
  (cache rows = prompt + out[:-1]; the fed token not yet written)
* ``pre_handoff`` — a disaggregated dispatch picked its replicas but the
  detached prefill has not run (the pending entry is requeue-safe)

Serving calls pass ``replica=<name>`` context so a chaos hook can target
one replica of a fleet; checkpoint calls pass no context.
"""

from __future__ import annotations

from typing import Callable, Optional

#: every labeled save-path point, in the order the save path reaches them
CRASH_POINTS = ("pre_write", "mid_pytree", "pre_manifest", "pre_rename", "mid_prune")

#: every labeled restore-path point, in the order the load path reaches
#: them — restore never mutates the checkpoint, so a crash at ANY of
#: these must leave it as valid as it was
RESTORE_CRASH_POINTS = ("pre_restore", "mid_restore_arrays", "pre_restore_rng")

#: serving-fleet points (ServingEngine tick phases + the router's
#: disaggregated dispatch) — each at a boundary where the engine's host
#: state is consistent, so in-flight work is exactly exportable
SERVING_CRASH_POINTS = ("pre_tick", "mid_prefill", "mid_decode", "pre_handoff")

#: the full label set CrashPoint accepts
ALL_CRASH_POINTS = CRASH_POINTS + RESTORE_CRASH_POINTS + SERVING_CRASH_POINTS

_hook: Optional[Callable[..., None]] = None


def set_crash_hook(hook: Optional[Callable[..., None]]):
    """Install (or clear, with ``None``) the process-wide crash hook.
    Test-only machinery — production code never sets a hook."""
    global _hook
    _hook = hook


def crash_point(label: str, **ctx):
    """Invoke the crash hook, if any, with ``label`` (+ context kwargs —
    serving passes ``replica=``). Called by the save path at each
    protocol transition and by the serving tick phases; a no-op unless a
    hook is installed."""
    if _hook is not None:
        _hook(label, **ctx)
