"""Topology records and elastic-restore planning.

A checkpoint written by ``save_accelerator_state`` is a set of per-host
orbax shards plus per-process host state (RNG pickles, sampler
positions). The *array* half has always been restorable onto a different
mesh — orbax reads arbitrary index ranges, and ``_load_pytree`` targets
the CURRENT shardings — but the *host-state* half was silently
topology-pinned: resume on a different host count or mesh layout kept a
prefix of the sampler states and fresh-process RNG for any rank whose
``rng_state_{i}.pkl`` did not exist. This module makes the topology an
explicit, versioned part of the checkpoint (the Orbax paper's
"topology-elastic restore" tier, PAPERS.md arXiv 2605.23066):

* :func:`build_topology_record` — stamped into the integrity manifest
  (schema v2) at save time: process count, mesh shape + DCN axes, the
  global shape / dtype / PartitionSpec of every orbax-saved array leaf,
  the data-parallel degree, and the RNG seed.
* :func:`compare_topology` — classifies a restore as ``identical``
  (bit-exact, the pre-elastic path), ``elastic`` (resharding restore:
  RNG streams re-derived, sampler offsets redistributed), or ``unknown``
  (schema-v1 checkpoint with no record: only an identical-topology
  restore is verifiable).
* :func:`predict_reshard` — prices the post-restore reshard with the
  PR-2 cost model *before* it runs: per-array wire bytes split into ICI
  vs DCN stages (``analysis.costmodel.reshard_cost``), surfaced by
  ``accelerate-tpu checkpoints describe`` and the ``ckpt_elastic_restore``
  telemetry event.
* :func:`derive_rng_state` — the deterministic re-derivation scheme for
  per-process host RNG when the saved pickles no longer map onto the
  live ranks: fold ``(seed, step, process_index)`` through a
  ``SeedSequence``. Same topology -> the pickles are used and resume is
  bit-exact; changed topology -> every rank (old or new) derives a
  reproducible stream, and the semantics change is announced via the
  ``ckpt_rng_rederive`` telemetry event, never silent.
* :func:`redistribute_sampler_state` — recomputes the global sample
  offset (``batches_yielded x saved global batch size``) and splits it
  across the new data-parallel degree.

Everything here operates on plain shape dicts (``{"data": 4}``) and JSON
records, so the ``checkpoints describe`` CLI stays jax-free; jax is only
imported by :func:`build_topology_record`, which runs inside a live save.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

#: version of the ``topology`` block inside the (v2) integrity manifest
TOPOLOGY_SCHEMA_VERSION = 1

#: restore-compatibility tiers, strongest first
IDENTICAL = "identical"
ELASTIC = "elastic"
UNKNOWN = "unknown"


def _nontrivial(shape: Optional[dict]) -> dict[str, int]:
    """Mesh shape normalised to its non-trivial axes — ``{"data": 4,
    "tensor": 1}`` and ``{"data": 4}`` describe the same topology."""
    if not shape:
        return {}
    return {str(a): int(s) for a, s in shape.items() if int(s) > 1}


def spec_to_json(spec) -> Optional[list]:
    """A ``PartitionSpec`` as JSON: one entry per array dim, each
    ``None`` | axis name | list of axis names."""
    if spec is None:
        return None
    out = []
    for entry in spec:
        if entry is None or isinstance(entry, str):
            out.append(entry)
        else:
            out.append([str(a) for a in entry])
    return out


def capture_array_specs(tag: str, tree) -> dict[str, dict]:
    """Flatten a pytree about to be orbax-saved under directory ``tag``
    into ``{leaf_name: {shape, dtype, spec, bytes}}``. ``spec`` is the
    leaf's ``NamedSharding`` PartitionSpec, or ``None`` for host arrays /
    single-device-committed leaves (they restore as replicated)."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out: dict[str, dict] = {}
    for path, leaf in flat:
        if not hasattr(leaf, "shape"):
            continue
        name = tag + jax.tree_util.keystr(path)
        sharding = getattr(leaf, "sharding", None)
        spec = None
        if isinstance(sharding, jax.sharding.NamedSharding):
            spec = spec_to_json(sharding.spec)
        dtype = getattr(leaf, "dtype", None)
        dtype = np.dtype(dtype) if dtype is not None else np.asarray(leaf).dtype
        shape = tuple(int(d) for d in np.shape(leaf))
        out[name] = {
            "shape": list(shape),
            "dtype": dtype.name,
            "spec": spec,
            "bytes": int(np.prod(shape or (1,))) * dtype.itemsize,
        }
    return out


def build_topology_record(accelerator, array_trees: Sequence[tuple]) -> dict:
    """The topology block for this save, stamped into the manifest by
    ``save_accelerator_state``. ``array_trees`` is ``[(dir_name, pytree),
    ...]`` — exactly the pytrees handed to orbax, keyed by their
    checkpoint subdirectory."""
    from ..parallel.mesh import data_parallel_size, dcn_axes
    from ..utils.random import get_seed

    arrays: dict[str, dict] = {}
    for tag, tree in array_trees:
        arrays.update(capture_array_specs(tag, tree))
    mesh = accelerator.mesh
    plugin = getattr(accelerator.state, "parallelism_plugin", None)
    return {
        "schema_version": TOPOLOGY_SCHEMA_VERSION,
        "process_count": int(accelerator.num_processes),
        "mesh_shape": {str(a): int(s) for a, s in dict(mesh.shape).items()},
        "mesh_devices": int(mesh.size),
        "dcn_axes": list(dcn_axes()),
        "data_parallel_degree": int(data_parallel_size(mesh)),
        # ZeRO-1 flat-shard optimizer state is padded to a multiple of the
        # data-parallel degree; an elastic restore re-pads using the two
        # degrees, and `checkpoints describe` surfaces the mode
        "zero_stage": int(getattr(plugin, "zero_stage", 0) or 0) if plugin is not None else 0,
        "seed": get_seed(),
        "arrays": arrays,
    }


def live_topology(accelerator) -> dict:
    """The running job's topology in the same shape as the saved record."""
    from ..parallel.mesh import data_parallel_size, dcn_axes

    mesh = accelerator.mesh
    return {
        "process_count": int(accelerator.num_processes),
        "mesh_shape": {str(a): int(s) for a, s in dict(mesh.shape).items()},
        "mesh_devices": int(mesh.size),
        "dcn_axes": list(dcn_axes()),
        "data_parallel_degree": int(data_parallel_size(mesh)),
    }


@dataclass
class TopologyDelta:
    """Outcome of :func:`compare_topology`.

    ``status`` is one of :data:`IDENTICAL` / :data:`ELASTIC` /
    :data:`UNKNOWN`; ``changes`` is a human-readable list of what moved
    (empty for identical)."""

    status: str
    changes: list[str] = field(default_factory=list)
    saved: Optional[dict] = None
    live: Optional[dict] = None

    @property
    def is_elastic(self) -> bool:
        return self.status == ELASTIC

    def describe(self) -> str:
        if self.status == IDENTICAL:
            return "identical topology: bit-exact restore (RNG pickles + sampler positions reused)"
        if self.status == UNKNOWN:
            return (
                "no topology record (pre-elastic checkpoint): restore is only "
                "verifiable on the topology that wrote it"
            )
        return "topology changed: elastic restore (arrays reshard on load, RNG re-derived, sampler offset redistributed)"


def _shape_str(shape: dict) -> str:
    nt = _nontrivial(shape)
    if not nt:
        return "single-device"
    return ",".join(f"{a}={s}" for a, s in sorted(nt.items()))


def compare_topology(saved: Optional[dict], live: dict) -> TopologyDelta:
    """Classify a restore of a checkpoint whose manifest carried ``saved``
    (or ``None`` for schema-v1 manifests) onto the ``live`` topology."""
    if not saved:
        return TopologyDelta(UNKNOWN, saved=saved, live=live)
    changes: list[str] = []
    if int(saved.get("process_count", 1)) != int(live.get("process_count", 1)):
        changes.append(
            f"process count {saved.get('process_count')} -> {live.get('process_count')}"
        )
    s_shape, l_shape = _nontrivial(saved.get("mesh_shape")), _nontrivial(live.get("mesh_shape"))
    if s_shape != l_shape:
        changes.append(f"mesh {_shape_str(saved.get('mesh_shape', {}))} -> {_shape_str(live.get('mesh_shape', {}))}")
    s_dp, l_dp = saved.get("data_parallel_degree"), live.get("data_parallel_degree")
    if s_dp is not None and l_dp is not None and int(s_dp) != int(l_dp):
        changes.append(f"data-parallel degree {s_dp} -> {l_dp}")
    if tuple(saved.get("dcn_axes", ())) != tuple(live.get("dcn_axes", ())):
        changes.append(
            f"dcn axes {list(saved.get('dcn_axes', []))} -> {list(live.get('dcn_axes', []))}"
        )
    status = ELASTIC if changes else IDENTICAL
    return TopologyDelta(status, changes=changes, saved=saved, live=live)


@dataclass
class ReshardPrediction:
    """Cost-model estimate of the post-restore reshard: per-device wire
    bytes, split into the ICI and DCN stages of a hierarchical
    re-gather (see ``analysis.costmodel.reshard_cost``)."""

    ici_bytes: int = 0
    dcn_bytes: int = 0
    array_count: int = 0
    moved_count: int = 0
    total_array_bytes: int = 0
    per_array: dict = field(default_factory=dict)

    @property
    def total_bytes(self) -> int:
        return self.ici_bytes + self.dcn_bytes


def predict_reshard(
    saved: Optional[dict],
    target_shape: Optional[dict] = None,
    target_dcn: Sequence[str] = (),
) -> ReshardPrediction:
    """Price the reshard of every recorded array onto ``target_shape``
    (a plain ``{axis: size}`` dict; defaults to the saved shape, i.e. a
    same-topology restore, which moves nothing). Identical topologies
    predict zero; otherwise each array is modelled as a hierarchical
    ring re-gather over the target mesh — an upper bound, since
    overlapping shard layouts move less."""
    from ..analysis.costmodel import reshard_cost

    pred = ReshardPrediction()
    if not saved:
        return pred
    arrays = saved.get("arrays", {})
    pred.array_count = len(arrays)
    pred.total_array_bytes = sum(int(a.get("bytes", 0)) for a in arrays.values())
    src_shape = _nontrivial(saved.get("mesh_shape"))
    dst_shape = _nontrivial(target_shape if target_shape is not None else saved.get("mesh_shape"))
    same = src_shape == dst_shape and tuple(saved.get("dcn_axes", ())) == tuple(target_dcn or ())
    if same:
        return pred
    for name, rec in arrays.items():
        nbytes = int(rec.get("bytes", 0))
        cost = reshard_cost(nbytes, dst_shape, target_dcn)
        pred.per_array[name] = cost
        pred.ici_bytes += cost["ici"]
        pred.dcn_bytes += cost["dcn"]
        if cost["ici"] or cost["dcn"]:
            pred.moved_count += 1
    return pred


# ---------------------------------------------------------------------------
# deterministic host-RNG re-derivation (elastic restores)
# ---------------------------------------------------------------------------

def derive_rng_state(seed: Optional[int], process_index: int, step: int = 0) -> dict:
    """Deterministic per-process host RNG for a topology-changed resume.

    The saved ``rng_state_{i}.pkl`` pickles encode exact stream positions
    for the *old* rank set; after an elastic restore there may be more
    ranks than pickles (grow) or pickles than ranks (shrink), and reusing
    rank ``i``'s stream on a different data shard would correlate draws
    across the new layout anyway. Instead every rank folds
    ``(seed, step, process_index)`` through a ``SeedSequence`` — the
    elastic analogue of ``set_seed(device_specific=True)``: reproducible
    (the same resume always draws the same streams) but NOT a
    continuation of the old streams. Callers must surface that semantics
    change (``ckpt_rng_rederive``)."""
    # domain tag keeps these streams disjoint from any other SeedSequence
    # use of the same seed
    entropy = [0xE1A57, int(seed) if seed is not None else 0, int(step), int(process_index)]
    ss = np.random.SeedSequence(entropy)
    py_seed, np_seed = (int(x) for x in ss.generate_state(2, np.uint64))
    return {"python_seed": py_seed, "numpy_seed": np_seed % (2**32), "seed": seed}


def apply_derived_rng_state(derived: dict) -> None:
    """Seed python/numpy from :func:`derive_rng_state` output and restore
    the JAX key-derivation seed (without re-clobbering stream positions —
    same contract as ``utils.random.restore_seed_for_keys``)."""
    from ..utils.random import restore_seed_for_keys

    random.seed(derived["python_seed"])
    np.random.seed(derived["numpy_seed"])
    restore_seed_for_keys(derived.get("seed"))


# ---------------------------------------------------------------------------
# sampler / dataloader redistribution
# ---------------------------------------------------------------------------

def redistribute_sampler_state(state: dict, new_global_batch_size: Optional[int]) -> tuple[dict, int]:
    """Recompute a saved dataloader position for a new data-parallel
    degree. The saved ``batches_yielded`` counts *global* batches of
    ``global_batch_size`` samples; the invariant that survives an elastic
    restore is the global sample offset — their product. Returns
    ``(new_state, replayed_samples)`` where ``replayed_samples`` counts
    samples that will be delivered a second time because the offset is
    not divisible by the new global batch (rounded DOWN: replaying a few
    samples is benign, skipping unseen ones is not)."""
    old_gb = state.get("global_batch_size")
    yielded = int(state.get("batches_yielded", 0) or 0)
    if not old_gb or not new_global_batch_size or int(old_gb) == int(new_global_batch_size):
        return dict(state), 0
    offset = yielded * int(old_gb)
    new_batches = offset // int(new_global_batch_size)
    replayed = offset - new_batches * int(new_global_batch_size)
    new_state = dict(state)
    new_state["batches_yielded"] = new_batches
    new_state["global_batch_size"] = int(new_global_batch_size)
    return new_state, replayed
