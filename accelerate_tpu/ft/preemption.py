"""SIGTERM/SIGINT -> a clean final checkpoint instead of a dead run.

TPU pods are preempted with a SIGTERM and a short grace window. A
:class:`PreemptionHandler` converts the signal into a *flag* — it does
no work inside the signal handler (async-signal context must not take
locks or touch jax) — which the training loop observes through
``Accelerator.should_checkpoint`` / ``Accelerator.should_stop``::

    for batch in loader:
        step(batch)
        if accelerator.should_checkpoint:
            accelerator.save_state()      # drains async saves, saves SYNC
        if accelerator.should_stop:
            break                          # exit cleanly inside the grace window

``Accelerator(kwargs_handlers=[FaultToleranceKwargs()])`` installs one
automatically; the handler chains to any previously installed handler on
``uninstall()`` restore.
"""

from __future__ import annotations

import signal
import threading
from typing import Callable, Iterable, Optional

from ..logging import get_logger

logger = get_logger(__name__)

#: default signals: SIGTERM is what preemption sends; SIGINT makes
#: ctrl-C during local runs take the same clean-exit path
DEFAULT_SIGNALS = ("SIGTERM", "SIGINT")


class PreemptionHandler:
    """Latches preemption signals into a checkable flag.

    ``on_preempt(signame)`` (optional) runs once on the first signal —
    the Accelerator wires a telemetry ``preempt`` event through it. A
    second SIGINT while preempted re-raises ``KeyboardInterrupt`` so a
    user hammering ctrl-C can still kill a hung drain."""

    def __init__(
        self,
        signals: Iterable[str] = DEFAULT_SIGNALS,
        on_preempt: Optional[Callable[[str], None]] = None,
    ):
        self.signal_names = tuple(signals)
        self.on_preempt = on_preempt
        self.received: Optional[str] = None
        self.installed = False
        self._prev_handlers: dict[int, object] = {}

    # ------------------------------------------------------------------ #

    @property
    def preempted(self) -> bool:
        return self.received is not None

    def install(self) -> bool:
        """Register the handlers. Returns ``False`` (with a warning)
        instead of raising when not on the main thread — ``signal.signal``
        only works there, and a notebook/background-thread Accelerator
        should degrade, not crash."""
        if self.installed:
            return True
        if threading.current_thread() is not threading.main_thread():
            logger.warning("PreemptionHandler.install skipped: not on the main thread")
            return False
        for name in self.signal_names:
            signum = getattr(signal, name, None)
            if signum is None:
                continue
            try:
                self._prev_handlers[signum] = signal.signal(signum, self._handle)
            except (ValueError, OSError) as e:  # embedded interpreters
                logger.warning(f"could not install handler for {name}: {e}")
        self.installed = bool(self._prev_handlers)
        return self.installed

    def uninstall(self):
        """Restore the previously installed handlers."""
        for signum, prev in self._prev_handlers.items():
            try:
                signal.signal(signum, prev)
            except (ValueError, OSError):
                pass
        self._prev_handlers.clear()
        self.installed = False

    def reset(self):
        """Clear the latched flag (tests; or a run that checkpointed and
        decided to keep going after a spurious SIGINT)."""
        self.received = None

    def mark_remote(self):
        """Latch the flag because ANOTHER host received the signal.

        Preemption notices often reach only some hosts; the all-hosts
        agreement step (``parallel.collectives.agree_preempt_max``, run by
        ``Accelerator.should_checkpoint``/``should_stop``) calls this on
        every rank whose local handler saw nothing, so the whole fleet
        behaves as if uniformly signalled — one coherent final checkpoint
        instead of a half-stopped job."""
        if self.received is None:
            self.received = "REMOTE"
            logger.warning(
                "preemption agreed via all-hosts max-reduce (signal landed on another "
                "rank) — will checkpoint and stop at the next step boundary"
            )
            if self.on_preempt is not None:
                try:
                    self.on_preempt("REMOTE")
                except Exception as e:
                    logger.warning(f"on_preempt callback failed: {e}")

    # ------------------------------------------------------------------ #

    def _handle(self, signum, frame):
        first = self.received is None
        name = signal.Signals(signum).name
        if not first and signum == getattr(signal, "SIGINT", None):
            raise KeyboardInterrupt  # second ctrl-C: user really means it
        self.received = name
        if first:
            logger.warning(f"{name} received — will checkpoint and stop at the next step boundary")
            if self.on_preempt is not None:
                try:
                    self.on_preempt(name)
                except Exception as e:  # the flag must latch even if telemetry hiccups
                    logger.warning(f"on_preempt callback failed: {e}")

    def __enter__(self):
        self.install()
        return self

    def __exit__(self, *exc):
        self.uninstall()
