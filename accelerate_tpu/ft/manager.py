"""Checkpoint discovery, verification, recovery, GC, and pruning.

Operates on the directory layout ``save_accelerator_state`` produces
under ``{project_dir}/checkpoints/``::

    checkpoints/
      checkpoint_0/                 # committed (has commit_success.json)
      checkpoint_1/
      checkpoint_2.tmp/             # in-flight, crashed, or recoverable

The invariants this module maintains:

* discovery (``latest`` / ``all_valid``) never returns an uncommitted or
  manifest-failing directory;
* a ``.tmp`` dir whose manifest IS valid was fully written and committed
  — only the final rename was lost — so ``gc()`` finishes the rename
  instead of deleting data;
* ``prune`` keeps the newest ``total_limit`` checkpoints and NEVER
  removes a protected path (the checkpoint a run resumed from, the one
  it just wrote) — so no code path can delete the last valid checkpoint
  before a newer one has committed.
"""

from __future__ import annotations

import re
import shutil
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Optional

from ..logging import get_logger
from .manifest import TMP_SUFFIX, read_manifest, verify_manifest

logger = get_logger(__name__)

_CKPT_RE = re.compile(r"^checkpoint_(\d+)$")


def checkpoint_index(path) -> Optional[int]:
    """``checkpoint_7`` -> 7 (also accepts ``checkpoint_7.tmp``); ``None``
    for anything else."""
    name = Path(path).name
    if name.endswith(TMP_SUFFIX):
        name = name[: -len(TMP_SUFFIX)]
    m = _CKPT_RE.match(name)
    return int(m.group(1)) if m else None


@dataclass
class VerifyResult:
    """Outcome of :meth:`CheckpointManager.verify` for one directory."""

    path: str
    ok: bool
    problems: list = field(default_factory=list)
    manifest: Optional[dict] = None


class CheckpointManager:
    """Manage the ``checkpoint_N`` family under one base directory.

    ``accelerate-tpu checkpoints list|verify|gc`` is a thin CLI over this
    class; ``Accelerator.load_state(input_dir=None)`` uses ``latest()``
    for auto-resume. The manager holds no state beyond ``base_dir`` —
    every call re-reads the filesystem, so it stays correct under
    concurrent writers."""

    def __init__(self, base_dir):
        self.base_dir = Path(base_dir)

    # ------------------------------------------------------------------ #
    # discovery
    # ------------------------------------------------------------------ #

    def all_checkpoints(self) -> list[Path]:
        """Committed-named (no ``.tmp``) checkpoint dirs, oldest first.
        Makes no validity claim — see :meth:`all_valid`."""
        if not self.base_dir.is_dir():
            return []
        out = [
            d for d in self.base_dir.iterdir()
            if d.is_dir() and not d.name.endswith(TMP_SUFFIX) and checkpoint_index(d) is not None
        ]
        return sorted(out, key=checkpoint_index)

    def tmp_dirs(self) -> list[Path]:
        """``checkpoint_N.tmp`` leftovers, oldest first."""
        if not self.base_dir.is_dir():
            return []
        out = [
            d for d in self.base_dir.iterdir()
            if d.is_dir() and d.name.endswith(TMP_SUFFIX) and checkpoint_index(d) is not None
        ]
        return sorted(out, key=checkpoint_index)

    def all_valid(self, deep: bool = False) -> list[Path]:
        """Committed checkpoints whose manifest verifies, oldest first."""
        return [d for d in self.all_checkpoints() if self.verify(d, deep=deep).ok]

    def latest(self, deep: bool = True) -> Optional[Path]:
        """The newest VALID checkpoint, walking back past corrupt or
        uncommitted ones (a truncated newest checkpoint must not block
        resume from the one before it)."""
        for d in reversed(self.all_checkpoints()):
            result = self.verify(d, deep=deep)
            if result.ok:
                return d
            logger.warning(f"skipping invalid checkpoint {d.name}: {result.problems[:3]}")
        return None

    # ------------------------------------------------------------------ #
    # integrity
    # ------------------------------------------------------------------ #

    def verify(self, path=None, deep: bool = True) -> VerifyResult:
        """Deep integrity check of one checkpoint dir (default: the
        newest committed one)."""
        if path is None:
            ckpts = self.all_checkpoints()
            if not ckpts:
                return VerifyResult(str(self.base_dir), False, ["no checkpoints found"])
            path = ckpts[-1]
        problems = verify_manifest(path, deep=deep)
        return VerifyResult(str(path), not problems, problems, manifest=read_manifest(path))


    # ------------------------------------------------------------------ #
    # recovery / GC / pruning
    # ------------------------------------------------------------------ #

    def recover(self) -> list[Path]:
        """Finish interrupted renames: a ``checkpoint_N.tmp`` whose
        manifest deep-verifies was fully committed (the manifest is only
        ever written after the all-host barrier) — rename it to
        ``checkpoint_N`` unless that name already exists. Returns the
        recovered paths."""
        recovered = []
        for tmp in self.tmp_dirs():
            final = tmp.with_name(tmp.name[: -len(TMP_SUFFIX)])
            if final.exists():
                continue  # a committed twin exists; the tmp is garbage
            if not verify_manifest(tmp, deep=True):
                tmp.rename(final)
                logger.info(f"recovered committed checkpoint from interrupted rename: {final.name}")
                recovered.append(final)
        return recovered

    def gc(self, dry_run: bool = False) -> dict:
        """Garbage-collect: first :meth:`recover` committed ``.tmp`` dirs,
        then delete the rest (partial writes from crashed or failed
        saves). Never touches a committed-named directory. Returns
        ``{"recovered": [...], "removed": [...]}`` of the ``.tmp`` names."""
        recoverable = {
            t for t in self.tmp_dirs()
            if not t.with_name(t.name[: -len(TMP_SUFFIX)]).exists()
            and not verify_manifest(t, deep=True)
        }
        report = {
            "recovered": sorted(t.name for t in recoverable),
            "removed": sorted(t.name for t in self.tmp_dirs() if t not in recoverable),
        }
        if not dry_run:
            self.recover()
            for tmp in self.tmp_dirs():
                shutil.rmtree(tmp, ignore_errors=True)
        return report

    def prune(self, total_limit: Optional[int], protect: Iterable = ()) -> list[Path]:
        """Delete the oldest committed checkpoints beyond ``total_limit``,
        never touching ``protect``-ed paths (resolved for comparison).
        Runs strictly AFTER a new checkpoint commits — callers must not
        invoke this with a save in flight. Returns the removed paths."""
        if not total_limit or total_limit < 1:
            return []
        protected = {Path(p).resolve() for p in protect}
        ckpts = self.all_checkpoints()
        removed = []
        from .crashpoints import crash_point

        for victim in ckpts[:-total_limit] if len(ckpts) > total_limit else []:
            if victim.resolve() in protected:
                continue
            crash_point("mid_prune")
            shutil.rmtree(victim, ignore_errors=True)
            removed.append(victim)
        return removed
