"""The checkpoint integrity manifest (``commit_success.json``).

The manifest is the **commit point** of the atomic save protocol: every
host writes its shards into ``checkpoint_N.tmp/``, all hosts barrier,
and only then does the main process build + write the manifest and
rename the directory. A directory without a parseable, matching
manifest is by definition uncommitted — discovery
(:class:`~accelerate_tpu.ft.manager.CheckpointManager`) never returns
it, and ``gc()`` may delete it.

Schema (``MANIFEST_SCHEMA_VERSION`` 2; v1 files — written before the
elastic-restore work — parse identically, they just carry no
``topology`` block)::

    {
      "schema_version": 2,
      "step": 12,                      # accelerator.step at save time
      "iteration": 3,                  # ProjectConfiguration.iteration (or null)
      "num_processes": 1,
      "files": {                       # small top-level state files
        "accelerate_state.json": {"size": 97, "crc32": 2614},
        "rng_state_0.pkl":       {"size": 1201, "crc32": 991},
        ...
      },
      "pytree_files": {                # every file under the orbax dirs
        "model/_METADATA": 307, ...    # relpath -> size (bytes)
      },
      "pytree_dirs": ["model", "optimizer"],
      "orbax_metadata": {"model": true, "optimizer": true},
      "topology": {                    # v2: what wrote this checkpoint
        "schema_version": 1,           # (ft/topology.py)
        "process_count": 4,
        "mesh_shape": {"data": 4, "tensor": 1, ...},
        "mesh_devices": 4,
        "dcn_axes": [],
        "data_parallel_degree": 4,
        "seed": 42,
        "arrays": {                    # every orbax-saved pytree leaf
          "model['a']": {"shape": [8, 4], "dtype": "float32",
                          "spec": ["data", null], "bytes": 128},
          ...
        }
      }
    }

The ``topology`` block is what makes restore *elastic*: on load,
``compare_topology`` decides between the bit-exact identical-topology
path and the explicit elastic path (reshard-on-load, RNG re-derivation,
sampler redistribution) — see :mod:`accelerate_tpu.ft.topology` and
``accelerate-tpu checkpoints describe``.

Digest policy: crc32 (zlib) for the small JSON/pkl control files — they
decide *what* gets restored, so silent corruption there is the worst
case; the multi-GB orbax array files get exact sizes (orbax carries its
own per-array checksums in OCDBT). ``verify_manifest`` re-walks the
directory and reports every mismatch rather than stopping at the first.
"""

from __future__ import annotations

import json
import os
import zlib
from pathlib import Path
from typing import Optional

MANIFEST_NAME = "commit_success.json"
MANIFEST_SCHEMA_VERSION = 2

#: versions ``read_manifest`` accepts: v1 (pre-elastic, no topology
#: record) still commits and restores on an identical topology
SUPPORTED_SCHEMA_VERSIONS = (1, 2)

#: suffix a checkpoint directory carries until its rename commit
TMP_SUFFIX = ".tmp"

#: crc32 is computed for top-level files up to this size (the control
#: files are KBs; a custom_checkpoint pkl holding a replay buffer could
#: be huge — size-check only past the cap)
DIGEST_SIZE_LIMIT = 64 * 1024 * 1024

#: orbax StandardCheckpointer writes these markers into every pytree dir
_ORBAX_METADATA_FILES = ("_METADATA", "_CHECKPOINT_METADATA")


def _crc32(path: Path) -> int:
    crc = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def build_manifest(ckpt_dir, *, step: Optional[int] = None, iteration: Optional[int] = None,
                   num_processes: int = 1, topology: Optional[dict] = None) -> dict:
    """Walk a fully written checkpoint directory and produce its manifest
    dict. Called by the main process AFTER the all-host barrier, so every
    shard file is on disk. The manifest file itself is excluded."""
    root = Path(ckpt_dir)
    files: dict[str, dict] = {}
    pytree_files: dict[str, int] = {}
    pytree_dirs: list[str] = []
    orbax_metadata: dict[str, bool] = {}

    for entry in sorted(root.iterdir()):
        if entry.name == MANIFEST_NAME:
            continue
        if entry.is_dir():
            pytree_dirs.append(entry.name)
            orbax_metadata[entry.name] = any((entry / m).exists() for m in _ORBAX_METADATA_FILES)
            for sub in sorted(entry.rglob("*")):
                if sub.is_file():
                    pytree_files[sub.relative_to(root).as_posix()] = sub.stat().st_size
        else:
            size = entry.stat().st_size
            rec = {"size": size}
            if size <= DIGEST_SIZE_LIMIT:
                rec["crc32"] = _crc32(entry)
            files[entry.name] = rec

    manifest = {
        "schema_version": MANIFEST_SCHEMA_VERSION,
        "step": step,
        "iteration": iteration,
        "num_processes": num_processes,
        "files": files,
        "pytree_files": pytree_files,
        "pytree_dirs": pytree_dirs,
        "orbax_metadata": orbax_metadata,
    }
    if topology is not None:
        manifest["topology"] = topology
    return manifest


def write_manifest(ckpt_dir, manifest: dict) -> str:
    """Durably write the manifest: write + flush + fsync a sibling temp
    file, then ``os.replace`` onto ``commit_success.json`` — a crash
    mid-write must not leave a half-written manifest that *parses* (a
    truncated JSON fails to parse, which verify treats as uncommitted,
    so even the non-fsync'd worst case degrades safely)."""
    path = Path(ckpt_dir) / MANIFEST_NAME
    tmp = path.with_suffix(".json.writing")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return str(path)


def read_manifest(ckpt_dir) -> Optional[dict]:
    """Parse a checkpoint's manifest; ``None`` when missing, unparseable,
    or of an unknown schema version (all three mean: not committed)."""
    path = Path(ckpt_dir) / MANIFEST_NAME
    if not path.is_file():
        return None
    try:
        manifest = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(manifest, dict) or manifest.get("schema_version") not in SUPPORTED_SCHEMA_VERSIONS:
        return None
    return manifest


def verify_manifest(ckpt_dir, *, deep: bool = True) -> list[str]:
    """Check a checkpoint directory against its manifest; returns a list
    of human-readable problems (empty == valid).

    Shallow: manifest present + parseable + known schema. Deep adds:
    every recorded file exists with the exact recorded size, crc32
    matches where recorded, and each pytree dir still carries its orbax
    metadata marker."""
    root = Path(ckpt_dir)
    if not root.is_dir():
        return [f"not a directory: {root}"]
    manifest = read_manifest(root)
    if manifest is None:
        raw = root / MANIFEST_NAME
        if raw.is_file():
            return [f"manifest unreadable or unknown schema: {raw}"]
        return ["no commit manifest (uncommitted or pre-fault-tolerance checkpoint)"]
    if not deep:
        return []

    problems: list[str] = []
    for name, rec in manifest.get("files", {}).items():
        path = root / name
        if not path.is_file():
            problems.append(f"missing file: {name}")
            continue
        size = path.stat().st_size
        if size != rec.get("size"):
            problems.append(f"size mismatch: {name} is {size}B, manifest says {rec.get('size')}B")
            continue
        if "crc32" in rec and _crc32(path) != rec["crc32"]:
            problems.append(f"crc32 mismatch: {name} is corrupt")
    for rel, size in manifest.get("pytree_files", {}).items():
        path = root / rel
        if not path.is_file():
            problems.append(f"missing pytree file: {rel}")
        elif path.stat().st_size != size:
            problems.append(f"size mismatch: {rel} is {path.stat().st_size}B, manifest says {size}B")
    for d in manifest.get("pytree_dirs", []):
        if not (root / d).is_dir():
            problems.append(f"missing pytree dir: {d}")
        elif manifest.get("orbax_metadata", {}).get(d) and not any(
            (root / d / m).exists() for m in _ORBAX_METADATA_FILES
        ):
            problems.append(f"pytree dir lost its orbax metadata: {d}")
    return problems
