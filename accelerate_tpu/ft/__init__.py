"""Fault tolerance: atomic checkpoint commits, integrity manifests,
preemption-safe auto-resume, and fault-injection crash points.

TPU pods are preemptible and multi-host saves are not atomic — a kill
mid-save must never leave a checkpoint that a later
``Accelerator.load_state()`` mistakes for a complete one, and pruning
must never delete the last good checkpoint before a new one has
committed. This package provides the pieces ``checkpointing.py`` builds
its atomic commit protocol from (the in-repo analogue of Orbax's
distributed checkpointing design, PAPERS.md arXiv 2605.23066):

* :mod:`~accelerate_tpu.ft.manifest` — the ``commit_success.json``
  schema: per-file sizes + crc32 digests written by the main process
  only after every host has finished writing; its presence IS the
  commit point.
* :mod:`~accelerate_tpu.ft.manager` — :class:`CheckpointManager`:
  discovery that skips uncommitted/corrupt directories, deep
  ``verify()``, ``gc()`` of orphaned ``.tmp`` dirs (recovering fully
  written ones), and post-commit ``prune()`` that never touches the
  resume source.
* :mod:`~accelerate_tpu.ft.preemption` — :class:`PreemptionHandler`:
  SIGTERM/SIGINT -> a flag surfaced as ``Accelerator.should_checkpoint``
  / ``Accelerator.should_stop`` so the loop takes one final synchronous
  checkpoint and exits cleanly.
* :mod:`~accelerate_tpu.ft.topology` — the manifest's (schema v2)
  topology record and the elastic-restore planners: compare saved vs
  live topology, price the post-restore reshard with the cost model,
  re-derive per-process RNG deterministically, and redistribute sampler
  offsets across a new data-parallel degree.
* :mod:`~accelerate_tpu.ft.crashpoints` — the labeled points inside the
  save AND restore paths that
  :mod:`accelerate_tpu.test_utils.fault_injection` kills at, proving
  resume always lands on a valid checkpoint.

See ``docs/usage_guides/fault_tolerance.md``.
"""

from .crashpoints import (
    ALL_CRASH_POINTS,
    CRASH_POINTS,
    RESTORE_CRASH_POINTS,
    crash_point,
    set_crash_hook,
)
from .manifest import (
    MANIFEST_NAME,
    MANIFEST_SCHEMA_VERSION,
    SUPPORTED_SCHEMA_VERSIONS,
    TMP_SUFFIX,
    build_manifest,
    read_manifest,
    verify_manifest,
    write_manifest,
)
from .manager import CheckpointManager, VerifyResult
from .preemption import PreemptionHandler
from .topology import (
    ELASTIC,
    IDENTICAL,
    UNKNOWN,
    ReshardPrediction,
    TopologyDelta,
    build_topology_record,
    compare_topology,
    derive_rng_state,
    live_topology,
    predict_reshard,
    redistribute_sampler_state,
)

__all__ = [
    "ALL_CRASH_POINTS",
    "CRASH_POINTS",
    "RESTORE_CRASH_POINTS",
    "crash_point",
    "set_crash_hook",
    "MANIFEST_NAME",
    "MANIFEST_SCHEMA_VERSION",
    "SUPPORTED_SCHEMA_VERSIONS",
    "TMP_SUFFIX",
    "build_manifest",
    "write_manifest",
    "read_manifest",
    "verify_manifest",
    "CheckpointManager",
    "VerifyResult",
    "PreemptionHandler",
    "ELASTIC",
    "IDENTICAL",
    "UNKNOWN",
    "TopologyDelta",
    "ReshardPrediction",
    "build_topology_record",
    "compare_topology",
    "live_topology",
    "predict_reshard",
    "derive_rng_state",
    "redistribute_sampler_state",
]
