"""The model container: an ``apply_fn`` paired with its parameter pytree.

The reference mutates ``nn.Module``s in place (DDP wrap, autocast-wrap,
``.to(device)`` — reference: src/accelerate/accelerator.py:1549-1750). JAX
models are (function, pytree) pairs, so the prepared "model" object is this
thin container: callable like the reference's wrapped module, but its
parameters are an explicit, shardable pytree that ``Accelerator.prepare``
lays out on the mesh.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np


def _jax():
    import jax

    return jax


class Model:
    """Pairs ``apply_fn(params, *args, **kwargs)`` with ``params``.

    ``sharding_rules`` may carry model-provided ``(regex, PartitionSpec)``
    rules (e.g. Megatron-style TP splits) consumed by
    :meth:`Accelerator.prepare`.
    """

    def __init__(
        self,
        apply_fn: Callable,
        params: Any,
        *,
        sharding_rules=None,
        name: Optional[str] = None,
        eval_apply_fn: Optional[Callable] = None,
    ):
        self.apply_fn = apply_fn
        self.eval_apply_fn = eval_apply_fn or apply_fn
        self.params = params
        # non-trainable mutable collections (flax batch_stats etc.),
        # threaded through build_train_step(has_state=True)
        self.state = None
        self.sharding_rules = sharding_rules
        self.name = name or getattr(apply_fn, "__name__", "model")
        self._is_accelerate_prepared = False  # reference marker: accelerator.py:1470
        self.training = True

    # -- construction ------------------------------------------------------

    @classmethod
    def from_flax(cls, module, params: Any, *, sharding_rules=None, **apply_kwargs) -> "Model":
        """Wrap a ``flax.linen.Module`` + params."""

        def apply_fn(p, *args, **kwargs):
            return module.apply({"params": p}, *args, **{**apply_kwargs, **kwargs})

        m = cls(apply_fn, params, sharding_rules=sharding_rules, name=type(module).__name__)
        m.module = module
        return m

    # -- behaviour ---------------------------------------------------------

    def __call__(self, *args, **kwargs):
        fn = self.apply_fn if self.training else self.eval_apply_fn
        return fn(self.params, *args, **kwargs)

    def eval(self) -> "Model":
        self.training = False
        return self

    def train(self, mode: bool = True) -> "Model":
        self.training = mode
        return self

    def num_parameters(self) -> int:
        jax = _jax()
        return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(self.params) if hasattr(p, "shape"))

    def parameter_bytes(self) -> int:
        jax = _jax()
        return sum(
            int(np.prod(p.shape)) * np.dtype(p.dtype).itemsize
            for p in jax.tree_util.tree_leaves(self.params)
            if hasattr(p, "shape")
        )

    def state_dict(self) -> Any:
        """Flat ``{path: np.ndarray}`` view (for save/export)."""
        jax = _jax()
        flat = jax.tree_util.tree_flatten_with_path(self.params)[0]
        from .parallel.sharding import path_str

        return {path_str(kp): np.asarray(jax.device_get(v)) for kp, v in flat}

    def load_state_dict(self, state_dict: dict) -> None:
        jax = _jax()
        from .parallel.sharding import path_str

        leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(self.params)
        new_leaves = []
        for kp, old in leaves_with_path:
            key = path_str(kp)
            if key not in state_dict:
                raise KeyError(f"missing parameter {key!r} in state_dict")
            new = np.asarray(state_dict[key])
            if tuple(new.shape) != tuple(old.shape):
                raise ValueError(f"shape mismatch for {key!r}: {new.shape} vs {old.shape}")
            if hasattr(old, "sharding"):
                new = jax.device_put(new.astype(old.dtype), old.sharding)
            new_leaves.append(new)
        self.params = jax.tree_util.tree_unflatten(treedef, new_leaves)

    def __repr__(self) -> str:
        return f"Model({self.name}, params={self.num_parameters():,})"


def as_model(model) -> Model:
    """Coerce supported inputs to :class:`Model`:

    * a :class:`Model` — unchanged
    * ``(flax_module, params)`` tuple
    * ``(apply_fn, params)`` tuple
    """
    if isinstance(model, Model):
        return model
    if isinstance(model, tuple) and len(model) == 2:
        head, params = model
        if hasattr(head, "apply"):
            return Model.from_flax(head, params)
        if callable(head):
            return Model(head, params)
    raise TypeError(
        f"Cannot interpret {type(model)} as a model. Pass an accelerate_tpu.Model, "
        "a (flax_module, params) pair, or an (apply_fn, params) pair."
    )
