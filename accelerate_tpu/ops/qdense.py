"""Quantized Dense: weight-only int8/int4/nf4 linear layer for decode.

The reference gets its memory-bound decode win from bitsandbytes' fused
dequant kernels inside each ``nn.Linear`` (reference:
src/accelerate/utils/bnb.py:276-373 ``replace_with_bnb_layers``). The
TPU-native equivalent is a flax module whose *parameters are the packed
integer codes*: ``qdata`` (int8, or two 4-bit codes per byte) plus
``qscale``. Because they are ordinary array params,

* ``nn.scan`` over layers slices them along the stacked layer dim like any
  other kernel — the dequantize runs **inside** the scan body, per layer,
  so HBM reads per decode step are the packed bytes, not a full-precision
  copy of the stack;
* XLA fuses the int8→bf16 convert into the consuming matmul (per-channel
  int8 keeps the operand a pure ``convert``, the most fusion-friendly
  shape), which is where the ~2× (int8) / ~3.5× (int4) decode-bandwidth
  win comes from on a memory-bound matvec.

Layout matches :func:`accelerate_tpu.utils.quantization.quantize`:
``qdata [n_groups, g, out]`` (int8) or ``[n_groups, g/2, out]`` (packed
4-bit), ``qscale [n_groups, 1, out]`` — groups tile the contraction dim.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..utils.quantization import grouped_dequantize


class QuantDense(nn.Module):
    """Drop-in ``nn.Dense`` replacement with a weight-only quantized kernel.

    Fresh-initialised params are zeros — meaningful values come from
    converting a float checkpoint (``utils.quantization.quantize`` →
    ``qdata``/``qscale``), e.g. via ``load_and_quantize_model``.
    """

    features: int
    method: str = "int8"  # int8 | int4 | nf4
    group_size: Optional[int] = None  # None = one scale per output channel
    dtype: Any = jnp.bfloat16
    use_bias: bool = False

    @nn.compact
    def __call__(self, x):
        if self.method not in ("int8", "w8a8", "int4", "nf4"):
            raise ValueError(f"method must be int8|w8a8|int4|nf4, got {self.method!r}")
        in_features = x.shape[-1]
        g = self.group_size or in_features
        if in_features % g != 0:
            raise ValueError(f"input dim {in_features} not divisible by group_size {g}")
        n_groups = in_features // g
        packed = self.method in ("int4", "nf4")
        if packed and g % 2 != 0:
            raise ValueError(f"group size {g} must be even for 4-bit packing")
        rows = g // 2 if packed else g
        qdata = self.param(
            "qdata",
            nn.initializers.zeros,
            (n_groups, rows, self.features),
            jnp.uint8 if packed else jnp.int8,
        )
        qscale = self.param("qscale", nn.initializers.ones, (n_groups, 1, self.features), jnp.float32)
        dtype = self.dtype or x.dtype
        x = x.astype(dtype)

        if self.method == "w8a8" and n_groups > 1:
            raise ValueError("w8a8 requires per-channel scales (group_size=None)")
        if self.method == "w8a8":
            # W8A8: per-row dynamic activation quant feeds the NATIVE int8
            # MXU path — no per-weight convert at all, so decode's floor is
            # HBM bandwidth rather than VPU convert throughput
            w8 = qdata.reshape(in_features, self.features)
            x32 = x.astype(jnp.float32)
            sx = jnp.maximum(jnp.max(jnp.abs(x32), axis=-1, keepdims=True), 1e-12) / 127.0
            xq = jnp.clip(jnp.round(x32 / sx), -127, 127).astype(jnp.int8)
            y32 = jax.lax.dot_general(
                xq, w8, (((x.ndim - 1,), (0,)), ((), ())), preferred_element_type=jnp.int32
            )
            y = (y32.astype(jnp.float32) * sx * qscale.reshape(-1)).astype(dtype)
        elif self.method == "int8" and n_groups == 1:
            # per-channel fast path: the matmul operand is a pure int8→bf16
            # convert (fuses into the dot); the per-out-channel scale
            # commutes with the contraction and applies to the output
            w8 = qdata.reshape(in_features, self.features)
            y = jax.lax.dot_general(
                x,
                w8.astype(dtype),
                (((x.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            y = (y * qscale.reshape(-1)).astype(dtype)
        else:
            from .pallas_qmatmul import int4_matmul, pallas_int4_supported

            if pallas_int4_supported(x, self.method, self.group_size, n_groups, self.features):
                # fused dequant+matmul kernel: packed nibbles are the only
                # HBM traffic (XLA materialises a full-precision W here)
                lead = x.shape[:-1]
                y = int4_matmul(
                    x.reshape(-1, in_features), qdata, qscale, group_size=g
                ).reshape(*lead, self.features)
            else:
                wg = grouped_dequantize(qdata, qscale, self.method)
                w = wg.reshape(in_features, self.features).astype(dtype)
                y = x @ w
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, (self.features,), jnp.float32)
            y = y + bias.astype(dtype)
        return y
