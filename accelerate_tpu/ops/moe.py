"""Mixture-of-Experts: top-k routing + expert-parallel FFN.

The reference has **no** expert-parallel strategy — its only MoE support is
marking DeepSpeed MoE layer classes as ZeRO-3 leaves
(reference: src/accelerate/utils/dataclasses.py deepspeed_moe_layer_cls_names,
accelerator.py:2049). Expert parallelism is therefore a parity-plus
subsystem here, built the GSPMD way (GShard/Mesh-TF idiom):

* experts are **stacked params** with a leading expert dim, sharded over the
  ``expert`` mesh axis;
* token -> expert dispatch is a dense one-hot ``[tokens, experts, capacity]``
  mask consumed by einsums — XLA turns the sharded einsums into exactly the
  all-to-all shuffles a hand-written MPI MoE would do, and overlaps them;
* fixed per-expert ``capacity`` keeps every shape static (jit-friendly);
  overflow tokens fall through the residual connection (standard GShard
  behavior), and the load-balancing aux loss keeps overflow rare.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn


def top_k_routing(
    router_logits: jax.Array,  # [T, E]
    num_selected: int,
    capacity: int,
    norm_topk: bool = True,
):
    """GShard-style top-k token routing with fixed expert capacity.

    Returns ``(dispatch, combine, aux_loss)``:
    dispatch — bool [T, E, C], token t occupies slot c of expert e;
    combine — float [T, E, C], routing weight for the same slots
    (normalised over the selected experts when ``norm_topk``, the
    Mixtral convention; Qwen3-MoE checkpoints with
    ``norm_topk_prob=False`` keep the raw full-softmax probabilities —
    HF calls this "the only diff with the mixtral sparse moe block");
    aux_loss — load-balance loss (mean fraction routed x mean router prob,
    scaled by E; Shazeer/GShard form).
    """
    t, e = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)

    dispatch = jnp.zeros((t, e, capacity), jnp.bool_)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    remaining = probs
    # slots already taken per expert by earlier (higher-priority) choices
    fill = jnp.zeros((e,), jnp.int32)
    selected_mass = jnp.zeros((t,), jnp.float32)
    for _ in range(num_selected):  # num_selected is tiny and static
        choice = jnp.argmax(remaining, axis=-1)  # [T]
        onehot = jax.nn.one_hot(choice, e, dtype=jnp.int32)  # [T, E]
        # position of each token within its chosen expert's queue, offset by
        # slots filled in earlier rounds
        pos = (jnp.cumsum(onehot, axis=0) - 1) + fill[None, :]  # [T, E]
        pos_tok = jnp.sum(pos * onehot, axis=-1)  # [T]
        keep = pos_tok < capacity
        gate = jnp.sum(remaining * onehot, axis=-1)  # [T] prob of this choice
        slot = jax.nn.one_hot(jnp.clip(pos_tok, 0, capacity - 1), capacity, dtype=jnp.float32)
        contrib = (
            onehot.astype(jnp.float32)[:, :, None]
            * slot[:, None, :]
            * keep[:, None, None]
        )
        dispatch = dispatch | (contrib > 0)
        combine = combine + contrib * gate[:, None, None]
        selected_mass = selected_mass + gate * keep
        fill = fill + jnp.sum(onehot * keep[:, None], axis=0)
        remaining = remaining * (1.0 - onehot)  # mask chosen expert out

    if norm_topk:
        # normalise combine weights over the actually-kept choices
        combine = combine / jnp.maximum(selected_mass, 1e-9)[:, None, None]

    # load-balance aux: E * mean_e(frac_tokens_e * mean_prob_e)
    frac = jnp.mean(jax.nn.one_hot(jnp.argmax(probs, -1), e, dtype=jnp.float32), axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux_loss = e * jnp.sum(frac * mean_prob)
    return dispatch, combine, aux_loss


def moe_ffn(
    x: jax.Array,  # [T, d]
    router_kernel: jax.Array,  # [d, E]
    wi: jax.Array,  # [E, d, ff] (or gate/up pair for swiglu)
    wo: jax.Array,  # [E, ff, d]
    num_selected: int = 2,
    capacity_factor: float = 1.25,
    wi_gate: Optional[jax.Array] = None,  # [E, d, ff] for SwiGLU experts
    activation=nn.gelu,
    norm_topk: bool = True,
):
    """Dense-dispatch MoE feed-forward. Returns (out [T, d], aux_loss).

    All einsums are GSPMD-friendly: with ``wi/wo`` sharded over the
    ``expert`` axis and tokens over the batch axes, XLA inserts the
    dispatch/return all-to-alls automatically.
    """
    t, d = x.shape
    e = router_kernel.shape[-1]
    # GShard/Mixtral convention: capacity_factor scales the *per-assignment*
    # budget, so top-k routing gets k*T total slots before the factor
    capacity = max(1, int(capacity_factor * num_selected * t / e))
    logits = x @ router_kernel.astype(x.dtype)
    dispatch, combine, aux = top_k_routing(logits, num_selected, capacity, norm_topk=norm_topk)

    xe = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)  # all-to-all in
    if wi_gate is not None:
        h = nn.silu(jnp.einsum("ecd,edf->ecf", xe, wi_gate.astype(x.dtype)))
        h = h * jnp.einsum("ecd,edf->ecf", xe, wi.astype(x.dtype))
    else:
        h = activation(jnp.einsum("ecd,edf->ecf", xe, wi.astype(x.dtype)))
    ye = jnp.einsum("ecf,efd->ecd", h, wo.astype(x.dtype))  # [E, C, d]
    out = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), ye)  # all-to-all out
    return out, aux


class MoEBlock(nn.Module):
    """Sparse SwiGLU FFN block (Mixtral-style): top-k routed experts with a
    shared residual path for dropped tokens. Expects [B, S, d]; returns
    [B, S, d]. The load-balancing aux loss is exposed via
    ``sow("intermediates", "moe_aux_loss")`` — read it from the mutable
    ``intermediates`` collection after ``apply``."""

    num_experts: int
    intermediate_size: int
    num_selected: int = 2
    capacity_factor: float = 1.25
    norm_topk: bool = True  # False = Qwen3-MoE's raw-softmax combine weights

    @nn.compact
    def __call__(self, x):
        b, s, d = x.shape
        ff, e = self.intermediate_size, self.num_experts
        router = self.param("router/kernel", nn.initializers.lecun_normal(), (d, e))
        wi_gate = self.param("experts/gate_proj", nn.initializers.lecun_normal(), (e, d, ff))
        wi_up = self.param("experts/up_proj", nn.initializers.lecun_normal(), (e, d, ff))
        wo = self.param("experts/down_proj", nn.initializers.lecun_normal(), (e, ff, d))
        flat = x.reshape(b * s, d)
        out, aux = moe_ffn(
            flat,
            router,
            wi_up,
            wo,
            num_selected=self.num_selected,
            capacity_factor=self.capacity_factor,
            wi_gate=wi_gate,
            norm_topk=self.norm_topk,
        )
        self.sow("intermediates", "moe_aux_loss", aux)
        return out.reshape(b, s, d)
