"""Hand-tiled Pallas TPU flash-attention kernel (forward + custom VJP).

This is the MXU-resident hot path behind
:func:`accelerate_tpu.ops.attention.dot_product_attention` on TPU. The
reference framework ships no attention kernels at all (it is an
orchestration layer over torch models — SURVEY §1); this kernel exists
because our build carries its own model zoo and attention dominates the
FLOP/byte profile of every model in it.

Design (classic FlashAttention-2 tiling, TPU-shaped):

* internal layout ``[B, H, S, D]`` so every block's trailing dims are
  ``(seq_block, head_dim)`` — Mosaic-tileable (sublane ÷8, lane ÷128 or
  full-dim);
* grid ``(batch, q_heads, q_blocks, k_blocks)`` with the KV-block dimension
  innermost — each ``(b, h, qi)`` owns a VMEM accumulator/running-max/
  running-sum scratch re-initialised at ``ki == 0`` and flushed at
  ``ki == nk-1`` (standard revisited-output-block pattern);
* online softmax in fp32 on the VPU, both matmuls (``q·kᵀ`` and ``p·v``)
  on the MXU via ``dot_general`` with ``preferred_element_type=float32``;
* causal blocks strictly above the (bottom-right aligned) diagonal are
  skipped entirely with ``pl.when`` — ~2× for long causal sequences;
* GQA reads K/V through an ``h // group`` index map, so KV blocks are
  never materialised per-query-head;
* backward = two kernels (dq over KV blocks; dk/dv over Q blocks) using the
  saved logsumexp + the precomputed ``delta = Σ dout·out`` row term, both
  stored lane-replicated at width 8 (min-tile trick, same idea as the
  in-tree TPU kernels' 128-lane stat arrays but 16× less HBM).

On non-TPU backends the kernel runs in interpreter mode (tests) — real
deployments dispatch to the ``lax.scan`` fallback in
:mod:`accelerate_tpu.ops.flash_attention` instead.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
_LANES = 128  # VPU lane width; VMEM running stats are (block_q, 128)
_STAT_LANES = 8  # lane replication for the HBM-resident lse/delta arrays


def _vmem_spec(block_shape, index_map):
    return pl.BlockSpec(block_shape, index_map, memory_space=pltpu.VMEM)


def _scratch(shape):
    return pltpu.VMEM(shape, jnp.float32)


def _mask(sq, sk, q_start, k_start, block_q, block_k, causal, window=None):
    """Validity mask for one (Q block, K block) tile; positions beyond the
    true lengths and (optionally) above the bottom-right diagonal are off.
    ``window`` adds the Mistral band: keys older than ``window`` positions
    below the (aligned) query are off too."""
    row = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    col = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    valid = (col < sk) & (row < sq)
    if causal:
        valid &= row + (sk - sq) >= col
    if window is not None:
        valid &= col > row + (sk - sq) - window
    return valid


def _block_live(q_start, k_start, block_q, block_k, offset, causal, window):
    """Whether any element of this (Q, K) tile can be unmasked: K blocks
    strictly above the causal diagonal OR entirely below the band are
    skipped (the band skip makes banded attention O(S*W), not O(S^2))."""
    live = (q_start + block_q - 1 + offset >= k_start) if causal else True
    if window is not None:
        live &= k_start + block_k - 1 > q_start + offset - window
    return live


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref, *, sq, sk, block_q, block_k, causal, scale, window):
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)
    offset = sk - sq  # bottom-right causal alignment (decode: sq < sk)
    q_start, k_start = qi * block_q, ki * block_k

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)

    run = _block_live(q_start, k_start, block_q, block_k, offset, causal, window)

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) * scale
        valid = _mask(sq, sk, q_start, k_start, block_q, block_k, causal, window)
        s = jnp.where(valid, s, -jnp.inf)

        m_prev = m_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # rows with every position masked keep m == -inf; exp against 0 then
        safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.where(valid, jnp.exp(s - safe_m), 0.0)
        corr = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - safe_m), 0.0)
        l_new = l_ref[:, :1] * corr + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0, 0]
        pv = jax.lax.dot_general(p.astype(v.dtype), v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * corr + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l = jnp.maximum(l_ref[:, :1], 1e-37)
        o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)
        m = m_ref[:, :1]
        lse = jnp.where(jnp.isfinite(m), m + jnp.log(l), -jnp.inf)
        lse_ref[0, 0] = jnp.broadcast_to(lse, (block_q, _STAT_LANES))


def _run_fwd(q, k, v, sq, sk, causal, scale, block_q, block_k, interpret, window=None):
    """q [B,H,Sqp,D], k/v [B,Hkv,Skp,D], padded to block multiples; sq/sk
    are the true (unpadded) lengths. Returns out [B,H,Sqp,D] and the
    lane-replicated logsumexp [B,H,Sqp,_STAT_LANES]."""
    b, h, sqp, d = q.shape
    h_kv, skp = k.shape[1], k.shape[2]
    g = h // h_kv
    nq, nk = sqp // block_q, skp // block_k

    kernel = functools.partial(
        _fwd_kernel, sq=sq, sk=sk, block_q=block_q, block_k=block_k, causal=causal, scale=scale,
        window=window,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=[
            _vmem_spec((1, 1, block_q, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            _vmem_spec((1, 1, block_k, d), lambda b_, h_, qi, ki: (b_, h_ // g, ki, 0)),
            _vmem_spec((1, 1, block_k, d), lambda b_, h_, qi, ki: (b_, h_ // g, ki, 0)),
        ],
        out_specs=[
            _vmem_spec((1, 1, block_q, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            _vmem_spec((1, 1, block_q, _STAT_LANES), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sqp, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sqp, _STAT_LANES), jnp.float32),
        ],
        scratch_shapes=[
            _scratch((block_q, d)),  # output accumulator
            _scratch((block_q, _LANES)),  # running max
            _scratch((block_q, _LANES)),  # running normaliser
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc, *, sq, sk, block_q, block_k, causal, scale, window):
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)
    offset = sk - sq
    q_start, k_start = qi * block_q, ki * block_k

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = _block_live(q_start, k_start, block_q, block_k, offset, causal, window)

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) * scale
        valid = _mask(sq, sk, q_start, k_start, block_q, block_k, causal, window)
        lse = lse_ref[0, 0][:, :1]
        lse_safe = jnp.where(jnp.isfinite(lse), lse, 0.0)
        p = jnp.where(valid & jnp.isfinite(lse), jnp.exp(s - lse_safe), 0.0)
        do = do_ref[0, 0]
        v = v_ref[0, 0]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        delta = delta_ref[0, 0][:, :1]
        ds = (p * (dp - delta) * scale).astype(k.dtype)
        dq_acc[:] += jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_acc[:]


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_acc, dv_acc, *, sq, sk, block_q, block_k, causal, scale, window):
    ki, qi = pl.program_id(2), pl.program_id(3)
    nq = pl.num_programs(3)
    offset = sk - sq
    q_start, k_start = qi * block_q, ki * block_k

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = _block_live(q_start, k_start, block_q, block_k, offset, causal, window)

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) * scale
        valid = _mask(sq, sk, q_start, k_start, block_q, block_k, causal, window)
        lse = lse_ref[0, 0][:, :1]
        lse_safe = jnp.where(jnp.isfinite(lse), lse, 0.0)
        p = jnp.where(valid & jnp.isfinite(lse), jnp.exp(s - lse_safe), 0.0)
        do = do_ref[0, 0]
        v = v_ref[0, 0]
        pt = p.astype(do.dtype)
        dv_acc[:] += jax.lax.dot_general(pt, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        delta = delta_ref[0, 0][:, :1]
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        dk_acc[:] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_acc[:]
        dv_ref[0, 0] = dv_acc[:]


def _run_bwd(q, k, v, out, lse, do, sq, sk, causal, scale, block_q, block_k, interpret, window=None):
    b, h, sqp, d = q.shape
    h_kv, skp = k.shape[1], k.shape[2]
    g = h // h_kv
    nq, nk = sqp // block_q, skp // block_k

    # delta_i = Σ_d dout_i · out_i (the softmax-jacobian row term); cheap
    # elementwise reduce — XLA fuses it, no kernel needed.
    delta = jnp.einsum("bhqd,bhqd->bhq", do.astype(jnp.float32), out.astype(jnp.float32))
    delta = jnp.broadcast_to(delta[..., None], (b, h, sqp, _STAT_LANES))

    static = dict(sq=sq, sk=sk, block_q=block_q, block_k=block_k, causal=causal, scale=scale, window=window)
    q_spec = _vmem_spec((1, 1, block_q, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0))
    kv_spec = _vmem_spec((1, 1, block_k, d), lambda b_, h_, qi, ki: (b_, h_ // g, ki, 0))
    row_spec = _vmem_spec((1, 1, block_q, _STAT_LANES), lambda b_, h_, qi, ki: (b_, h_, qi, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, **static),
        grid=(b, h, nq, nk),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=[q_spec],
        out_shape=[jax.ShapeDtypeStruct((b, h, sqp, d), jnp.float32)],
        scratch_shapes=[_scratch((block_q, d))],
        interpret=interpret,
    )(q, k, v, do, lse, delta)[0]

    # dk/dv: grid transposed so Q blocks are innermost; GQA groups are
    # accumulated per-query-head then summed below (reads stay unexpanded).
    q_spec_t = _vmem_spec((1, 1, block_q, d), lambda b_, h_, ki, qi: (b_, h_, qi, 0))
    kv_spec_t = _vmem_spec((1, 1, block_k, d), lambda b_, h_, ki, qi: (b_, h_ // g, ki, 0))
    kv_out_t = _vmem_spec((1, 1, block_k, d), lambda b_, h_, ki, qi: (b_, h_, ki, 0))
    row_spec_t = _vmem_spec((1, 1, block_q, _STAT_LANES), lambda b_, h_, ki, qi: (b_, h_, qi, 0))
    dk_full, dv_full = pl.pallas_call(
        functools.partial(_dkv_kernel, **static),
        grid=(b, h, nk, nq),
        in_specs=[q_spec_t, kv_spec_t, kv_spec_t, q_spec_t, row_spec_t, row_spec_t],
        out_specs=[kv_out_t, kv_out_t],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, skp, d), jnp.float32),
            jax.ShapeDtypeStruct((b, h, skp, d), jnp.float32),
        ],
        scratch_shapes=[_scratch((block_k, d)), _scratch((block_k, d))],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    if g > 1:  # GQA: sum query-head contributions within each KV group
        dk = dk_full.reshape(b, h_kv, g, skp, d).sum(axis=2)
        dv = dv_full.reshape(b, h_kv, g, skp, d).sum(axis=2)
    else:
        dk, dv = dk_full, dv_full
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-VJP wrapper
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4, 5, 6, 7))
def _flash(causal, scale, block_q, block_k, interpret, sq, sk, window, q, k, v):
    out, _ = _run_fwd(q, k, v, sq, sk, causal, scale, block_q, block_k, interpret, window)
    return out


def _flash_fwd(causal, scale, block_q, block_k, interpret, sq, sk, window, q, k, v):
    out, lse = _run_fwd(q, k, v, sq, sk, causal, scale, block_q, block_k, interpret, window)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, sq, sk, window, residuals, do):
    q, k, v, out, lse = residuals
    dq, dk, dv = _run_bwd(q, k, v, out, lse, do, sq, sk, causal, scale, block_q, block_k, interpret, window)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _pad_seq(x, multiple):
    """Pad the sequence axis (dim 2 of [B,H,S,D]) to a block multiple."""
    pad = (-x.shape[2]) % multiple
    if pad:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))
    return x


def pallas_flash_attention(
    q: jax.Array,  # [B, Sq, H, D]
    k: jax.Array,  # [B, Sk, H_kv, D]
    v: jax.Array,  # [B, Sk, H_kv, D]
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: Optional[bool] = None,
    window: Optional[int] = None,
) -> jax.Array:
    """Flash attention on the Pallas TPU kernel. Same contract as
    :func:`accelerate_tpu.ops.flash_attention.flash_attention`: GQA when
    ``H_kv`` divides ``H``, bottom-right-aligned causal masking for
    ``Sq != Sk``, output ``[B, Sq, H, D]`` in ``q.dtype``. ``window``
    (requires ``causal``) adds the Mistral sliding-window band and skips
    K blocks entirely below it — O(S*W) work instead of O(S^2)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if window is not None and not causal:
        raise ValueError("window requires causal=True (sliding-window is a causal band)")
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1 (got {window}); a 0-width band masks everything")
    sq, sk = q.shape[1], k.shape[1]
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    block_q = min(block_q, _pow2_ge(sq))
    block_k = min(block_k, _pow2_ge(sk))
    qt = _pad_seq(q.transpose(0, 2, 1, 3), block_q)
    kt = _pad_seq(k.transpose(0, 2, 1, 3), block_k)
    vt = _pad_seq(v.transpose(0, 2, 1, 3), block_k)
    out = _flash(causal, float(scale), block_q, block_k, interpret, sq, sk, window, qt, kt, vt)
    return out[:, :, :sq].transpose(0, 2, 1, 3)


def _pow2_ge(n: int) -> int:
    """Smallest power of two >= n, floored at the fp32 sublane tile (8)."""
    p = 8
    while p < n:
        p *= 2
    return p
