"""Pallas TPU kernel: fused 4-bit dequantize + matmul for decode.

Why a kernel: XLA will not fuse the nibble unpack + codebook/affine decode
into the consuming matmul — it materialises a full-precision copy of the
weight per call, so 4-bit decode runs SLOWER than bf16 (measured 33.8
ms/token vs 6.0 on a 1.1B llama on v5e, and the nf4 gather path crashes
the worker outright). Here the packed bytes are the only HBM traffic:
each grid cell DMAs one ``[g/2, N_TILE]`` uint8 block into VMEM, decodes
in-register, and feeds the MXU.

Layout trick: ``_pack4`` stores code pairs ``(2r, 2r+1)`` in byte row
``r`` (lo/hi nibble). Rather than re-interleaving rows in-kernel, split
the activation once on the host side: ``out = x_even @ W_lo + x_odd @
W_hi`` — two matmuls against the nibble planes, no shuffles.

Scope: linear int4 codes (``(code-8) * scale``) with one scale group per
grid chunk (``group_size`` in {64, 128, 256, 512}); nf4's irregular
codebook would need a 15-select decode tree per element, which is
VPU-bound — grouped int4 matches its accuracy envelope closely and stays
bandwidth-bound. Other configs fall back to the XLA path in QuantDense.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

MAX_N_TILE = 512  # preferred lanes per out tile (multiple of 128)


def _n_tile(out_features: int) -> int:
    for t in (MAX_N_TILE, 256, 128):
        if out_features % t == 0:
            return t
    raise ValueError(f"out dim {out_features} must divide by 128")


def _int4_matmul_kernel(x_even_ref, x_odd_ref, packed_ref, scale_ref, out_ref, *, chunk: int):
    """One grid cell: ``chunk`` groups x one out tile. Groups are an
    unrolled static loop so the accumulator stays in registers — revisiting
    the f32 out block once per GROUP would move more HBM bytes than the
    packed weights themselves."""

    @pl.when(pl.program_id(1) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    acc = jnp.zeros_like(out_ref)
    for c in range(chunk):
        # dequant is VPU-bound, so keep it to ~5 ops/byte: matmul the RAW
        # 4-bit codes (exact in bf16) and fold the -8 zero-point and the
        # per-group scale into per-dot corrections —
        #   sum_r x_r*(c_r - 8)*s = s*(sum_r x_r*c_r) - 8*s*(sum_r x_r)
        packed = packed_ref[c].astype(jnp.int32)  # Mosaic lacks u8->f32
        lo = (packed & 0x0F).astype(jnp.bfloat16)  # [g/2, N]
        hi = (packed >> 4).astype(jnp.bfloat16)
        partial = jax.lax.dot_general(
            x_even_ref[c], lo, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        partial += jax.lax.dot_general(
            x_odd_ref[c], hi, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        xsum = jnp.sum(
            (x_even_ref[c] + x_odd_ref[c]).astype(jnp.float32), axis=1, keepdims=True
        )  # [B, 1]
        acc += (partial - 8.0 * xsum) * scale_ref[c]
    out_ref[:] += acc


@functools.partial(jax.jit, static_argnames=("group_size", "interpret"))
def int4_matmul(
    x: jax.Array, packed: jax.Array, scale: jax.Array, *, group_size: int, interpret: bool = False
) -> jax.Array:
    """``x [B, in] @ dequant(packed [in/g, g/2, out], scale [in/g, 1, out])``.

    Returns ``[B, out]`` in ``x.dtype``. ``in`` must divide by
    ``group_size``; ``out`` by ``N_TILE``; ``group_size`` by 64 (the uint8
    sublane tile is 32).
    """
    from jax.experimental.pallas import tpu as pltpu

    b, in_features = x.shape
    n_groups, half_g, out_features = packed.shape
    g = group_size
    if half_g != g // 2 or n_groups * g != in_features:
        raise ValueError(f"packed shape {packed.shape} inconsistent with in={in_features}, group={g}")
    if g % 64 != 0:
        raise ValueError(f"group_size must be a multiple of 64, got {g}")
    n_tile = _n_tile(out_features)

    # pad batch to the f32 sublane tile so tiny decode batches map cleanly
    b_pad = max(8, -(-b // 8) * 8)
    if b_pad != b:
        x = jnp.pad(x, ((0, b_pad - b), (0, 0)))
    # group-major activations: block trailing dims equal the array's
    # trailing dims (a Pallas lowering requirement when they aren't
    # 128-multiples), so the group index is a LEADING blocked dim
    xg = x.astype(jnp.bfloat16).reshape(b_pad, n_groups, g).transpose(1, 0, 2)
    xe = xg[:, :, 0::2]  # [n_g, B, g/2]: rows matching lo nibbles
    xo = xg[:, :, 1::2]

    chunk = 1
    for c in (8, 4, 2):
        if n_groups % c == 0:
            chunk = c
            break
    grid = (out_features // n_tile, n_groups // chunk)
    out = pl.pallas_call(
        functools.partial(_int4_matmul_kernel, chunk=chunk),
        out_shape=jax.ShapeDtypeStruct((b_pad, out_features), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((chunk, b_pad, half_g), lambda j, k: (k, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((chunk, b_pad, half_g), lambda j, k: (k, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((chunk, half_g, n_tile), lambda j, k: (k, 0, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((chunk, 1, n_tile), lambda j, k: (k, 0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((b_pad, n_tile), lambda j, k: (0, j), memory_space=pltpu.VMEM),
        interpret=interpret,
    )(xe, xo, packed, scale)
    return out[:b].astype(x.dtype)


def pallas_int4_supported(x, method: str, group_size, n_groups: int, features: int) -> bool:
    """Static eligibility check used by QuantDense at trace time."""
    if method != "int4" or group_size is None or group_size % 64 != 0:
        return False
    if features % 128 != 0:
        return False
    if x.ndim < 1 or jax.default_backend() != "tpu":
        return False
    return True
