"""Hot ops: attention dispatch (Pallas flash kernel on TPU, lax reference
elsewhere), fp8 scaled matmuls, MoE routing."""

from .attention import dot_product_attention
