from .attention import dot_product_attention
