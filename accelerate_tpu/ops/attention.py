"""Attention ops: XLA reference implementation + Pallas flash-attention
dispatch.

The reference framework has no attention kernels at all (it delegates to
torch models); this module exists because the build is a *framework with a
model zoo* and attention is the hot op. Dispatch policy:

* small/medium sequence or non-TPU backend -> plain XLA einsum attention
  (XLA fuses the softmax chain well);
* long sequence on TPU -> Pallas flash attention
  (:mod:`accelerate_tpu.ops.flash_attention`), O(S) memory;
* ``seq``-sharded activations -> ring attention
  (:mod:`accelerate_tpu.parallel.ring_attention`).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

# Below this many query positions the quadratic XLA path is faster than the
# Pallas kernel's grid overhead (empirical on v5e; see bench notes).
FLASH_MIN_SEQ = 1024


def dot_product_attention(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,  # [B, S, H_kv, D]
    v: jax.Array,  # [B, S, H_kv, D]
    mask: Optional[jax.Array] = None,  # bool, broadcastable to [B, H, Sq, Sk]
    causal: bool = False,
    scale: Optional[float] = None,
    use_flash: Optional[bool] = None,
) -> jax.Array:
    """Multi-head attention with optional GQA (H_kv divides H) and
    flash-kernel dispatch. Returns [B, S, H, D]."""
    head_dim = q.shape[-1]
    scale = scale if scale is not None else head_dim**-0.5
    seq_len = q.shape[1]

    if use_flash is None:
        use_flash = (
            jax.default_backend() == "tpu"
            and seq_len >= FLASH_MIN_SEQ
            and mask is None  # kernel supports causal masking only
        )
    if use_flash:
        from .flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal, scale=scale)

    num_heads, num_kv = q.shape[-2], k.shape[-2]
    if num_kv != num_heads:  # GQA: repeat kv groups
        reps = num_heads // num_kv
        k = jnp.repeat(k, reps, axis=-2)
        v = jnp.repeat(v, reps, axis=-2)

    # [B,S,H,D] -> [B,H,Sq,Sk]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    logits = logits.astype(jnp.float32)
    if causal:
        q_pos = jnp.arange(seq_len)[:, None]
        k_pos = jnp.arange(k.shape[1])[None, :]
        causal_mask = q_pos >= k_pos
        logits = jnp.where(causal_mask[None, None], logits, -jnp.inf)
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    weights = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)
