"""Attention ops: XLA reference implementation + Pallas flash-attention
dispatch.

The reference framework has no attention kernels at all (it delegates to
torch models); this module exists because the build is a *framework with a
model zoo* and attention is the hot op. Dispatch policy:

* small/medium sequence or non-TPU backend -> plain XLA einsum attention
  (XLA fuses the softmax chain well);
* long sequence on TPU -> Pallas flash attention
  (:mod:`accelerate_tpu.ops.flash_attention`), O(S) memory;
* ``seq``-sharded activations -> ring attention
  (:mod:`accelerate_tpu.parallel.ring_attention`).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

# Below this many query positions the quadratic XLA path is faster than the
# Pallas kernel's grid overhead. Measured on v5e (fwd+bwd, batch 4 x 12
# heads x 64 dim, value-fetch sync): seq 1024 flash is 0.86x XLA, seq 2048
# flash is 1.82x — the crossover sits between them.
FLASH_MIN_SEQ = 2048


def dot_product_attention(
    q: jax.Array,  # [B, Sq, H, D]
    k: jax.Array,  # [B, Sk, H_kv, D]
    v: jax.Array,  # [B, Sk, H_kv, D]
    mask: Optional[jax.Array] = None,  # bool, broadcastable to [B, H, Sq, Sk]
    causal: bool = False,
    scale: Optional[float] = None,
    use_flash: Optional[bool] = None,
    dropout_rate: float = 0.0,
    dropout_rng=None,
    mesh=None,  # pin the mesh for the sharded pallas path (else read from state at trace time)
    window: Optional[int] = None,  # Mistral band: keys <= q_pos - window are masked
    logit_softcap: Optional[float] = None,  # Gemma2: tanh-bound scores (XLA path only)
) -> jax.Array:
    """Multi-head attention with optional GQA (H_kv divides H) and
    flash-kernel dispatch. Causal masking is bottom-right aligned when
    Sq != Sk (decode/chunked attention: query i attends keys
    ``0..Sk-Sq+i``). ``window`` adds the sliding-window band (requires
    ``causal``); on TPU at flash lengths it runs the banded kernel —
    O(S*W) — else the band folds into the XLA mask. Returns
    [B, Sq, H, D]."""
    head_dim = q.shape[-1]
    scale = scale if scale is not None else head_dim**-0.5
    seq_len = q.shape[1]
    if window is not None and not causal:
        raise ValueError("window requires causal=True (sliding-window is a causal band)")
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1 (got {window}); a 0-width band masks everything")

    explicit_flash = use_flash is not None
    if use_flash is None:
        use_flash = (
            jax.default_backend() == "tpu"
            and seq_len >= FLASH_MIN_SEQ
            and mask is None  # kernel supports causal/banded masking only
            and dropout_rate == 0.0
            and logit_softcap is None  # the kernel has no tanh-cap branch
        )
    if use_flash and logit_softcap is not None:
        raise ValueError("logit_softcap runs on the XLA path only; drop use_flash=True")
    if explicit_flash and use_flash and window is not None and jax.default_backend() != "tpu":
        # the scan fallback has no band support: refuse the explicit
        # request (consistent with the mask/dropout guards below). The
        # auto path never picks flash off-TPU, so it needs no fallback.
        raise ValueError("banded flash (window=) runs on the TPU kernel only; drop use_flash=True off-TPU")
    if use_flash:
        if mask is not None:
            raise ValueError(
                "flash attention supports causal (optionally banded via window=) masking only; "
                "pass mask=None or use_flash=False"
            )
        if dropout_rate > 0.0 and dropout_rng is not None:
            raise ValueError("flash attention does not support attention-prob dropout; use_flash=False")
        if jax.default_backend() == "tpu":
            return sharded_pallas_attention(q, k, v, causal=causal, scale=scale, mesh=mesh, window=window)
        from .flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal, scale=scale)

    if window is not None:
        s = seq_len
        q_pos = jnp.arange(s)[:, None] + (k.shape[1] - s)
        band = (jnp.arange(k.shape[1])[None, :] > q_pos - window)[None, None]
        mask = band if mask is None else (mask & band)
    return _xla_attention(
        q, k, v, mask, causal, scale, dropout_rate, dropout_rng, _softmax_dtype(),
        logit_softcap=logit_softcap,
    )


def softcap(x: jax.Array, cap) -> jax.Array:
    """Gemma2 logit softcapping: ``tanh(x / cap) * cap`` in ``x``'s dtype —
    the ONE definition shared by the XLA attention path, the KV-cache
    decode path, and the final-logits head."""
    c = jnp.asarray(cap, x.dtype)
    return jnp.tanh(x / c) * c


def _softmax_dtype():
    """The policy's attention-softmax dtype (trace-time read; None = f32).
    Opt-in bandwidth lever: the f32 [B, H, S, S] logits materialisation is
    the HBM-bound training step's biggest avoidable traffic
    (MixedPrecisionPolicy.softmax_dtype)."""
    from ..state import AcceleratorState

    state = AcceleratorState._shared_state
    policy = state.get("dtype_policy") if state.get("_initialized") else None
    return getattr(policy, "softmax_dtype", None)


def active_mesh():
    """The mesh model code should trace against: a ``mesh_context``
    override (generation.py pins the params' mesh there) wins over the
    Accelerator singleton's mesh; None when neither is set."""
    from ..parallel.sharding import context_mesh

    mesh = context_mesh()
    if mesh is not None:
        return mesh
    from ..state import AcceleratorState

    state = AcceleratorState._shared_state
    return state.get("mesh") if state.get("_initialized") else None


def sharded_pallas_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: Optional[float] = None,
    mesh=None,
    interpret: Optional[bool] = None,
    window: Optional[int] = None,
) -> jax.Array:
    """Pallas flash attention that stays partitioned under GSPMD.

    ``pallas_call`` lowers to an opaque custom call, so jitting it directly
    on sharded activations makes XLA all-gather q/k/v and replicate the
    output (mesh-size multiple of memory + FLOPs). Attention is independent
    per batch element and per head, so we wrap the kernel in ``shard_map``
    over the batch (``data``/``fsdp``) and head (``tensor``) axes of the
    active mesh — each device runs the kernel on exactly its local block and
    no collective is emitted. Falls back to the bare kernel when no
    non-trivial mesh is active or shapes don't divide."""
    import functools

    from .pallas_attention import pallas_flash_attention

    kernel = functools.partial(
        pallas_flash_attention, causal=causal, scale=scale, interpret=interpret, window=window
    )
    # Already inside a shard_map region (e.g. the GPipe trunk): inputs are
    # per-shard blocks and axes are Manual — nesting another shard_map over
    # the same mesh is an error; the bare kernel is exactly right here.
    from ..utils.compat import in_manual_region, shard_map

    if in_manual_region():
        return kernel(q, k, v)
    if mesh is None:
        # NOTE: resolved at trace time — a forward traced before the
        # Accelerator initialises bakes in the unsharded path (pass ``mesh``
        # explicitly to pin it; model code in models/ does).
        mesh = active_mesh()
    if mesh is None:
        return kernel(q, k, v)

    from ..parallel.mesh import BATCH_AXES, axis_size, axis_spec

    bspec = axis_spec(mesh, BATCH_AXES)
    hspec = axis_spec(mesh, "tensor")
    n_b, n_h = axis_size(mesh, BATCH_AXES), axis_size(mesh, "tensor")
    divisible = (
        q.shape[0] % n_b == 0
        and q.shape[2] % n_h == 0
        and k.shape[2] % n_h == 0  # GQA: kv heads must split the same way
    )
    if (bspec is None and hspec is None) or not divisible:
        return kernel(q, k, v)
    from jax.sharding import PartitionSpec as P

    spec = P(bspec, None, hspec, None)
    fn = shard_map(kernel, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False)
    return fn(q, k, v)


def _xla_attention(
    q, k, v, mask, causal, scale, dropout_rate, dropout_rng, softmax_dtype=None, logit_softcap=None
):
    seq_len = q.shape[1]
    num_heads, num_kv = q.shape[-2], k.shape[-2]
    if num_kv != num_heads:  # GQA: repeat kv groups
        reps = num_heads // num_kv
        k = jnp.repeat(k, reps, axis=-2)
        v = jnp.repeat(v, reps, axis=-2)

    # [B,S,H,D] -> [B,H,Sq,Sk]. precision="highest": JAX's DEFAULT matmul
    # precision decomposes fp32 operands to bf16 passes (on TPU MXU and on
    # the oneDNN CPU backend), injecting ~1e-3 relative error into the
    # logits — enough to break fp32 parity with reference implementations.
    # bf16 operands are a single MXU pass either way, so the bf16 training
    # path is not slowed.
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, precision="highest") * scale
    # f32 softmax math by default; an explicit policy softmax_dtype (e.g.
    # bfloat16) skips the f32 [B, H, Sq, Sk] materialisation — the
    # HBM-bound step's biggest avoidable traffic (1.10x measured on the
    # BERT v5e step; MixedPrecisionPolicy.softmax_dtype)
    sm_dtype = jnp.dtype(softmax_dtype) if softmax_dtype is not None else jnp.float32
    logits = logits.astype(sm_dtype)
    if logit_softcap is not None:
        # Gemma2 attention softcapping: tanh-bound the scores BEFORE the
        # mask (HF order), keeping gradients finite at long context
        logits = softcap(logits, logit_softcap)
    if causal:
        offset = k.shape[1] - seq_len  # bottom-right alignment
        q_pos = jnp.arange(seq_len)[:, None] + offset
        k_pos = jnp.arange(k.shape[1])[None, :]
        causal_mask = q_pos >= k_pos
        logits = jnp.where(causal_mask[None, None], logits, -jnp.inf)
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(sm_dtype).min)
    weights = jax.nn.softmax(logits, axis=-1)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, weights.shape)
        weights = jnp.where(keep, weights / (1.0 - dropout_rate), 0.0)
    weights = weights.astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v, precision="highest")
