"""Attention ops: XLA reference implementation + Pallas flash-attention
dispatch.

The reference framework has no attention kernels at all (it delegates to
torch models); this module exists because the build is a *framework with a
model zoo* and attention is the hot op. Dispatch policy:

* small/medium sequence or non-TPU backend -> plain XLA einsum attention
  (XLA fuses the softmax chain well);
* long sequence on TPU -> Pallas flash attention
  (:mod:`accelerate_tpu.ops.flash_attention`), O(S) memory;
* ``seq``-sharded activations -> ring attention
  (:mod:`accelerate_tpu.parallel.ring_attention`).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

# Below this many query positions the quadratic XLA path is faster than the
# Pallas kernel's grid overhead (empirical on v5e; see bench notes).
FLASH_MIN_SEQ = 1024


def dot_product_attention(
    q: jax.Array,  # [B, Sq, H, D]
    k: jax.Array,  # [B, Sk, H_kv, D]
    v: jax.Array,  # [B, Sk, H_kv, D]
    mask: Optional[jax.Array] = None,  # bool, broadcastable to [B, H, Sq, Sk]
    causal: bool = False,
    scale: Optional[float] = None,
    use_flash: Optional[bool] = None,
    dropout_rate: float = 0.0,
    dropout_rng=None,
) -> jax.Array:
    """Multi-head attention with optional GQA (H_kv divides H) and
    flash-kernel dispatch. Causal masking is bottom-right aligned when
    Sq != Sk (decode/chunked attention: query i attends keys
    ``0..Sk-Sq+i``). Returns [B, Sq, H, D]."""
    head_dim = q.shape[-1]
    scale = scale if scale is not None else head_dim**-0.5
    seq_len = q.shape[1]

    if use_flash is None:
        use_flash = (
            jax.default_backend() == "tpu"
            and seq_len >= FLASH_MIN_SEQ
            and mask is None  # kernel supports causal masking only
            and dropout_rate == 0.0
        )
    if use_flash:
        if mask is not None:
            raise ValueError("flash attention supports causal masking only; pass mask=None or use_flash=False")
        if dropout_rate > 0.0 and dropout_rng is not None:
            raise ValueError("flash attention does not support attention-prob dropout; use_flash=False")
        if jax.default_backend() == "tpu":
            from .pallas_attention import pallas_flash_attention

            return pallas_flash_attention(q, k, v, causal=causal, scale=scale)
        from .flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal, scale=scale)

    num_heads, num_kv = q.shape[-2], k.shape[-2]
    if num_kv != num_heads:  # GQA: repeat kv groups
        reps = num_heads // num_kv
        k = jnp.repeat(k, reps, axis=-2)
        v = jnp.repeat(v, reps, axis=-2)

    # [B,S,H,D] -> [B,H,Sq,Sk]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    logits = logits.astype(jnp.float32)
    if causal:
        offset = k.shape[1] - seq_len  # bottom-right alignment
        q_pos = jnp.arange(seq_len)[:, None] + offset
        k_pos = jnp.arange(k.shape[1])[None, :]
        causal_mask = q_pos >= k_pos
        logits = jnp.where(causal_mask[None, None], logits, -jnp.inf)
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    weights = jax.nn.softmax(logits, axis=-1)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, weights.shape)
        weights = jnp.where(keep, weights / (1.0 - dropout_rate), 0.0)
    weights = weights.astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)
