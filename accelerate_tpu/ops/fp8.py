"""FP8 training path: scaled e4m3/e5m2 matmuls behind a policy switch.

TPU-native collapse of the reference's three fp8 backends
(TransformerEngine: src/accelerate/utils/transformer_engine.py:26-163,
torchao: utils/ao.py:104-140, MS-AMP): instead of swapping ``nn.Linear``
modules for backend-specific ones, every ``nn.Dense`` in the model zoo takes
its ``dot_general`` from :func:`policy_dot_general` — ``lax.dot_general``
normally, :func:`fp8_dot_general` when ``mixed_precision="fp8"``.

Recipe (the TE "hybrid" default): forward activations/weights quantized
per-tensor to e4m3, gradients to e5m2, fp32 accumulation, dynamic (amax)
scaling. Scales are constants w.r.t. autodiff (custom VJP), matching TE's
non-differentiable scale bookkeeping.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..utils.quantization import fp8_quantize as _quantize


@jax.custom_vjp
def _fp8_matmul(lhs: jax.Array, rhs: jax.Array) -> jax.Array:
    """``lhs[..., K] @ rhs[K, N]`` with e4m3 inputs, fp32 accumulation."""
    l8, sl = _quantize(lhs, jnp.float8_e4m3fn)
    r8, sr = _quantize(rhs, jnp.float8_e4m3fn)
    y = jax.lax.dot_general(
        l8, r8, (((lhs.ndim - 1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    return (y * (sl * sr)).astype(lhs.dtype)


def _fp8_matmul_fwd(lhs, rhs):
    return _fp8_matmul(lhs, rhs), (lhs, rhs)


def _fp8_matmul_bwd(res, g):
    lhs, rhs = res
    g8, sg = _quantize(g, jnp.float8_e5m2)  # gradients in e5m2 (TE hybrid)
    r8, sr = _quantize(rhs, jnp.float8_e4m3fn)
    l8, sl = _quantize(lhs, jnp.float8_e4m3fn)
    # dlhs[..., K] = g[..., N] @ rhs.T[N, K]
    dlhs = jax.lax.dot_general(
        g8, r8, (((g.ndim - 1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * (sg * sr)
    # drhs[K, N] = lhs.T[K, B] @ g[B, N] with batch dims flattened
    k, n = rhs.shape
    l2 = l8.reshape(-1, k)
    g2 = g8.reshape(-1, n)
    drhs = jax.lax.dot_general(
        l2, g2, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ) * (sl * sg)
    return dlhs.astype(lhs.dtype), drhs.astype(rhs.dtype)


_fp8_matmul.defvjp(_fp8_matmul_fwd, _fp8_matmul_bwd)


def fp8_dot_general(lhs, rhs, dimension_numbers, precision=None, preferred_element_type=None):
    """Drop-in ``lax.dot_general`` for the ``nn.Dense`` contraction pattern
    (last dim of lhs x first dim of rhs, no batch dims). Other patterns fall
    back to the plain dot — same behavior as the reference converting only
    ``Linear`` layers (utils/transformer_engine.py:41)."""
    ((lc, rc), (lb, rb)) = dimension_numbers
    if tuple(lc) == (lhs.ndim - 1,) and tuple(rc) == (0,) and not lb and not rb and rhs.ndim == 2:
        return _fp8_matmul(lhs, rhs)
    return jax.lax.dot_general(
        lhs, rhs, dimension_numbers, precision=precision,
        preferred_element_type=preferred_element_type,
    )


def fp8_enabled() -> bool:
    """True when the active Accelerator's dtype policy requests fp8."""
    from ..state import AcceleratorState

    state = AcceleratorState._shared_state
    if not state.get("_initialized"):
        return False
    policy = state.get("dtype_policy")
    return bool(policy is not None and getattr(policy, "fp8", False))


def fp8_recipe():
    """The active :class:`~accelerate_tpu.utils.dataclasses.Fp8RecipeKwargs`
    (None when fp8 is off)."""
    from ..state import AcceleratorState

    if not fp8_enabled():
        return None
    policy = AcceleratorState._shared_state.get("dtype_policy")
    recipe = getattr(policy, "fp8_recipe", None)
    if recipe is None:
        from ..utils.dataclasses import Fp8RecipeKwargs

        recipe = Fp8RecipeKwargs()
    return recipe


# --------------------------------------------------------------------------- #
# delayed (amax-history) scaling — the TE "DelayedScaling" recipe
# --------------------------------------------------------------------------- #

E4M3_MAX = 448.0


@jax.custom_vjp
def _fp8_delayed_matmul(lhs, rhs, scale_l, scale_r):
    """``lhs @ rhs`` quantized with PRE-COMPUTED scales (from the amax
    history), e4m3 forward / fp32 accumulation. Out-of-range values clip —
    the history absorbs the new amax so the next step's scale adapts."""
    l8 = jnp.clip(lhs.astype(jnp.float32) * scale_l, -E4M3_MAX, E4M3_MAX).astype(jnp.float8_e4m3fn)
    r8 = jnp.clip(rhs.astype(jnp.float32) * scale_r, -E4M3_MAX, E4M3_MAX).astype(jnp.float8_e4m3fn)
    y = jax.lax.dot_general(
        l8, r8, (((lhs.ndim - 1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    return (y / (scale_l * scale_r)).astype(lhs.dtype)


def _fp8_delayed_fwd(lhs, rhs, scale_l, scale_r):
    return _fp8_delayed_matmul(lhs, rhs, scale_l, scale_r), (lhs, rhs)


def _fp8_delayed_bwd(res, g):
    # gradients keep the dynamic e5m2 path (grad magnitudes move too fast
    # for a useful history; TE's hybrid format choice)
    lhs, rhs = res
    dlhs, drhs = _fp8_matmul_bwd((lhs, rhs), g)
    return dlhs, drhs, None, None


_fp8_delayed_matmul.defvjp(_fp8_delayed_fwd, _fp8_delayed_bwd)


def scale_from_history(history: jax.Array, margin: int = 0, algo: str = "max") -> jax.Array:
    """TE DelayedScaling: ``scale = fmax / (amax * 2**margin)`` with amax
    taken over the rolling history (or its newest entry). A zero amax —
    unwarmed history slots, or an all-zero tensor (the init dummy input) —
    yields the neutral scale 1.0 rather than a ~1e14 blowup that clips
    everything on the first real step."""
    amax = jnp.max(history) if algo == "max" else history[0]
    return jnp.where(amax > 0, E4M3_MAX / (jnp.maximum(amax, 1e-30) * (2.0**margin)), 1.0).astype(
        jnp.float32
    )


class FP8Dense(nn.Module):
    """``nn.Dense`` with TE-style delayed-scaling fp8 matmul.

    The per-tensor amax histories live in a flax ``fp8`` collection (one
    rolling [H] buffer each for the activation and the kernel), so they
    stack per layer under ``nn.scan`` and thread through the train step as
    ``model.state`` (``build_train_step(has_state=True)``). Step k
    quantizes with scales derived from steps < k — the hot path has no
    serial dependency on the current tensor's amax reduction."""

    features: int
    use_bias: bool = False
    dtype: Any = None
    amax_history_len: int = 16
    amax_compute_algo: str = "max"
    margin: int = 0

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(), (x.shape[-1], self.features), jnp.float32
        )
        # zero-filled histories: unwarmed slots are neutral under both amax
        # algos (scale_from_history maps zero amax to scale 1.0), unlike a
        # ones-fill which pins the scale wrong while true amax < 1
        hist_x = self.variable("fp8", "amax_history_x", jnp.zeros, (self.amax_history_len,), jnp.float32)
        hist_k = self.variable("fp8", "amax_history_k", jnp.zeros, (self.amax_history_len,), jnp.float32)
        dtype = self.dtype or x.dtype
        x = x.astype(dtype)
        kernel = kernel.astype(dtype)
        scale_x = scale_from_history(hist_x.value, self.margin, self.amax_compute_algo)
        scale_k = scale_from_history(hist_k.value, self.margin, self.amax_compute_algo)
        y = _fp8_delayed_matmul(x, kernel, scale_x, scale_k)
        # roll the current amaxes into the histories (stop_gradient: scale
        # bookkeeping is not differentiated, matching TE)
        amax_x = jnp.max(jnp.abs(jax.lax.stop_gradient(x))).astype(jnp.float32)
        amax_k = jnp.max(jnp.abs(jax.lax.stop_gradient(kernel))).astype(jnp.float32)
        hist_x.value = jnp.concatenate([amax_x[None], hist_x.value[:-1]])
        hist_k.value = jnp.concatenate([amax_k[None], hist_k.value[:-1]])
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, (self.features,), jnp.float32)
            y = y + bias.astype(dtype)
        return y


def policy_dot_general():
    """The ``dot_general`` the model zoo passes to every ``nn.Dense``.
    Resolved at trace time (module ``__call__``), so the choice is burned
    into the jitted program — set ``mixed_precision`` before building the
    train step."""
    return fp8_dot_general if fp8_enabled() else jax.lax.dot_general
