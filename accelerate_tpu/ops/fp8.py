"""FP8 training path: scaled e4m3/e5m2 matmuls behind a policy switch.

TPU-native collapse of the reference's three fp8 backends
(TransformerEngine: src/accelerate/utils/transformer_engine.py:26-163,
torchao: utils/ao.py:104-140, MS-AMP): instead of swapping ``nn.Linear``
modules for backend-specific ones, every ``nn.Dense`` in the model zoo takes
its ``dot_general`` from :func:`policy_dot_general` — ``lax.dot_general``
normally, :func:`fp8_dot_general` when ``mixed_precision="fp8"``.

Recipe (the TE "hybrid" default): forward activations/weights quantized
per-tensor to e4m3, gradients to e5m2, fp32 accumulation, dynamic (amax)
scaling. Scales are constants w.r.t. autodiff (custom VJP), matching TE's
non-differentiable scale bookkeeping.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..utils.quantization import fp8_quantize as _quantize


@jax.custom_vjp
def _fp8_matmul(lhs: jax.Array, rhs: jax.Array) -> jax.Array:
    """``lhs[..., K] @ rhs[K, N]`` with e4m3 inputs, fp32 accumulation."""
    l8, sl = _quantize(lhs, jnp.float8_e4m3fn)
    r8, sr = _quantize(rhs, jnp.float8_e4m3fn)
    y = jax.lax.dot_general(
        l8, r8, (((lhs.ndim - 1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    return (y * (sl * sr)).astype(lhs.dtype)


def _fp8_matmul_fwd(lhs, rhs):
    return _fp8_matmul(lhs, rhs), (lhs, rhs)


def _fp8_matmul_bwd(res, g):
    lhs, rhs = res
    g8, sg = _quantize(g, jnp.float8_e5m2)  # gradients in e5m2 (TE hybrid)
    r8, sr = _quantize(rhs, jnp.float8_e4m3fn)
    l8, sl = _quantize(lhs, jnp.float8_e4m3fn)
    # dlhs[..., K] = g[..., N] @ rhs.T[N, K]
    dlhs = jax.lax.dot_general(
        g8, r8, (((g.ndim - 1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * (sg * sr)
    # drhs[K, N] = lhs.T[K, B] @ g[B, N] with batch dims flattened
    k, n = rhs.shape
    l2 = l8.reshape(-1, k)
    g2 = g8.reshape(-1, n)
    drhs = jax.lax.dot_general(
        l2, g2, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ) * (sl * sg)
    return dlhs.astype(lhs.dtype), drhs.astype(rhs.dtype)


_fp8_matmul.defvjp(_fp8_matmul_fwd, _fp8_matmul_bwd)


def fp8_dot_general(lhs, rhs, dimension_numbers, precision=None, preferred_element_type=None):
    """Drop-in ``lax.dot_general`` for the ``nn.Dense`` contraction pattern
    (last dim of lhs x first dim of rhs, no batch dims). Other patterns fall
    back to the plain dot — same behavior as the reference converting only
    ``Linear`` layers (utils/transformer_engine.py:41)."""
    ((lc, rc), (lb, rb)) = dimension_numbers
    if tuple(lc) == (lhs.ndim - 1,) and tuple(rc) == (0,) and not lb and not rb and rhs.ndim == 2:
        return _fp8_matmul(lhs, rhs)
    return jax.lax.dot_general(
        lhs, rhs, dimension_numbers, precision=precision,
        preferred_element_type=preferred_element_type,
    )


def fp8_enabled() -> bool:
    """True when the active Accelerator's dtype policy requests fp8."""
    from ..state import AcceleratorState

    state = AcceleratorState._shared_state
    if not state.get("_initialized"):
        return False
    policy = state.get("dtype_policy")
    return bool(policy is not None and getattr(policy, "fp8", False))


def policy_dot_general():
    """The ``dot_general`` the model zoo passes to every ``nn.Dense``.
    Resolved at trace time (module ``__call__``), so the choice is burned
    into the jitted program — set ``mixed_precision`` before building the
    train step."""
    return fp8_dot_general if fp8_enabled() else jax.lax.dot_general
