"""Shared KV-cache incremental attention for the model zoo's decode path.

The cache is a flax ``cache`` collection: fixed-size ``[B, max_len, H_kv,
D]`` buffers updated in place with ``dynamic_update_slice`` — static
shapes, so the whole decode loop jits into one XLA program
(:mod:`accelerate_tpu.generation`). The reference has no in-framework
decode (it delegates generation to transformers); on TPU the cache layout
and the single-program loop ARE the per-token latency story.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import BATCH_AXES

# Mesh layout of the cache buffers [B, max_len, H(_kv), D]: batch over the
# data-parallel axes (same BATCH_AXES as the training data path), heads
# over ``tensor`` — the TP decode layout. With q/k/v projections
# column-split over ``tensor`` (the zoo's Megatron rules) this keeps the
# whole decode loop partitioned: each tensor shard attends with its own
# heads against its own cache slice and only the o_proj row-parallel
# reduction communicates. ``maybe_shard`` drops axes that don't divide
# (e.g. GQA with fewer kv heads than tensor shards) or that the active
# mesh doesn't have, and is a no-op when no mesh is active.
CACHE_KV_SPEC = P(BATCH_AXES, None, "tensor", None)


def _constrain(x):
    from ..parallel.sharding import maybe_shard

    return maybe_shard(x, CACHE_KV_SPEC)


def cached_attention(
    module, q, k, v, max_len: int, scale=None, bias_fn=None, sliding_window=None, logit_softcap=None
):
    """Incremental causal attention against a growing cache.

    ``module``: the calling flax module (owns the ``cache`` variables).
    ``q`` [B, S_new, H, D]; ``k``/``v`` [B, S_new, H_kv, D] (GQA when
    H_kv < H). Returns [B, S_new, H, D]. Prefill (S_new = prompt) and
    per-token decode (S_new = 1) share this path.

    ``scale``: logit multiplier (default ``1/sqrt(D)``; T5 passes 1.0).
    ``bias_fn(q_pos [S_new], key_pos [max_len]) -> [1, H, S_new, max_len]``
    adds a position-dependent logit bias (T5's relative bias) — computed
    from ABSOLUTE positions so prefill and steps agree.
    ``sliding_window``: Mistral-style band — each query attends only the
    last ``sliding_window`` keys (the cache still stores ``max_len`` rows;
    out-of-window rows are masked, matching the non-decode band mask).
    """
    from . import paged_kv

    pcfg = paged_kv.active_paged_config()
    if pcfg is not None:
        if logit_softcap is not None:
            raise NotImplementedError(
                "attention logit softcapping (Gemma2) is not supported by the paged "
                "cache kernel yet; serve with the dense engine layout"
            )
        # serving engine's paged mode: block-pool cache layout instead of
        # dense per-row buffers (trace-time switch; see ops/paged_kv.py)
        return paged_kv.paged_cached_attention(
            module, q, k, v, max_len, scale=scale, bias_fn=bias_fn,
            sliding_window=sliding_window, cfg=pcfg,
        )
    b, s_new, h_kv, d = k.shape
    scale = (1.0 / math.sqrt(d)) if scale is None else scale
    ck = module.variable("cache", "key", jnp.zeros, (b, max_len, h_kv, d), k.dtype)
    cv = module.variable("cache", "value", jnp.zeros, (b, max_len, h_kv, d), v.dtype)
    idx = module.variable("cache", "index", lambda: jnp.zeros((), jnp.int32))
    cur = idx.value
    ck.value = _constrain(jax.lax.dynamic_update_slice(ck.value, k, (0, cur, 0, 0)))
    cv.value = _constrain(jax.lax.dynamic_update_slice(cv.value, v, (0, cur, 0, 0)))
    idx.value = cur + s_new

    k_all, v_all = ck.value, cv.value
    groups = q.shape[2] // h_kv
    # causal over absolute positions: new token i attends to <= cur+i;
    # with a sliding window, also to > cur+i - W (the Mistral band)
    key_pos = jnp.arange(max_len)
    q_pos = cur + jnp.arange(s_new)
    live = key_pos[None, :] <= q_pos[:, None]  # [S_new, max_len]
    if sliding_window is not None:
        live &= key_pos[None, :] > q_pos[:, None] - sliding_window
    bias = bias_fn(q_pos, key_pos) if bias_fn is not None else None
    def cap(scores):
        if logit_softcap is None:
            return scores
        from .attention import softcap  # Gemma2: tanh-bound BEFORE the mask

        return softcap(scores, logit_softcap)

    if groups > 1:
        # GQA: contract grouped queries against the UN-repeated cache —
        # materializing jnp.repeat over [B, max_len, H, D] would 4x the
        # cache's memory traffic on every decode step
        qg = q.reshape(b, s_new, h_kv, groups, d)
        scores = cap(jnp.einsum("bqhgd,bkhd->bhgqk", qg, k_all).astype(jnp.float32) * scale)
        if bias is not None:
            scores = scores + bias.reshape(1, h_kv, groups, s_new, max_len)
        mask = live[None, None, None]
        probs = jax.nn.softmax(jnp.where(mask, scores, -jnp.inf), axis=-1).astype(q.dtype)
        out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, v_all)
        return out.reshape(b, s_new, h_kv * groups, d)
    scores = cap(jnp.einsum("bqhd,bkhd->bhqk", q, k_all).astype(jnp.float32) * scale)
    if bias is not None:
        scores = scores + bias
    mask = live[None, None]
    probs = jax.nn.softmax(jnp.where(mask, scores, -jnp.inf), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v_all)


def reset_cache_index(cache, new_index):
    """Set every ``index`` leaf of a cache pytree to ``new_index`` — the
    frontier reset shared by the serving engine's padded prefill and
    speculative decoding's accept/reject step: rows past the new frontier
    are stale but sit beyond the causal mask until overwritten."""
    import jax

    def fix(path, leaf):
        name = str(path[-1].key) if hasattr(path[-1], "key") else str(path[-1])
        if name == "index":
            return jnp.full(leaf.shape, new_index, leaf.dtype)
        return leaf

    return jax.tree_util.tree_map_with_path(fix, cache)


def cached_cross_kv(module, kv, num_heads: int, head_dim: int, make_k, make_v, prime: bool):
    """Cross-attention K/V cache shared by the encoder-decoder zoo: project
    the encoder output ONCE at prefill (``prime=True``) and reuse the
    stored projections on every decode step. ``make_k``/``make_v`` are
    zero-arg closures running the projection submodules (only invoked when
    priming, so step traces skip the projection entirely)."""
    b, s_enc = kv.shape[:2]
    ck = module.variable("cache", "cross_key", jnp.zeros, (b, s_enc, num_heads, head_dim), jnp.float32)
    cv = module.variable("cache", "cross_value", jnp.zeros, (b, s_enc, num_heads, head_dim), jnp.float32)
    if prime:
        ck.value = _constrain(make_k().astype(jnp.float32))
        cv.value = _constrain(make_v().astype(jnp.float32))
    return ck.value, cv.value
