"""Shared KV-cache incremental attention for the model zoo's decode path.

The cache is a flax ``cache`` collection: fixed-size ``[B, max_len, H_kv,
D]`` buffers updated in place with ``dynamic_update_slice`` — static
shapes, so the whole decode loop jits into one XLA program
(:mod:`accelerate_tpu.generation`). The reference has no in-framework
decode (it delegates generation to transformers); on TPU the cache layout
and the single-program loop ARE the per-token latency story.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def cached_attention(module, q, k, v, max_len: int):
    """Incremental causal attention against a growing cache.

    ``module``: the calling flax module (owns the ``cache`` variables).
    ``q`` [B, S_new, H, D]; ``k``/``v`` [B, S_new, H_kv, D] (GQA when
    H_kv < H). Returns [B, S_new, H, D]. Prefill (S_new = prompt) and
    per-token decode (S_new = 1) share this path.
    """
    b, s_new, h_kv, d = k.shape
    ck = module.variable("cache", "key", jnp.zeros, (b, max_len, h_kv, d), k.dtype)
    cv = module.variable("cache", "value", jnp.zeros, (b, max_len, h_kv, d), v.dtype)
    idx = module.variable("cache", "index", lambda: jnp.zeros((), jnp.int32))
    cur = idx.value
    ck.value = jax.lax.dynamic_update_slice(ck.value, k, (0, cur, 0, 0))
    cv.value = jax.lax.dynamic_update_slice(cv.value, v, (0, cur, 0, 0))
    idx.value = cur + s_new

    k_all, v_all = ck.value, cv.value
    groups = q.shape[2] // h_kv
    # causal over absolute positions: new token i attends to <= cur+i
    key_pos = jnp.arange(max_len)
    q_pos = cur + jnp.arange(s_new)
    if groups > 1:
        # GQA: contract grouped queries against the UN-repeated cache —
        # materializing jnp.repeat over [B, max_len, H, D] would 4x the
        # cache's memory traffic on every decode step
        qg = q.reshape(b, s_new, h_kv, groups, d)
        scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k_all).astype(jnp.float32) / math.sqrt(d)
        mask = key_pos[None, None, None, None, :] <= q_pos[None, None, None, :, None]
        probs = jax.nn.softmax(jnp.where(mask, scores, -jnp.inf), axis=-1).astype(q.dtype)
        out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, v_all)
        return out.reshape(b, s_new, h_kv * groups, d)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_all).astype(jnp.float32) / math.sqrt(d)
    mask = key_pos[None, None, None, :] <= q_pos[None, None, :, None]
    probs = jax.nn.softmax(jnp.where(mask, scores, -jnp.inf), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v_all)
