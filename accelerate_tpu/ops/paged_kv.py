"""Paged KV cache: a shared block pool + per-sequence block tables.

The dense serving cache reserves ``max_len`` rows per slot, so HBM caps
the slot count at ``pool_bytes = slots x max_len`` even when most
requests are short. Paging (the vLLM design, shaped for XLA's static
shapes) allocates cache in fixed-size *blocks* from one shared pool:

* ``key_pool`` / ``value_pool``: ``[num_blocks, block_size, H_kv, D]``
  per layer — the only large buffers, sized by *expected total tokens in
  flight*, not ``slots x max_len``;
* ``block_table``: ``[B, max_blocks]`` int32 per row — position ``p`` of
  row ``b`` lives at ``pool[table[b, p // bs], p % bs]``;
* block 0 is a reserved **trash sink**: padded table entries and the
  post-retirement overshoot writes of a static decode tick land there,
  so a retired slot can never corrupt a block that was freed and
  reallocated to another request (see ``ServingEngine._retire``, which
  also re-points the whole retired row at the sink);
* shared prompt prefixes alias their *full* blocks into many tables
  (refcounted host-side) — prefix reuse without copying cache rows.

Everything stays static-shape. On TPU the decode step dispatches to the
Pallas kernel in :mod:`.pallas_paged_attention`, which DMAs each page
into VMEM exactly once via scalar-prefetched table indexing (under a
``shard_map`` over ``tensor`` when the pool is TP-sharded — a
``pallas_call`` can't be auto-partitioned). The XLA fallback (CPU, or
head counts the tensor axis can't split) gathers ``pool[table]`` into a
contiguous ``[B, L, H_kv, D]`` copy — dense-equivalent read bytes plus
the gather write. Either way paging wins pool *capacity* (more
concurrent slots per GB); the kernel also wins decode traffic.

The reference has no serving/paged-cache analogue (it delegates
generation entirely — SURVEY §2.2/§7); this is parity-plus. The paged
branch is selected at *trace time* by :func:`paged_mode`, so the model
zoo's ``cached_attention`` call sites need no changes.
"""

from __future__ import annotations

import contextlib
import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class PagedConfig:
    block_size: int
    num_blocks: int  # total pool blocks INCLUDING the reserved trash block 0


_ACTIVE: Optional[PagedConfig] = None

# Route the off-TPU paged path through the Pallas kernel in interpret
# mode instead of the XLA gather — CI's hook for exercising the exact
# kernel-in-engine composition TPU serving runs, without a chip.
FORCE_KERNEL_INTERPRET = False


def active_paged_config() -> Optional[PagedConfig]:
    return _ACTIVE


@contextlib.contextmanager
def paged_mode(cfg: PagedConfig):
    """Trace-time switch: while active, ``cached_attention`` declares and
    updates the paged cache layout instead of dense ``[B, max_len]``
    buffers. Only the *tracing* of a program needs the context — the
    serving engine re-enters it around every (lazily jitted) tick call,
    which is free on cache hits and lets jit re-trace when GSPMD
    propagates new shardings onto the pool. Do NOT eagerly
    ``.lower().compile()`` under this context: that pins the input
    shardings seen at construction and rejects the runtime arrays on
    data-sharded meshes (see tests/test_serving_paged.py)."""
    global _ACTIVE
    prev, _ACTIVE = _ACTIVE, cfg
    try:
        yield
    finally:
        _ACTIVE = prev


# Pool layout on a mesh: heads over ``tensor`` (same TP decode layout as
# the dense CACHE_KV_SPEC); the block axis is NOT batch — the pool is
# shared by every row — so it stays unsharded.
POOL_KV_SPEC = P(None, None, "tensor", None)


def _constrain_pool(x):
    from ..parallel.sharding import maybe_shard

    return maybe_shard(x, POOL_KV_SPEC)


def paged_cached_attention(
    module, q, k, v, max_len: int, scale=None, bias_fn=None, sliding_window=None, cfg: PagedConfig = None
):
    """Single-token incremental attention against the paged pool.

    Declares (per layer) ``key_pool``/``value_pool`` ``[NB, bs, H_kv, D]``,
    ``block_table`` ``[B, MB]`` and a PER-ROW ``index`` ``[B]`` — ragged
    row positions are native here (the dense branch's scalar frontier
    forces the serving engine to vmap row-wise; the paged tick runs one
    batched program instead). Prefill always runs dense and is pasted
    into the pool by :func:`paste_row`, so only ``S_new == 1`` decode
    steps ever trace this branch.
    """
    b, s_new, h_kv, d = k.shape
    if s_new != 1:
        raise ValueError(
            f"paged attention is decode-only (S_new == 1, got {s_new}); "
            "prefill runs the dense path and is pasted into the pool"
        )
    if bias_fn is not None:
        raise NotImplementedError("paged attention does not support bias_fn (T5-style relative bias)")
    bs_, nb = cfg.block_size, cfg.num_blocks
    mb = -(-max_len // bs_)
    scale = (1.0 / math.sqrt(d)) if scale is None else scale

    kp = module.variable("cache", "key_pool", jnp.zeros, (nb, bs_, h_kv, d), k.dtype)
    vp = module.variable("cache", "value_pool", jnp.zeros, (nb, bs_, h_kv, d), v.dtype)
    bt = module.variable("cache", "block_table", jnp.zeros, (b, mb), jnp.int32)
    idx = module.variable("cache", "index", jnp.zeros, (b,), jnp.int32)

    cur = idx.value  # [B] per-row write positions
    rows = jnp.arange(b)
    # overshoot clamp: a slot that finished mid-tick keeps computing with
    # growing cur; past the table it clamps to the last entry (its own
    # reserved block or the trash sink — never another row's block)
    blk = jnp.minimum(cur // bs_, mb - 1)
    dest = bt.value[rows, blk]  # [B] pool block ids
    off = cur % bs_
    kp.value = _constrain_pool(kp.value.at[dest, off].set(k[:, 0]))
    vp.value = _constrain_pool(vp.value.at[dest, off].set(v[:, 0]))
    idx.value = cur + 1

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu or FORCE_KERNEL_INTERPRET:
        # Pallas kernel: reads each page once via scalar-prefetched table
        # indexing — no [B, L, H_kv, D] gather materialisation (the XLA
        # fallback below writes+rereads one; ~3x the attention traffic)
        import functools

        from .pallas_paged_attention import paged_decode_attention

        fn = functools.partial(
            paged_decode_attention, sliding_window=sliding_window, scale=scale, interpret=not on_tpu
        )
        run = _kernel_runner(fn, q.shape[2], h_kv)
        if run is not None:  # None: TP mesh the heads can't split -> XLA path
            return run(q[:, 0], kp.value, vp.value, bt.value, cur)[:, None]

    # gather each row's pages: [B, MB, bs, H_kv, D] -> [B, L, H_kv, D]
    k_all = kp.value[bt.value].reshape(b, mb * bs_, h_kv, d)
    v_all = vp.value[bt.value].reshape(b, mb * bs_, h_kv, d)
    key_pos = jnp.arange(mb * bs_)
    live = key_pos[None, :] <= cur[:, None]  # [B, L] causal frontier per row
    if sliding_window is not None:
        live &= key_pos[None, :] > cur[:, None] - sliding_window  # Mistral band

    groups = q.shape[2] // h_kv
    if groups > 1:
        # GQA: contract grouped queries against the un-repeated pool rows
        # (same traffic argument as the dense branch)
        qg = q.reshape(b, 1, h_kv, groups, d)
        scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k_all).astype(jnp.float32) * scale
        mask = live[:, None, None, None, :]
        probs = jax.nn.softmax(jnp.where(mask, scores, -jnp.inf), axis=-1).astype(q.dtype)
        out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, v_all)
        return out.reshape(b, 1, h_kv * groups, d)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_all).astype(jnp.float32) * scale
    probs = jax.nn.softmax(jnp.where(live[:, None, None, :], scores, -jnp.inf), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v_all)


def _kernel_runner(fn, heads: int, kv_heads: int):
    """How to invoke the paged kernel under the active mesh. A
    ``pallas_call`` is an opaque custom call XLA's partitioner cannot
    split, so a tensor-parallel pool must be fed per-shard via
    ``shard_map`` over the ``tensor`` axis (heads are independent in
    attention; the table/frontier are replicated) — the same treatment
    as ``sharded_pallas_attention``. Returns ``fn`` directly when no
    non-trivial tensor axis is active (or we're already inside a
    shard_map region), and None when heads don't divide the axis — the
    caller then uses the XLA gather path, which partitions naturally."""
    from ..utils.compat import in_manual_region, shard_map

    if in_manual_region():
        return fn
    from .attention import active_mesh

    mesh = active_mesh()
    if mesh is None:
        return fn
    from ..parallel.mesh import axis_size

    n_t = axis_size(mesh, "tensor")
    if n_t <= 1:
        return fn
    if heads % n_t or kv_heads % n_t:
        return None
    qspec = P(None, "tensor", None)
    pspec = P(None, None, "tensor", None)
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=(qspec, pspec, pspec, P(None, None), P(None)),
        out_specs=qspec,
        check_vma=False,
    )


def _path_names(path):
    return tuple(p.key if hasattr(p, "key") else str(p) for p in path)


def _scatter_pools(paged_cache, row_cache, write_row, table_updates):
    """Blockify a dense per-row cache and scatter it into the pools at
    ``write_row``'s block ids; apply ``table_updates(name, leaf)`` to the
    ``block_table``/``index`` leaves (or leave them untouched if it
    returns None)."""
    dense = {_path_names(p): leaf for p, leaf in jax.tree_util.tree_flatten_with_path(row_cache)[0]}

    def write(path, leaf):
        names = _path_names(path)
        name, prefix = names[-1], names[:-1]
        if name in ("key_pool", "value_pool"):
            row = dense[prefix + (name[: -len("_pool")],)]  # key_pool -> key
            lead = leaf.ndim - 4  # leading layer-scan axes (0 or 1)
            bs_ = leaf.shape[lead + 1]
            mb = write_row.shape[0]
            max_len = row.shape[lead + 1]
            pad = mb * bs_ - max_len
            if pad:
                widths = [(0, 0)] * (lead + 1) + [(0, pad), (0, 0), (0, 0)]
                row = jnp.pad(row, widths)
            # absorb the B=1 row axis while blockifying
            blocks = row.reshape(*leaf.shape[:lead], mb, bs_, *leaf.shape[-2:])
            sel = (slice(None),) * lead + (write_row,)
            return leaf.at[sel].set(blocks.astype(leaf.dtype))
        if name in ("block_table", "index"):
            out = table_updates(name, leaf)
            return leaf if out is None else out
        raise ValueError(f"unexpected paged cache leaf {'/'.join(names)}")

    return jax.tree_util.tree_map_with_path(write, paged_cache)


def paste_row(paged_cache, row_cache, write_row, table_row, slot, new_index):
    """Install a dense prefill row cache into the pool for ``slot``.

    ``row_cache`` is the ordinary dense per-row cache a prefill program
    produced (leaves ``key``/``value`` ``[..., 1, max_len, H_kv, D]``);
    every leaf is blockified and scattered at ``write_row``'s pool ids,
    and ``slot``'s table row / frontier index are set to ``table_row`` /
    ``new_index``. ``write_row`` and ``table_row`` differ exactly on
    entries the admit must NOT write: pad entries and shared prefix
    blocks point at the trash sink in ``write_row`` (shared content is
    written once, at registration — rewriting it per admit would race
    other slots decoding against it and waste the write traffic), while
    ``table_row`` keeps the real ids for reads. Pure — jit once.
    """

    def tables(name, leaf):
        if name == "block_table":
            sel = (slice(None),) * (leaf.ndim - 2) + (slot,)
            return leaf.at[sel].set(table_row.astype(leaf.dtype))
        sel = (slice(None),) * (leaf.ndim - 1) + (slot,)
        return leaf.at[sel].set(jnp.asarray(new_index, leaf.dtype))

    return _scatter_pools(paged_cache, row_cache, write_row, tables)


def paste_blocks(paged_cache, row_cache, write_row):
    """Write pool content only (no slot table/index): used once per
    registered prefix to install its full blocks as the canonical shared
    content every aliasing request reads. Pure — jit once."""
    return _scatter_pools(paged_cache, row_cache, write_row, lambda name, leaf: None)


def set_table_row(paged_cache, slot, table_row):
    """Replace ``slot``'s block-table row (leaving pools and frontier
    untouched): the engine's window-recycling path re-points expired
    entries at the trash sink as the frontier moves past them. Pure —
    jit once."""

    def write(path, leaf):
        if _path_names(path)[-1] == "block_table":
            sel = (slice(None),) * (leaf.ndim - 2) + (slot,)
            return leaf.at[sel].set(table_row.astype(leaf.dtype))
        return leaf

    return jax.tree_util.tree_map_with_path(write, paged_cache)


def clear_slot(paged_cache, slot):
    """Re-point ``slot``'s table row at the trash sink and zero its
    frontier. MUST run when a slot retires: the static decode tick keeps
    computing (and writing) for every slot, and a stale table would
    corrupt blocks after they are freed and reallocated. Pure — jit it."""

    def write(path, leaf):
        name = _path_names(path)[-1]
        if name == "block_table":
            sel = (slice(None),) * (leaf.ndim - 2) + (slot,)
            return leaf.at[sel].set(jnp.zeros((leaf.shape[-1],), leaf.dtype))
        if name == "index":
            sel = (slice(None),) * (leaf.ndim - 1) + (slot,)
            return leaf.at[sel].set(jnp.zeros((), leaf.dtype))
        return leaf

    return jax.tree_util.tree_map_with_path(write, paged_cache)


class BlockAllocator:
    """Host-side free list over pool blocks ``1..num_blocks-1`` (block 0
    is the trash sink and is never handed out)."""

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(f"pool needs >= 2 blocks (one is the trash sink), got {num_blocks}")
        self.num_blocks = num_blocks
        self._free = list(range(num_blocks - 1, 0, -1))

    @property
    def free_count(self) -> int:
        return len(self._free)

    def alloc(self, n: int):
        """``n`` block ids, or None if the pool can't satisfy the request
        (callers keep the request queued and retry after a retirement)."""
        if n > len(self._free):
            return None
        return [self._free.pop() for _ in range(n)]

    def free(self, ids) -> None:
        for i in ids:
            if not 0 < i < self.num_blocks:
                raise ValueError(f"bad block id {i}")
            self._free.append(i)
