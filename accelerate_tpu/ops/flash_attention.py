"""Memory-efficient attention: blockwise online-softmax over key/value
chunks.

The flash-attention recurrence (running max + running normaliser) expressed
as ``lax.scan`` over KV blocks: O(S) activation memory instead of the
O(S^2) logits tensor, fully differentiable (AD through the scan yields the
standard recompute-style backward), and XLA fuses each block's
matmul+softmax chain onto the MXU. GQA is handled natively — K/V are never
repeated; queries are grouped as [B, Sq, H_kv, G, D] and contracted against
the unexpanded KV blocks, preserving GQA's KV-memory saving. Causal masking
is bottom-right aligned when Sq != Sk (decode/chunked attention).

The reference framework has no long-context mechanism at all (SURVEY §5
long-context: only Megatron-SP); this op is the parity-plus path, and the
hand-tiled Pallas kernel (same signature) can replace the scan body without
touching callers.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_size"))
def flash_attention(
    q: jax.Array,  # [B, Sq, H, D]
    k: jax.Array,  # [B, Sk, H_kv, D]
    v: jax.Array,  # [B, Sk, H_kv, D]
    causal: bool = False,
    scale: Optional[float] = None,
    block_size: int = 512,
) -> jax.Array:
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    b, sq, h, d = q.shape
    sk, h_kv = k.shape[1], k.shape[-2]
    g = h // h_kv  # query groups per KV head (1 = vanilla MHA)

    blk = min(block_size, sk)
    if sk % blk != 0:
        # pad keys to a block multiple; padded positions are masked out
        pad = blk - sk % blk
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    n_blocks = k.shape[1] // blk

    qf = (q * scale).reshape(b, sq, h_kv, g, d)
    k_blocks = k.reshape(b, n_blocks, blk, h_kv, d)
    v_blocks = v.reshape(b, n_blocks, blk, h_kv, d)

    # bottom-right aligned absolute query positions (decode: Sq < Sk)
    q_pos = jnp.arange(sq) + (sk - sq)

    def body(carry, inputs):
        acc, m, l = carry  # [B,Sq,Hkv,G,D], [B,Hkv,G,Sq], [B,Hkv,G,Sq]
        (k_blk, v_blk, blk_idx) = inputs
        s = jnp.einsum("bqhgd,bkhd->bhgqk", qf, k_blk).astype(jnp.float32)  # [B,Hkv,G,Sq,blk]
        k_pos = blk_idx * blk + jnp.arange(blk)
        valid = k_pos < sk
        if causal:
            valid = valid[None, :] & (q_pos[:, None] >= k_pos[None, :])
            s = jnp.where(valid[None, None, None], s, -jnp.inf)
        else:
            s = jnp.where(valid[None, None, None, None, :], s, -jnp.inf)
        m_blk = s.max(axis=-1)  # [B,Hkv,G,Sq]
        m_new = jnp.maximum(m, m_blk)
        # guard fully-masked rows (all -inf): exp(-inf - -inf) -> use 0
        safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - safe_m[..., None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        correction = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)  # [B,Hkv,G,Sq]
        l_new = l * correction + p.sum(axis=-1)
        pv = jnp.einsum("bhgqk,bkhd->bqhgd", p.astype(v_blk.dtype), v_blk).astype(jnp.float32)
        acc = acc * correction.transpose(0, 3, 1, 2)[..., None] + pv
        return (acc, m_new, l_new), None

    acc0 = jnp.zeros((b, sq, h_kv, g, d), jnp.float32)
    m0 = jnp.full((b, h_kv, g, sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h_kv, g, sq), jnp.float32)
    (acc, m, l), _ = jax.lax.scan(
        jax.checkpoint(body),
        (acc0, m0, l0),
        (
            k_blocks.transpose(1, 0, 2, 3, 4),
            v_blocks.transpose(1, 0, 2, 3, 4),
            jnp.arange(n_blocks),
        ),
    )
    l = jnp.maximum(l, 1e-37)
    out = acc / l.transpose(0, 3, 1, 2)[..., None]
    return out.reshape(b, sq, h, d).astype(q.dtype)
