"""Pallas TPU kernel: paged-attention decode over a shared block pool.

The XLA paged path (:func:`accelerate_tpu.ops.paged_kv.paged_cached_attention`)
gathers each row's pages into a contiguous ``[B, L, H_kv, D]`` copy
every step — the gather WRITES a full cache-sized array and the two
attention einsums read it back, roughly tripling the per-step HBM
traffic of the (bandwidth-bound) decode attention. This kernel reads
each page exactly once: the grid walks ``(row, table_entry)``, the
block table is a scalar-prefetch operand so each step's ``index_map``
DMAs the right pool block directly into VMEM, and an online-softmax
accumulator (flash-attention style) folds every page into ``[H, D]``
scratch without materialising the gathered cache.

* pages fully beyond the row's frontier (or entirely outside the
  sliding-window band) are skipped with ``pl.when`` — and because pad
  table entries all point at the trash-sink block, their repeated index
  elides the DMA as well;
* GQA runs as one batched ``dot_general`` over KV heads (queries
  reshaped ``[H_kv, G, D]``), never repeating K/V;
* the decode contract matches the XLA branch bit-for-bit in masking:
  keys at positions ``> cur - W`` and ``<= cur``.

The public paged-attention kernel in ``jax.experimental`` follows the
same scalar-prefetch shape; this one is written for THIS engine's
layout (trash-sink block 0, per-row frontiers, optional band) and is
dispatched from ``paged_cached_attention`` on TPU.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _kernel(
    tbl_ref,  # [B, MB] int32 (scalar prefetch)
    cur_ref,  # [B] int32 (scalar prefetch)
    q_ref,  # [1, H, D]
    k_ref,  # [1, bs, Hkv, D]
    v_ref,  # [1, bs, Hkv, D]
    o_ref,  # [1, H, D]
    m_ref,  # [H, 1] f32 scratch
    l_ref,  # [H, 1] f32 scratch
    acc_ref,  # [H, D] f32 scratch
    *,
    block_size: int,
    kv_heads: int,
    window: Optional[int],
    scale: float,
):
    b, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    cur = cur_ref[b]
    lo = j * block_size
    live = lo <= cur  # any causal-live key in this page
    if window is not None:
        live &= lo + block_size - 1 > cur - window  # any in-band key

    @pl.when(live)
    def _page():
        q = q_ref[0].astype(jnp.float32)  # [H, D]
        k = k_ref[0].astype(jnp.float32)  # [bs, Hkv, D]
        v = v_ref[0].astype(jnp.float32)
        heads, dim = q.shape
        groups = heads // kv_heads
        # [Hkv, G, D] x [Hkv, bs, D] -> [Hkv, G, bs]: one batched matmul,
        # K/V never repeated (the GQA traffic argument, in-kernel)
        qg = q.reshape(kv_heads, groups, dim)
        kt = k.transpose(1, 0, 2)  # [Hkv, bs, D]
        s = jax.lax.dot_general(
            qg, kt, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
        )
        s = s.reshape(heads, block_size) * scale
        pos = lo + jax.lax.broadcasted_iota(jnp.int32, (1, block_size), 1)  # [1, bs]
        mask = pos <= cur
        if window is not None:
            mask &= pos > cur - window
        s = jnp.where(mask, s, -jnp.inf)

        m_prev = m_ref[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))  # finite: live page has a live key
        alpha = jnp.exp(m_prev - m_new)  # 0 when m_prev == -inf
        p = jnp.exp(s - m_new[:, None])  # [H, bs]
        l_ref[:, 0] = l_ref[:, 0] * alpha + jnp.sum(p, axis=1)
        pv = jax.lax.dot_general(
            p.reshape(kv_heads, groups, block_size),
            v.transpose(1, 0, 2),  # [Hkv, bs, D]
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ).reshape(heads, dim)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + pv
        m_ref[:, 0] = m_new

    @pl.when(j == pl.num_programs(1) - 1)
    def _finalize():
        # l can be 0 for a long-retired slot whose windowed frontier moved
        # past every live page: its output is discarded host-side, but an
        # unguarded 0/0 would trip jax_debug_nans / NaN-scan tooling.
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:, 0], 1.0)[:, None]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("sliding_window", "scale", "interpret"))
def paged_decode_attention(
    q: jax.Array,  # [B, H, D]
    key_pool: jax.Array,  # [NB, bs, Hkv, D]
    value_pool: jax.Array,  # [NB, bs, Hkv, D]
    block_table: jax.Array,  # [B, MB] int32
    cur: jax.Array,  # [B] int32 — per-row frontier (attend to <= cur)
    *,
    sliding_window: Optional[int] = None,
    scale: Optional[float] = None,
    interpret: bool = False,
) -> jax.Array:
    """One decode step of attention for every row against its paged KV.

    Returns ``[B, H, D]`` in ``q.dtype``. The caller has already written
    the step's K/V into the pool at position ``cur`` (the engine's
    scatter), so the frontier key is included.
    """
    from jax.experimental.pallas import tpu as pltpu

    b, heads, dim = q.shape
    nb, block_size, kv_heads, _ = key_pool.shape
    mb = block_table.shape[1]
    scale = (1.0 / math.sqrt(dim)) if scale is None else scale

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, mb),
        in_specs=[
            pl.BlockSpec((1, heads, dim), lambda b, j, tbl, cur: (b, 0, 0)),
            pl.BlockSpec((1, block_size, kv_heads, dim), lambda b, j, tbl, cur: (tbl[b, j], 0, 0, 0)),
            pl.BlockSpec((1, block_size, kv_heads, dim), lambda b, j, tbl, cur: (tbl[b, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, heads, dim), lambda b, j, tbl, cur: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((heads, 1), jnp.float32),
            pltpu.VMEM((heads, 1), jnp.float32),
            pltpu.VMEM((heads, dim), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _kernel,
        block_size=block_size,
        kv_heads=kv_heads,
        window=sliding_window,
        scale=scale,
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, heads, dim), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(block_table.astype(jnp.int32), cur.astype(jnp.int32), q, key_pool, value_pool)
