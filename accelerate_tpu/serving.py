"""Continuous batching: a slot-based serving engine over the KV-cache decode.

Static batching (``generate``) decodes one fixed batch to completion —
short requests wait for the longest one, and new requests wait for the
whole batch. Continuous batching keeps a fixed pool of ``num_slots``
sequences in flight: finished sequences retire and free their slot
immediately, queued prompts prefill into free slots, and ONE jitted
vmapped decode step advances every active slot per tick (the vLLM-style
serving loop, shaped for XLA: all programs have static shapes, so the
engine compiles a handful of programs once and replays them forever).

No reference analogue (the reference delegates generation entirely);
parity-plus. Design notes:

* per-slot KV caches are the model's ordinary cache pytree with a leading
  slot axis; the decode tick is ``jax.vmap`` of the single-sequence step,
  so per-slot positions/cache indices need NO model changes;
* prompt prefill pads up to a size bucket (one compile per bucket). The
  padded tail DOES write garbage rows into the cache at positions >=
  true_len — harmless by construction: they sit beyond the causal
  frontier (key_pos > q_pos masks them) and each decode step overwrites
  the next one, because the cache write index is reset to ``true_len``
  after prefill;
* inactive slots still compute in the tick (static shapes; masking out
  their tokens is host-side bookkeeping). Their caches accumulate
  garbage that the next prefill-insert fully replaces.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Optional

import numpy as np


def _jax():
    import jax

    return jax


@dataclasses.dataclass
class _Request:
    uid: int
    prompt: np.ndarray  # [S] int32
    max_new_tokens: int
    out_tokens: list


class ServingEngine:
    """Continuous-batching decode engine for a zoo model with the decode
    contract (``apply_fn(params, ids, positions=..., decode=True,
    cache=...) -> (logits, cache)``; llama / gpt2 / gptneox).

    ``prompt_buckets``: ascending prefill sizes; each distinct bucket
    compiles one prefill program. ``max_len``: cache capacity per slot
    (default: the model's ``max_position_embeddings``). Decoding is
    greedy at ``temperature=0`` (the token-exact-vs-generate setting) or
    temperature/top-k sampling with an independent per-slot key chain
    folded on the request uid (deterministic per ``seed``).
    """

    def __init__(
        self,
        model,
        num_slots: int = 4,
        prompt_buckets=(32, 128),
        max_len: Optional[int] = None,
        eos_token_id: Optional[int] = None,
        tick_block: int = 8,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        seed: int = 0,
    ):
        jax = _jax()
        jnp = jax.numpy
        self.model = model
        self.num_slots = num_slots
        self.prompt_buckets = tuple(sorted(prompt_buckets))
        self.max_len = max_len or model.config.max_position_embeddings
        if self.max_len > model.config.max_position_embeddings:
            raise ValueError(
                f"max_len {self.max_len} exceeds the model cache "
                f"(max_position_embeddings={model.config.max_position_embeddings})"
            )
        if max(self.prompt_buckets) > self.max_len:
            raise ValueError(
                f"prompt bucket {max(self.prompt_buckets)} exceeds the slot cache "
                f"(max_len={self.max_len})"
            )
        self.eos_token_id = eos_token_id
        self._seed = seed

        from .generation import _make_sampler

        sampler = _make_sampler(temperature, top_k)

        params = model.params
        apply_fn = model.apply_fn

        # empty per-row cache template from a 1-token dummy prefill
        _, cache0 = jax.eval_shape(
            lambda p, i: apply_fn(p, i, positions=jnp.zeros((1, 1), jnp.int32), decode=True, cache=None),
            params,
            jnp.zeros((1, 1), jnp.int32),
        )
        # slot pool: leading slot axis over the per-row cache pytree
        self.slot_caches = jax.tree.map(
            lambda l: jnp.zeros((num_slots, *l.shape), l.dtype), cache0
        )

        # host-side slot state
        self.slot_req: list[Optional[_Request]] = [None] * num_slots
        self.slot_tok = np.zeros((num_slots,), np.int32)
        self.slot_pos = np.zeros((num_slots,), np.int32)
        self.queue: collections.deque[_Request] = collections.deque()
        self.done: dict[int, np.ndarray] = {}
        self._uid = 0

        # ---- jitted programs (compiled once each) ----
        def prefill(params, ids, true_len, key):
            """[1, B] padded prompt -> (first next-token, per-row cache with
            write index reset to true_len, advanced key)."""
            b_len = ids.shape[1]
            positions = jnp.broadcast_to(jnp.arange(b_len), (1, b_len))
            logits, cache = apply_fn(params, ids, positions=positions, decode=True, cache=None)
            key, sub = jax.random.split(key)
            next_tok = sampler(logits[0, true_len - 1][None], sub)[0]
            from .ops.kv_cache import reset_cache_index

            cache = reset_cache_index(cache, true_len)
            return next_tok, cache, key

        key_aval = jax.eval_shape(lambda: jax.random.key(0))
        self._prefill = {
            b: jax.jit(prefill).lower(
                params, jax.ShapeDtypeStruct((1, b), jnp.int32),
                jax.ShapeDtypeStruct((), jnp.int32), key_aval
            ).compile()
            for b in self.prompt_buckets
        }

        @jax.jit
        def insert(slot_caches, row_cache, slot):
            return jax.tree.map(
                lambda big, row: jax.lax.dynamic_update_index_in_dim(big, row.astype(big.dtype), slot, 0),
                slot_caches,
                row_cache,
            )

        self._insert = insert

        # Decode K steps per host round-trip: one sync per TOKEN would be
        # latency-bound (10s of ms on tunnel-attached backends); the block
        # scan amortises it K-fold. A slot that finishes (eos / budget)
        # mid-block keeps computing until the block ends — those overshoot
        # tokens are discarded host-side and the slot's cache is fully
        # replaced at the next prefill-insert, so outputs stay token-exact.
        if tick_block < 1:
            raise ValueError(f"tick_block must be >= 1, got {tick_block}")
        self.tick_block = tick_block

        def one_step(params, cache_row, tok, pos, key):
            logits, cache_row = apply_fn(
                params, tok.reshape(1, 1), positions=pos.reshape(1, 1), decode=True, cache=cache_row
            )
            key, sub = jax.random.split(key)
            nxt = sampler(logits[0, -1][None], sub)[0]
            return cache_row, nxt, key

        @jax.jit
        def decode_tick(params, slot_caches, toks, poss, keys):
            def block_step(carry, _):
                caches, toks, poss, keys = carry
                caches, nxt, keys = jax.vmap(one_step, in_axes=(None, 0, 0, 0, 0))(
                    params, caches, toks, poss, keys
                )
                return (caches, nxt, poss + 1, keys), nxt

            (slot_caches, _, _, keys), toks_k = jax.lax.scan(
                block_step, (slot_caches, toks, poss, keys), None, length=tick_block
            )
            return slot_caches, toks_k, keys  # toks_k [K, slots]

        self._decode_tick = decode_tick
        # independent sampling chain per slot (re-folded with the request
        # uid at each admit, so retries/new requests don't replay a chain)
        self._slot_keys = jax.vmap(jax.random.fold_in, (None, 0))(
            jax.random.key(seed), jnp.arange(num_slots)
        )

    # ---- public API ----------------------------------------------------

    def submit(self, prompt_ids, max_new_tokens: int = 32) -> int:
        """Queue a prompt; returns a request id resolved via :meth:`poll`."""
        prompt = np.asarray(prompt_ids, np.int32).ravel()
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if len(prompt) > max(self.prompt_buckets):
            raise ValueError(
                f"prompt length {len(prompt)} exceeds the largest prompt bucket "
                f"{max(self.prompt_buckets)}"
            )
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds the slot cache ({self.max_len})"
            )
        uid = self._uid
        self._uid += 1
        self.queue.append(_Request(uid, prompt, max_new_tokens, []))
        return uid

    def poll(self, uid: int):
        """The finished [S + new] tokens for ``uid``, or None if pending."""
        return self.done.get(uid)

    @property
    def active_count(self) -> int:
        return sum(r is not None for r in self.slot_req)

    def step(self) -> int:
        """One engine tick: fill free slots from the queue (one prefill
        each), then ONE vmapped decode step for all slots. Returns the
        number of active slots after the tick."""
        jax = _jax()
        jnp = jax.numpy

        # admit queued requests into free slots
        for slot in range(self.num_slots):
            if self.slot_req[slot] is not None or not self.queue:
                continue
            req = self.queue.popleft()
            bucket = next(b for b in self.prompt_buckets if b >= len(req.prompt))
            padded = np.zeros((1, bucket), np.int32)
            padded[0, : len(req.prompt)] = req.prompt
            key = jax.random.fold_in(jax.random.key(self._seed), req.uid)
            next_tok, row_cache, key = self._prefill[bucket](
                self.model.params, jnp.asarray(padded), jnp.int32(len(req.prompt)), key
            )
            self._slot_keys = self._slot_keys.at[slot].set(key)
            self.slot_caches = self._insert(self.slot_caches, row_cache, jnp.int32(slot))
            tok = int(next_tok)
            self.slot_req[slot] = req
            req.out_tokens.append(tok)
            if self._finished(req, tok):
                self._retire(slot)
                continue
            self.slot_tok[slot] = tok
            self.slot_pos[slot] = len(req.prompt)

        if self.active_count == 0:
            return 0

        self.slot_caches, toks_k, self._slot_keys = self._decode_tick(
            self.model.params, self.slot_caches,
            jnp.asarray(self.slot_tok), jnp.asarray(self.slot_pos), self._slot_keys
        )
        toks_k = np.asarray(toks_k)  # [K, slots] — ONE host sync per block
        for slot, req in enumerate(self.slot_req):
            if req is None:
                continue
            for k in range(self.tick_block):
                tok = int(toks_k[k, slot])
                req.out_tokens.append(tok)
                self.slot_pos[slot] += 1
                self.slot_tok[slot] = tok
                if self._finished(req, tok):
                    self._retire(slot)
                    break  # remaining block tokens are overshoot — discarded
        return self.active_count

    def run(self) -> dict:
        """Drive ticks until queue and slots drain; returns {uid: tokens}."""
        while self.queue or self.active_count:
            self.step()
        return dict(self.done)

    def generate_many(self, prompts, max_new_tokens: int = 32) -> list:
        """Convenience: submit all prompts, run to completion, return the
        completed token arrays in submission order."""
        uids = [self.submit(p, max_new_tokens) for p in prompts]
        self.run()
        return [self.done[u] for u in uids]

    # ---- internals ------------------------------------------------------

    def _finished(self, req: _Request, tok: int) -> bool:
        if self.eos_token_id is not None and tok == self.eos_token_id:
            return True
        return len(req.out_tokens) >= req.max_new_tokens

    def _retire(self, slot: int):
        req = self.slot_req[slot]
        self.done[req.uid] = np.concatenate([req.prompt, np.asarray(req.out_tokens, np.int32)])
        self.slot_req[slot] = None
