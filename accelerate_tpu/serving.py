"""Continuous batching: a slot-based serving engine over the KV-cache decode.

Static batching (``generate``) decodes one fixed batch to completion —
short requests wait for the longest one, and new requests wait for the
whole batch. Continuous batching keeps a fixed pool of ``num_slots``
sequences in flight: finished sequences retire and free their slot
immediately, queued prompts prefill into free slots, and ONE jitted
vmapped decode step advances every active slot per tick (the vLLM-style
serving loop, shaped for XLA: all programs have static shapes, so the
engine compiles a handful of programs once and replays them forever).

No reference analogue (the reference delegates generation entirely);
parity-plus. Design notes:

* per-slot KV caches are the model's ordinary cache pytree with a leading
  slot axis; the decode tick is ``jax.vmap`` of the single-sequence step,
  so per-slot positions/cache indices need NO model changes;
* prompt prefill pads up to a size bucket (one compile per bucket). The
  padded tail DOES write garbage rows into the cache at positions >=
  true_len — harmless by construction: they sit beyond the causal
  frontier (key_pos > q_pos masks them) and each decode step overwrites
  the next one, because the cache write index is reset to ``true_len``
  after prefill;
* inactive slots still compute in the tick (static shapes; masking out
  their tokens is host-side bookkeeping). Their caches accumulate
  garbage that the next prefill-insert fully replaces;
* **chunked prefill**: a prompt longer than the largest bucket streams
  through the decode path in largest-bucket-sized chunks against the
  growing cache (``cached_attention`` is the same program for S_new = 1
  and S_new = C) — so prompt length is bounded by cache capacity, not by
  the compiled bucket set, and the compile count stays O(buckets);
* **prefix caching**: :meth:`register_prefix` prefills a shared prompt
  prefix (e.g. a system prompt) ONCE and stores the row cache;
  ``submit(..., prefix_id=...)`` requests copy it and prefill only their
  suffix — the vLLM prefix-reuse win, token-exact by construction because
  the copied cache is bit-identical to what a full prefill would write;
* **paged KV cache** (``paged_block_size=...``): slot caches live in one
  shared block pool addressed through per-slot block tables
  (:mod:`accelerate_tpu.ops.paged_kv`) instead of ``slots x max_len``
  dense rows — pool capacity is sized by expected tokens in flight
  (``pool_blocks``), admission waits when the pool is exhausted, and
  prefix blocks are refcount-shared across requests rather than copied.
  The decode tick becomes ONE batched program (per-row frontiers are
  native to the paged layout) and outputs stay token-exact vs dense;
* **token-budget continuous batching** (``scheduler=SchedulerConfig``,
  :mod:`accelerate_tpu.scheduling`): each tick spends at most
  ``token_budget`` tokens — active decodes claim theirs first, and the
  remainder streams *chunks* of pending prefills through the existing
  chunked-prefill windows, so a long prompt makes TTFT progress without
  ever stalling a running decode for its whole prefill. Priority-class
  admission, SLO-aware load shedding (structured :class:`ShedError` +
  ``shed`` events instead of silent queueing), and decode preemption
  (the youngest low-priority decode releases its slot and KV blocks,
  requeues, and resumes by prefix-style recomputation — token- and
  logprob-exact) ride on the same tick loop. The default config is
  behavior-preserving: unlimited budget, one priority class, no
  shedding, no preemption.
"""

from __future__ import annotations

import bisect
import dataclasses
import time
from typing import Optional

import numpy as np

from .ft.crashpoints import crash_point
from .scheduling import Scheduler, SchedulerConfig, ShedError


def _jax():
    import jax

    return jax


def _row_axis(shape: tuple, cap: int):
    """Index of a cache leaf's position-row axis (the one sized to the
    model's cache capacity), or None for non-row leaves (write-index
    scalars). K/V buffers are at least [B, rows, heads, dim]-shaped —
    possibly with a leading scan-over-layers axis — so the first
    ``cap``-sized axis of an ndim >= 3 leaf is the row axis."""
    if len(shape) < 3:
        return None
    for i, d in enumerate(shape):
        if d == cap:
            return i
    return None


class _LazyBuckets:
    """dict-like ``bucket -> compiled program`` that compiles on FIRST
    use instead of eagerly at engine construction: startup pays only for
    the buckets traffic actually hits, and each build is attributed by a
    per-bucket ``serving_bucket_compile`` telemetry event."""

    def __init__(self, build):
        self._build = build
        self._programs: dict = {}

    def __getitem__(self, bucket: int):
        prog = self._programs.get(bucket)
        if prog is None:
            prog = self._programs[bucket] = self._build(bucket)
        return prog

    def __contains__(self, bucket) -> bool:
        return bucket in self._programs

    def __len__(self) -> int:
        return len(self._programs)

    def compiled_buckets(self) -> tuple:
        return tuple(sorted(self._programs))


@dataclasses.dataclass
class _Request:
    uid: int
    prompt: np.ndarray  # [S] int32
    max_new_tokens: int
    out_tokens: list
    prefix_id: Optional[int] = None
    # per-request stop token-id sequences (engine eos still applies); a
    # request finishes when its generated tail equals any sequence, with
    # the stop tokens kept in the output (eos convention)
    stop_sequences: tuple = ()
    # log P(tok) for each generated token, aligned with out_tokens
    out_lps: list = dataclasses.field(default_factory=list)
    # scheduling state (accelerate_tpu.scheduling): admission class (lower
    # admits sooner), submit timestamp (queue-wait SLO + metrics), and the
    # preemption/resume carry — a preempted decode requeues with its
    # generated-so-far tokens plus its sampling key so the resumed stream
    # is token- and logprob-exact
    priority: int = 0
    submit_ts: float = 0.0
    preempted: bool = False
    deprioritized: bool = False
    ttft_done: bool = False
    resume_key: object = None
    # disaggregated serving (serving_fleet): a request whose prefill ran
    # on ANOTHER replica carries the handed-off KV payload; consumed once
    # at admission (a later preemption resumes by ordinary recompute)
    handoff: object = None
    # distributed-tracing context (telemetry.trace): the trace id minted
    # at submit. Rides the handoff blob and failover snapshots, so one
    # id follows the request across replicas end to end
    trace: Optional[int] = None


class ServingEngine:
    """Continuous-batching decode engine for a zoo model with the decode
    contract (``apply_fn(params, ids, positions=..., decode=True,
    cache=...) -> (logits, cache)``; llama / gpt2 / gptneox).

    ``prompt_buckets``: ascending prefill sizes; each distinct bucket
    compiles one prefill program. ``max_len``: cache capacity per slot
    (default: the model's ``max_position_embeddings``). Decoding is
    greedy at ``temperature=0`` (the token-exact-vs-generate setting) or
    temperature/top-k sampling with an independent per-slot key chain
    folded on the request uid (deterministic per ``seed``).

    ``paged_block_size``: enable the paged KV cache with this block size
    (rows per pool block; 16-64 keeps tables small and pool granularity
    useful). ``pool_blocks``: total pool blocks including the reserved
    trash sink (default ``num_slots * ceil(max_len / block_size) + 1``,
    i.e. dense-equivalent capacity — pass less to oversubscribe HBM and
    let admission control queue requests when the pool is full).
    """

    def __init__(
        self,
        model,
        num_slots: int = 4,
        prompt_buckets=(32, 128),
        max_len: Optional[int] = None,
        eos_token_id: Optional[int] = None,
        tick_block: int = 8,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        seed: int = 0,
        paged_block_size: Optional[int] = None,
        pool_blocks: Optional[int] = None,
        draft_model=None,
        gamma: int = 4,
        telemetry_log=None,
        program_cache=None,
        auto_bucketing: bool = False,
        scheduler=None,
        tracer=None,
    ):
        jax = _jax()
        jnp = jax.numpy
        # serving-side observability (TTFT, tokens/sec, queue depth, KV
        # utilisation, preemptions + Prometheus dump); ``telemetry_log``
        # (an EventLog) additionally mirrors snapshots into a run's JSONL
        from .telemetry.serving_metrics import ServingMetrics

        self.metrics = ServingMetrics(self, log=telemetry_log)
        self.model = model
        self.num_slots = num_slots
        self.prompt_buckets = tuple(sorted(prompt_buckets))
        self.max_len = max_len or model.config.max_position_embeddings
        # Compile management (docs/usage_guides/compilation.md): EVERY
        # engine program goes through one ProgramCache — construction
        # compiles nothing (buckets are lazy, ticks jit on first call),
        # and with a persistent store (``program_cache=`` or
        # ``ACCELERATE_COMPILE_CACHE_DIR``) a new replica deserializes
        # the programs a previous process compiled instead of re-JITting.
        from .telemetry.eventlog import EventLog

        self._log = telemetry_log if telemetry_log is not None else EventLog(None)
        # request tracing (telemetry.trace.Tracer, usually the fleet
        # router's shared instance): segments are recorded at admission,
        # prefill windows, decode ticks, preemption/resume, and retire.
        # None disables tracing with zero overhead beyond these guards.
        self.tracer = tracer
        if program_cache is None:
            from .aot import ProgramCache

            program_cache = ProgramCache.from_env(log=self._log, name="serving")
        self._pc = program_cache
        # Auto-bucketing: the static prompt_buckets seed a learned set —
        # prompt lengths beyond the seed grow new (power-of-two) buckets
        # on demand instead of falling to the chunked path, refined online
        # from the observed length histogram; compile count stays
        # O(len(buckets)) by construction.
        self.bucketer = None
        if auto_bucketing:
            from .aot import ShapeBucketer

            self.bucketer = ShapeBucketer(self.prompt_buckets, max_size=self.max_len)
        # Scheduling policy (accelerate_tpu.scheduling): accepts a
        # SchedulerConfig, a Scheduler, or anything with
        # ``to_scheduler_config()`` (utils.ServingSchedulerKwargs). The
        # default is behavior-preserving: unlimited budget, one priority
        # class, no shedding, no preemption.
        if scheduler is None:
            scheduler = SchedulerConfig()
        if hasattr(scheduler, "to_scheduler_config"):
            scheduler = scheduler.to_scheduler_config()
        self._sched = scheduler if isinstance(scheduler, Scheduler) else Scheduler(scheduler)
        if draft_model is not None and self._sched.config.enable_preemption:
            raise NotImplementedError(
                "decode preemption does not compose with speculative serving yet "
                "(resume recomputes only the target cache)"
            )
        # Speculative continuous batching: a draft model proposes gamma
        # tokens per slot, ONE target forward verifies them (greedy
        # accept-prefix; emitted tokens are exactly the target's own
        # greedy stream). Constraints are enforced below: dense layout,
        # temperature 0, bucket-sized prompts, no prefix caching.
        self.draft_model = draft_model
        self.gamma = int(gamma)
        if draft_model is not None:
            if paged_block_size is not None:
                raise NotImplementedError("speculative serving is dense-layout only (no paged cache yet)")
            if temperature != 0.0:
                raise NotImplementedError("speculative serving is greedy-only (temperature=0)")
            if self.gamma < 1:
                raise ValueError(f"gamma must be >= 1, got {gamma}")
            draft_cap = draft_model.config.max_position_embeddings
            if self.max_len > draft_cap:
                raise ValueError(
                    f"max_len {self.max_len} exceeds the draft cache "
                    f"(max_position_embeddings={draft_cap})"
                )
        if self.max_len > model.config.max_position_embeddings:
            raise ValueError(
                f"max_len {self.max_len} exceeds the model cache "
                f"(max_position_embeddings={model.config.max_position_embeddings})"
            )
        if max(self.prompt_buckets) > self.max_len:
            raise ValueError(
                f"prompt bucket {max(self.prompt_buckets)} exceeds the slot cache "
                f"(max_len={self.max_len})"
            )
        self.eos_token_id = eos_token_id
        self._seed = seed

        from .generation import _make_sampler

        sampler = _make_sampler(temperature, top_k)

        def ctx_jit(fn, name=None):
            """jit + re-enter the model's mesh context around every call:
            a shard_model'ed model pins ITS mesh for the cache sharding
            constraints and the paged kernel's shard_map (constraints
            bake in at the first trace; later calls hit the jit cache).

            Dispatch goes through the engine's ProgramCache (lowering at
            CALL time with the real input shardings, so GSPMD-propagated
            layouts are honoured exactly like lazy jit): with a
            persistent store attached, a restarted replica deserializes
            these programs instead of recompiling them."""
            jitted = self._pc.wrap_jit(jax.jit(fn), name=name or getattr(fn, "__name__", "program"))

            def call(*args):
                with self._trace_ctx():
                    return jitted(*args)

            return call

        params = model.params
        apply_fn = model.apply_fn

        # Cache layout: dense = leading slot axis over the per-row cache
        # pytree (each slot reserves max_len rows); paged = one shared
        # block pool + per-slot block tables (ops/paged_kv.py) — same
        # decode roofline, pool capacity decoupled from slots x max_len.
        self.paged = paged_block_size is not None
        if self.paged:
            from .ops.paged_kv import BlockAllocator, PagedConfig, paged_mode

            bs_ = int(paged_block_size)
            if bs_ < 1:
                raise ValueError(f"paged_block_size must be >= 1, got {paged_block_size}")
            # table width follows the MODEL's cache horizon: the zoo's
            # cached_attention declares [B, ceil(max_position_embeddings /
            # bs)] tables regardless of the engine's (possibly smaller)
            # max_len — but reservations and the default pool are budgeted
            # by max_len, which submit() enforces
            self._mb = -(-model.config.max_position_embeddings // bs_)
            nb = int(pool_blocks) if pool_blocks is not None else num_slots * (-(-self.max_len // bs_)) + 1
            self._pcfg = PagedConfig(block_size=bs_, num_blocks=nb)
            self._alloc = BlockAllocator(nb)
            self._shared_refs: dict[int, int] = {}  # prefix block id -> refcount
            # per-slot {table entry index -> pool block id}: owned blocks
            # are freed at retirement OR when the sliding window expires
            # them; shared (prefix) entries only drop a refcount
            self._slot_blocks: list[dict] = [{} for _ in range(num_slots)]
            self._slot_shared: list[dict] = [{} for _ in range(num_slots)]
            self._slot_table = [np.zeros((self._mb,), np.int32) for _ in range(num_slots)]
            # windowed models never read keys <= frontier - W, so their
            # pool cost is O(window + max_new), not O(total): below-band
            # entries start as trash and blocks expire behind the frontier.
            # Per-layer attention kinds (Gemma2 alternating local/global)
            # disable the recycling: a full_attention layer reads EVERY
            # position, so no block ever becomes dead
            self._window = getattr(model.config, "sliding_window", None)
            layer_types = getattr(model.config, "layer_types", None)
            if layer_types is not None and any(t != "sliding_attention" for t in layer_types):
                self._window = None
            with paged_mode(self._pcfg):
                _, pcache = jax.eval_shape(
                    lambda p, i, pos: apply_fn(p, i, positions=pos, decode=True, cache=None),
                    params,
                    jnp.zeros((num_slots, 1), jnp.int32),
                    jnp.zeros((num_slots, 1), jnp.int32),
                )
            self.slot_caches = jax.tree.map(lambda l: jnp.zeros(l.shape, l.dtype), pcache)
        elif pool_blocks is not None:
            raise ValueError("pool_blocks requires paged_block_size (paged mode)")
        else:
            # empty per-row cache template from a 1-token dummy prefill,
            # then a leading slot axis over the per-row cache pytree
            _, cache0 = jax.eval_shape(
                lambda p, i: apply_fn(p, i, positions=jnp.zeros((1, 1), jnp.int32), decode=True, cache=None),
                params,
                jnp.zeros((1, 1), jnp.int32),
            )
            if draft_model is not None:
                # the slot cache pytree becomes a {target, draft} pair; all
                # the slot machinery (insert, tree zeros) is pytree-generic
                _, d_cache0 = jax.eval_shape(
                    lambda p, i: draft_model.apply_fn(
                        p, i, positions=jnp.zeros((1, 1), jnp.int32), decode=True, cache=None
                    ),
                    draft_model.params,
                    jnp.zeros((1, 1), jnp.int32),
                )
                cache0 = {"t": cache0, "d": d_cache0}
            self.slot_caches = jax.tree.map(
                lambda l: jnp.zeros((num_slots, *l.shape), l.dtype), cache0
            )

        # host-side slot state
        self.slot_req: list[Optional[_Request]] = [None] * num_slots
        self.slot_tok = np.zeros((num_slots,), np.int32)
        self.slot_pos = np.zeros((num_slots,), np.int32)
        # slot phase: None (free) | "prefill" (streaming its prompt into a
        # row cache across ticks) | "decode" (advanced by the decode tick)
        self.slot_phase: list[Optional[str]] = [None] * num_slots
        self._prefill_state: list[Optional[dict]] = [None] * num_slots
        self._prefill_order: list[int] = []  # prefilling slots, admission order
        # pending requests, kept sorted by the scheduler's order key
        # (priority class, then submission order)
        self.queue: list[_Request] = []
        # uid -> ("queued"|"active"|"done", req|None): the O(1) lookup
        # behind every streaming accessor (admit/retire/cancel/preempt
        # maintain it; a linear slot+queue scan per poll() would be
        # O(requests) under thousands of queued uids)
        self._index: dict[int, tuple] = {}
        self._shed: dict[int, ShedError] = {}  # uid -> structured rejection
        self.done: dict[int, np.ndarray] = {}
        self._done_new: dict[int, np.ndarray] = {}  # uid -> generated suffix only
        self._done_lps: dict[int, np.ndarray] = {}  # uid -> per-generated-token logprobs
        self._uid = 0
        self._pool_blocked = False  # last admit pass hit pool exhaustion
        self.bucket_compile_ms: dict = {}  # (kind, bucket) -> build wall ms
        # raw (pre-jit) program + sample-args builder + trace contexts per
        # engine program, so perf_check() can roofline the real prefill /
        # decode jaxprs without compiling anything
        self._perf_programs: dict = {}

        # ---- jitted programs (compiled once each) ----
        def pick_lp(row, tok):
            """log P(tok) under the model's FULL distribution at this step
            (f32 log-softmax) — the standard serving logprob surface, even
            when sampling is temperature/top-k shaped."""
            return jax.nn.log_softmax(row.astype(jnp.float32))[tok]

        def prefill(params, ids, true_len, key):
            """[1, B] padded prompt -> (first next-token, its logprob,
            per-row cache with write index reset to true_len, advanced
            key)."""
            b_len = ids.shape[1]
            positions = jnp.broadcast_to(jnp.arange(b_len), (1, b_len))
            logits, cache = apply_fn(params, ids, positions=positions, decode=True, cache=None)
            key, sub = jax.random.split(key)
            row = logits[0, true_len - 1]
            next_tok = sampler(row[None], sub)[0]
            from .ops.kv_cache import reset_cache_index

            cache = reset_cache_index(cache, true_len)
            return next_tok, pick_lp(row, next_tok), cache, key

        key_aval = jax.eval_shape(lambda: jax.random.key(0))
        if draft_model is None:  # speculative admits route to _spec_prefill

            def _build_prefill(b):
                t0 = time.perf_counter()
                with self._trace_ctx():
                    prog = self._pc.compile(
                        prefill, params, jax.ShapeDtypeStruct((1, b), jnp.int32),
                        jax.ShapeDtypeStruct((), jnp.int32), key_aval,
                        name=f"prefill_b{b}",
                    )
                self._note_bucket_compile("prefill", b, (time.perf_counter() - t0) * 1000.0)
                return prog

            self._prefill = _LazyBuckets(_build_prefill)
            self._perf_programs["prefill"] = (
                prefill,
                lambda b: (
                    params,
                    jax.ShapeDtypeStruct((1, b), jnp.int32),
                    jax.ShapeDtypeStruct((), jnp.int32),
                    key_aval,
                ),
                (self._trace_ctx,),
            )

        # ---- chunked-prefill programs (long prompts / prefix suffixes) ----
        # one chunk size (the largest bucket) x {cold, warm}: compile count
        # stays O(buckets), prompt length is bounded only by max_len
        chunk = max(self.prompt_buckets)
        self._chunk = chunk

        def chunk_cold(params, ids):
            positions = jnp.broadcast_to(jnp.arange(ids.shape[1]), ids.shape)
            return apply_fn(params, ids, positions=positions, decode=True, cache=None)

        def chunk_warm(params, ids, pos0, cache):
            positions = pos0 + jnp.broadcast_to(jnp.arange(ids.shape[1]), ids.shape)
            return apply_fn(params, ids, positions=positions, decode=True, cache=cache)

        self._chunk_cold = ctx_jit(chunk_cold)
        self._chunk_warm = ctx_jit(chunk_warm)

        def sample_at(logits, offset, key):
            key, sub = jax.random.split(key)
            row = logits[0, offset]
            tok = sampler(row[None], sub)[0]
            return tok, pick_lp(row, tok), key

        self._sample_at = ctx_jit(sample_at)

        def reset_idx(cache, n):
            from .ops.kv_cache import reset_cache_index

            return reset_cache_index(cache, n)

        self._reset_idx = ctx_jit(reset_idx)

        if draft_model is None:
            # The resume-recompute program (preempt -> requeue -> resume
            # rebuilds the evicted KV by warm chunk windows) registered for
            # perf_check()/numerics_check(): the analysis stack must cover
            # every program the scheduler can launch, and this one is the
            # only engine program that reads AND extends a warm row cache.
            # The row-cache aval is the dense per-row template (chunk
            # windows run outside paged_mode in both layouts).
            _, row_aval = jax.eval_shape(
                lambda p, i: apply_fn(
                    p, i, positions=jnp.zeros((1, 1), jnp.int32), decode=True, cache=None
                ),
                params,
                jnp.zeros((1, 1), jnp.int32),
            )
            # the dense per-row cache template: what a KV handoff ships
            # (trimmed to true_len rows) and what the receiving replica
            # pads back before its paste/insert
            self._row_template = row_aval
            self._perf_programs["resume_recompute"] = (
                chunk_warm,
                lambda b: (
                    params,
                    jax.ShapeDtypeStruct((1, self._chunk), jnp.int32),
                    jax.ShapeDtypeStruct((), jnp.int32),
                    row_aval,
                ),
                (self._trace_ctx,),
            )

        # registered shared prefixes: id -> {"len", "cache", "tokens"}
        self._prefixes: dict[int, dict] = {}
        self._prefix_uid = 0

        def insert(slot_caches, row_cache, slot):
            return jax.tree.map(
                lambda big, row: jax.lax.dynamic_update_index_in_dim(big, row.astype(big.dtype), slot, 0),
                slot_caches,
                row_cache,
            )

        self._insert = ctx_jit(insert)

        # Decode K steps per host round-trip: one sync per TOKEN would be
        # latency-bound (10s of ms on tunnel-attached backends); the block
        # scan amortises it K-fold. A slot that finishes (eos / budget)
        # mid-block keeps computing until the block ends — those overshoot
        # tokens are discarded host-side and the slot's cache is fully
        # replaced at the next prefill-insert, so outputs stay token-exact.
        if tick_block < 1:
            raise ValueError(f"tick_block must be >= 1, got {tick_block}")
        self.tick_block = tick_block

        # independent sampling chain per slot (re-folded with the request
        # uid at each admit, so retries/new requests don't replay a chain)
        self._slot_keys = jax.vmap(jax.random.fold_in, (None, 0))(
            jax.random.key(seed), jnp.arange(num_slots)
        )

        def make_tick(step_body):
            """K-step tick scaffold shared by both cache layouts:
            ``step_body(params, caches, toks, poss, keys) -> (caches,
            next_toks, logprobs, keys)`` advances every slot one token."""

            def decode_tick(params, slot_caches, toks, poss, keys):
                def block_step(carry, _):
                    caches, toks, poss, keys = carry
                    caches, nxt, lps, keys = step_body(params, caches, toks, poss, keys)
                    return (caches, nxt, poss + 1, keys), (nxt, lps)

                (slot_caches, _, _, keys), (toks_k, lps_k) = jax.lax.scan(
                    block_step, (slot_caches, toks, poss, keys), None, length=tick_block
                )
                return slot_caches, toks_k, lps_k, keys  # each [K, slots]

            return decode_tick

        if self.paged:
            # Per-row frontiers are native to the paged layout (index is
            # [B], not a scalar), so the tick is ONE batched program — no
            # per-row vmap. Same key-split order as the dense one_step,
            # so outputs stay token-exact across layouts.
            def paged_step(params, cache, toks, poss, keys):
                logits, cache = apply_fn(
                    params, toks[:, None], positions=poss[:, None], decode=True, cache=cache
                )
                split = jax.vmap(jax.random.split)(keys)
                keys, subs = split[:, 0], split[:, 1]
                nxt = jax.vmap(lambda lg, s: sampler(lg[None], s)[0])(logits[:, -1], subs)
                lps = jax.vmap(pick_lp)(logits[:, -1], nxt)
                return cache, nxt, lps, keys

            from .ops.paged_kv import clear_slot, paged_mode, paste_blocks, paste_row, set_table_row

            # Lazy dispatch wrapped in BOTH trace contexts (paged layout +
            # model mesh), re-entered every call: contexts only matter at
            # trace time, and call-time lowering (ProgramCache.wrap_jit
            # lowers with the REAL concrete inputs) lets the program adapt
            # to whatever input shardings GSPMD propagates onto the pool
            # between pastes — an eagerly .lower()ed program would pin the
            # shardings it saw at construction and reject the real ones.
            raw_tick = make_tick(paged_step)
            tick = self._pc.wrap_jit(jax.jit(raw_tick), name="paged_decode_tick")
            pcfg = self._pcfg

            def decode_tick(*args):
                with paged_mode(pcfg), self._trace_ctx():
                    return tick(*args)

            self._decode_tick = decode_tick
            self._perf_programs["decode_tick"] = (
                raw_tick,
                lambda b: (params, self.slot_caches, self.slot_tok, self.slot_pos, self._slot_keys),
                (lambda: paged_mode(pcfg), self._trace_ctx),
            )
            self._paste = ctx_jit(paste_row)
            self._paste_blocks = ctx_jit(paste_blocks)
            self._clear_slot = ctx_jit(clear_slot)
            self._set_table = ctx_jit(set_table_row)
        else:
            def one_step(params, cache_row, tok, pos, key):
                logits, cache_row = apply_fn(
                    params, tok.reshape(1, 1), positions=pos.reshape(1, 1), decode=True, cache=cache_row
                )
                key, sub = jax.random.split(key)
                row = logits[0, -1]
                nxt = sampler(row[None], sub)[0]
                return cache_row, nxt, pick_lp(row, nxt), key

            def dense_step(params, caches, toks, poss, keys):
                return jax.vmap(one_step, in_axes=(None, 0, 0, 0, 0))(params, caches, toks, poss, keys)

            if draft_model is None:
                raw_dense_tick = make_tick(dense_step)
            else:
                # the spec engine's PLAIN tick (scheduler gating can route
                # ticks away from speculation): advance only the target
                # half of the {t, d} slot pytree. The draft cache goes
                # stale for plainly-decoded tokens — harmless, because
                # greedy speculative emission is the target's own argmax
                # stream regardless of what the draft proposes; staleness
                # costs acceptance rate, never tokens.
                def pair_step(params, caches, toks, poss, keys):
                    t_caches, nxt, lps, keys = dense_step(params, caches["t"], toks, poss, keys)
                    return {"t": t_caches, "d": caches["d"]}, nxt, lps, keys

                raw_dense_tick = make_tick(pair_step)
            self._decode_tick = ctx_jit(raw_dense_tick)
            self._perf_programs["decode_tick"] = (
                raw_dense_tick,
                lambda b: (params, self.slot_caches, self.slot_tok, self.slot_pos, self._slot_keys),
                (self._trace_ctx,),
            )

        if draft_model is not None:
            # ---- speculative programs (dense layout; greedy) ----------
            # One tick iteration per slot: speculative.py's shared
            # draft-propose / target-verify core, vmapped over the slot
            # axis — emitted tokens are exactly the target's greedy stream.
            d_apply = draft_model.apply_fn
            g = self.gamma
            from .speculative import build_spec_step

            _spec_core = build_spec_step(apply_fn, d_apply, g)

            def spec_row_step(t_params, d_params, row_caches, tok, pos):
                t_cache, d_cache, emit, lps, n_emit = _spec_core(
                    t_params, d_params, row_caches["t"], row_caches["d"], tok, pos
                )
                # the slot's next fed token is the last emitted one
                return {"t": t_cache, "d": d_cache}, emit, lps, n_emit, emit[n_emit - 1], pos + n_emit

            def spec_tick(t_params, d_params, slot_caches, toks, poss):
                def block_step(carry, _):
                    caches, toks, poss = carry
                    caches, emits, lps, n_emits, last, poss = jax.vmap(
                        spec_row_step, in_axes=(None, None, 0, 0, 0)
                    )(t_params, d_params, caches, toks, poss)
                    return (caches, last, poss), (emits, lps, n_emits)

                (slot_caches, _, poss), (emits_k, lps_k, n_k) = jax.lax.scan(
                    block_step, (slot_caches, toks, poss), None, length=tick_block
                )
                # [K, slots, g+1] tokens/lps; [K, slots] emit counts
                return slot_caches, emits_k, lps_k, n_k

            self._spec_tick = ctx_jit(spec_tick)
            # the spec engine decodes through spec_tick, not the dense tick
            self._perf_programs["decode_tick"] = (
                spec_tick,
                lambda b: (params, draft_model.params, self.slot_caches, self.slot_tok, self.slot_pos),
                (self._trace_ctx,),
            )

            from .ops.kv_cache import reset_cache_index

            def spec_prefill(t_params, d_params, ids, true_len):
                b_len = ids.shape[1]
                positions = jnp.broadcast_to(jnp.arange(b_len), (1, b_len))
                t_logits, t_cache = apply_fn(t_params, ids, positions=positions, decode=True, cache=None)
                _, d_cache = d_apply(d_params, ids, positions=positions, decode=True, cache=None)
                row = t_logits[0, true_len - 1].astype(jnp.float32)
                first = jnp.argmax(row).astype(jnp.int32)
                t_cache = reset_cache_index(t_cache, true_len)
                d_cache = reset_cache_index(d_cache, true_len)
                return first, jax.nn.log_softmax(row)[first], {"t": t_cache, "d": d_cache}

            def _build_spec_prefill(b):
                t0 = time.perf_counter()
                with self._trace_ctx():
                    prog = self._pc.compile(
                        spec_prefill, params, draft_model.params,
                        jax.ShapeDtypeStruct((1, b), jnp.int32), jax.ShapeDtypeStruct((), jnp.int32),
                        name=f"spec_prefill_b{b}",
                    )
                self._note_bucket_compile("spec_prefill", b, (time.perf_counter() - t0) * 1000.0)
                return prog

            self._spec_prefill = _LazyBuckets(_build_spec_prefill)
            self._perf_programs["prefill"] = (
                spec_prefill,
                lambda b: (
                    params,
                    draft_model.params,
                    jax.ShapeDtypeStruct((1, b), jnp.int32),
                    jax.ShapeDtypeStruct((), jnp.int32),
                ),
                (self._trace_ctx,),
            )
            # accept-rate telemetry: {"steps", "accepted", "emitted"}
            self.spec_stats = {"steps": 0, "accepted": 0, "emitted": 0}

    # ---- chunked prefill (host driver) ----------------------------------

    def _chunked_prefill(
        self, full_tokens: np.ndarray, row_cache=None, done_upto: int = 0, key=None, trace=None
    ):
        """Stream ``full_tokens[done_upto:]`` through the decode path in
        ``self._chunk``-sized end-aligned windows against ``row_cache``
        (None = fresh, ``done_upto`` must then be 0).

        Windows are END-aligned: a window covering new tokens ``[s, e)``
        runs as ``[max(0, e - C), e)`` — never past ``e`` — so cache writes
        stay inside ``[0, max_len)`` (a forward-padded tail would exceed it
        and ``dynamic_update_slice``'s start-clamping would silently corrupt
        the earliest rows). The overlapped head of a window recomputes
        bit-identical K/V from the true tokens (positions are absolute), so
        overlap is token-exact by construction; only a ``T < C`` window has
        a pad tail, whose garbage rows sit beyond the causal frontier and
        are overwritten by decode, exactly as in bucket prefill. Returns
        ``(next_tok | None, cache, key)`` with the cache write index reset
        to ``len(full_tokens)``; sampling happens only when ``key`` is given
        (prefix registration skips it).

        The continuous-batching scheduler does NOT call this loop — it
        advances the same :meth:`_run_window` steps one budget-claimed
        window per tick, so a long prompt never stalls running decodes."""
        jnp = _jax().numpy
        t = len(full_tokens)
        logits, s_last = None, 0
        s = done_upto
        while s < t:
            logits, row_cache, s_last, s = self._run_window(full_tokens, s, row_cache, trace=trace)
        row_cache = self._reset_idx(row_cache, jnp.int32(t))
        next_tok = lp = None
        if key is not None:
            next_tok, lp, key = self._sample_at(logits, jnp.int32(t - 1 - s_last), key)
        return next_tok, lp, row_cache, key

    def _next_window(self, t: int, s: int):
        """Plan the next end-aligned prefill window over ``full[ s, t)``:
        ``(w, s_adj, e)`` — width = smallest bucket covering the remainder
        (a short suffix after a long prefix runs a suffix-sized program,
        not a full chunk), else the largest chunk; jit specializes per
        width, so the compile count stays O(buckets). Auto-bucketing
        consults the CURRENT learned set without growing it (lookup, not
        bucket) — long-remainder chunks must not mint unbounded buckets.
        The width is also the window's token-budget claim."""
        c = self._chunk
        if self.bucketer is not None:
            w = self.bucketer.lookup(t - s) or c
        else:
            w = next((b for b in self.prompt_buckets if b >= t - s), c)
        e = min(s + w, t)
        return w, max(0, e - w), e  # end-aligned window [s_adj, s_adj + w)

    def _run_window(self, full_tokens: np.ndarray, s: int, row_cache, trace=None):
        """Execute ONE prefill window starting at new-token offset ``s``;
        returns ``(logits, cache, s_adj, e)``. With a trace id, each
        window records one ``prefill`` span — its frontier-contiguous
        wall time plus the compute-only dispatch in ``compute_ms``."""
        jnp = _jax().numpy
        t = len(full_tokens)
        w, s_adj, e = self._next_window(t, s)
        window = np.zeros((1, w), np.int32)
        real = full_tokens[s_adj : s_adj + w]
        window[0, : len(real)] = real
        t0 = time.perf_counter()
        if row_cache is None:
            logits, row_cache = self._chunk_cold(self.model.params, jnp.asarray(window))
        else:
            row_cache = self._reset_idx(row_cache, jnp.int32(s_adj))
            logits, row_cache = self._chunk_warm(
                self.model.params, jnp.asarray(window), jnp.int32(s_adj), row_cache
            )
        if self.tracer is not None and trace is not None:
            self.tracer.seg(
                trace, "prefill", tokens=int(w),
                compute_ms=round((time.perf_counter() - t0) * 1000.0, 3),
            )
        return logits, row_cache, s_adj, e

    # ---- public API ----------------------------------------------------

    def register_prefix(self, prefix_ids) -> int:
        """Prefill a shared prompt prefix ONCE; requests submitted with the
        returned ``prefix_id`` copy its KV cache and prefill only their
        suffix. The finished output includes the prefix tokens."""
        toks = np.asarray(prefix_ids, np.int32).ravel()
        if self.draft_model is not None:
            raise NotImplementedError("speculative serving does not compose with prefix caching yet")
        if len(toks) == 0:
            raise ValueError("empty prefix")
        if len(toks) + 1 > self.max_len:
            raise ValueError(
                f"prefix length {len(toks)} leaves no room in the slot cache "
                f"(max_len={self.max_len})"
            )
        _, _, cache, _ = self._chunked_prefill(toks)
        pid = self._prefix_uid
        self._prefix_uid += 1
        entry = {"len": len(toks), "cache": cache, "tokens": toks}
        if self.paged:
            # reserve the prefix's FULL blocks and write their content ONCE
            # — this registration-time paste is the canonical shared bytes
            # every aliasing request reads; admits never rewrite them (a
            # rewrite would race slots actively decoding against the
            # blocks, and cross-program recomputes of the same K/V are not
            # guaranteed bit-identical)
            bs_ = self._pcfg.block_size
            n_full = len(toks) // bs_
            # windowed models: no request can ever read below the minimum
            # band (shortest suffix is 1 token), so registering those
            # blocks would pin pool space every aliasing table sets to
            # trash anyway — a 24k-token prefix with a 4k window pins
            # O(window), not O(prefix)
            lo_min = 0
            if self._window is not None:
                lo_min = min(max(0, len(toks) + 1 - self._window + 1) // bs_, n_full)
            ids = self._alloc.alloc(n_full - lo_min)
            if ids is None:
                raise ValueError(
                    f"prefix needs {n_full - lo_min} pool blocks but only "
                    f"{self._alloc.free_count} are free; raise pool_blocks or unregister prefixes"
                )
            entry["block_ids"] = dict(zip(range(lo_min, n_full), ids))
            for bid in ids:
                self._shared_refs[bid] = 1  # registration's own reference
            if ids:
                jnp = _jax().numpy
                write_row = np.zeros((self._mb,), np.int32)  # pad -> trash sink
                for i, bid in entry["block_ids"].items():
                    write_row[i] = bid
                self.slot_caches = self._paste_blocks(self.slot_caches, cache, jnp.asarray(write_row))
        self._prefixes[pid] = entry
        return pid

    def unregister_prefix(self, prefix_id: int) -> None:
        """Release a registered prefix's device cache (each prefix pins a
        full per-row KV pytree in HBM — long-running servers should evict
        prefixes they no longer route requests to)."""
        if prefix_id not in self._prefixes:
            raise ValueError(f"unknown prefix_id {prefix_id}")
        if any(r is not None and r.prefix_id == prefix_id for r in self.slot_req) or any(
            r.prefix_id == prefix_id for r in self.queue
        ):
            raise ValueError(f"prefix_id {prefix_id} still referenced by active/queued requests")
        entry = self._prefixes[prefix_id]
        if self.paged:
            # Validate every refcount BEFORE mutating anything so a failed
            # invariant (must survive python -O) leaves the pool accounting
            # intact for diagnosis rather than half-freed.
            for bid in entry.get("block_ids", {}).values():
                refs = self._shared_refs.get(bid)
                if refs != 1:
                    raise RuntimeError(f"shared block {bid} still referenced ({refs})")
        del self._prefixes[prefix_id]
        if self.paged:
            for bid in entry.get("block_ids", {}).values():
                self._shared_refs.pop(bid)
                self._alloc.free([bid])

    def submit(
        self,
        prompt_ids,
        max_new_tokens: int = 32,
        prefix_id: Optional[int] = None,
        stop_sequences=None,
        priority: int = 0,
        trace: Optional[int] = None,
    ) -> int:
        """Queue a prompt; returns a request id resolved via :meth:`poll`.
        With ``prefix_id``, ``prompt_ids`` is the SUFFIX after the registered
        prefix (at least one token — its logits seed the first sample).
        ``stop_sequences``: per-request token-id sequences (each a list of
        ints) that end generation when they appear in the generated tail —
        the token-level analogue of vLLM's ``stop``; the matched tokens stay
        in the output like an EOS does. ``priority``: admission class —
        lower admits sooner; sheddable/preemptible classes are configured
        by the engine's :class:`~accelerate_tpu.scheduling.SchedulerConfig`.
        When the queue-depth SLO is blown, sheddable submissions raise a
        structured :class:`~accelerate_tpu.scheduling.ShedError` (or are
        demoted, with ``shed_action="deprioritize"``) instead of silently
        queueing into a blown latency target."""
        prompt = np.asarray(prompt_ids, np.int32).ravel()
        if len(prompt) == 0:
            raise ValueError("empty prompt" + (" suffix" if prefix_id is not None else ""))
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        stops = tuple(tuple(int(t) for t in s) for s in (stop_sequences or ()))
        if any(len(s) == 0 for s in stops):
            raise ValueError("empty stop sequence")
        if self.draft_model is not None:
            if prefix_id is not None:
                raise NotImplementedError("speculative serving does not compose with prefix caching yet")
            if self.bucketer is None and len(prompt) > max(self.prompt_buckets):
                # auto-bucketing mints a covering bucket instead; the
                # max_len headroom check below still bounds the prompt
                raise ValueError(
                    f"speculative serving needs bucket-sized prompts "
                    f"(len {len(prompt)} > largest bucket {max(self.prompt_buckets)})"
                )
            if len(prompt) + max_new_tokens + self.gamma > self.max_len:
                raise ValueError(
                    f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens}) + gamma "
                    f"({self.gamma}) headroom exceeds the slot cache ({self.max_len})"
                )
        plen = 0
        if prefix_id is not None:
            if prefix_id not in self._prefixes:
                raise ValueError(f"unknown prefix_id {prefix_id}; call register_prefix first")
            plen = self._prefixes[prefix_id]["len"]
        if plen + len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prefix ({plen}) + prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the slot cache ({self.max_len})"
            )
        if self.paged:
            need = self._new_blocks_for(plen, len(prompt), max_new_tokens)
            if need > self._pcfg.num_blocks - 1:
                raise ValueError(
                    f"request needs {need} pool blocks but the pool has "
                    f"{self._pcfg.num_blocks - 1}; raise pool_blocks or paged_block_size"
                )
        priority = self._admission_shed_check(int(priority), trace=trace)
        uid = self._uid
        self._uid += 1
        if self.tracer is not None:
            # a router-minted trace arrives via ``trace=``; standalone
            # engines mint their own here, after the shed gate passed
            if trace is None:
                trace = self.tracer.start()
            self.tracer.attach(trace, uid=uid, prompt_tokens=len(prompt))
        req = _Request(
            uid, prompt, max_new_tokens, [], prefix_id, stops,
            priority=priority, submit_ts=time.monotonic(), trace=trace,
        )
        self._queue_push(req)
        self._index[uid] = ("queued", req)
        self.metrics.on_submit(uid)
        return uid

    # ---- disaggregated prefill / KV handoff (serving_fleet) -------------

    def kv_handoff_dims(self) -> tuple:
        """``(bytes_per_token, fixed_bytes)`` of this engine's dense
        per-row KV cache — the inputs
        :func:`~accelerate_tpu.analysis.costmodel.price_kv_handoff` needs
        to price a prefill→decode handoff BEFORE the prefill runs.
        Row-axis leaves (one K/V row per position) contribute per-token
        bytes; everything else (the write-index scalar) is fixed. The
        prediction and a router's post-transfer accounting
        (``handoff["wire_bytes"]``) must agree byte-for-byte."""
        jax = _jax()
        if self.draft_model is not None:
            raise NotImplementedError("disaggregated prefill does not compose with speculative serving")
        cap = self.model.config.max_position_embeddings
        per_tok = fixed = 0
        for leaf in jax.tree_util.tree_leaves(self._row_template):
            shape = tuple(int(d) for d in leaf.shape)
            n = 1
            for d in shape:
                n *= d
            nbytes = n * np.dtype(leaf.dtype).itemsize
            if _row_axis(shape, cap) is not None:
                per_tok += nbytes // cap
            else:
                fixed += nbytes
        return per_tok, fixed

    def _trim_row_cache(self, cache, n: int):
        """Host-side copy of a dense row cache keeping only its first
        ``n`` K/V rows — the handoff wire payload (garbage pad rows past
        the frontier never ship). Non-row leaves (the write index) pass
        through whole."""
        jax = _jax()
        cap = self.model.config.max_position_embeddings

        def trim(t, leaf):
            ax = _row_axis(tuple(int(d) for d in t.shape), cap)
            if ax is None:
                return np.asarray(leaf)
            idx = (slice(None),) * ax + (slice(0, n),)
            return np.asarray(leaf[idx])

        return jax.tree_util.tree_map(trim, self._row_template, cache)

    def _untrim_row_cache(self, cache, n: int):
        """Pad a trimmed handoff cache back to the full row template
        (zeros past row ``n`` — beyond the causal frontier by
        construction, overwritten by decode exactly like prefill pad)."""
        jax = _jax()
        jnp = jax.numpy
        cap = self.model.config.max_position_embeddings

        def pad(t, leaf):
            arr = np.asarray(leaf)
            shape = tuple(int(d) for d in t.shape)
            if tuple(arr.shape) != shape:
                ax = _row_axis(shape, cap)
                full = np.zeros(shape, t.dtype)
                full[(slice(None),) * ax + (slice(0, n),)] = arr
                arr = full
            return jnp.asarray(arr.astype(t.dtype, copy=False))

        return jax.tree_util.tree_map(pad, self._row_template, cache)

    def prefill_detached(
        self,
        prompt_ids,
        max_new_tokens: int = 32,
        *,
        uid_key: int = 0,
        prefix_id: Optional[int] = None,
        trace: Optional[int] = None,
    ) -> dict:
        """Run ONE request's prefill on THIS engine and return a
        host-transferable KV handoff instead of admitting it — the
        prefill half of disaggregated serving
        (:mod:`accelerate_tpu.serving_fleet`). The handoff carries the
        full prompt, the trimmed-to-``total``-rows KV cache as numpy
        leaves, the sampled first token + its logprob, and the advanced
        sampling-key data, so :meth:`submit_prefilled` on ANOTHER replica
        continues token- and logprob-exactly where a local prefill would
        have. ``wire_bytes`` is the payload a router accounts after the
        move; it equals ``price_kv_handoff``'s prediction exactly.

        ``uid_key`` seeds the per-request sampling chain (use the fleet
        uid: the stream is then deterministic per ``(seed, uid_key)``).
        With ``prefix_id``, ``prompt_ids`` is still the FULL prompt; its
        head must equal the registered prefix, whose cache seeds the
        chunk windows (radix-cache reuse composes with disaggregation on
        the prefill side)."""
        jax = _jax()
        if self.draft_model is not None:
            raise NotImplementedError("disaggregated prefill does not compose with speculative serving")
        prompt = np.asarray(prompt_ids, np.int32).ravel()
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        plen, pre = 0, None
        if prefix_id is not None:
            if prefix_id not in self._prefixes:
                raise ValueError(f"unknown prefix_id {prefix_id}; call register_prefix first")
            pre = self._prefixes[prefix_id]
            plen = pre["len"]
            if len(prompt) < plen + 1 or not np.array_equal(prompt[:plen], pre["tokens"]):
                raise ValueError("prompt does not start with the registered prefix tokens")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds the slot cache ({self.max_len})"
            )
        key = jax.random.fold_in(jax.random.key(self._seed), int(uid_key))
        next_tok, lp, cache, key = self._chunked_prefill(
            prompt, row_cache=None if pre is None else pre["cache"], done_upto=plen, key=key,
            trace=trace,
        )
        total = len(prompt)
        trimmed = self._trim_row_cache(cache, total)
        wire = int(sum(leaf.nbytes for leaf in jax.tree_util.tree_leaves(trimmed)))
        return {
            "prompt": prompt,
            "total": total,
            "max_new_tokens": int(max_new_tokens),
            "next_tok": int(next_tok),
            "lp": float(lp),
            "key_data": np.asarray(jax.random.key_data(key)),
            "cache": trimmed,
            "wire_bytes": wire,
            "reused_prefix_tokens": int(plen),
            "trace": trace,
        }

    def submit_prefilled(self, handoff: dict, stop_sequences=None, priority: int = 0) -> int:
        """Queue a request whose prefill already ran on another replica
        (:meth:`prefill_detached`): admission pastes the handed-off KV
        rows and emits the carried first token — ZERO prefill compute and
        zero tick token budget on this engine. Same shed/priority
        semantics as :meth:`submit`; outputs (tokens AND logprobs) are
        exact vs a local prefill by construction. A later preemption
        resumes by ordinary prefix recompute — the handoff payload is
        consumed at first admission."""
        if self.draft_model is not None:
            raise NotImplementedError("disaggregated prefill does not compose with speculative serving")
        prompt = np.asarray(handoff["prompt"], np.int32).ravel()
        total, max_new = int(handoff["total"]), int(handoff["max_new_tokens"])
        if total != len(prompt):
            raise ValueError(f"handoff total {total} != prompt length {len(prompt)}")
        stops = tuple(tuple(int(t) for t in s) for s in (stop_sequences or ()))
        if any(len(s) == 0 for s in stops):
            raise ValueError("empty stop sequence")
        if total + max_new > self.max_len:
            raise ValueError(
                f"prompt ({total}) + max_new_tokens ({max_new}) "
                f"exceeds the slot cache ({self.max_len})"
            )
        if self.paged:
            need = self._new_blocks_for(0, total, max_new)
            if need > self._pcfg.num_blocks - 1:
                raise ValueError(
                    f"request needs {need} pool blocks but the pool has "
                    f"{self._pcfg.num_blocks - 1}; raise pool_blocks or paged_block_size"
                )
        trace = handoff.get("trace")
        priority = self._admission_shed_check(int(priority), trace=trace)
        uid = self._uid
        self._uid += 1
        if self.tracer is not None and trace is not None:
            self.tracer.attach(trace, decode_uid=uid)
        req = _Request(
            uid, prompt, max_new, [], None, stops,
            priority=priority, submit_ts=time.monotonic(), handoff=dict(handoff),
            trace=trace,
        )
        self._queue_push(req)
        self._index[uid] = ("queued", req)
        self.metrics.on_submit(uid)
        return uid

    # ---- fleet failover: in-flight export / import (serving_fleet) ------

    def _snapshot_request(self, req: _Request) -> dict:
        """Portable base snapshot of one request: the FULL prompt (a
        registered prefix is inlined — the destination replica may not
        have it), the generated-so-far tokens/logprobs, and the admission
        metadata. The caller adds the sampling-chain ``key_data`` (which
        depends on where the request currently lives)."""
        prompt = req.prompt
        if req.prefix_id is not None:
            pre = self._prefixes[req.prefix_id]
            prompt = np.concatenate([np.asarray(pre["tokens"], np.int32), prompt])
        return {
            "uid": int(req.uid),
            "prompt": np.asarray(prompt, np.int32),
            "max_new_tokens": int(req.max_new_tokens),
            "out_tokens": [int(t) for t in req.out_tokens],
            "out_lps": [float(v) for v in req.out_lps],
            "stop_sequences": req.stop_sequences,
            "priority": int(req.priority),
            "trace": req.trace,
        }

    def export_inflight(self, include_kv: bool = True) -> list:
        """Snapshot EVERY in-flight request (queued + active) for
        migration to another replica — the failover half of
        :mod:`accelerate_tpu.serving_fleet`. Non-mutating: the engine is
        left exactly as found (the router decides what to do with the
        husk). Each snapshot carries the request plus its sampling-chain
        ``key_data``, so :meth:`import_inflight` on a survivor continues
        token- and logprob-exactly; decoding slots additionally export
        their trimmed KV rows (``cache`` + ``rows``) when ``include_kv``
        and the layout allows (dense, non-speculative — paged/speculative
        slots fail over by prefix recompute, which is equally exact).

        Safe at every labeled serving crash point by construction: the
        crash hooks fire BEFORE the jitted tick calls, so the host
        bookkeeping (out_tokens, slot_pos, slot keys, unconsumed
        handoffs) is always consistent when a failover export runs."""
        jax = _jax()
        kv_ok = include_kv and not self.paged and self.draft_model is None
        snaps = []

        def handoff_snap(req, h):
            # an unconsumed handoff payload (queued or awaiting paste):
            # fold its sampled first token into the output stream — the
            # importer re-feeds it at the pasted frontier (or recomputes)
            snap = self._snapshot_request(req)
            if h["next_tok"] is not None:
                snap["out_tokens"] = snap["out_tokens"] + [int(h["next_tok"])]
                snap["out_lps"] = snap["out_lps"] + [float(h["lp"])]
            snap["key_data"] = np.asarray(h["key_data"])
            if kv_ok and h.get("cache") is not None:
                snap["cache"], snap["rows"] = h["cache"], int(h["total"])
            return snap

        for slot, req in enumerate(self.slot_req):
            if req is None:
                continue
            if self.slot_phase[slot] == "decode":
                snap = self._snapshot_request(req)
                snap["key_data"] = np.asarray(jax.random.key_data(self._slot_keys[slot]))
                if kv_ok:
                    rows = int(self.slot_pos[slot])
                    row = jax.tree_util.tree_map(lambda big: big[slot], self.slot_caches)
                    snap["cache"], snap["rows"] = self._trim_row_cache(row, rows), rows
                snaps.append(snap)
                continue
            st = self._prefill_state[slot]
            if st is not None and st.get("handoff") is not None:
                snaps.append(handoff_snap(req, st["handoff"]))
                continue
            snap = self._snapshot_request(req)
            key = st["key"] if st is not None else jax.random.fold_in(
                jax.random.key(self._seed), req.uid
            )
            snap["key_data"] = np.asarray(jax.random.key_data(key))
            snaps.append(snap)
        for req in self.queue:
            if req.handoff is not None:
                snaps.append(handoff_snap(req, req.handoff))
                continue
            snap = self._snapshot_request(req)
            key = req.resume_key if req.resume_key is not None else jax.random.fold_in(
                jax.random.key(self._seed), req.uid
            )
            snap["key_data"] = np.asarray(jax.random.key_data(key))
            snaps.append(snap)
        return snaps

    def import_inflight(self, snap: dict) -> int:
        """Admit a migrated request exported by another replica's
        :meth:`export_inflight`, continuing its stream token- and
        logprob-exactly: the carried ``key_data`` pins the sampling chain
        and the resume machinery re-feeds the last generated token at the
        recomputed (or KV-pasted, when ``cache`` shipped) frontier.
        Bypasses the submit-time shed gate — migrated work already passed
        admission once; shedding it now would LOSE it. Returns this
        engine's local uid for the request."""
        jax = _jax()
        if self.draft_model is not None:
            raise NotImplementedError("failover import does not compose with speculative serving")
        prompt = np.asarray(snap["prompt"], np.int32).ravel()
        out = [int(t) for t in snap.get("out_tokens") or []]
        lps = [float(v) for v in snap.get("out_lps") or []]
        max_new = int(snap["max_new_tokens"])
        if len(prompt) == 0:
            raise ValueError("empty prompt in failover snapshot")
        if len(lps) != len(out):
            raise ValueError(f"snapshot logprobs ({len(lps)}) misaligned with tokens ({len(out)})")
        if len(out) > max_new:
            raise ValueError(f"snapshot carries {len(out)} tokens > max_new_tokens {max_new}")
        if len(prompt) + max_new > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new}) "
                f"exceeds the slot cache ({self.max_len})"
            )
        cache, rows = snap.get("cache"), int(snap.get("rows") or 0)
        if cache is not None:
            if not out:
                raise ValueError("KV failover import needs a generated token to re-feed")
            if rows != len(prompt) + len(out) - 1:
                raise ValueError(
                    f"KV rows ({rows}) != prompt ({len(prompt)}) + "
                    f"generated ({len(out)}) - 1 — not a consistent decode frontier"
                )
        stops = tuple(tuple(int(t) for t in s) for s in (snap.get("stop_sequences") or ()))
        uid = self._uid
        self._uid += 1
        req = _Request(
            uid, prompt, max_new, out, None, stops,
            out_lps=lps, priority=int(snap.get("priority", 0)),
            submit_ts=time.monotonic(), preempted=bool(out), ttft_done=bool(out),
            resume_key=jax.random.wrap_key_data(jax.numpy.asarray(snap["key_data"])),
            trace=snap.get("trace"),
        )
        if cache is not None:
            req.handoff = {
                "cache": cache, "total": rows, "next_tok": None, "lp": None,
                "key_data": np.asarray(snap["key_data"]),
            }
        self._queue_push(req)
        self._index[uid] = ("queued", req)
        self.metrics.on_submit(uid)
        self.metrics.on_failover_in()
        self._log.event(
            "failover_in", uid=uid, source_uid=int(snap.get("uid", -1)),
            generated=len(out), kv_rows=rows if cache is not None else 0,
            trace=snap.get("trace"),
        )
        return uid

    def _admission_shed_check(self, priority: int, trace: Optional[int] = None) -> int:
        """Shared submit-time SLO gate (:meth:`submit` /
        :meth:`submit_prefilled`): returns the possibly-demoted priority,
        or raises the structured :class:`ShedError` rejection. A shed
        rejection closes the request's trace (status ``shed``) — the
        trace id rides the shed event and the raised error."""
        reason = self._sched.shed_on_submit(priority, len(self.queue))
        if reason is None:
            return priority
        cfg = self._sched.config
        if cfg.shed_action == "deprioritize":
            self.metrics.on_deprioritize(None)
            self._log.event(
                "shed", action="deprioritize", priority=priority,
                queue_depth=len(self.queue), reason=reason, trace=trace,
            )
            return max(priority, cfg.deprioritize_to)
        self.metrics.on_shed(None)
        self._log.event(
            "shed", action="reject", priority=priority,
            queue_depth=len(self.queue), reason=reason, trace=trace,
        )
        if self.tracer is not None and trace is not None:
            self.tracer.finish(trace, status="shed", reason=reason)
        raise ShedError(reason, priority=priority, queue_depth=len(self.queue), trace_id=trace)

    def _queue_push(self, req: _Request) -> None:
        """Insert by the scheduler's order key (priority class, then
        submission order) — a preempted request's original uid keeps its
        place ahead of later arrivals in the same class."""
        bisect.insort(self.queue, req, key=lambda r: self._sched.order_key(r.priority, r.uid))

    def poll(self, uid: int):
        """The finished [S + new] tokens for ``uid``, or None if pending.
        Raises the request's structured :class:`ShedError` if the
        scheduler shed it from the queue (SLO load shedding)."""
        if uid in self._shed:
            raise self._shed[uid]
        return self.done.get(uid)

    def _locate(self, uid: int):
        """``("done"|"active"|"queued", req)`` for a known id (``req`` is
        None once done); raises KeyError for unknown/cancelled ids and the
        stored ShedError for shed ids. O(1): admit/retire/cancel/preempt
        maintain the uid index — streaming accessors never scan slots or
        the queue, so ``poll``/``partial`` stay flat under thousands of
        queued requests."""
        if uid in self._shed:
            raise self._shed[uid]
        try:
            return self._index[uid]
        except KeyError:
            raise KeyError(f"unknown request id {uid}") from None

    def partial(self, uid: int) -> np.ndarray:
        """Tokens generated SO FAR for ``uid`` (streaming surface) —
        ALWAYS the generated suffix (empty while queued), including after
        completion, so a delta-by-length streamer never re-emits prompt
        tokens; ``poll`` returns the full prompt+output sequence. Raises
        KeyError for unknown (or cancelled) ids. A preempted-and-requeued
        request keeps exposing its already-streamed tokens while it waits
        to resume — a delta streamer sees no regression across the
        eviction."""
        state, req = self._locate(uid)
        if state == "done":
            return self._done_new[uid]
        return np.asarray(req.out_tokens, np.int32)

    def logprobs(self, uid: int) -> np.ndarray:
        """log P(token) for each GENERATED token so far, under the model's
        full next-token distribution (f32 log-softmax — the standard
        serving logprob surface even when sampling is temperature/top-k
        shaped). Aligned with :meth:`partial` while decoding and with
        :meth:`poll`'s generated suffix once finished; empty while queued.
        Raises KeyError for unknown (or cancelled) ids."""
        state, req = self._locate(uid)
        if state == "done":
            return self._done_lps[uid]
        return np.asarray(req.out_lps, np.float32)

    def cancel(self, uid: int) -> np.ndarray:
        """Abort a queued, prefilling, or decoding request, returning
        whatever tokens it had generated (a preempted-and-requeued request
        returns its carried tokens). Its slot/pool blocks free
        immediately; ``poll`` never resolves a cancelled id. Raises
        ValueError if already finished, KeyError if unknown or shed."""
        if uid in self.done:
            raise ValueError(f"request {uid} already finished; poll() it instead")
        state, req = self._index.get(uid, (None, None))
        if state == "active":
            slot = next(s for s, r in enumerate(self.slot_req) if r is req)
            out = np.asarray(req.out_tokens, np.int32)
            self._release(slot)
            del self._index[uid]
            self.metrics.on_cancel(uid)
            if self.tracer is not None:
                self.tracer.finish(req.trace, status="cancelled")
            return out
        if state == "queued":
            self.queue.remove(req)
            del self._index[uid]
            self.metrics.on_cancel(uid)
            if self.tracer is not None:
                self.tracer.finish(req.trace, status="cancelled")
            return np.asarray(req.out_tokens, np.int32)
        raise KeyError(f"unknown request id {uid}")

    @property
    def active_count(self) -> int:
        return sum(r is not None for r in self.slot_req)

    def step(self) -> int:
        """One engine tick under the token-budget continuous-batching
        scheduler: shed over-SLO queue entries, advance in-flight prefill
        chunks and admissions inside the tick's remaining token budget
        (active decodes claim ``n_decoding x tick_block`` first), then
        ONE decode tick for every decoding slot. Returns the number of
        occupied slots after the tick.

        With the default config (unlimited budget) every admitted prefill
        completes in its admission tick — the pre-scheduler behavior.
        With a budget, a long prompt streams one chunk window per tick
        while decodes keep ticking: new requests make TTFT progress
        without ever stalling running decodes. The engine always forces
        at least one unit of progress per tick, so no budget setting can
        livelock ``run()``."""
        crash_point("pre_tick", replica=self.metrics.replica)
        now = time.monotonic()
        self._pool_blocked = False
        self._shed_pass(now)
        n_dec = sum(1 for ph in self.slot_phase if ph == "decode")
        budget = self._sched.tick_budget(n_dec, self.tick_block)
        # Admissions run FIRST and one admission per tick may overrun the
        # budget: a queued request's TTFT progress must not wait for an
        # in-flight long prefill to finish streaming (head-of-line
        # blocking is exactly what this scheduler removes). In-flight
        # prefills then take the leftover budget oldest-first, with a
        # one-window anti-starvation guarantee so a long prompt finishes
        # in at most windows-many ticks under sustained arrivals. Decodes
        # tick every step regardless — the per-tick prefill stall is
        # bounded by budget + two forced windows, never a whole prompt.
        force = True
        while self.queue:
            if budget <= 0 and not force:
                break
            slot = next((s for s in range(self.num_slots) if self.slot_req[s] is None), None)
            if slot is None:
                # priority inversion: a strictly more important request
                # waits while a lower class decodes — evict the youngest
                # such decode (policy-gated; None without preemption)
                slot = self._sched.pick_victim(self.queue[0].priority, self._decoding_info())
                if slot is None:
                    break
                self._preempt(slot)
            if not self._admit(slot):
                break  # pool blocked: the whole queue waits on its head
            budget = self._advance_prefill(slot, budget, force=force)
            force = False
        force = True
        for slot in list(self._prefill_order):
            budget = self._advance_prefill(slot, budget, force=force)
            force = False
        if any(ph == "decode" for ph in self.slot_phase):
            if self.draft_model is not None and self._sched.use_speculative(
                [p for _, p, _ in self._decoding_info()]
            ):
                self._spec_decode_pass()
            else:
                self._plain_decode_pass()
        self._expire_window_blocks()
        return self.active_count

    # ---- scheduler passes (one step() = one tick) -----------------------

    def _decoding_info(self) -> list:
        """``[(slot, priority, uid), ...]`` for decode-phase slots — the
        scheduler's victim-selection / speculative-gating view."""
        return [
            (slot, req.priority, req.uid)
            for slot, req in enumerate(self.slot_req)
            if req is not None and self.slot_phase[slot] == "decode"
        ]

    def _shed_pass(self, now: float) -> None:
        """SLO queue-wait enforcement: sheddable requests whose wait has
        blown ``max_queue_wait_s`` are rejected with a structured
        :class:`ShedError` (surfaced by the next ``poll``) or demoted
        once (``shed_action="deprioritize"``) — never silently queued."""
        cfg = self._sched.config
        if cfg.max_queue_wait_s is None or not self.queue:
            return
        for req in list(self.queue):
            wait_s = now - req.submit_ts
            reason = self._sched.shed_on_wait(req.priority, wait_s)
            if reason is None:
                continue
            if cfg.shed_action == "deprioritize":
                if req.deprioritized or req.priority >= cfg.deprioritize_to:
                    continue
                self.queue.remove(req)
                req.deprioritized = True
                req.priority = cfg.deprioritize_to
                self._queue_push(req)
                self.metrics.on_deprioritize(req.uid)
                self._log.event(
                    "shed", action="deprioritize", uid=req.uid, priority=req.priority,
                    queue_wait_ms=round(wait_s * 1000.0, 3), reason=reason,
                )
            else:
                self.queue.remove(req)
                err = ShedError(
                    reason, uid=req.uid, priority=req.priority,
                    queue_depth=len(self.queue), queue_wait_ms=wait_s * 1000.0,
                    trace_id=req.trace,
                )
                self._shed[req.uid] = err
                self._index.pop(req.uid, None)
                self.metrics.on_shed(req.uid)
                self._log.event(
                    "shed", action="reject", uid=req.uid, priority=req.priority,
                    queue_wait_ms=round(wait_s * 1000.0, 3), reason=reason, trace=req.trace,
                )
                if self.tracer is not None:
                    self.tracer.finish(req.trace, status="shed", reason=reason)

    def _reserve_blocks(self, req: _Request):
        """Reserve the paged pool blocks a request needs (resume-aware);
        ``(owned, shared_entries, table, write_row)`` or None when the
        pool cannot satisfy it."""
        plen, prompt_len, max_new = self._request_block_dims(req)
        lo, hi, alias_hi = self._plan_blocks(plen, prompt_len, max_new)
        shared_entries: dict[int, int] = {}
        if req.prefix_id is not None:
            pids = self._prefixes[req.prefix_id]["block_ids"]
            # every i in [lo, alias_hi) is registered: the prefix's
            # lo_min (suffix length 1) lower-bounds any request's lo
            shared_entries = {i: pids[i] for i in range(lo, alias_hi)}
        new_ids = self._alloc.alloc((hi - lo) - len(shared_entries))
        if new_ids is None:
            return None
        for bid in shared_entries.values():
            self._shared_refs[bid] += 1
        table = np.zeros((self._mb,), np.int32)  # pad/out-of-band -> trash sink
        owned: dict[int, int] = {}
        ids = iter(new_ids)
        for i in range(lo, hi):
            if i in shared_entries:
                table[i] = shared_entries[i]
            else:
                owned[i] = table[i] = next(ids)
        # the paste writes ONLY this request's own blocks: shared prefix
        # entries go to the trash sink in the write row (their canonical
        # content was written at registration)
        write_row = table.copy()
        for i in shared_entries:
            write_row[i] = 0
        return owned, shared_entries, table, write_row

    def _admit(self, slot: int) -> bool:
        """Move the queue head into ``slot`` in the prefill phase,
        reserving its pool blocks first (paged). Under pool exhaustion,
        policy may evict the youngest lower-priority decode and retry
        once; failing that, admission blocks (returns False) and the
        whole queue waits on its head — no starvation of large requests
        by later small ones."""
        jax = _jax()
        req = self.queue[0]
        if self.paged:
            plan = self._reserve_blocks(req)
            if plan is None:
                victim = self._sched.pick_victim(req.priority, self._decoding_info())
                if victim is not None:
                    self._preempt(victim)
                    plan = self._reserve_blocks(req)
            if plan is None:
                self._pool_blocked = True
                self.metrics.on_pool_blocked()
                return False
            owned, shared_entries, table, write_row = plan
        self.queue.pop(0)
        resume = req.preempted and len(req.out_tokens) > 0
        st: dict = {"req": req, "resume": resume, "bucket": None}
        if self.paged:
            self._slot_blocks[slot], self._slot_shared[slot] = owned, shared_entries
            self._slot_table[slot] = table
            st["table"], st["write_row"] = table, write_row
        # the per-request sampling chain: fold the uid at first admission,
        # carry the evicted chain across a preemption — the resumed stream
        # continues the SAME chain, so sampled outputs stay request-exact
        if req.resume_key is not None:
            st["key"] = req.resume_key
        else:
            st["key"] = jax.random.fold_in(jax.random.key(self._seed), req.uid)
        if req.handoff is not None:
            # disaggregated admission: the KV rows, first token, and the
            # advanced sampling chain all arrived with the handoff — no
            # prefill program runs here. Consumed once: a preemption
            # resumes by the ordinary recompute path below. A FAILOVER
            # import (export_inflight -> import_inflight) rides the same
            # path with resume=True: the pasted rows are the migrated
            # request's exact KV frontier, and the resume finalize re-feeds
            # its carried last token instead of emitting h["next_tok"].
            st["handoff"] = req.handoff
            st["key"] = jax.random.wrap_key_data(jax.numpy.asarray(req.handoff["key_data"]))
            req.handoff = None
        elif self.draft_model is not None:
            st["bucket"], st["spec"] = self._bucket_for(len(req.prompt)), True
        elif not resume and req.prefix_id is None and (b := self._bucket_for(len(req.prompt))) is not None:
            # short prompt, no prefix: the one-shot fused program
            # (auto-bucketing: the bucketer can mint a new covering
            # bucket here, so "short" stretches to any prompt <= max_len)
            st["bucket"] = b
        else:
            # prefix-seeded, long, or resumed prompt: chunk windows. The
            # stored prefix cache is never mutated — jax arrays are
            # immutable, each request builds on its own copy. A resumed
            # request recomputes prompt + all-but-last generated tokens;
            # its last token is re-fed at the recomputed frontier.
            pre = self._prefixes[req.prefix_id] if req.prefix_id is not None else None
            parts = ([] if pre is None else [pre["tokens"]]) + [req.prompt]
            if resume:
                parts.append(np.asarray(req.out_tokens[:-1], np.int32))
            st["full"] = parts[0] if len(parts) == 1 else np.concatenate(parts)
            st["done"] = 0 if pre is None else pre["len"]
            st["cache"] = None if pre is None else pre["cache"]
            st["logits"], st["s_last"] = None, 0
        self.slot_req[slot] = req
        self.slot_phase[slot] = "prefill"
        self._prefill_state[slot] = st
        self._prefill_order.append(slot)
        self._index[req.uid] = ("active", req)
        wait_ms = (time.monotonic() - req.submit_ts) * 1000.0
        self.metrics.on_admit(req.uid, priority=req.priority, queue_wait_ms=wait_ms)
        if not resume:
            self._log.event(
                "admit", uid=req.uid, priority=req.priority, queue_wait_ms=round(wait_ms, 3)
            )
        if self.tracer is not None:
            # queue_wait absorbs everything since the frontier (for a
            # fresh submit: since the trace started); accounted_ms is the
            # scheduler's own number — critpath cross-checks the two
            self.tracer.seg(req.trace, "queue_wait", accounted_ms=round(wait_ms, 3))
            self.tracer.seg(req.trace, "admit", resume=resume)
        return True

    def _advance_prefill(self, slot: int, budget: float, force: bool = False) -> float:
        """Spend tick budget on one slot's prefill: whole fused-bucket
        programs or chunk windows, each claiming its width in tokens.
        ``force`` lets the first window run even over budget (admission
        TTFT progress / anti-starvation — also why no budget setting can
        livelock ``run()``); an unaffordable later window waits for the
        next tick's budget."""
        jnp = _jax().numpy
        st = self._prefill_state[slot]
        if st is None:
            return budget
        crash_point("mid_prefill", replica=self.metrics.replica)
        req = st["req"]
        if st.get("handoff") is not None:
            # the prefill compute already happened on another replica:
            # pad the trimmed rows back onto the template and paste —
            # zero tokens of this tick's budget are spent
            h = st.pop("handoff")
            cache = self._untrim_row_cache(h["cache"], h["total"])
            if self.tracer is not None:
                # the paste half of the handoff (the router recorded the
                # priced wire move); no moved_bytes here, so critpath
                # skips this span's byte check by design
                self.tracer.seg(req.trace, "kv_handoff", phase="paste", rows=int(h["total"]))
            self._finalize_prefill(slot, cache, h["total"], h["next_tok"], h["lp"], st["key"])
            return budget
        if st["bucket"] is not None:
            b = st["bucket"]
            if budget < b and not force:
                return budget
            padded = np.zeros((1, b), np.int32)
            padded[0, : len(req.prompt)] = req.prompt
            t0 = time.perf_counter()
            if st.get("spec"):
                # speculative admit: both models prefill the prompt (greedy)
                next_tok, lp, row_cache = self._spec_prefill[b](
                    self.model.params, self.draft_model.params,
                    jnp.asarray(padded), jnp.int32(len(req.prompt)),
                )
                key = st["key"]
            else:
                next_tok, lp, row_cache, key = self._prefill[b](
                    self.model.params, jnp.asarray(padded), jnp.int32(len(req.prompt)), st["key"]
                )
            if self.tracer is not None:
                self.tracer.seg(
                    req.trace, "prefill", tokens=int(b),
                    compute_ms=round((time.perf_counter() - t0) * 1000.0, 3),
                )
            self._finalize_prefill(slot, row_cache, len(req.prompt), next_tok, lp, key)
            return budget - b
        full = st["full"]
        t = len(full)
        while st["done"] < t:
            w, _, _ = self._next_window(t, st["done"])
            if budget < w and not force:
                return budget
            st["logits"], st["cache"], st["s_last"], st["done"] = self._run_window(
                full, st["done"], st["cache"], trace=req.trace
            )
            budget -= w
            force = False
        cache = self._reset_idx(st["cache"], jnp.int32(t))
        if st["resume"]:
            self._finalize_prefill(slot, cache, t, None, None, st["key"])
        else:
            next_tok, lp, key = self._sample_at(
                st["logits"], jnp.int32(t - 1 - st["s_last"]), st["key"]
            )
            self._finalize_prefill(slot, cache, t, next_tok, lp, key)
        return budget

    def _finalize_prefill(self, slot: int, row_cache, total: int, next_tok, lp, key) -> None:
        """Prefill complete: paste/insert the row cache, move the slot to
        the decode phase, and either emit the sampled first token (TTFT)
        or — resume — re-feed the carried last token at the recomputed
        frontier without sampling anything."""
        jnp = _jax().numpy
        st = self._prefill_state[slot]
        req = st["req"]
        self._slot_keys = self._slot_keys.at[slot].set(key)
        if self.paged:
            self.slot_caches = self._paste(
                self.slot_caches, row_cache, jnp.asarray(st["write_row"]),
                jnp.asarray(st["table"]), jnp.int32(slot), jnp.int32(total),
            )
        else:
            self.slot_caches = self._insert(self.slot_caches, row_cache, jnp.int32(slot))
        self._prefill_state[slot] = None
        self._prefill_order.remove(slot)
        self.slot_phase[slot] = "decode"
        if st["resume"]:
            # token- and logprob-exact by construction: nothing is
            # re-sampled; already-streamed tokens/logprobs are untouched
            self.slot_tok[slot] = int(req.out_tokens[-1])
            self.slot_pos[slot] = total
            self.metrics.on_resume(req.uid)
            self._log.event(
                "resume", uid=req.uid, priority=req.priority,
                recomputed_tokens=int(total), generated=len(req.out_tokens),
            )
            if self.tracer is not None:
                self.tracer.seg(req.trace, "resume", recomputed_tokens=int(total))
            return
        tok = int(next_tok)
        req.out_tokens.append(tok)
        req.out_lps.append(float(lp))
        if not req.ttft_done:
            req.ttft_done = True
            self.metrics.on_first_token(req.uid)  # TTFT: prefill's tail token
        self.metrics.on_tokens(1)
        if self._finished(req, tok):
            self._retire(slot)
            return
        self.slot_tok[slot] = tok
        self.slot_pos[slot] = total

    def _preempt(self, slot: int) -> None:
        """Evict a decoding slot: requeue its request with the
        generated-so-far tokens and its sampling chain, free the slot and
        its KV blocks now. The resume admission rebuilds the cache by
        chunked recomputation — see :meth:`_finalize_prefill`."""
        req = self.slot_req[slot]
        req.resume_key = self._slot_keys[slot]
        req.preempted = True
        self._release(slot)
        self._queue_push(req)
        self._index[req.uid] = ("queued", req)
        self.metrics.on_preempt_decode(req.uid)
        self._log.event(
            "preempt_decode", uid=req.uid, priority=req.priority,
            generated=len(req.out_tokens),
        )
        if self.tracer is not None:
            self.tracer.seg(req.trace, "preempt", generated=len(req.out_tokens))

    def _plain_decode_pass(self) -> None:
        """ONE jitted K-step tick for every decode-phase slot, then the
        host walk that streams tokens/logprobs out. Prefilling slots
        compute garbage rows by construction (static shapes) — their
        caches are fully replaced at prefill paste/insert."""
        crash_point("mid_decode", replica=self.metrics.replica)
        jnp = _jax().numpy
        self.slot_caches, toks_k, lps_k, self._slot_keys = self._decode_tick(
            self.model.params, self.slot_caches,
            jnp.asarray(self.slot_tok), jnp.asarray(self.slot_pos), self._slot_keys
        )
        toks_k = np.asarray(toks_k)  # [K, slots] — ONE host sync per block
        lps_k = np.asarray(lps_k)
        for slot, req in enumerate(self.slot_req):
            if req is None or self.slot_phase[slot] != "decode":
                continue
            n_new, retired = 0, False
            for k in range(self.tick_block):
                tok = int(toks_k[k, slot])
                req.out_tokens.append(tok)
                req.out_lps.append(float(lps_k[k, slot]))
                self.metrics.on_tokens(1)
                n_new += 1
                self.slot_pos[slot] += 1
                self.slot_tok[slot] = tok
                if self._finished(req, tok):
                    retired = True
                    break  # remaining block tokens are overshoot — discarded
            if n_new:
                self.metrics.on_tick_tokens(req.uid, n_new)
                if self.tracer is not None:
                    self.tracer.window(req.trace, "decode", tokens=n_new)
            if retired:
                self._retire(slot)

    def _expire_window_blocks(self) -> None:
        """Sliding-window models: expire blocks the band can no longer
        read — entries fully below frontier - W + 1 return to the pool
        (owned) or drop a refcount (shared); their table entries point at
        the trash sink before the next tick, so the (masked) reads stay
        valid."""
        if not self.paged or self._window is None:
            return
        jnp = _jax().numpy
        bs_ = self._pcfg.block_size
        for slot, req in enumerate(self.slot_req):
            if req is None or self.slot_phase[slot] != "decode":
                continue
            keep_from = max(0, int(self.slot_pos[slot]) - self._window + 1) // bs_
            dead_own = [i for i in self._slot_blocks[slot] if i < keep_from]
            dead_shared = [i for i in self._slot_shared[slot] if i < keep_from]
            if not dead_own and not dead_shared:
                continue
            for i in dead_own:
                self._alloc.free([self._slot_blocks[slot].pop(i)])
                self._slot_table[slot][i] = 0
            for i in dead_shared:
                self._shared_refs[self._slot_shared[slot].pop(i)] -= 1
                self._slot_table[slot][i] = 0
            self.slot_caches = self._set_table(
                self.slot_caches, jnp.int32(slot), jnp.asarray(self._slot_table[slot])
            )

    def run(self) -> dict:
        """Drive ticks until queue and slots drain; returns {uid: tokens}."""
        while self.queue or self.active_count:
            if self.step() == 0 and self.queue and self._pool_blocked:
                # admission hit pool exhaustion and NOTHING is active any
                # more — every block that can ever be free is free NOW. If
                # the head still doesn't fit, it is unsatisfiable
                # (registered prefixes hold the rest of the pool) and
                # raising beats the silent busy-loop; if it fits, the
                # blocking was transient (the tick's retirements freed
                # blocks after the admit pass) and the next step admits it.
                need = self._head_new_blocks()
                if need > self._alloc.free_count:
                    raise RuntimeError(
                        f"request {self.queue[0].uid} needs {need} pool blocks but "
                        f"only {self._alloc.free_count} can ever be free (registered prefixes "
                        "hold the rest); raise pool_blocks or unregister unused prefixes"
                    )
        return dict(self.done)

    def generate_many(self, prompts, max_new_tokens: int = 32) -> list:
        """Convenience: submit all prompts, run to completion, return the
        completed token arrays in submission order."""
        uids = [self.submit(p, max_new_tokens) for p in prompts]
        self.run()
        return [self.done[u] for u in uids]

    # ---- internals ------------------------------------------------------

    def _spec_decode_pass(self) -> int:
        """The speculative tick's host half: run ``tick_block`` draft+verify
        iterations on device, then walk the variable per-slot emit counts
        (``n_emit = accepted + 1`` tokens per iteration) exactly like the
        one-token tick walks its block — overshoot past retirement is
        discarded identically."""
        jnp = _jax().numpy
        self.slot_caches, emits_k, lps_k, n_k = self._spec_tick(
            self.model.params, self.draft_model.params, self.slot_caches,
            jnp.asarray(self.slot_tok), jnp.asarray(self.slot_pos),
        )
        emits_k = np.asarray(emits_k)  # [K, slots, gamma+1]
        lps_k = np.asarray(lps_k)
        n_k = np.asarray(n_k)  # [K, slots]
        for slot, req in enumerate(self.slot_req):
            if req is None or self.slot_phase[slot] != "decode":
                continue
            retired, n_new = False, 0
            for k in range(self.tick_block):
                n = int(n_k[k, slot])
                self.spec_stats["steps"] += 1  # one target forward spent
                walked = 0
                for j in range(n):
                    tok = int(emits_k[k, slot, j])
                    req.out_tokens.append(tok)
                    req.out_lps.append(float(lps_k[k, slot, j]))
                    self.metrics.on_tokens(1)
                    walked += 1
                    n_new += 1
                    self.slot_pos[slot] += 1
                    self.slot_tok[slot] = tok
                    if self._finished(req, tok):
                        retired = True
                        break
                # only USED tokens count (a mid-run EOS discards the rest;
                # the correction/bonus token is target-sourced, not a
                # draft acceptance) — matches speculative_generate's stats
                self.spec_stats["emitted"] += walked
                self.spec_stats["accepted"] += min(walked, n - 1)
                if retired:
                    break
            if n_new:
                self.metrics.on_tick_tokens(req.uid, n_new)
                if self.tracer is not None:
                    self.tracer.window(req.trace, "decode", tokens=n_new)
            if retired:
                self._retire(slot)
        return self.active_count

    def _finished(self, req: _Request, tok: int) -> bool:
        if self.eos_token_id is not None and tok == self.eos_token_id:
            return True
        for seq in req.stop_sequences:
            if len(req.out_tokens) >= len(seq) and req.out_tokens[-len(seq):] == list(seq):
                return True
        return len(req.out_tokens) >= req.max_new_tokens

    def _trace_ctx(self):
        """Mesh context for tracing engine programs: a sharded model's
        mesh (shard_model sets ``model.mesh``), else a no-op."""
        from .generation import _trace_ctx

        return _trace_ctx(getattr(self.model, "mesh", None))

    def perf_check(self, mesh=None, generation=None, bucket=None, dcn=None) -> dict:
        """Static roofline of the engine's real serving programs — the
        prefill at ``bucket`` (default: the smallest prompt bucket) and
        the decode tick — via :func:`analysis.perfmodel.perf_check`.
        Nothing compiles or executes: the same raw functions the engine
        jits are traced abstractly, so the report prices exactly the
        programs that serve traffic (per-op FLOPs / HBM bytes /
        bytes-on-wire, predicted step time, MFU upper bound, TPU5xx
        findings). Returns ``{"prefill": PerfReport, "decode_tick":
        PerfReport}`` (whichever programs this engine configuration
        has). ``mesh`` defaults to the sharded model's mesh, else a
        single-device mesh."""
        jax = _jax()
        import contextlib

        from .analysis.perfmodel import perf_check as _perf_check

        if mesh is None:
            mesh = getattr(self.model, "mesh", None)
        if mesh is None:
            from .parallel.mesh import MeshConfig

            mesh = MeshConfig(data=1).build(jax.devices()[:1])
        b = int(bucket) if bucket is not None else min(self.prompt_buckets)
        reports = {}
        for name, (fn, args_fn, ctx_factories) in self._perf_programs.items():
            with contextlib.ExitStack() as stack:
                for factory in ctx_factories:
                    stack.enter_context(factory())
                reports[name] = _perf_check(
                    fn, *args_fn(b), mesh=mesh, generation=generation, dcn=dcn
                )
        return reports

    def numerics_check(self, mesh=None, bucket=None, assume=None) -> dict:
        """Static numerics analysis of the engine's real serving programs
        (same program registry as :meth:`perf_check`) via
        :func:`analysis.numerics.numerics_check`: value intervals +
        dtype provenance over the prefill and decode-tick jaxprs, plus
        the TPU6xx precision findings — attention softmax overflow in
        low precision and unguarded normalisations are exactly the
        decode-path hazards this catches before a compile. Returns
        ``{"prefill": NumericsReport, "decode_tick": NumericsReport}``."""
        jax = _jax()
        import contextlib

        from .analysis.numerics import numerics_check as _numerics_check

        if mesh is None:
            mesh = getattr(self.model, "mesh", None)
        if mesh is None:
            from .parallel.mesh import MeshConfig

            mesh = MeshConfig(data=1).build(jax.devices()[:1])
        b = int(bucket) if bucket is not None else min(self.prompt_buckets)
        reports = {}
        for name, (fn, args_fn, ctx_factories) in self._perf_programs.items():
            with contextlib.ExitStack() as stack:
                for factory in ctx_factories:
                    stack.enter_context(factory())
                reports[name] = _numerics_check(fn, *args_fn(b), mesh=mesh, assume=assume)
        return reports

    def _bucket_for(self, n: int) -> Optional[int]:
        """Covering prefill bucket for an ``n``-token prompt: the minimal
        static bucket, or (auto-bucketing) the learned bucketer's choice —
        which records the observation and may mint a new bucket. ``None``
        routes the prompt to the chunked-prefill path."""
        if self.bucketer is not None:
            return self.bucketer.bucket(n)
        return next((b for b in self.prompt_buckets if b >= n), None)

    def _note_bucket_compile(self, kind: str, bucket: int, ms: float):
        """Per-bucket program-build attribution: lands in
        ``bucket_compile_ms`` (host-side inspection) and as ONE
        ``serving_bucket_compile`` telemetry event — startup/first-hit
        latency is attributable to the exact bucket that caused it. The
        wall time includes trace+lower plus either the XLA compile or
        (warm store) the deserialize; the paired ``compile_cache_*``
        event says which."""
        self.bucket_compile_ms[(kind, int(bucket))] = round(ms, 3)
        self._log.event(
            "serving_bucket_compile", program=kind, bucket=int(bucket), compile_ms=round(ms, 3)
        )

    @property
    def scheduler_config(self) -> SchedulerConfig:
        """The active :class:`~accelerate_tpu.scheduling.SchedulerConfig`
        (budget, priorities, SLO thresholds, preemption)."""
        return self._sched.config

    @property
    def program_cache(self):
        """The engine's :class:`~accelerate_tpu.aot.ProgramCache` (every
        prefill bucket and tick program routes through it)."""
        return self._pc

    def _plan_blocks(self, plen: int, prompt_len: int, max_new: int):
        """Live table-entry range ``[lo, hi)`` for a request, plus the
        count of leading prefix FULL blocks eligible for aliasing.
        ``hi`` reserves through the last *kept* write — position
        total + max_new - 2 (a finished slot's discarded overshoot
        writes land in trash entries or its own last block, never a
        neighbour's). ``lo`` is 0 unless the model has a sliding window:
        the decode band never reads positions <= total - W, so blocks
        entirely below it start as trash — a windowed request's pool
        cost is O(window + max_new) regardless of prompt length."""
        bs_ = self._pcfg.block_size
        total = plen + prompt_len
        hi = min(self._mb, -(-(total + max_new - 1) // bs_))
        lo = 0
        if self._window is not None:
            lo = min(max(0, total - self._window + 1) // bs_, hi)
        alias_hi = min(plen // bs_, hi)  # plen=0 (no prefix) -> nothing aliasable
        return lo, hi, alias_hi

    def _new_blocks_for(self, plen: int, prompt_len: int, max_new: int) -> int:
        """New (non-aliased) blocks a request allocates — the ONE place
        the capacity arithmetic lives (submit's feasibility check, the
        admission allocation, and run()'s unsatisfiable-head diagnostic
        must agree or admission deadlocks/overcommits)."""
        lo, hi, alias_hi = self._plan_blocks(plen, prompt_len, max_new)
        return (hi - lo) - max(0, alias_hi - lo)

    def _request_block_dims(self, req: _Request) -> tuple:
        """``(plen, prompt_len, max_new)`` for block planning — a
        preempted request resumes as prompt + all-but-last generated
        tokens with the remaining budget, which reserves exactly the
        blocks the original request would have (``hi`` is invariant
        across preemptions, so resume can never deadlock a pool the
        original admission fit)."""
        plen = self._prefixes[req.prefix_id]["len"] if req.prefix_id is not None else 0
        g = len(req.out_tokens)
        if req.preempted and g:
            return plen, len(req.prompt) + g - 1, req.max_new_tokens - g + 1
        return plen, len(req.prompt), req.max_new_tokens

    def _head_new_blocks(self) -> int:
        return self._new_blocks_for(*self._request_block_dims(self.queue[0]))

    @property
    def pool_free_blocks(self) -> Optional[int]:
        """Free blocks in the paged pool (None in dense mode)."""
        return self._alloc.free_count if self.paged else None

    def _retire(self, slot: int):
        req = self.slot_req[slot]
        parts = [req.prompt, np.asarray(req.out_tokens, np.int32)]
        if req.prefix_id is not None:
            parts.insert(0, self._prefixes[req.prefix_id]["tokens"])
        self.done[req.uid] = np.concatenate(parts)
        self._done_new[req.uid] = np.asarray(req.out_tokens, np.int32)
        self._done_lps[req.uid] = np.asarray(req.out_lps, np.float32)
        self._release(slot)
        self._index[req.uid] = ("done", None)
        self.metrics.on_complete(req.uid)
        if self.tracer is not None:
            self.tracer.finish(req.trace, status="ok", tokens=len(req.out_tokens))

    def _release(self, slot: int):
        """Free a slot's resources without publishing a result (shared by
        retirement, cancellation, and decode preemption)."""
        self.slot_phase[slot] = None
        self._prefill_state[slot] = None
        if slot in self._prefill_order:
            self._prefill_order.remove(slot)
        if self.paged:
            # Validate shared refcounts BEFORE any mutation (must survive
            # python -O): a tripped invariant must leave the slot, pool, and
            # table state intact for diagnosis, not half-freed.
            for bid in self._slot_shared[slot].values():
                if self._shared_refs.get(bid, 0) < 2:
                    raise RuntimeError(f"shared block {bid} over-freed")
        self.slot_req[slot] = None
        if self.paged:
            # free this request's blocks and re-point the whole row at the
            # trash sink — the static tick keeps computing for every slot,
            # and a stale table would corrupt blocks once they're
            # reallocated to another request
            jnp = _jax().numpy
            self._alloc.free(list(self._slot_blocks[slot].values()))
            self._slot_blocks[slot] = {}
            for bid in self._slot_shared[slot].values():
                self._shared_refs[bid] -= 1
            self._slot_shared[slot] = {}
            self._slot_table[slot][:] = 0
            self.slot_caches = self._clear_slot(self.slot_caches, jnp.int32(slot))
