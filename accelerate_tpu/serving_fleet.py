"""Fleet-scale serving: a multi-replica router over N ``ServingEngine``
replicas, with disaggregated prefill/decode roles, cost-model-priced KV
handoff, cross-request radix prefix reuse, and zero-compile replica
spin-up from a shared executable store.

A single :class:`~accelerate_tpu.serving.ServingEngine` is one process'
worth of serving; production traffic needs a *fleet*. This module owns
the layer above the engine:

* **routing** — :class:`FleetRouter` spreads an open-loop request stream
  over replicas. Policy (least-loaded / round-robin, fleet-level SLO
  shedding) lives in :class:`~accelerate_tpu.scheduling.RoutingConfig` /
  :class:`~accelerate_tpu.scheduling.FleetRoutingPolicy` — the same
  policy/mechanism split (and the same priority classes + structured
  :class:`~accelerate_tpu.scheduling.ShedError`) as the per-engine
  scheduler. Prefix affinity beats the load policy: a replica that
  already holds a request's shared preamble in its radix cache serves it
  without re-prefilling the preamble;

* **disaggregated prefill/decode** — with ``roles=("prefill", ...,
  "decode", ...)``, prefill replicas run prompt prefills and hand the KV
  rows to decode replicas (``ServingEngine.prefill_detached`` →
  ``submit_prefilled``; token- and logprob-exact by construction). Every
  handoff is priced BEFORE it happens by
  :func:`~accelerate_tpu.analysis.costmodel.price_kv_handoff` (per-token
  KV bytes × prompt length over the configured ICI/DCN transport), and
  under ``handoff="auto"`` the router compares that against
  :func:`~accelerate_tpu.analysis.costmodel.prefill_compute_us` — short
  prompts decode locally, long ones ship their blocks. The router's
  post-transfer accounting must equal the prediction byte-for-byte
  (``bench_serving --fleet`` asserts it);

* **radix prefix cache** — :class:`RadixPrefixCache` is a compressed
  token trie over observed prompts. When ``promote_after`` prompts share
  a preamble of at least ``min_prefix_tokens`` tokens, the shared part
  is registered with the engine ONCE (``register_prefix``) and every
  later prompt starting with it prefills only its suffix — the dominant
  p95-TTFT lever under realistic traffic where most prompt tokens are a
  shared system preamble. Reuse is token- and logprob-exact because the
  engine's prefix path copies the registered cache bit-identically.
  Entries evict LRU (``max_entries``), never while referenced by an
  active/queued request; hit/miss/eviction counters land in
  :class:`~accelerate_tpu.telemetry.serving_metrics.ServingMetrics`;

* **zero-compile spin-up** — replicas built over one shared
  :class:`~accelerate_tpu.aot.ExecutableStore` deserialize every engine
  program a sibling already compiled: :meth:`FleetRouter.spin_up` warms
  a new replica and reports its compile count (asserted 0 in the bench
  and the fleet tests — the PR-7 warm-replica story at fleet level);

* **fault tolerance** — every :class:`Replica` runs a ``healthy →
  degraded → quarantined → dead`` health state machine driven by error
  classification (engine exceptions, tick wall-time SLO violations,
  :class:`NonFinitePoison` from the non-finite watchdog) with a circuit
  breaker: the routing policy never sees quarantined/dead replicas, and
  when surviving capacity is gone submissions shed at the fleet edge
  with the structured :class:`~accelerate_tpu.scheduling.ShedError`. On
  failure (or :meth:`FleetRouter.drain`) every in-flight request
  migrates to a survivor **token- and logprob-exactly** — by prefix
  recompute (the preemption/resume machinery: carried sampling key +
  re-fed last token) or, when the dying replica can still export its
  dense KV rows, by the same handoff path disaggregated serving uses
  (``export_inflight`` → ``import_inflight``), the choice priced
  BEFORE the move by
  :func:`~accelerate_tpu.analysis.costmodel.price_failover` and the
  handoff leg hardened with :func:`~accelerate_tpu.utils.retry.retry_call`
  jittered backoff. Capacity recovers by :meth:`FleetRouter.add_replica`
  over the shared store (zero compiles). The serving chaos matrix
  (``test_utils.fault_injection.ReplicaChaos`` at the labeled
  ``ft.crashpoints.SERVING_CRASH_POINTS``) proves every crash point
  loses zero requests; :class:`HandoffCodec` serializes the handoff
  payload to bytes — the first step toward a socket/queue replica
  transport.

Everything is CPU-runnable: replicas are in-process engines (optionally
over device subsets via ``MeshConfig.num_devices``-built meshes), driven
either deterministically (:meth:`FleetRouter.step` round-robin) or by
one thread per replica (:meth:`FleetRouter.drain_threaded` — each
replica's lock serializes host bookkeeping; XLA releases the GIL during
device compute, so replicas overlap).
"""

from __future__ import annotations

import dataclasses
import io
import os
import threading
import time
from typing import Optional, Sequence

import numpy as np

from .ft.crashpoints import crash_point
from .scheduling import FleetRoutingPolicy, RoutingConfig, ShedError
from .utils.retry import retry_call


def _jax():
    import jax

    return jax


#: replica health levels, in degradation order; the index is the
#: ``replica_state`` gauge value Prometheus exposes
HEALTH_STATES = ("healthy", "degraded", "quarantined", "dead")


class NonFinitePoison(RuntimeError):
    """A replica's numerics are poisoned (the non-finite watchdog
    latched, or a tick surfaced NaN/Inf). Unlike a plain crash the
    replica's KV caches are SUSPECT: the router quarantines it and fails
    its in-flight work over by recompute only — shipped KV rows from a
    poisoned engine would carry the corruption to the survivor."""


class FleetRequestError(KeyError):
    """Structured lookup failure for a fleet request id, naming the
    request's last known state (``unknown`` / ``lost`` / a failed
    replica) — a client can distinguish "you never submitted this" from
    "the fleet lost it at a failover" and react accordingly. Subclasses
    ``KeyError`` so existing bare-lookup handling keeps working."""

    def __init__(self, fuid: int, state: str, detail: Optional[str] = None,
                 trace_id: Optional[int] = None):
        self.fuid = int(fuid)
        self.state = state
        self.detail = detail
        # the request's distributed-tracing id (telemetry.trace), when
        # the router was tracing — grep the eventlog/flight dumps for it
        self.trace_id = trace_id
        if state == "unknown":
            msg = f"unknown request id {fuid} (never submitted, already cancelled, or shed)"
        else:
            msg = f"request id {fuid} last known state: {state}"
        if detail:
            msg += f" — {detail}"
        if trace_id is not None:
            msg += f" (trace {trace_id})"
        super().__init__(msg)


class HandoffCodec:
    """Serialize a ``prefill_detached`` / ``export_inflight`` KV handoff
    payload to bytes and back — the subprocess-readiness shim for the
    roadmap's socket/queue replica transport: today's in-process handoff
    passes live numpy trees between engines; a process-per-replica fleet
    passes ``HandoffCodec.encode(handoff)`` over the wire instead, and
    the decode side is token- and logprob-exact by the same round-trip
    the tests pin.

    The wire format is a single ``.npz`` blob: prompt, sampling
    ``key_data``, scalar metadata, and each KV leaf as raw bytes + shape
    (dtype-agnostic on purpose — bf16 and friends round-trip through the
    receiving engine's row template, which is the single source of truth
    for leaf dtypes and tree structure)."""

    @staticmethod
    def encode(handoff: dict) -> bytes:
        jax = _jax()
        leaves = jax.tree_util.tree_leaves(handoff["cache"])
        arrays = {
            "prompt": np.asarray(handoff["prompt"], np.int32),
            "key_data": np.asarray(handoff["key_data"]),
            "imeta": np.asarray(
                [
                    int(handoff["total"]),
                    int(handoff["max_new_tokens"]),
                    int(handoff["next_tok"]),
                    int(handoff["wire_bytes"]),
                    int(handoff.get("reused_prefix_tokens", 0)),
                    len(leaves),
                ],
                np.int64,
            ),
            "fmeta": np.asarray([float(handoff["lp"])], np.float64),
        }
        # v2: the trace id rides the blob so one id follows the request
        # across hosts; omitted when untraced, so v1 decoders (and v1
        # blobs fed to this decoder) keep working
        if handoff.get("trace") is not None:
            arrays["tmeta"] = np.asarray([int(handoff["trace"])], np.int64)
        for i, leaf in enumerate(leaves):
            arr = np.asarray(leaf)
            arrays[f"leaf_{i}"] = np.frombuffer(arr.tobytes(), np.uint8)
            arrays[f"shape_{i}"] = np.asarray(arr.shape, np.int64)
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        return buf.getvalue()

    @staticmethod
    def decode(data: bytes, engine) -> dict:
        """Rebuild the handoff dict against ``engine``'s row template
        (leaf dtypes + tree structure); the result feeds
        ``engine.submit_prefilled`` unchanged."""
        jax = _jax()
        with np.load(io.BytesIO(data)) as z:
            imeta = z["imeta"]
            n_leaves = int(imeta[5])
            template = jax.tree_util.tree_leaves(engine._row_template)
            if n_leaves != len(template):
                raise ValueError(
                    f"payload has {n_leaves} KV leaves; this engine's row "
                    f"template has {len(template)} — engine/model mismatch"
                )
            leaves = []
            for i, t in enumerate(template):
                shape = tuple(int(d) for d in z[f"shape_{i}"])
                raw = z[f"leaf_{i}"].tobytes()
                leaves.append(np.frombuffer(raw, dtype=t.dtype).reshape(shape))
            cache = jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(engine._row_template), leaves
            )
            return {
                "prompt": np.asarray(z["prompt"], np.int32),
                "total": int(imeta[0]),
                "max_new_tokens": int(imeta[1]),
                "next_tok": int(imeta[2]),
                "lp": float(z["fmeta"][0]),
                "key_data": np.asarray(z["key_data"]),
                "cache": cache,
                "wire_bytes": int(imeta[3]),
                "reused_prefix_tokens": int(imeta[4]),
                # absent in v1 blobs — tolerate them forever
                "trace": int(z["tmeta"][0]) if "tmeta" in z.files else None,
            }


# --------------------------------------------------------------------- #
# radix prefix cache
# --------------------------------------------------------------------- #


class _RadixNode:
    """One node of the compressed token trie. ``edge`` is the token label
    on the edge INTO this node; children key on their edge's first
    token. ``count`` = observed prompts whose path passes through;
    ``prefix_id`` = the engine prefix registered at this depth (None =
    structural node only)."""

    __slots__ = ("edge", "children", "count", "prefix_id", "depth", "last_used")

    def __init__(self, edge=(), depth: int = 0):
        self.edge = tuple(edge)
        self.children: dict = {}
        self.count = 0
        self.prefix_id: Optional[int] = None
        self.depth = depth
        self.last_used = 0.0


class RadixPrefixCache:
    """Cross-request prefix reuse over one engine's KV-block prefix store.

    The engine mechanism (``register_prefix`` / ``submit(prefix_id=)``)
    is token-exact but manual; this cache decides WHICH preambles are
    worth a registration and matches every prompt against them:

    * :meth:`lookup` — longest registered preamble that is a proper
      prefix of the prompt (at least one suffix token must remain —
      its logits seed the first sample). Counts a hit (+ reused tokens)
      or a miss in the engine's :class:`ServingMetrics`;
    * :meth:`observe` — inserts the prompt's path into the trie. A trie
      node exists exactly where observed prompts diverge, so the deepest
      node with ``count >= promote_after`` and ``depth >=
      min_prefix_tokens`` IS the longest preamble shared often enough to
      pay for a registration — it gets registered (one engine prefill +
      one pinned KV row cache);
    * **eviction** — past ``max_entries`` registrations, the
      least-recently-used entry is unregistered (its HBM rows freed).
      An entry still referenced by an active/queued request is skipped
      this round (the engine refuses to drop it) and retried on the
      next eviction pass. :meth:`invalidate` drops one/all entries
      explicitly — required after anything that changes what the
      registered tokens would prefill to (new model weights, changed
      tokenizer); the cache itself never goes stale within a process
      because jax caches are immutable and requests copy them.

    The trie observes at most ``max_observe_tokens`` leading tokens per
    prompt (promotion candidates never exceed it), so trie memory is
    O(distinct preambles), not O(total traffic).
    """

    def __init__(
        self,
        engine,
        *,
        min_prefix_tokens: int = 8,
        promote_after: int = 2,
        max_entries: int = 8,
        max_observe_tokens: int = 4096,
        clock=time.monotonic,
    ):
        if min_prefix_tokens < 1:
            raise ValueError(f"min_prefix_tokens must be >= 1, got {min_prefix_tokens}")
        if promote_after < 2:
            raise ValueError(f"promote_after must be >= 2, got {promote_after}")
        if max_entries < 1:
            raise ValueError(f"max_entries must be >= 1, got {max_entries}")
        self.engine = engine
        self.min_prefix_tokens = int(min_prefix_tokens)
        self.promote_after = int(promote_after)
        self.max_entries = int(max_entries)
        self.max_observe_tokens = int(max_observe_tokens)
        self._clock = clock
        self.root = _RadixNode()
        self.entries: dict[int, _RadixNode] = {}  # prefix_id -> owning node
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.registrations = 0
        self.tokens_reused = 0

    # -- matching -------------------------------------------------------- #

    def _walk(self, toks: tuple):
        """Yield trie nodes along ``toks``' path (root excluded), stopping
        at the first divergence."""
        node, i = self.root, 0
        while i < len(toks):
            nxt = node.children.get(toks[i])
            if nxt is None:
                return
            e = nxt.edge
            if len(toks) - i < len(e) or toks[i : i + len(e)] != e:
                return
            i += len(e)
            node = nxt
            yield node

    def lookup(self, prompt_ids) -> Optional[tuple]:
        """``(prefix_id, length)`` of the longest registered preamble
        that properly prefixes ``prompt_ids`` (>= 1 suffix token left),
        or None. Counts the hit/miss and refreshes the entry's LRU
        stamp."""
        toks = tuple(int(t) for t in np.asarray(prompt_ids).ravel())
        best = None
        for node in self._walk(toks):
            if node.prefix_id is not None and node.depth < len(toks):
                best = node
        m = self.engine.metrics
        if best is None:
            self.misses += 1
            m.on_prefix_miss()
            return None
        best.last_used = self._clock()
        self.hits += 1
        self.tokens_reused += best.depth
        m.on_prefix_hit(best.depth)
        return best.prefix_id, best.depth

    # -- observation + promotion ----------------------------------------- #

    def observe(self, prompt_ids) -> Optional[int]:
        """Insert the prompt's (capped) path into the trie; register the
        deepest preamble that just crossed the promotion threshold.
        Returns the newly registered ``prefix_id`` or None."""
        toks = tuple(int(t) for t in np.asarray(prompt_ids).ravel())
        # a registered preamble must leave >= 1 suffix token AND fit the
        # slot cache with one generated token of headroom
        cap = min(len(toks) - 1, self.max_observe_tokens, self.engine.max_len - 2)
        if cap < self.min_prefix_tokens:
            return None
        toks = toks[:cap]
        node, i = self.root, 0
        promoted: Optional[_RadixNode] = None
        while i < len(toks):
            nxt = node.children.get(toks[i])
            if nxt is None:
                child = _RadixNode(toks[i:], depth=len(toks))
                child.count = 1
                node.children[toks[i]] = child
                break
            e = nxt.edge
            common = 0
            limit = min(len(e), len(toks) - i)
            while common < limit and e[common] == toks[i + common]:
                common += 1
            if common < len(e):
                # split the edge at the divergence point: the new middle
                # node's depth IS the shared-preamble length
                mid = _RadixNode(e[:common], depth=nxt.depth - (len(e) - common))
                mid.count = nxt.count
                nxt.edge = e[common:]
                mid.children[nxt.edge[0]] = nxt
                node.children[toks[i]] = mid
                nxt = mid
            i += common if common < len(e) else len(e)
            nxt.count += 1
            node = nxt
            if (
                nxt.count >= self.promote_after
                and nxt.depth >= self.min_prefix_tokens
                and nxt.prefix_id is None
                and i == nxt.depth  # full edge consumed: toks[:i] ends here
            ):
                promoted = nxt  # keep the deepest qualifying node
            if common < len(e):
                # remainder of the prompt diverges below the split
                if i < len(toks):
                    child = _RadixNode(toks[i:], depth=len(toks))
                    child.count = 1
                    nxt.children[toks[i]] = child
                break
        if promoted is None:
            return None
        return self._register(promoted, toks[: promoted.depth])

    def _register(self, node: _RadixNode, tokens: tuple) -> Optional[int]:
        try:
            pid = self.engine.register_prefix(np.asarray(tokens, np.int32))
        except ValueError:
            # pool exhaustion (paged) or headroom: skip this round — the
            # node keeps its count and a later observe retries
            return None
        node.prefix_id = pid
        node.last_used = self._clock()
        self.entries[pid] = node
        self.registrations += 1
        self.engine.metrics.on_prefix_register()
        self._evict_over_budget()
        return pid

    def _evict_over_budget(self) -> None:
        while len(self.entries) > self.max_entries:
            ordered = sorted(self.entries.items(), key=lambda kv: kv[1].last_used)
            evicted = False
            # never the hottest entry: when an older entry is pinned by
            # in-flight requests, churning the just-registered one would
            # throw away exactly the cache the next request hits
            for pid, node in ordered[:-1]:
                try:
                    self.engine.unregister_prefix(pid)
                except ValueError:
                    continue  # still referenced; try the next-oldest
                node.prefix_id = None
                del self.entries[pid]
                self.evictions += 1
                self.engine.metrics.on_prefix_evict()
                evicted = True
                break
            if not evicted:
                return  # everything evictable is pinned: over budget until drains

    def invalidate(self, prefix_id: Optional[int] = None) -> int:
        """Unregister one entry (or all, ``prefix_id=None``) — the
        explicit invalidation hook for weight swaps / tokenizer changes.
        Raises ValueError if a targeted entry is still referenced by an
        active or queued request. Returns the number of entries
        dropped."""
        pids = [prefix_id] if prefix_id is not None else list(self.entries)
        dropped = 0
        for pid in pids:
            node = self.entries.get(pid)
            if node is None:
                raise ValueError(f"unknown prefix_id {pid}")
            self.engine.unregister_prefix(pid)
            node.prefix_id = None
            del self.entries[pid]
            dropped += 1
        return dropped

    def stats(self) -> dict:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "registrations": self.registrations,
            "entries": len(self.entries),
            "tokens_reused": self.tokens_reused,
        }


# --------------------------------------------------------------------- #
# fleet configuration + replicas
# --------------------------------------------------------------------- #


@dataclasses.dataclass
class FleetConfig:
    """Knobs for :class:`FleetRouter`.

    ``roles``: per-replica role tuple (``"mixed"`` | ``"prefill"`` |
    ``"decode"``). None = every replica mixed (no disaggregation).
    Disaggregation needs at least one prefill and one decode replica;
    mixed replicas count as both.

    ``handoff``: ``"auto"`` ships KV blocks only when the priced
    transfer beats the priced local re-prefill, ``"always"`` /
    ``"never"`` pin the decision (the bench's A/B arms).

    ``transport`` / ``generation``: what the cost model prices the
    replica-to-replica link as (``"ici"`` within a slice or host,
    ``"dcn"`` across) — see
    :func:`~accelerate_tpu.analysis.costmodel.price_kv_handoff`.

    ``prefix_reuse`` + radix knobs: see :class:`RadixPrefixCache`.

    Fault tolerance: ``tick_timeout_s`` (None = no tick wall-time SLO)
    degrades a replica on one slow tick and quarantines it after
    ``quarantine_after_timeouts`` consecutive ones (its in-flight work
    migrates); ``heal_after_ticks`` clean ticks promote a degraded
    replica back to healthy. ``failover`` picks the migration path —
    ``"auto"`` prices KV handoff vs recompute per request
    (:func:`~accelerate_tpu.analysis.costmodel.price_failover`),
    ``"handoff"`` / ``"recompute"`` pin it (the chaos matrix's A/B
    arms; handoff silently falls back to recompute when the dying
    replica cannot export). The handoff leg retries with jittered
    backoff (``failover_retry_attempts`` ×
    ``failover_retry_base_delay_s``) before falling back.
    """

    routing: RoutingConfig = dataclasses.field(default_factory=RoutingConfig)
    roles: Optional[tuple] = None
    handoff: str = "auto"
    transport: str = "ici"
    generation: str = "cpu"
    prefix_reuse: bool = True
    min_prefix_tokens: int = 8
    promote_after: int = 2
    max_prefix_entries: int = 8
    tick_timeout_s: Optional[float] = None
    quarantine_after_timeouts: int = 2
    heal_after_ticks: int = 16
    failover: str = "auto"
    failover_retry_attempts: int = 3
    failover_retry_base_delay_s: float = 0.02

    def __post_init__(self):
        if self.handoff not in ("auto", "always", "never"):
            raise ValueError(f"handoff must be auto|always|never, got {self.handoff!r}")
        if self.transport not in ("ici", "dcn"):
            raise ValueError(f"transport must be ici|dcn, got {self.transport!r}")
        if self.roles is not None:
            bad = [r for r in self.roles if r not in ("mixed", "prefill", "decode")]
            if bad:
                raise ValueError(f"roles must be mixed|prefill|decode, got {bad}")
        if self.failover not in ("auto", "handoff", "recompute"):
            raise ValueError(f"failover must be auto|handoff|recompute, got {self.failover!r}")
        if self.tick_timeout_s is not None and self.tick_timeout_s <= 0:
            raise ValueError(f"tick_timeout_s must be > 0, got {self.tick_timeout_s}")
        if self.quarantine_after_timeouts < 1:
            raise ValueError(
                f"quarantine_after_timeouts must be >= 1, got {self.quarantine_after_timeouts}"
            )
        if self.heal_after_ticks < 1:
            raise ValueError(f"heal_after_ticks must be >= 1, got {self.heal_after_ticks}")
        if self.failover_retry_attempts < 1:
            raise ValueError(
                f"failover_retry_attempts must be >= 1, got {self.failover_retry_attempts}"
            )


class Replica:
    """One engine + its fleet-side state. ``lock`` serializes host
    bookkeeping between the router and a per-replica drain thread; the
    engine itself is single-threaded by contract.

    Health (router-driven, see :meth:`FleetRouter._tick_replica`):
    ``healthy`` serves normally; ``degraded`` (a tick blew the wall-time
    SLO) still serves but is one strike from quarantine and heals after
    ``heal_after_ticks`` clean ticks; ``quarantined`` (circuit broken:
    repeated timeouts or poisoned numerics) and ``dead`` (the engine
    raised) never tick or receive routes again — their in-flight work
    has already migrated. ``draining`` additionally blocks NEW routes
    while :meth:`FleetRouter.drain` moves the existing work off."""

    def __init__(self, engine, name: str, role: str = "mixed"):
        self.engine = engine
        self.name = name
        self.role = role
        self.radix: Optional[RadixPrefixCache] = None
        # per-replica crash flight recorder (telemetry.flightrec), wired
        # by a tracing router as a tap on the engine's eventlog
        self.flightrec = None
        self.lock = threading.RLock()
        self.health = "healthy"
        self.draining = False
        self.consecutive_timeouts = 0
        self.clean_ticks = 0
        self.last_error: Optional[str] = None
        engine.metrics.replica = name

    @property
    def load(self) -> int:
        return len(self.engine.queue) + self.engine.active_count

    @property
    def busy(self) -> bool:
        return bool(self.engine.queue or self.engine.active_count)

    @property
    def is_serving(self) -> bool:
        """Still ticking: healthy or degraded (a dead/quarantined
        engine's host state is a read-only husk for failover export)."""
        return self.health in ("healthy", "degraded")

    @property
    def routable(self) -> bool:
        """Eligible for NEW work: serving and not draining."""
        return self.is_serving and not self.draining

    def can_prefill(self) -> bool:
        return self.role in ("mixed", "prefill")

    def can_decode(self) -> bool:
        return self.role in ("mixed", "decode")


# --------------------------------------------------------------------- #
# the router
# --------------------------------------------------------------------- #


class FleetRouter:
    """Route an open-loop request stream over N engine replicas.

    Build it from pre-constructed engines (tests, heterogeneous meshes)
    or :meth:`from_model` (N uniform replicas, optionally over one
    shared executable store so spin-up never compiles). The public
    surface mirrors the engine: :meth:`submit` → fleet uid,
    :meth:`step` / :meth:`run` / :meth:`drain_threaded` drive,
    :meth:`poll` / :meth:`partial` / :meth:`logprobs` / :meth:`cancel`
    resolve, :meth:`metrics_merged` / :meth:`prometheus_text` observe.
    """

    def __init__(
        self,
        engines: Sequence,
        config: Optional[FleetConfig] = None,
        names=None,
        trace=None,
    ):
        if not engines:
            raise ValueError("need at least one engine")
        self.config = config or FleetConfig()
        roles = self.config.roles or ("mixed",) * len(engines)
        if len(roles) != len(engines):
            raise ValueError(f"{len(roles)} roles for {len(engines)} engines")
        names = names or [f"r{i}" for i in range(len(engines))]
        self.replicas = [Replica(e, n, r) for e, n, r in zip(engines, names, roles)]
        self.disaggregated = any(r.role == "prefill" for r in self.replicas)
        if self.disaggregated and not any(r.can_decode() for r in self.replicas):
            raise ValueError("disaggregated fleet needs at least one decode-capable replica")
        if self.config.prefix_reuse:
            for rep in self.replicas:
                if rep.can_prefill() and rep.engine.draft_model is None:
                    rep.radix = RadixPrefixCache(
                        rep.engine,
                        min_prefix_tokens=self.config.min_prefix_tokens,
                        promote_after=self.config.promote_after,
                        max_entries=self.config.max_prefix_entries,
                    )
        self._policy = FleetRoutingPolicy(self.config.routing)
        self._uid = 0
        # fleet uid -> ("replica", idx, local_uid) | ("pending", None)
        #            | ("done", full, new, lps)  — results salvaged off a
        #              failed/drained replica before it left the fleet
        self._map: dict[int, tuple] = {}
        self._shed: dict[int, ShedError] = {}
        self._lost: dict[int, str] = {}  # fuid -> why failover could not save it
        self._pending: list[dict] = []  # disaggregated requests awaiting prefill+handoff
        self._lock = threading.RLock()
        self._mk_engine = None  # set by from_model: spin_up's factory
        self._replica_seq = len(self.replicas)  # monotonic spin_up naming
        # KV-handoff accounting: predictions are priced BEFORE each
        # transfer; moved bytes are what actually shipped — the two must
        # agree exactly (bench-asserted)
        self.handoffs = 0
        self.handoffs_local = 0  # auto-decision chose local re-prefill
        self.handoff_bytes_predicted = 0
        self.handoff_bytes_moved = 0
        self.handoff_time_us_predicted = 0.0
        self.fleet_shed = 0  # fleet-level SLO rejections (router edge)
        # failover accounting — same predicted-vs-moved discipline as the
        # KV handoffs (the pin the chaos tests assert)
        self.failovers = 0
        self.failovers_kv = 0
        self.failovers_recompute = 0
        self.failovers_lost = 0
        self.failover_bytes_predicted = 0
        self.failover_bytes_moved = 0
        self.failover_time_us_predicted = 0.0
        self.failover_recompute_us_predicted = 0.0
        # ---- request tracing + flight recorder (telemetry.trace) ----
        # `trace` is None (off), True (defaults), or a TraceConfig. One
        # Tracer spans the whole fleet (trace ids are fleet-global); each
        # replica gets a bounded flight recorder tapping its eventlog.
        self.tracer = None
        self.critpath = None
        self.trace_config = None
        self._trace_ids: dict[int, int] = {}  # fuid -> trace id
        if trace is not None and trace is not False:
            from .telemetry.critpath import CritPathMonitor
            from .telemetry.trace import TraceConfig, Tracer

            tcfg = TraceConfig() if trace is True else trace
            if tcfg.enabled:
                self.trace_config = tcfg
                tlog = self.replicas[0].engine._log
                if tcfg.drift_check:
                    self.critpath = CritPathMonitor(tlog, thresholds=tcfg.drift_thresholds)
                self.tracer = Tracer(
                    max_traces=tcfg.max_traces,
                    log=tlog,
                    on_finish=None if self.critpath is None else self.critpath.observe,
                )
                for rep in self.replicas:
                    self._wire_replica_tracing(rep)

    def _wire_replica_tracing(self, rep: Replica) -> None:
        """Hand the fleet tracer to one replica's engine and tap its
        eventlog into a per-replica crash flight recorder."""
        if self.tracer is None:
            return
        rep.engine.tracer = self.tracer
        tcfg = self.trace_config
        if tcfg.flight_recorder and rep.flightrec is None:
            from .telemetry.flightrec import FlightRecorder

            rep.flightrec = FlightRecorder(tcfg.flight_capacity, name=rep.name)
            rep.engine._log.add_tap(rep.flightrec.record)

    # -- construction ---------------------------------------------------- #

    @classmethod
    def from_model(
        cls,
        model,
        num_replicas: int = 2,
        config: Optional[FleetConfig] = None,
        store_dir: Optional[str] = None,
        trace=None,
        **engine_kwargs,
    ) -> "FleetRouter":
        """N uniform replicas over one model. With ``store_dir``, every
        replica's :class:`~accelerate_tpu.aot.ProgramCache` shares one
        :class:`~accelerate_tpu.aot.ExecutableStore` — the first replica
        to build a program stores it, every later replica (including
        :meth:`spin_up` at runtime) deserializes it with zero XLA
        compiles. Replicas over device *subsets* come from building each
        replica's model on a ``MeshConfig(num_devices=...)`` mesh and
        using the engine-list constructor instead."""
        from .serving import ServingEngine

        def mk(name: str) -> "ServingEngine":
            pc = None
            if store_dir is not None:
                from .aot import ExecutableStore, ProgramCache

                pc = ProgramCache(store=ExecutableStore(store_dir), name=name)
            return ServingEngine(model, program_cache=pc, **engine_kwargs)

        router = cls([mk(f"r{i}") for i in range(num_replicas)], config=config, trace=trace)
        router._mk_engine = mk
        return router

    def spin_up(self, warm_prompt_lens=(4,), max_new_tokens: int = 2, role: str = "mixed") -> dict:
        """Add one replica at runtime and warm its serving programs.
        Returns ``{"replica", "spinup_ms", "compiles", "deserialized"}``
        — over a shared store the compile count is 0 (every program
        deserializes; the zero-compile spin-up contract the fleet bench
        asserts). Only available on a :meth:`from_model` router."""
        if self._mk_engine is None:
            raise ValueError("spin_up needs a from_model router (an engine factory)")
        with self._lock:
            # monotonic sequence, skipping anything still (or ever) taken:
            # after a drain removed "r1", the next spin-up must NOT mint a
            # second "r1" and alias its metrics/events
            taken = {r.name for r in self.replicas}
            while f"r{self._replica_seq}" in taken:
                self._replica_seq += 1
            name = f"r{self._replica_seq}"
            self._replica_seq += 1
        t0 = time.perf_counter()
        engine = self._mk_engine(name)
        rep = Replica(engine, name, role)
        if self.config.prefix_reuse and rep.can_prefill():
            rep.radix = RadixPrefixCache(
                engine,
                min_prefix_tokens=self.config.min_prefix_tokens,
                promote_after=self.config.promote_after,
                max_entries=self.config.max_prefix_entries,
            )
        rng = np.random.default_rng(0)
        for n in warm_prompt_lens:
            engine.submit(rng.integers(1, 100, size=int(n)).astype(np.int32), max_new_tokens)
        engine.run()
        # wire tracing only AFTER the warm-up requests drained, so the
        # synthetic warm prompts never show up as traced fleet requests
        self._wire_replica_tracing(rep)
        ms = (time.perf_counter() - t0) * 1000.0
        with self._lock:
            self.replicas.append(rep)
        pc = engine.program_cache
        return {
            "replica": name,
            "spinup_ms": round(ms, 3),
            "compiles": pc.misses,
            "deserialized": pc.deserialized,
        }

    def add_replica(
        self, role: str = "mixed", warm_prompt_lens=(4,), max_new_tokens: int = 2
    ) -> dict:
        """Hot re-add: recover capacity lost to a quarantine/death/drain
        by spinning up a fresh replica over the shared executable store —
        zero XLA compiles when every program was already stored
        (:meth:`spin_up` reports the count). The recovery half of the
        fault-tolerance story; returns the spin-up report."""
        return self.spin_up(
            warm_prompt_lens=warm_prompt_lens, max_new_tokens=max_new_tokens, role=role
        )

    # -- submission ------------------------------------------------------ #

    def submit(
        self,
        prompt_ids,
        max_new_tokens: int = 32,
        priority: int = 0,
        stop_sequences=None,
    ) -> int:
        """Route one request; returns a FLEET uid (resolve via
        :meth:`poll`). Fleet-level SLO shedding raises the structured
        :class:`ShedError` before any replica is touched; per-replica
        scheduler SLOs still apply after routing."""
        prompt = np.asarray(prompt_ids, np.int32).ravel()
        with self._lock:
            routable = self._routable_indices()
            # circuit breaker: with zero serving capacity, reject at the
            # edge instead of queueing into replicas that will never tick
            reason = self._policy.shed_on_capacity(len(routable))
            if reason is None:
                depth = sum(
                    len(self.replicas[i].engine.queue) for i in routable
                ) + len(self._pending)
                reason = self._policy.shed_on_submit(int(priority), depth)
            else:
                depth = len(self._pending)
            if reason is not None:
                self.fleet_shed += 1
                raise ShedError(reason, priority=int(priority), queue_depth=depth)
            if self.disaggregated:
                # validate BEFORE queueing a pending entry: a bad request
                # must fail the caller here, not blow up a prefill replica
                # at dispatch (where an engine error means replica death)
                if len(prompt) == 0:
                    raise ValueError("empty prompt")
                if int(max_new_tokens) < 1:
                    raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
                cap = min(self.replicas[i].engine.max_len for i in routable)
                if len(prompt) + int(max_new_tokens) > cap:
                    raise ValueError(
                        f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens}) "
                        f"exceeds the slot cache ({cap})"
                    )
            fuid = self._uid
            self._uid += 1
            # trace minted AFTER the fleet-edge shed gates: an edge
            # rejection never touched a replica, so it carries no trace
            tid = None
            if self.tracer is not None:
                tid = self.tracer.start(fuid=fuid, prompt_tokens=int(len(prompt)))
                self._trace_ids[fuid] = tid
            if self.disaggregated and not self._handoff_decision(len(prompt)):
                self.handoffs_local += 1
            elif self.disaggregated:
                self._pending.append(
                    {
                        "fuid": fuid,
                        "prompt": prompt,
                        "max_new_tokens": int(max_new_tokens),
                        "priority": int(priority),
                        "stop_sequences": stop_sequences,
                        "trace": tid,
                    }
                )
                self._map[fuid] = ("pending", None)
                return fuid
            idx = self._route_local(prompt)
        rep = self.replicas[idx]
        with rep.lock:
            prefix = rep.radix.lookup(prompt) if rep.radix is not None else None
            if prefix is not None:
                pid, plen = prefix
                local = rep.engine.submit(
                    prompt[plen:], max_new_tokens, prefix_id=pid,
                    stop_sequences=stop_sequences, priority=priority, trace=tid,
                )
            else:
                local = rep.engine.submit(
                    prompt, max_new_tokens, stop_sequences=stop_sequences,
                    priority=priority, trace=tid,
                )
                if rep.radix is not None:
                    rep.radix.observe(prompt)
        with self._lock:
            self._map[fuid] = ("replica", idx, local)
        return fuid

    def _routable_indices(self, *, prefill: bool = False, decode: bool = False, exclude=None):
        """Replica indices the circuit breaker allows NEW work onto
        (serving, not draining), optionally role-filtered and excluding
        one replica (a failover's source)."""
        out = []
        for i, r in enumerate(self.replicas):
            if not r.routable or r is exclude:
                continue
            if prefill and not r.can_prefill():
                continue
            if decode and not r.can_decode():
                continue
            out.append(i)
        return out

    def _route_local(self, prompt: np.ndarray) -> int:
        """Replica index for a locally-prefilled request: prefix affinity
        first (the replica already holding the longest registered
        preamble), else the routing policy over decode-capable load.
        Quarantined/dead/draining replicas are never candidates."""
        eligible = [
            i for i in self._routable_indices(decode=True)
            if self.replicas[i].can_prefill()
        ]
        if not eligible:  # disaggregated fleet deciding "local": decode side prefills
            eligible = self._routable_indices(decode=True)
        if not eligible:
            self.fleet_shed += 1
            raise ShedError("no decode-capable serving replicas (fleet capacity lost)")
        best_i, best_len = None, 0
        toks = tuple(int(t) for t in prompt)
        for i in eligible:
            radix = self.replicas[i].radix
            if radix is None:
                continue
            # peek without counting a hit/miss: only the routed replica's
            # lookup() is the real match
            depth = 0
            for node in radix._walk(toks):
                if node.prefix_id is not None and node.depth < len(toks):
                    depth = node.depth
            if depth > best_len:
                best_i, best_len = i, depth
        if best_i is not None:
            return best_i
        loads = [r.load for r in self.replicas]
        return self._policy.pick_replica(loads, eligible)

    def _handoff_decision(self, prompt_len: int) -> bool:
        """Ship the KV blocks (True) or let the decode replica re-prefill
        locally (False) — priced before anything runs."""
        mode = self.config.handoff
        if mode == "always":
            return True
        if mode == "never":
            return False
        pred, alt_us = self._price_handoff(prompt_len)
        return pred["time_us"] <= alt_us

    def _price_handoff(self, tokens: int):
        """(price_kv_handoff dict, local re-prefill us) for one prompt."""
        from .analysis.costmodel import prefill_compute_us, price_kv_handoff

        src = next(
            (r for r in self.replicas if r.routable and r.can_prefill()),
            next((r for r in self.replicas if r.can_prefill()), self.replicas[0]),
        )
        per_tok, fixed = src.engine.kv_handoff_dims()
        pred = price_kv_handoff(
            per_tok, tokens, fixed_bytes=fixed,
            transport=self.config.transport, generation=self.config.generation,
        )
        if not hasattr(self, "_param_count"):
            jax = _jax()
            self._param_count = sum(
                int(np.prod(leaf.shape)) if getattr(leaf, "shape", None) else 1
                for leaf in jax.tree_util.tree_leaves(src.engine.model.params)
            )
        return pred, prefill_compute_us(
            self._param_count, tokens, generation=self.config.generation
        )

    # -- replica health + failover ---------------------------------------- #

    def _replica_by_name(self, name: str) -> Replica:
        for r in self.replicas:
            if r.name == name:
                return r
        raise ValueError(f"unknown replica {name!r} (have {[r.name for r in self.replicas]})")

    def _set_health(self, rep: Replica, state: str, reason: str = "") -> None:
        # rep.lock (an RLock, and always ordered BEFORE self._lock) so the
        # drain_threaded workers' is_serving checks can't read a torn
        # transition — the TPU902 finding this tier was built to catch
        with rep.lock:
            if rep.health == state:
                return
            prev, rep.health = rep.health, state
        rep.engine.metrics.on_replica_state(HEALTH_STATES.index(state))
        rep.engine._log.event(
            "replica_state", replica=rep.name, prev=prev, state=state, reason=reason
        )
        # fatal transitions auto-dump the replica's flight recorder: the
        # ring already holds the fault's events (the emit above included),
        # plus the in-flight table and any open trace spans
        if state in ("quarantined", "dead"):
            self._flight_dump(rep, reason=f"{state}: {reason}")

    def _flight_dump(self, rep: Replica, reason: str) -> None:
        """Dump one replica's flight recorder (no-op when tracing is off).
        Never raises — the dump rides a failure path that must complete."""
        fr = rep.flightrec
        if fr is None:
            return
        inflight = []
        try:
            for uid, (state, req) in list(rep.engine._index.items()):
                if state == "done" or req is None:
                    continue
                inflight.append(
                    {
                        "uid": int(uid),
                        "state": state,
                        "generated": len(req.out_tokens),
                        "priority": int(req.priority),
                        "trace": req.trace,
                    }
                )
        except Exception:  # noqa: BLE001 — a husk's host tables may be torn
            pass
        spans = self.tracer.open_spans() if self.tracer is not None else []
        path = None
        tcfg = self.trace_config
        if tcfg is not None and tcfg.flight_dump_dir:
            path = os.path.join(tcfg.flight_dump_dir, f"flight_{rep.name}.json")
        doc = fr.dump(reason=reason, inflight=inflight, open_spans=spans, path=path)
        rep.engine._log.event(
            "flight_dump", replica=rep.name, reason=reason,
            events=len(doc["events"]), inflight=len(inflight),
            open_spans=len(spans), path=path,
        )

    @staticmethod
    def _classify(exc: BaseException) -> str:
        """``"poison"`` (numerics suspect — quarantine, recompute-only
        failover) or ``"crash"`` (process-style death — dead, KV export
        still trusted). Non-finite surfaces either as the typed
        :class:`NonFinitePoison` or as a message from the watchdog's
        ``nonfinite`` vocabulary."""
        if isinstance(exc, NonFinitePoison):
            return "poison"
        if "nonfinite" in str(exc).lower().replace("-", "").replace(" ", ""):
            return "poison"
        return "crash"

    def _on_replica_error(self, rep: Replica, exc: BaseException) -> None:
        """An engine raised (or was declared failed): classify, break the
        circuit, and migrate every in-flight request to survivors."""
        kind = self._classify(exc)
        rep.last_error = f"{type(exc).__name__}: {exc}"
        rep.engine.metrics.on_replica_error()
        self._set_health(
            rep, "quarantined" if kind == "poison" else "dead", reason=rep.last_error
        )
        self._migrate_all(rep, reason=kind, allow_kv=(kind != "poison"))

    def _on_replica_timeout(self, rep: Replica, dt: float) -> None:
        rep.consecutive_timeouts += 1
        rep.clean_ticks = 0
        rep.engine.metrics.on_replica_timeout()
        rep.engine._log.event(
            "replica_timeout", replica=rep.name, tick_s=round(dt, 4),
            consecutive=rep.consecutive_timeouts,
        )
        if rep.consecutive_timeouts >= self.config.quarantine_after_timeouts:
            rep.last_error = (
                f"tick timeout x{rep.consecutive_timeouts} "
                f"({dt:.3f}s > {self.config.tick_timeout_s}s)"
            )
            self._set_health(rep, "quarantined", reason=rep.last_error)
            # a hung-then-quarantined replica's host state is intact (the
            # tick finished, just late) — its KV rows are trustworthy
            self._migrate_all(rep, reason="timeout", allow_kv=True)
        elif rep.health == "healthy":
            self._set_health(
                rep, "degraded", reason=f"tick {dt:.3f}s > {self.config.tick_timeout_s}s"
            )

    def _on_replica_clean(self, rep: Replica) -> None:
        rep.consecutive_timeouts = 0
        if rep.health == "degraded":
            rep.clean_ticks += 1
            if rep.clean_ticks >= self.config.heal_after_ticks:
                rep.clean_ticks = 0
                self._set_health(rep, "healthy", reason="clean ticks")

    def _tick_replica(self, rep: Replica) -> int:
        """One guarded engine tick: exceptions classify the replica
        failed (and migrate its work); wall-time drives the
        degraded/quarantined transitions when ``tick_timeout_s`` is
        set."""
        try:
            with rep.lock:
                if not rep.busy:
                    return 0
                t0 = time.perf_counter()
                active = rep.engine.step()
                dt = time.perf_counter() - t0
        except Exception as e:  # noqa: BLE001 — any engine death is a replica fault
            self._on_replica_error(rep, e)
            return 0
        if self.config.tick_timeout_s is not None and dt > self.config.tick_timeout_s:
            self._on_replica_timeout(rep, dt)
        else:
            self._on_replica_clean(rep)
        return active if rep.is_serving else 0

    def _migrate_all(self, rep: Replica, reason: str, allow_kv: bool = True) -> dict:
        """Move EVERY in-flight request owned by ``rep`` to survivors:
        finished results are salvaged as-is, shed requests keep their
        structured error, and live requests fail over token-exactly via
        :meth:`ServingEngine.export_inflight`. Anything unsnapshottable
        lands in ``_lost`` with a reason (surfaced by
        :class:`FleetRequestError`) — counted, never silent."""
        with self._lock:
            idx = self.replicas.index(rep)
            owned = {
                loc[2]: fuid
                for fuid, loc in self._map.items()
                if loc[0] == "replica" and loc[1] == idx
            }
        migrated = lost = 0
        with rep.lock:
            eng = rep.engine
            for local, fuid in list(owned.items()):
                got = eng.done.get(local)
                if got is not None:
                    with self._lock:
                        self._map[fuid] = (
                            "done", got, eng._done_new.get(local), eng._done_lps.get(local)
                        )
                    del owned[local]
                    continue
                err = eng._shed.get(local)
                if err is not None:
                    with self._lock:
                        self._shed[fuid] = err
                        self._map.pop(fuid, None)
                    del owned[local]
            by_uid = {}
            if owned:
                try:
                    by_uid = {
                        int(s["uid"]): s for s in eng.export_inflight(include_kv=allow_kv)
                    }
                except Exception as e:  # noqa: BLE001 — a husk too broken to export
                    eng._log.event(
                        "failover_export_failed", replica=rep.name,
                        error=f"{type(e).__name__}: {e}",
                    )
        for local, fuid in owned.items():
            snap = by_uid.get(local)
            if snap is None:
                with self._lock:
                    self._map.pop(fuid, None)
                    self._lost[fuid] = (
                        f"in-flight on replica {rep.name!r} at {reason}; no snapshot recovered"
                    )
                    self.failovers_lost += 1
                rep.engine.metrics.on_failover_lost()
                if self.tracer is not None:
                    self.tracer.finish(
                        self._trace_ids.get(fuid), status="lost",
                        reason=f"no snapshot recovered ({reason})",
                    )
                lost += 1
                continue
            if self._failover_one(rep, fuid, snap, reason):
                migrated += 1
            else:
                lost += 1
        return {"migrated": migrated, "lost": lost}

    def _failover_choice(self, snap: dict):
        """``(path, handoff_pred, recompute_us)`` for one snapshot,
        priced BEFORE anything moves
        (:func:`~accelerate_tpu.analysis.costmodel.price_failover`);
        ``config.failover`` pins the path for the A/B arms."""
        if snap.get("cache") is None:
            return "recompute", {"bytes": 0, "time_us": 0.0}, 0.0
        from .analysis.costmodel import price_failover

        src = next(
            (r for r in self.replicas if r.can_prefill()), self.replicas[0]
        )
        per_tok, fixed = src.engine.kv_handoff_dims()
        self._price_handoff(1)  # ensures _param_count is cached
        priced = price_failover(
            per_tok,
            len(snap["prompt"]),
            len(snap.get("out_tokens") or []),
            self._param_count,
            fixed_bytes=fixed,
            transport=self.config.transport,
            generation=self.config.generation,
        )
        mode = self.config.failover
        path = priced["path"] if mode == "auto" else mode
        return path, priced["handoff"], priced["recompute_us"]

    def _failover_one(self, src_rep: Replica, fuid: int, snap: dict, reason: str) -> bool:
        """Migrate ONE snapshotted request to a surviving replica; the
        KV-handoff leg retries with jittered backoff and falls back to
        recompute (always available) rather than losing the request."""
        cfg = self.config
        cand = self._routable_indices(decode=True, exclude=src_rep)
        if not cand:
            cand = self._routable_indices(exclude=src_rep)
        if not cand:
            with self._lock:
                self._map.pop(fuid, None)
                self._lost[fuid] = f"no surviving replica to migrate to ({reason})"
                self.failovers_lost += 1
            src_rep.engine.metrics.on_failover_lost()
            if self.tracer is not None:
                self.tracer.finish(
                    snap.get("trace"), status="lost",
                    reason=f"no surviving replica ({reason})",
                )
            return False
        with self._lock:
            loads = [r.load for r in self.replicas]
            d_idx = self._policy.pick_replica(loads, cand)
        dst = self.replicas[d_idx]
        path, pred, recompute_us = self._failover_choice(snap)
        moved = 0
        local = None
        if path == "handoff":
            jax = _jax()
            moved = int(
                sum(np.asarray(l).nbytes for l in jax.tree_util.tree_leaves(snap["cache"]))
            )

            def leg():
                with dst.lock:
                    return dst.engine.import_inflight(snap)

            try:
                local = retry_call(
                    leg,
                    attempts=cfg.failover_retry_attempts,
                    base_delay=cfg.failover_retry_base_delay_s,
                    max_delay=0.5,
                    on_retry=lambda attempt, delay, e: dst.engine._log.event(
                        "failover_retry", fuid=fuid, dst=dst.name, attempt=attempt,
                        delay_s=round(delay, 4), error=f"{type(e).__name__}: {e}",
                    ),
                )
            except Exception:  # noqa: BLE001 — the KV leg is an optimisation, never a requirement
                path, moved, local = "recompute", 0, None
        if local is None:
            slim = {k: v for k, v in snap.items() if k not in ("cache", "rows")}
            with dst.lock:
                local = dst.engine.import_inflight(slim)
        with self._lock:
            self._map[fuid] = ("replica", d_idx, local)
            self.failovers += 1
            if path == "handoff":
                self.failovers_kv += 1
                self.failover_bytes_predicted += int(pred["bytes"])
                self.failover_bytes_moved += moved
                self.failover_time_us_predicted += float(pred["time_us"])
            else:
                self.failovers_recompute += 1
                self.failover_recompute_us_predicted += float(recompute_us)
        src_rep.engine.metrics.on_failover_out()
        if self.tracer is not None:
            # drain migrations get their own segment class so a planned
            # removal never pollutes the failover latency distribution
            self.tracer.seg(
                snap.get("trace"), "drain" if reason == "drain" else "failover",
                src=src_rep.name, dst=dst.name, path=path, reason=reason,
                moved_bytes=moved,
                predicted_bytes=int(pred["bytes"]) if path == "handoff" else 0,
                predicted_us=round(float(pred["time_us"]), 3),
                recompute_us=round(float(recompute_us), 3),
            )
        dst.engine._log.event(
            "failover", fuid=fuid, src=src_rep.name, dst=dst.name, path=path,
            reason=reason, generated=len(snap.get("out_tokens") or []),
            predicted_bytes=int(pred["bytes"]) if path == "handoff" else 0,
            moved_bytes=moved, predicted_us=round(float(pred["time_us"]), 3),
            recompute_us=round(float(recompute_us), 3),
            trace=snap.get("trace"),
        )
        return True

    def fail_replica(self, name: str, error: Optional[BaseException] = None) -> dict:
        """Operator surface: declare a replica failed out-of-band (its
        pod died, its host is being reclaimed) — classifies, breaks the
        circuit, migrates its in-flight work. Returns the replica's
        post-transition health entry."""
        rep = self._replica_by_name(name)
        self._on_replica_error(
            rep, error if error is not None else RuntimeError("declared failed by operator")
        )
        return self.health()[rep.name]

    def drain(self, name: str) -> dict:
        """Gracefully remove one replica: stop admissions to it, migrate
        its in-flight work to survivors (token- and logprob-exact, same
        machinery as failure — but the engine is healthy so its KV is
        always exportable), then drop it from the fleet. Returns
        ``{"replica", "migrated", "lost"}``."""
        rep = self._replica_by_name(name)
        with self._lock:
            if not [r for r in self.replicas if r is not rep and r.routable]:
                raise ValueError(
                    f"cannot drain {name!r}: no other serving replica to take its work"
                )
            rep.draining = True
        res = self._migrate_all(rep, reason="drain", allow_kv=True)
        self._remove_replica(rep)
        rep.engine._log.event(
            "replica_drain", replica=rep.name, migrated=res["migrated"], lost=res["lost"]
        )
        return {"replica": rep.name, **res}

    def _remove_replica(self, rep: Replica) -> None:
        with self._lock:
            idx = self.replicas.index(rep)
            self.replicas.pop(idx)
            for fuid, loc in list(self._map.items()):
                if loc[0] != "replica":
                    continue
                if loc[1] == idx:  # only if a migration leg failed above
                    self._map.pop(fuid)
                    self._lost[fuid] = f"replica {rep.name!r} removed"
                    if self.tracer is not None:
                        self.tracer.finish(
                            self._trace_ids.get(fuid), status="lost",
                            reason=f"replica {rep.name!r} removed",
                        )
                elif loc[1] > idx:
                    self._map[fuid] = ("replica", loc[1] - 1, loc[2])

    def health(self) -> dict:
        """Per-replica health view: ``{name: {health, role, draining,
        consecutive_timeouts, last_error, load}}``."""
        with self._lock:
            return {
                r.name: {
                    "health": r.health,
                    "role": r.role,
                    "draining": r.draining,
                    "consecutive_timeouts": r.consecutive_timeouts,
                    "last_error": r.last_error,
                    "load": r.load,
                }
                for r in self.replicas
            }

    # -- driving --------------------------------------------------------- #

    def dispatch_pending(self, limit: Optional[int] = None) -> int:
        """Run queued disaggregated prefills: each pending request
        prefills on the least-loaded prefill replica (radix reuse
        applies), its KV rows hand off to the least-loaded decode
        replica, and the router's byte accounting updates. Returns the
        number dispatched."""
        n = 0
        while True:
            with self._lock:
                if not self._pending or (limit is not None and n >= limit):
                    return n
                d_cand = self._routable_indices(decode=True)
                if not d_cand:
                    # terminal for pending work: nothing can ever decode
                    # these — account them lost instead of leaking
                    # forever-pending entries
                    for entry in self._pending:
                        self._map.pop(entry["fuid"], None)
                        self._lost[entry["fuid"]] = (
                            "no decode-capable serving replica for pending handoff"
                        )
                        self.failovers_lost += 1
                        if self.tracer is not None:
                            self.tracer.finish(
                                entry.get("trace"), status="lost",
                                reason="no decode-capable serving replica",
                            )
                    self._pending.clear()
                    return n
                # prefill side lost? decode replicas self-prefill detached
                # (role is a preference, not a capability — and uid_key
                # keeps the sampling chain identical either way)
                p_cand = self._routable_indices(prefill=True) or d_cand
                entry = self._pending.pop(0)
                loads = [r.load for r in self.replicas]
                p_idx = self._policy.pick_replica(loads, p_cand)
                d_idx = self._policy.pick_replica(loads, d_cand)
                pred, _ = self._price_handoff(len(entry["prompt"]))
            p_rep, d_rep = self.replicas[p_idx], self.replicas[d_idx]
            try:
                with p_rep.lock:
                    crash_point("pre_handoff", replica=p_rep.name)
                    prefix = (
                        p_rep.radix.lookup(entry["prompt"]) if p_rep.radix is not None else None
                    )
                    handoff = p_rep.engine.prefill_detached(
                        entry["prompt"], entry["max_new_tokens"],
                        uid_key=entry["fuid"],
                        prefix_id=None if prefix is None else prefix[0],
                        trace=entry.get("trace"),
                    )
                    if p_rep.radix is not None and prefix is None:
                        p_rep.radix.observe(entry["prompt"])
            except Exception as e:  # noqa: BLE001 — prefill replica died mid-dispatch
                with self._lock:
                    # the entry never left the router: requeue at the head
                    # (nothing ran — redispatch is exact by construction)
                    self._pending.insert(0, entry)
                self._on_replica_error(p_rep, e)
                continue
            with d_rep.lock:
                local = d_rep.engine.submit_prefilled(
                    handoff, stop_sequences=entry["stop_sequences"],
                    priority=entry["priority"],
                )
            with self._lock:
                self._map[entry["fuid"]] = ("replica", d_idx, local)
                self.handoffs += 1
                self.handoff_bytes_predicted += pred["bytes"]
                self.handoff_bytes_moved += handoff["wire_bytes"]
                self.handoff_time_us_predicted += pred["time_us"]
            if self.tracer is not None:
                # the router-side handoff span carries both sides of the
                # price: critpath pins moved_bytes == predicted_bytes
                self.tracer.seg(
                    entry.get("trace"), "kv_handoff",
                    src=p_rep.name, dst=d_rep.name, tokens=int(handoff["total"]),
                    moved_bytes=int(handoff["wire_bytes"]),
                    predicted_bytes=int(pred["bytes"]),
                    predicted_us=round(float(pred["time_us"]), 3),
                )
            p_rep.engine._log.event(
                "kv_handoff", fuid=entry["fuid"], src=p_rep.name, dst=d_rep.name,
                tokens=handoff["total"], predicted_bytes=pred["bytes"],
                moved_bytes=handoff["wire_bytes"],
                predicted_us=round(pred["time_us"], 3),
                reused_prefix_tokens=handoff["reused_prefix_tokens"],
                trace=entry.get("trace"),
            )
            n += 1

    def step(self) -> int:
        """One fleet tick: dispatch pending handoffs, then one guarded
        engine tick per busy SERVING replica (quarantined/dead replicas
        never tick — an engine exception fails the replica over instead
        of propagating). Returns occupied slots across the fleet (plus
        pending handoffs)."""
        self.dispatch_pending()
        active = 0
        for rep in list(self.replicas):
            if rep.is_serving:
                active += self._tick_replica(rep)
        with self._lock:
            return active + len(self._pending)

    def run(self) -> dict:
        """Drive ticks until every replica drains; returns
        ``{fleet_uid: full token array}`` — including results salvaged
        off failed/drained replicas."""
        while self._work_remaining():
            self.step()
        out = {}
        with self._lock:
            items = list(self._map.items())
        for fuid, loc in items:
            if loc[0] == "replica":
                got = self.replicas[loc[1]].engine.done.get(loc[2])
                if got is not None:
                    out[fuid] = got
            elif loc[0] == "done":
                out[fuid] = loc[1]
        return out

    def drain_threaded(self) -> float:
        """Drain all queued/pending work with one thread per replica
        (wall-clock overlap across replicas — XLA releases the GIL during
        compute); the caller's thread keeps dispatching handoffs.
        Returns elapsed seconds. Use :meth:`step` when determinism
        matters more than wall-clock.

        Worker-thread exceptions are NEVER invisible: each worker
        captures its exception, the caller's loop classifies it
        (:meth:`_on_replica_error` — replica marked failed, in-flight
        work failed over to survivors) and keeps draining. Only when no
        serving replica remains is the first captured exception
        re-raised — otherwise the fault is surfaced through replica
        health/events and the drain completes on the survivors."""
        t0 = time.perf_counter()
        stop = threading.Event()
        errors: list = []
        err_lock = threading.Lock()

        def worker(rep: Replica):
            while not stop.is_set():
                try:
                    with rep.lock:
                        # health is read under the same lock _set_health
                        # writes it: a failover on the caller's thread
                        # can't interleave with a half-observed state
                        if not rep.is_serving:
                            return
                        busy = rep.busy
                        if busy:
                            rep.engine.step()
                except Exception as e:  # noqa: BLE001 — surfaced by the caller's loop
                    with err_lock:
                        errors.append((rep, e))
                    return
                if not busy:
                    time.sleep(0.0005)

        threads = [threading.Thread(target=worker, args=(r,), daemon=True) for r in self.replicas]
        for t in threads:
            t.start()
        first_exc: Optional[BaseException] = None

        def handle_errors():
            nonlocal first_exc
            with err_lock:
                batch, errors[:] = list(errors), []
            for rep, exc in batch:
                if first_exc is None:
                    first_exc = exc
                # failover runs on the CALLER's thread: the dead worker
                # already released its lock on the way out, and survivors'
                # locks are only held a tick at a time
                self._on_replica_error(rep, exc)

        try:
            while True:
                handle_errors()
                if not self._work_remaining():
                    break
                self.dispatch_pending()
                time.sleep(0.0005)
        finally:
            stop.set()
            for t in threads:
                t.join()
            handle_errors()
        if first_exc is not None and not any(r.is_serving for r in self.replicas):
            raise first_exc
        return time.perf_counter() - t0

    def _work_remaining(self) -> bool:
        with self._lock:
            if self._pending and self._routable_indices(decode=True):
                return True
        return any(r.is_serving and r.busy for r in self.replicas)

    # -- request resolution ---------------------------------------------- #

    def _locate(self, fuid: int):
        """Raises the stored :class:`ShedError` for shed requests and a
        structured :class:`FleetRequestError` naming the last known state
        for unknown / failover-lost ids."""
        with self._lock:
            if fuid in self._shed:
                raise self._shed[fuid]
            loc = self._map.get(fuid)
            if loc is None:
                if fuid in self._lost:
                    raise FleetRequestError(
                        fuid, "lost", self._lost[fuid],
                        trace_id=self._trace_ids.get(fuid),
                    )
                raise FleetRequestError(fuid, "unknown", trace_id=self._trace_ids.get(fuid))
        return loc

    def _live_replica(self, fuid: int, loc) -> Replica:
        """The serving replica a map entry points at — raises the
        structured error instead of touching a failed engine (a transient
        state: failover re-homes the entry, after which the accessors
        resolve on the survivor)."""
        rep = self.replicas[loc[1]]
        if not rep.is_serving:
            raise FleetRequestError(
                fuid, f"on {rep.health} replica {rep.name!r}",
                rep.last_error or "failing over",
                trace_id=self._trace_ids.get(fuid),
            )
        return rep

    def poll(self, fuid: int):
        """Finished [prompt + generated] tokens, or None while pending.
        Raises the structured ShedError for a shed request (fleet- or
        replica-level) and :class:`FleetRequestError` for unknown or
        failover-lost ids. A request salvaged off a failed/drained
        replica resolves here exactly like a live one."""
        loc = self._locate(fuid)
        if loc[0] == "pending":
            return None
        if loc[0] == "done":
            return loc[1]
        rep = self._live_replica(fuid, loc)
        with rep.lock:
            try:
                return rep.engine.poll(loc[2])
            except ShedError as e:
                with self._lock:
                    self._shed[fuid] = e
                raise

    def partial(self, fuid: int) -> np.ndarray:
        """Tokens generated so far (streaming surface; empty while the
        request is queued or awaiting its handoff). A failed-over
        request keeps exposing its already-streamed tokens from the
        survivor — a delta streamer sees no regression across the
        migration."""
        loc = self._locate(fuid)
        if loc[0] == "pending":
            return np.zeros((0,), np.int32)
        if loc[0] == "done":
            return loc[2]
        rep = self._live_replica(fuid, loc)
        with rep.lock:
            return rep.engine.partial(loc[2])

    def logprobs(self, fuid: int) -> np.ndarray:
        loc = self._locate(fuid)
        if loc[0] == "pending":
            return np.zeros((0,), np.float32)
        if loc[0] == "done":
            return loc[3]
        rep = self._live_replica(fuid, loc)
        with rep.lock:
            return rep.engine.logprobs(loc[2])

    def cancel(self, fuid: int) -> np.ndarray:
        """Abort a request anywhere in the fleet (still-pending handoffs
        cancel before any prefill runs). Cancelling a request stranded
        on a quarantined/dead replica — or already LOST to a failed
        migration — succeeds WITHOUT touching the failed engine: the
        fleet-side tracking is dropped and the empty token array
        returned (the death already cancelled it for real)."""
        with self._lock:
            if fuid in self._shed:
                raise self._shed[fuid]
            loc = self._map.get(fuid)
            if loc is None:
                if fuid in self._lost:
                    del self._lost[fuid]
                    return np.zeros((0,), np.int32)
                raise FleetRequestError(fuid, "unknown", trace_id=self._trace_ids.get(fuid))
            if loc[0] == "pending":
                self._pending = [e for e in self._pending if e["fuid"] != fuid]
                del self._map[fuid]
                if self.tracer is not None:
                    self.tracer.finish(self._trace_ids.get(fuid), status="cancelled")
                return np.zeros((0,), np.int32)
            if loc[0] == "done":
                raise ValueError(f"request {fuid} already finished; poll() it instead")
        rep = self.replicas[loc[1]]
        if not rep.is_serving:
            with self._lock:
                self._map.pop(fuid, None)
            return np.zeros((0,), np.int32)
        with rep.lock:
            return rep.engine.cancel(loc[2])

    # -- observability ---------------------------------------------------- #

    def metrics_merged(self):
        """One fleet-view :class:`ServingMetrics` (summed counters,
        pooled latency windows — see ``ServingMetrics.merge``)."""
        from .telemetry.serving_metrics import ServingMetrics

        return ServingMetrics.merge([r.engine.metrics for r in self.replicas])

    def prometheus_text(self) -> str:
        """Prometheus exposition of every replica's metrics as ONE scrape
        (one HELP/TYPE block per metric, a ``replica`` label per
        sample)."""
        from .telemetry.serving_metrics import fleet_prometheus_text

        return fleet_prometheus_text([r.engine.metrics for r in self.replicas])

    def handoff_accounting(self) -> dict:
        with self._lock:
            return {
                "handoffs": self.handoffs,
                "handoffs_local": self.handoffs_local,
                "bytes_predicted": self.handoff_bytes_predicted,
                "bytes_moved": self.handoff_bytes_moved,
                "time_us_predicted": round(self.handoff_time_us_predicted, 3),
            }

    def failover_accounting(self) -> dict:
        """Byte/step accounting for every failover the router performed.
        ``bytes_predicted`` (the costmodel's pre-priced KV payload) is
        pinned equal to ``bytes_moved`` (actual leaf bytes shipped) by the
        test suite — failovers are priced BEFORE they happen, and the
        price must be honest."""
        with self._lock:
            return {
                "failovers": self.failovers,
                "failovers_kv": self.failovers_kv,
                "failovers_recompute": self.failovers_recompute,
                "failovers_lost": self.failovers_lost,
                "bytes_predicted": self.failover_bytes_predicted,
                "bytes_moved": self.failover_bytes_moved,
                "time_us_predicted": round(self.failover_time_us_predicted, 3),
                "recompute_us_predicted": round(self.failover_recompute_us_predicted, 3),
            }

    def radix_stats(self) -> dict:
        return {
            r.name: r.radix.stats() for r in self.replicas if r.radix is not None
        }
